"""North-star bench (BASELINE.json): LightGBM rows/sec/chip on 1M x 200.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline", ...extras}.

vs_baseline = TPU rows/sec divided by this host's CPU-executor rows/sec for
the identical trainer (the reference target is >=8x CPU-executor throughput,
BASELINE.md).  A ResNet-50 featurize images/sec/chip secondary metric rides
in the extras.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np


def gbdt_rows_per_sec(n=1_000_000, f=200, iters_a=2, iters_b=32) -> float:
    """Marginal boosting rate: rows * (B - A) / (t_B - t_A).  Subtracts the
    shared fixed costs (compile via cache warm, binning, transfer) so the
    number is the steady-state training rate both backends are judged by."""
    from mmlspark_tpu.lightgbm import GBDTParams, train
    rng = np.random.default_rng(0)
    X = rng.normal(size=(n, f)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] + rng.normal(scale=0.3, size=n) > 0).astype(np.float32)
    train(X, y, GBDTParams(num_iterations=1, objective="binary", max_depth=5))  # compile
    t0 = time.perf_counter()
    train(X, y, GBDTParams(num_iterations=iters_a, objective="binary", max_depth=5))
    t_a = time.perf_counter() - t0
    t0 = time.perf_counter()
    train(X, y, GBDTParams(num_iterations=iters_b, objective="binary", max_depth=5))
    t_b = time.perf_counter() - t0
    return n * (iters_b - iters_a) / max(t_b - t_a, 1e-9)


def resnet_images_per_sec(batch=32, steps=20, hw=224) -> float:
    import jax
    import jax.numpy as jnp
    from mmlspark_tpu.models import resnet50
    from mmlspark_tpu.ops import image as image_ops

    module = resnet50(num_classes=1000, dtype=jnp.bfloat16)
    x = jax.random.uniform(jax.random.PRNGKey(0), (batch, hw, hw, 3), jnp.float32, 0, 255)
    variables = module.init(jax.random.PRNGKey(1), x)

    @jax.jit
    def featurize(variables, batch):
        return module.apply(variables, image_ops.normalize(batch), features=True)

    featurize(variables, x).block_until_ready()
    xs = [jax.random.uniform(jax.random.PRNGKey(i + 2), (batch, hw, hw, 3),
                             jnp.float32, 0, 255) for i in range(min(8, steps))]
    for z in xs:
        z.block_until_ready()
    t0 = time.perf_counter()
    for i in range(steps):
        out = featurize(variables, xs[i % len(xs)])
        out.block_until_ready()
    return batch * steps / (time.perf_counter() - t0)


def cpu_probe() -> float:
    """CPU-executor baseline: identical trainer, scaled-down probe."""
    code = (
        "import os\n"
        "os.environ['JAX_PLATFORMS']='cpu'\n"
        "import jax\n"
        "jax.config.update('jax_platforms','cpu')\n"
        "import numpy as np, time\n"
        "from mmlspark_tpu.lightgbm import GBDTParams, train\n"
        "rng = np.random.default_rng(0)\n"
        "n, f = 200_000, 200\n"
        "X = rng.normal(size=(n, f)).astype(np.float32)\n"
        "y = (X[:,0] > 0).astype(np.float32)\n"
        "train(X, y, GBDTParams(num_iterations=1, objective='binary', max_depth=5))\n"
        "import time as _t\n"
        "t0 = _t.perf_counter()\n"
        "train(X, y, GBDTParams(num_iterations=2, objective='binary', max_depth=5))\n"
        "ta = _t.perf_counter() - t0\n"
        "t0 = _t.perf_counter()\n"
        "train(X, y, GBDTParams(num_iterations=7, objective='binary', max_depth=5))\n"
        "tb = _t.perf_counter() - t0\n"
        "print('CPU_RPS', n * 5 / max(tb - ta, 1e-9))\n"
    )
    try:
        out = subprocess.run([sys.executable, "-c", code],
                             cwd=os.path.dirname(os.path.abspath(__file__)),
                             capture_output=True, text=True, timeout=1200)
        for line in out.stdout.splitlines():
            if line.startswith("CPU_RPS"):
                return float(line.split()[1])
    except Exception:
        pass
    return 0.0


def _log(msg):
    import sys
    print(msg, file=sys.stderr, flush=True)


class _PhaseTimeout(Exception):
    pass


def _with_deadline(fn, seconds, default=None):
    """Run fn() with a SIGALRM deadline; on expiry return `default` so one
    wedged device phase can't hang the whole bench."""
    import signal

    def handler(signum, frame):
        raise _PhaseTimeout()

    old = signal.signal(signal.SIGALRM, handler)
    signal.alarm(int(seconds))
    try:
        return fn()
    except _PhaseTimeout:
        _log(f"[bench] phase timed out after {seconds}s")
        return default
    except Exception as e:  # noqa: BLE001
        _log(f"[bench] phase failed: {e}")
        return default
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


def main() -> None:
    # ResNet first: device state is clean (running after the 1M-row GBDT
    # dataset measurably degrades inference throughput in this environment)
    import time as _t
    t0 = _t.perf_counter()
    images_sec = _with_deadline(lambda: resnet_images_per_sec(batch=64), 900)
    _log(f"[bench] resnet done in {_t.perf_counter()-t0:.0f}s")
    t0 = _t.perf_counter()
    tpu_rps = _with_deadline(gbdt_rows_per_sec, 1200)
    if tpu_rps is None:  # degraded fallback: smaller workload
        tpu_rps = _with_deadline(lambda: gbdt_rows_per_sec(n=200_000, iters_b=12), 600,
                                 default=0.0)
    _log(f"[bench] gbdt tpu done in {_t.perf_counter()-t0:.0f}s")
    t0 = _t.perf_counter()
    cpu_rps = _with_deadline(cpu_probe, 1200, default=0.0)
    _log(f"[bench] cpu probe done in {_t.perf_counter()-t0:.0f}s")
    print(json.dumps({
        "metric": "lightgbm_train_rows_per_sec_per_chip_1Mx200",
        "value": round(tpu_rps, 1),
        "unit": "rows/sec",
        "vs_baseline": round(tpu_rps / cpu_rps, 3) if cpu_rps else None,
        "extras": {
            "cpu_executor_rows_per_sec": round(cpu_rps, 1) if cpu_rps else None,
            "resnet50_featurize_images_per_sec_per_chip": round(images_sec, 1)
            if images_sec else None,
        },
    }))


if __name__ == "__main__":
    main()
