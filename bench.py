"""North-star bench (BASELINE.json): LightGBM rows/sec/chip on 1M x 200.

Prints JSON lines {"metric", "value", "unit", "vs_baseline", ...extras};
the LAST line printed is the result (the driver parses last-JSON-wins).

vs_baseline = TPU rows/sec divided by this host's CPU-executor rows/sec for
the identical trainer (the reference target is >=8x CPU-executor throughput,
BASELINE.md).  ResNet-50 featurize images/sec/chip rides in the extras.

Resilience design (round 2, after BENCH_r01 ended rc=124 / parsed=null):
- a valid JSON result line is printed after EVERY phase, so an outer
  timeout can never erase completed measurements;
- the persistent XLA compilation cache is enabled (relay compiles dominated
  round 1: one conv net took 1502s) and bench shapes match __graft_entry__
  .entry() exactly, so the driver's compile check pre-warms the cache;
- the CPU baseline probe runs in a subprocess pinned to the CPU platform
  with sitecustomize TPU hooks scrubbed; it launches AFTER the timed TPU
  GBDT phase (host-CPU contention would deflate that phase's host-side
  binning) and overlaps only the ResNet phase, whose host work is
  negligible;
- phase deadlines keep the worst case under ~800s;
- timed loops vary their inputs every step and end with a host fetch: the
  relay can serve repeated (computation, args) pairs from cache without
  executing (.claude/skills/verify/SKILL.md).
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

_REPO = os.path.dirname(os.path.abspath(__file__))

RESULT = {
    "metric": "lightgbm_train_rows_per_sec_per_chip_1Mx200",
    "value": None,
    "unit": "rows/sec",
    "vs_baseline": None,
    "extras": {},
}


def _emit() -> None:
    print(json.dumps(RESULT), flush=True)


def _log(msg) -> None:
    print(msg, file=sys.stderr, flush=True)


def gbdt_rows_per_sec(n=1_000_000, f=200, iters_a=2, iters_b=12) -> float:
    """Marginal boosting rate: rows * (B - A) / (t_B - t_A).  Subtracts the
    shared fixed costs (compile — cached across runs since the jitted
    per-iteration program's key excludes num_iterations — binning, host->
    device transfer), leaving the steady-state training rate both backends
    are judged by.  Scores evolve every iteration, so each dispatch is a
    distinct (computation, args) pair — no relay result caching."""
    from mmlspark_tpu.lightgbm import GBDTParams, train
    rng = np.random.default_rng(0)
    X = rng.normal(size=(n, f)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] + rng.normal(scale=0.3, size=n) > 0).astype(np.float32)
    train(X, y, GBDTParams(num_iterations=1, objective="binary", max_depth=5))  # compile
    t0 = time.perf_counter()
    train(X, y, GBDTParams(num_iterations=iters_a, objective="binary", max_depth=5))
    t_a = time.perf_counter() - t0
    t0 = time.perf_counter()
    train(X, y, GBDTParams(num_iterations=iters_b, objective="binary", max_depth=5))
    t_b = time.perf_counter() - t0
    return n * (iters_b - iters_a) / max(t_b - t_a, 1e-9)


def resnet_images_per_sec(batch=32, steps=10, hw=224) -> float:
    """Same program as __graft_entry__.entry() (shapes, dtype, step-scalar),
    so the driver's compile check warms the persistent cache for this."""
    import jax
    import jax.numpy as jnp
    from mmlspark_tpu.models import resnet50
    from mmlspark_tpu.ops import image as image_ops

    module = resnet50(num_classes=1000, dtype=jnp.bfloat16)
    variables = module.init(jax.random.PRNGKey(0),
                            jnp.zeros((1, 64, 64, 3), jnp.float32))
    x = jax.random.uniform(jax.random.PRNGKey(1), (batch, hw, hw, 3),
                           jnp.float32, 0, 255)

    @jax.jit
    def featurize(variables, batch, step):
        return module.apply(variables, image_ops.normalize(batch + step),
                            features=True)

    # warm the EXACT benched shape; host fetch forces remote execution
    float(featurize(variables, x, jnp.float32(-1.0)).sum())
    t0 = time.perf_counter()
    out = None
    for i in range(steps):
        out = featurize(variables, x, jnp.float32(i))  # distinct args/step
    float(out.sum())  # drain the async dispatch queue
    return batch * steps / (time.perf_counter() - t0)


_CPU_PROBE_CODE = r"""
import os
os.environ['JAX_PLATFORMS'] = 'cpu'
import jax
jax.config.update('jax_platforms', 'cpu')
import numpy as np, time, sys
sys.path.insert(0, {repo!r})
from mmlspark_tpu.lightgbm import GBDTParams, train
rng = np.random.default_rng(0)
n, f = 200_000, 200
X = rng.normal(size=(n, f)).astype(np.float32)
y = (X[:, 0] + 0.5 * X[:, 1] + rng.normal(scale=0.3, size=n) > 0).astype(np.float32)
train(X, y, GBDTParams(num_iterations=1, objective='binary', max_depth=5))
t0 = time.perf_counter()
train(X, y, GBDTParams(num_iterations=2, objective='binary', max_depth=5))
ta = time.perf_counter() - t0
t0 = time.perf_counter()
train(X, y, GBDTParams(num_iterations=7, objective='binary', max_depth=5))
tb = time.perf_counter() - t0
print('CPU_RPS', n * 5 / max(tb - ta, 1e-9), flush=True)
"""


def launch_cpu_probe() -> subprocess.Popen:
    """CPU-executor baseline: identical trainer in a subprocess pinned to the
    CPU platform.  Runs concurrently with the TPU phases (it shares no
    device); PYTHONPATH is scrubbed so sitecustomize's TPU hooks never touch
    the relay from this process."""
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("TPU", "AXON"))}
    env.pop("PYTHONPATH", None)
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.Popen(
        [sys.executable, "-c", _CPU_PROBE_CODE.replace("{repo!r}", repr(_REPO))],
        cwd=_REPO, env=env, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        text=True)


def collect_cpu_probe(proc: subprocess.Popen, timeout: float) -> float:
    try:
        out, _ = proc.communicate(timeout=timeout)
        for line in out.splitlines():
            if line.startswith("CPU_RPS"):
                return float(line.split()[1])
    except subprocess.TimeoutExpired:
        proc.kill()
        _log("[bench] cpu probe timed out")
    except Exception as e:  # noqa: BLE001
        _log(f"[bench] cpu probe failed: {e}")
    return 0.0


class _PhaseTimeout(Exception):
    pass


def _with_deadline(fn, seconds, default=None):
    """Run fn() under a SIGALRM deadline so one wedged device phase can't
    consume the whole outer budget (note: the alarm cannot preempt a blocked
    relay RPC — it fires when control returns to Python — which is why the
    risky phases run LAST and results are emitted incrementally)."""
    import signal

    def handler(signum, frame):
        raise _PhaseTimeout()

    old = signal.signal(signal.SIGALRM, handler)
    signal.alarm(int(seconds))
    try:
        return fn()
    except _PhaseTimeout:
        _log(f"[bench] phase timed out after {seconds}s")
        return default
    except Exception as e:  # noqa: BLE001
        _log(f"[bench] phase failed: {e}")
        return default
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


def main() -> None:
    import gc
    from __graft_entry__ import enable_compilation_cache
    enable_compilation_cache()
    wall0 = time.perf_counter()

    # Phase 1 — headline metric: GBDT rows/sec on the real chip (no other
    # process competes for host CPU during its timed window).
    t0 = time.perf_counter()
    tpu_rps = _with_deadline(gbdt_rows_per_sec, 330)
    scaled = False
    if tpu_rps is None:  # degraded fallback: quarter-size, same trainer
        tpu_rps = _with_deadline(
            lambda: gbdt_rows_per_sec(n=250_000, iters_b=10), 150, default=0.0)
        scaled = tpu_rps > 0
    _log(f"[bench] gbdt tpu done in {time.perf_counter() - t0:.0f}s")
    RESULT["value"] = round(tpu_rps, 1)
    if scaled:
        RESULT["extras"]["note"] = (
            "measured at 250k x 200 (1M deadline exceeded); rows/sec is the "
            "steady-state marginal rate, which scales ~linearly in rows")
    _emit()

    # Phase 2 — ResNet-50 featurize.  The CPU probe overlaps this phase only
    # (its host work is a handful of dispatches).  GBDT host buffers are
    # dropped first: round 1 observed inference degradation after the 1M-row
    # dataset, so reclaim host/device memory before timing inference.
    cpu_proc = launch_cpu_probe()
    gc.collect()
    t0 = time.perf_counter()
    images_sec = _with_deadline(resnet_images_per_sec, 240)
    _log(f"[bench] resnet done in {time.perf_counter() - t0:.0f}s")
    if images_sec:
        RESULT["extras"]["resnet50_featurize_images_per_sec_per_chip"] = round(
            images_sec, 1)
    _emit()

    # Phase 3 — CPU-executor baseline (collect; it ran during phase 2).
    remaining = max(60.0, 780.0 - (time.perf_counter() - wall0))
    cpu_rps = collect_cpu_probe(cpu_proc, remaining)
    _log(f"[bench] cpu probe: {cpu_rps:.0f} rows/sec")
    if cpu_rps:
        RESULT["extras"]["cpu_executor_rows_per_sec"] = round(cpu_rps, 1)
        if tpu_rps:
            RESULT["vs_baseline"] = round(tpu_rps / cpu_rps, 3)
    _emit()


if __name__ == "__main__":
    main()
