"""Benchmark: ResNet-50 featurization images/sec/chip (BASELINE.json north star #2).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline context: the reference's CNTKModel/ImageFeaturizer ran per-executor
CPU/GPU inference; the driver-supplied target is >=8x CPU-executor throughput
(BASELINE.md).  vs_baseline is measured against this host's own CPU-executor
throughput for the identical model, so >=8 means target met.
"""
from __future__ import annotations

import json
import time

import numpy as np


def _images_per_sec(device_kind: str, batch: int = 32, steps: int = 20,
                    hw: int = 224) -> float:
    import jax
    import jax.numpy as jnp
    from mmlspark_tpu.models import resnet50
    from mmlspark_tpu.ops import image as image_ops

    module = resnet50(num_classes=1000, dtype=jnp.bfloat16)
    x = jax.random.uniform(jax.random.PRNGKey(0), (batch, hw, hw, 3),
                           jnp.float32, 0, 255)
    variables = module.init(jax.random.PRNGKey(1), x)

    @jax.jit
    def featurize(variables, batch):
        return module.apply(variables, image_ops.normalize(batch), features=True)

    featurize(variables, x).block_until_ready()  # compile
    # distinct pre-staged inputs each step + per-step sync: rules out
    # result caching and async-dispatch undercounting
    xs = [jax.random.uniform(jax.random.PRNGKey(i + 2), (batch, hw, hw, 3),
                             jnp.float32, 0, 255) for i in range(min(8, steps))]
    for z in xs:
        z.block_until_ready()
    t0 = time.perf_counter()
    for i in range(steps):
        out = featurize(variables, xs[i % len(xs)])
        out.block_until_ready()
    dt = time.perf_counter() - t0
    return batch * steps / dt


def main() -> None:
    import jax
    tpu_ips = _images_per_sec(jax.devices()[0].platform)

    # CPU-executor baseline: same model on host CPU, smaller workload scaled up.
    cpu_ips = None
    try:
        import subprocess, sys, os
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        code = (
            "import os\n"
            "import jax\n"
            "jax.config.update('jax_platforms','cpu')\n"
            "import bench\n"
            "print('CPU_IPS', bench._images_per_sec('cpu', batch=8, steps=3))\n"
        )
        out = subprocess.run([sys.executable, "-c", code], env=env, cwd=os.path.dirname(
            os.path.abspath(__file__)), capture_output=True, text=True, timeout=900)
        for line in out.stdout.splitlines():
            if line.startswith("CPU_IPS"):
                cpu_ips = float(line.split()[1])
    except Exception:
        pass

    vs = round(tpu_ips / cpu_ips, 3) if cpu_ips else None
    print(json.dumps({
        "metric": "resnet50_featurize_images_per_sec_per_chip",
        "value": round(tpu_ips, 2),
        "unit": "images/sec",
        "vs_baseline": vs,
    }))


if __name__ == "__main__":
    main()
