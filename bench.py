"""North-star bench (BASELINE.json): LightGBM rows/sec/chip on 1M x 200.

Prints JSON lines {"metric", "value", "unit", "vs_baseline", ...extras};
the LAST line printed is the result (the driver parses last-JSON-wins).

vs_baseline = TPU rows/sec divided by this host's CPU-executor rows/sec for
the identical trainer (the reference target is >=8x CPU-executor throughput,
BASELINE.md).  ResNet-50 featurize images/sec/chip rides in the extras.

Resilience design (round 2, after BENCH_r01 ended rc=124 / parsed=null):

- The PARENT process never touches the device.  Every TPU phase runs in a
  child process; the parent streams the child's merged output and kills
  ONLY on silence (round-4 post-mortem: a wall-clock kill landed mid-compile
  and wedged the relay for hours, costing every later phase AND the next
  session's runs).  The idle window is sized past the longest observed
  compile, so a kill now implies the child was already hung or the relay
  already wedged.
- A valid JSON result line is printed after EVERY phase, so an outer
  timeout can never erase completed measurements.
- A 120s health-check child gates the TPU phases: if a trivial matmul
  cannot complete, TPU phases are skipped with an explanatory note and the
  CPU baseline still gets measured and reported.
- The persistent XLA compilation cache is enabled in children, and bench
  shapes match __graft_entry__.entry() exactly so the driver's compile
  check pre-warms the cache.
- The CPU probe runs pinned to the CPU platform with sitecustomize TPU
  hooks scrubbed, FIRST and STRICTLY ALONE (VERDICT r4 weak #1: the host is
  one Xeon core — any concurrent phase halves the denominator), median-of-3
  with the host fingerprint (nproc/model/load) stamped into extras.
- TPU phases that miss their (compile-aware) deadline get ONE retry — a
  completed first-attempt compile lands in the persistent cache, making the
  retry measurement-only — and a killed phase leaves a note in
  extras.phase_notes instead of silence.
- Timed loops vary their inputs every step and end with a host fetch: the
  relay can serve repeated (computation, args) pairs from cache without
  executing (.claude/skills/verify/SKILL.md).
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.abspath(__file__))

RESULT = {
    "metric": "lightgbm_train_rows_per_sec_per_chip_1Mx200",
    "value": None,
    "unit": "rows/sec",
    "vs_baseline": None,
    "extras": {},
}


def _emit() -> None:
    print(json.dumps(RESULT), flush=True)


def _metrics_snapshot_json(max_bytes: int = 4096) -> str:
    """Bounded, redact-free ``/metrics``-style snapshot of this process's
    registry (ISSUE 11): each phase child prints it as a ``PHASE_METRICS``
    marker so bench regressions can be diagnosed from counters instead of
    reruns.  Redact-free by construction: exemplars (trace ids) and help
    text are stripped; when the JSON overflows ``max_bytes`` the largest
    families are dropped and NAMED — truncation must be attributable,
    never silent."""
    import json as _json
    from mmlspark_tpu.observability import get_registry
    body = get_registry().to_dict()
    for fam in body.values():
        fam.pop("help", None)
        for s in fam.get("samples", ()):
            s.pop("exemplars", None)
    dropped = []
    while True:
        payload = dict(body)
        if dropped:
            payload["_dropped_families"] = dropped
        out = _json.dumps(payload, separators=(",", ":"), default=str)
        if len(out) <= max_bytes or not body:
            return out
        largest = max(body,
                      key=lambda k: len(_json.dumps(body[k], default=str)))
        body.pop(largest)
        dropped.append(largest)


def _emit_phase_metrics() -> None:
    """Print the post-phase registry snapshot marker (child side)."""
    try:
        print(f"PHASE_METRICS {_metrics_snapshot_json()}", flush=True)
    except Exception as e:  # noqa: BLE001 — telemetry must not kill a phase
        _log(f"[bench] phase metrics snapshot failed: {e}")


def _record_phase_metrics(phase: str, got: dict) -> bool:
    """Fold a child's ``PHASE_METRICS`` snapshot into the run artifact's
    extras; absent or garbled markers fold nothing (False)."""
    raw = got.get("PHASE_METRICS")
    if not isinstance(raw, str) or not raw:
        return False
    try:
        snap = json.loads(raw)
    except ValueError:
        return False
    if not isinstance(snap, dict):
        return False
    RESULT["extras"].setdefault("phase_metrics", {})[phase] = snap
    return True


def _log(msg) -> None:
    print(msg, file=sys.stderr, flush=True)


# --------------------------------------------------------------------------
# phase bodies (run inside child processes; print MARKER lines on stdout)
# --------------------------------------------------------------------------

def phase_health(hold=0) -> None:
    """Trivial device round trip — proves the relay can compile + execute.

    ``hold=1`` turns the child into a *persistent warm relay*: after the
    probe it stays alive with its device client attached (heartbeating so
    the parent's silence detector never fires on it) until the parent kills
    it at bench end.  Keeping one live client on the relay across phases
    means later children attach to a warm relay instead of re-waking it —
    the cold-attach stall is what r05's lost TPU phases looked like."""
    from __graft_entry__ import enable_compilation_cache
    enable_compilation_cache()
    import jax
    import jax.numpy as jnp
    x = jnp.ones((256, 256))
    val = float((x @ x).sum())
    print(f"HEALTH_OK {val}", flush=True)
    while hold:
        time.sleep(60)
        # tiny periodic round trip keeps the relay session genuinely warm
        # (an idle socket can be reaped server-side); failures are logged,
        # never fatal — the holder is best-effort by design
        try:
            val = float((x @ x).sum())
            print(f"WARM_RELAY_ALIVE {val}", flush=True)
        except Exception as e:  # noqa: BLE001
            print(f"WARM_RELAY_ERR {e}", flush=True)


def phase_gbdt(n=1_000_000, f=200, iters_a=8, iters_b=24, reps=3) -> None:
    """Marginal boosting rate: rows * (B - A) / (t_B - t_A), median of
    ``reps`` repetitions.  The marginal form subtracts the shared fixed
    costs (compile — cached across calls since the jitted per-iteration
    program's key excludes num_iterations — binning, host->device
    transfer), leaving the steady-state training rate both backends are
    judged by.

    Cache-busting (round-4 finding): the device relay serves REPEATED
    identical (computation, args) dispatches from cache without executing —
    round 3's 3.16M rows/s outlier was exactly the 2x inflation a cached
    A-run produces.  Every train() call here flips a fresh window of
    labels, so init_score and the whole score trajectory differ and every
    dispatch is a first-sight args tuple.  Median-of-reps then absorbs
    relay-load variance (round 3 measured 1.4-3.2M for one config measured
    once)."""
    from __graft_entry__ import enable_compilation_cache
    enable_compilation_cache()
    import numpy as np
    from mmlspark_tpu.lightgbm import GBDTParams, train
    rng = np.random.default_rng(0)
    X = rng.normal(size=(n, f)).astype(np.float32)
    y0 = (X[:, 0] + 0.5 * X[:, 1] + rng.normal(scale=0.3, size=n) > 0).astype(np.float32)
    nonce = [0]

    def fresh_y():
        nonce[0] += 1
        y = y0.copy()
        a = (37 * nonce[0]) % (n - 64)
        y[a:a + 64] = 1.0 - y[a:a + 64]
        return y

    bc = {}   # binning + device-put memo: X never changes across calls
    t0 = time.perf_counter()
    # warm at iters_a so BOTH timed runs hit the chunked program (default
    # CH engages from 2*CH iterations; 1-iteration warm would only
    # compile the unchunked path)
    train(X, fresh_y(), GBDTParams(num_iterations=iters_a, objective="binary",
                                   max_depth=5), bin_cache=bc)
    _log(f"[bench] gbdt warm(compile) {time.perf_counter() - t0:.0f}s")
    rates = []
    for _ in range(reps):
        t0 = time.perf_counter()
        train(X, fresh_y(), GBDTParams(num_iterations=iters_a,
                                       objective="binary", max_depth=5),
              bin_cache=bc)
        t_a = time.perf_counter() - t0
        t0 = time.perf_counter()
        train(X, fresh_y(), GBDTParams(num_iterations=iters_b,
                                       objective="binary", max_depth=5),
              bin_cache=bc)
        t_b = time.perf_counter() - t0
        rates.append(n * (iters_b - iters_a) / max(t_b - t_a, 1e-9))
        _log(f"[bench] gbdt rep rate {rates[-1]:.0f}")
    rates.sort()
    rate = rates[len(rates) // 2]
    print(f"GBDT_RPS {rate} {n}", flush=True)

    # achievable-utilization denominator (PR 6 follow-up): the instrumented
    # jit captured cost_analysis for the per-iteration program — fold its
    # bytes-accessed into an HBM-roofline utilization % so tile-size tuning
    # (and the fused-kernel item) have a denominator, not just a rate.
    try:
        from mmlspark_tpu.observability.compute import compile_report
        fns = compile_report()["functions"]
        if "lightgbm.multi_iter" in fns:
            cost = fns["lightgbm.multi_iter"].get("last_cost_analysis") or {}
            ch = int(os.environ.get("MMLSPARK_TPU_GBDT_CHUNK") or 4)
        else:
            cost = (fns.get("lightgbm.iter") or {}).get(
                "last_cost_analysis") or {}
            ch = 1
        bytes_prog = cost.get("bytes_accessed")
        if bytes_prog:
            bytes_per_iter = bytes_prog / max(1, ch)
            peak = float(os.environ.get("MMLSPARK_TPU_PEAK_HBM_GBPS",
                                        "819")) * 1e9
            util_pct = 100.0 * bytes_per_iter * (rate / n) / peak
            print(f"GBDT_UTIL {bytes_per_iter} {util_pct}", flush=True)
    except Exception as e:  # noqa: BLE001 — cost analysis is best-effort
        _log(f"[bench] gbdt util skipped: {e}")


def phase_hist_ab(n=1_000_000, f=200, nodes=16, reps=3, proxy=0) -> None:
    """Packed-int vs f32 3-channel histogram build A/B on the SAME shape —
    the attribution artifact for the quantized-gradient pipeline (packed
    int8 MXU operands cut the hot kernel's HBM traffic ~3x vs the bf16
    residual channels; see ops/histogram.py).

    TPU mode compares the matmul backends at the bench shape (1M x 200):
    f32 = ``residuals=False`` (the 3-channel f32 build, the strongest f32
    baseline) vs quantize+``build_histograms_matmul_quantized``.  ``proxy=1``
    (relay down) compares the scatter backends on CPU at a reduced shape
    with many balanced nodes, where the int32 lane packing collapses three
    f32 segment-sums into one.  Quantization rides INSIDE the packed
    timing — the A/B charges the packed path its full per-iteration cost.
    Inputs perturb per rep (relay result-cache busting, as phase_gbdt)."""
    from __graft_entry__ import enable_compilation_cache
    enable_compilation_cache()
    import jax
    import jax.numpy as jnp
    import numpy as np
    from mmlspark_tpu.ops import histogram as hist_ops

    B = 256
    if proxy:
        n, f, nodes = min(n, 120_000), min(f, 50), 1024
    rng = np.random.default_rng(0)
    binned = jnp.asarray(rng.integers(0, B - 1, (n, f)).astype(np.uint8))
    g0 = jnp.asarray(rng.normal(size=n).astype(np.float32))
    h0 = jnp.asarray(rng.uniform(0.1, 1.0, size=n).astype(np.float32))
    node = jnp.asarray((np.arange(n) % nodes).astype(np.int32))
    bound = -(-n // nodes) if proxy else None   # balanced by construction

    if proxy:
        @jax.jit
        def f32_build(g, h):
            return hist_ops.build_histograms(binned, g, h, node, nodes, B)

        @jax.jit
        def packed_build(g, h):
            qg, qh, _, _ = hist_ops.quantize_gradients(g, h, 16)
            return hist_ops.build_histograms_quantized(
                binned, qg, qh, node, nodes, B, quant_bins=16,
                node_rows_bound=bound)
    else:
        @jax.jit
        def f32_build(g, h):
            return hist_ops.build_histograms_matmul(binned, g, h, node,
                                                    nodes, B,
                                                    residuals=False)

        @jax.jit
        def packed_build(g, h):
            qg, qh, _, _ = hist_ops.quantize_gradients(g, h, 16)
            return hist_ops.build_histograms_matmul_quantized(
                binned, qg, qh, node, nodes, B, quant_bins=16)

    def timed(fn, tag):
        # jax.block_until_ready handles arrays AND tuples — one timing
        # protocol for the build arms and the fused-frontier arm below
        jax.block_until_ready(fn(g0, h0))       # compile warm
        _log(f"[bench] hist_ab {tag} warm done")
        rates = []
        for r in range(1, reps + 1):
            g = g0 + 0.001 * r                  # first-sight args per rep
            t0 = time.perf_counter()
            jax.block_until_ready(fn(g, h0))
            rates.append(n / (time.perf_counter() - t0))
            _log(f"[bench] hist_ab {tag} rep rows/s {rates[-1]:.0f}")
        rates.sort()
        return rates[len(rates) // 2]

    r_f32 = timed(f32_build, "f32")
    r_packed = timed(packed_build, "packed")
    print(f"HIST_AB_RATES {r_f32} {r_packed} {r_packed / max(r_f32, 1e-9)}", flush=True)
    print(f"HIST_AB_MODE {'cpu_scatter_proxy' if proxy else 'tpu_matmul'} "
          f"{n} {f}", flush=True)

    # ---- fused-vs-separate frontier arm (ISSUE 8): one VMEM-resident
    # Pallas kernel (smaller-child build + integer sibling subtraction +
    # split-gain scan -> best (feature, bin, gain) per node) against the
    # SAME work as separate XLA dispatches (packed build, subtract,
    # dequantize/cumsum/argmax).  Frontier shape: P parents' smaller
    # children (~half the rows scattered), the level-wise grower's
    # steady-state step.  proxy=1 runs the kernel under the Pallas
    # interpreter (plain XLA on CPU); on TPU the compiled Mosaic kernel
    # runs — that number is the ROADMAP's on-chip gate.
    from mmlspark_tpu.observability.compute import instrumented_jit
    from mmlspark_tpu.ops import pallas_histogram as plh
    P = 8  # 16 frontier children
    interp = bool(proxy) or jax.default_backend() != "tpu"
    sep_backend = "scatter" if interp else "matmul"
    node_parent = jnp.asarray((np.arange(n) % P).astype(np.int32))
    in_small = jnp.asarray(((np.arange(n) // P) % 2 == 0))
    node_small = jnp.where(in_small, node_parent, -1)
    sl = jnp.ones((P,), bool)
    fmask = jnp.ones((f,), bool)
    edge_ok = jnp.asarray(np.concatenate(
        [np.ones((f, B - 1), bool), np.zeros((f, 1), bool)], axis=1))
    qg0, qh0, _, _ = hist_ops.quantize_gradients(g0, h0, 16)
    parent = hist_ops.build_quantized(binned, qg0, qh0, node_parent, P, B,
                                      quant_bins=16, backend=sep_backend)
    gain_kw = dict(quant_bins=16, l1=0.0, l2=1.0, min_data=20.0,
                   min_hess=1e-3)

    @instrumented_jit(name="ops.pallas_frontier")
    def fused_step(g, h):
        qg, qh, gs, hs = hist_ops.quantize_gradients(g, h, 16)
        hist, best = plh.fused_frontier(
            binned, qg, qh, node_small, P, B, gs, hs, fmask, edge_ok,
            parent_hist=parent, small_left=sl, interpret=interp, **gain_kw)
        return hist, best[0], best[1], best[2]

    @instrumented_jit(name="ops.hist_separate")
    def sep_step(g, h):
        qg, qh, gs, hs = hist_ops.quantize_gradients(g, h, 16)
        hsm = hist_ops.build_quantized(binned, qg, qh, node_small, P, B,
                                       quant_bins=16, backend=sep_backend)
        sib = parent - hsm
        sl4 = sl[:, None, None, None]
        hist_d = jnp.stack([jnp.where(sl4, hsm, sib),
                            jnp.where(sl4, sib, hsm)],
                           axis=1).reshape(2 * P, f, B, 3)
        hd = hist_ops.dequantize_histogram(hist_d, gs, hs)
        cum = jnp.cumsum(hd, axis=2)
        tot = cum[:, :1, -1, :]
        GL, HL, CL = cum[..., 0], cum[..., 1], cum[..., 2]
        Gp, Hp, Cp = tot[..., 0], tot[..., 1], tot[..., 2]
        GR, HR = Gp[:, :, None] - GL, Hp[:, :, None] - HL
        CR = Cp[:, :, None] - CL
        score = lambda G, H: G ** 2 / (H + 1.0)  # l1=0, l2=1 as fused
        gain = score(GL, HL) + score(GR, HR) - score(Gp, Hp)[:, :, None]
        ok = ((CL >= 20.0) & (CR >= 20.0) & (HL >= 1e-3) & (HR >= 1e-3)
              & fmask[None, :, None] & edge_ok[None])
        gain = jnp.where(ok, gain, -jnp.inf)
        flat = gain.reshape(2 * P, f * B)
        am = jnp.argmax(flat, axis=1)
        bg = jnp.take_along_axis(flat, am[:, None], axis=1)[:, 0]
        return hist_d, bg, am // B, am % B

    r_sep = timed(sep_step, "separate")
    r_fused = timed(fused_step, "fused")
    print(f"HIST_AB_FUSED {r_sep} {r_fused} {r_fused / max(r_sep, 1e-9)}",
          flush=True)


def phase_runner(n=2000, hw=32, batch=128, reps=3, vocab=512, dec_batch=8,
                 prompt=16, new_tokens=32, proxy=0) -> None:
    """Unified-runner A/B (ISSUE 9): batch featurize throughput through
    ``ModelRunner.apply_batch`` vs the legacy hand-rolled glue the runner
    replaced (per-bucket ``jax.jit`` + pad, inlined here verbatim since the
    library copy is gone) — same model, same buckets, same ragged row count,
    so the ratio isolates the runner's host-side overhead (acceptance:
    runner >= 0.9x legacy).  A decode arm then measures KV-cached batched
    generation (prefill + one compiled step re-dispatched per token) and
    reports tokens/sec — the ROADMAP's generative-serving number.  Inputs
    perturb per rep (relay result-cache busting, as phase_gbdt)."""
    from __graft_entry__ import enable_compilation_cache
    enable_compilation_cache()
    import jax
    import jax.numpy as jnp
    import numpy as np
    from mmlspark_tpu.models import ModelRunner, TransformerEncoder, resnet18
    from mmlspark_tpu.models.runner import bucket_rows

    if proxy:
        n, batch, new_tokens = min(n, 600), min(batch, 64), min(new_tokens, 16)
    module = resnet18(num_classes=64, dtype=jnp.float32)
    variables = module.init(jax.random.PRNGKey(0),
                            jnp.zeros((1, hw, hw, 3), jnp.float32))
    x0 = np.asarray(jax.random.uniform(jax.random.PRNGKey(1), (n, hw, hw, 3),
                                       jnp.float32))

    def pure(vs, chunk):
        return module.apply(vs, chunk, features=True)

    # --- legacy arm: the pre-runner JaxModel glue, one jit per bucket
    legacy_cache = {}

    def legacy_apply(x):
        outs = []
        for start in range(0, x.shape[0], batch):
            chunk = x[start:start + batch]
            m = chunk.shape[0]
            bucket = bucket_rows(m, batch)
            if m < bucket:
                chunk = np.concatenate(
                    [chunk, np.repeat(chunk[-1:], bucket - m, axis=0)])
            fn = legacy_cache.get(bucket)
            if fn is None:
                fn = legacy_cache[bucket] = jax.jit(pure)
            outs.append(np.asarray(fn(variables, chunk))[:m])
        return np.concatenate(outs)

    runner = ModelRunner(module=module, variables=variables,
                         apply_kwargs={"features": True},
                         name="bench.resnet", batch_size=batch)

    def timed(fn, tag):
        fn(x0)                                   # compile warm, all buckets
        _log(f"[bench] runner {tag} warm done")
        rates = []
        for r in range(1, reps + 1):
            x = x0 + np.float32(0.001 * r)       # first-sight args per rep
            t0 = time.perf_counter()
            fn(x)
            rates.append(n / (time.perf_counter() - t0))
            _log(f"[bench] runner {tag} rep rows/s {rates[-1]:.0f}")
        rates.sort()
        return rates[len(rates) // 2]

    r_legacy = timed(legacy_apply, "legacy")
    r_runner = timed(runner.apply_batch, "runner")
    print(f"RUNNER_AB {r_legacy} {r_runner} "
          f"{r_runner / max(r_legacy, 1e-9)}", flush=True)

    # --- decode arm: KV-cached batched generation tokens/sec
    lm = TransformerEncoder(vocab_size=vocab, num_classes=vocab,
                            embed_dim=256, num_heads=4, num_layers=4,
                            mlp_dim=512, max_len=4096, causal=True,
                            pool="none", dtype=jnp.float32)
    lm_vars = lm.init(jax.random.PRNGKey(2),
                      jnp.zeros((1, prompt), jnp.int32))
    dec = ModelRunner(module=lm, variables=lm_vars, name="bench.lm",
                      batch_size=dec_batch)
    rng = np.random.default_rng(0)
    prompts0 = rng.integers(0, vocab, (dec_batch, prompt)).astype(np.int32)
    dec.decode(prompts0, max_new_tokens=new_tokens)    # compile warm
    _log("[bench] runner decode warm done")
    rates = []
    for r in range(1, reps + 1):
        prompts = (prompts0 + r) % vocab               # first-sight args
        t0 = time.perf_counter()
        res = dec.decode(prompts, max_new_tokens=new_tokens)
        tps = res.tokens.size / (time.perf_counter() - t0)
        rates.append(tps)
        _log(f"[bench] runner decode rep tokens/s {tps:.1f}")
    rates.sort()
    print(f"RUNNER_DECODE {rates[len(rates) // 2]} {dec_batch} {new_tokens}",
          flush=True)

    # --- paged-vs-dense decode A/B at a high-concurrency ragged shape
    # (ISSUE 12): the paged cache reads W*page_size gathered slots instead
    # of the dense pow2 reservation AND updates pages donated in place, so
    # on-chip it must clear 1.2x dense tokens/sec; on the CPU proxy the
    # number is parity/accounting cover only (the gather costs more than
    # it saves without HBM in the loop) and the gate is queued for the
    # relay round.  Ragged lengths make the occupancy number honest.
    conc = 8 if proxy else 32
    rngp = np.random.default_rng(7)
    rag = rngp.integers(0, vocab, (conc, prompt)).astype(np.int32)
    rag_lens = rngp.integers(max(2, prompt // 4), prompt + 1,
                             conc).astype(np.int32)
    rag_lens[0] = prompt                       # keep the prompt bucket full
    page_size = 16
    paged_kw = {"kv_layout": "paged", "page_size": page_size}
    state = {}

    def timed_paged_ab(kw, tag):
        dec.decode(rag, lengths=rag_lens, max_new_tokens=new_tokens, **kw)
        _log(f"[bench] runner decode {tag} warm done")
        rates = []
        for r in range(1, reps + 1):
            p = (rag + r) % vocab
            t0 = time.perf_counter()
            res = dec.decode(p, lengths=rag_lens, max_new_tokens=new_tokens,
                             **kw)
            rates.append(res.extras["real_tokens"]
                         / (time.perf_counter() - t0))
            _log(f"[bench] runner decode {tag} rep tokens/s {rates[-1]:.1f}")
        state[tag] = res.extras
        rates.sort()
        return rates[len(rates) // 2]

    d_tps = timed_paged_ab({}, "dense")
    p_tps = timed_paged_ab(paged_kw, "paged")
    occ = state["paged"]["page_occupancy_pct"]
    hbm = state["paged"]["cache_bytes_per_seq"]
    print(f"RUNNER_PAGED {d_tps} {p_tps} {p_tps / max(d_tps, 1e-9)} "
          f"{occ} {hbm} {int(bool(proxy))}", flush=True)

    # --- continuous-vs-ticked decode A/B under ragged Poisson arrivals
    # (ISSUE 13): the SAME request trace — Poisson arrivals (in step units,
    # idle gaps fast-forwarded for free on both sides), ragged prompts and
    # ragged token budgets — served two ways.  Ticked: the pre-13 serving
    # drain — when the in-flight batch finishes, take whatever has arrived
    # (up to `slots`) and decode it as one batch bound by its SLOWEST
    # member's budget; arrivals mid-batch wait for the next tick.
    # Continuous: ContinuousDecoder — arrivals join free slots between
    # steps, finished sequences leave and free their slot mid-flight.
    # Both sides do identical useful work (each request's budget tokens),
    # so the wall ratio is the batching win; acceptance on-chip >= 1.5x
    # (the CPU proxy records the ratio + a parity note).  The trace also
    # counter-checks the no-new-compile-keys rule: joins after warmup must
    # cause ZERO new step-executable compiles.
    from collections import deque as _deque
    from mmlspark_tpu.models import SlotsExhausted
    slots = 4 if proxy else 8
    n_req = 20 if proxy else 48
    page = 16
    rngc = np.random.default_rng(17)
    # WIDELY ragged budgets are the ticked drain's waste driver (every
    # member runs to the group max); 1.25x-capacity Poisson arrivals keep
    # both engines saturated, so wall ratio == dispatched-work ratio and
    # the free idle fast-forward below almost never triggers
    min_b = max(2, new_tokens // 8)
    reqs = []
    rate = 1.25 * slots / ((min_b + new_tokens) / 2.0)
    arrive = 0.0
    for _ in range(n_req):
        arrive += rngc.exponential(1.0 / rate)
        plen = int(rngc.integers(max(2, prompt // 4), prompt + 1))
        reqs.append((rngc.integers(0, vocab, plen).astype(np.int32),
                     plen, int(rngc.integers(min_b, new_tokens + 1)),
                     int(arrive)))
    useful = sum(r[2] for r in reqs)

    disp = {"ticked": (0, 0), "cont": (0, 0)}   # (prefills, steps)

    def ticked_engine():
        t0 = time.perf_counter()
        clock_steps, i = 0, 0
        n_pre = n_steps = 0
        while i < len(reqs):
            if reqs[i][3] > clock_steps:
                clock_steps = reqs[i][3]          # idle: jump to arrival
            group = []
            while i < len(reqs) and reqs[i][3] <= clock_steps \
                    and len(group) < slots:
                group.append(reqs[i])
                i += 1
            gmax = max(r[2] for r in group)
            stacked = np.zeros((len(group), prompt), np.int32)
            lens = np.asarray([r[1] for r in group], np.int32)
            for j, r in enumerate(group):
                stacked[j, :r[1]] = r[0]
            res = dec.decode(stacked, lengths=lens, max_new_tokens=gmax,
                             kv_layout="paged", page_size=page,
                             batch_bucket=slots, prompt_bucket=prompt)
            n_pre += 1
            n_steps += res.steps
            clock_steps += gmax                   # batch held the engine
        disp["ticked"] = (n_pre, n_steps)
        return useful / (time.perf_counter() - t0)

    def continuous_engine():
        decoder = dec.decode_stream(slots=slots, prompt_bucket=prompt,
                                    max_new_tokens=new_tokens,
                                    page_size=page)
        b0 = dec._c_batches["decode"].value   # join-prefill dispatch base
        pend = _deque(reqs)
        handles = []
        t0 = time.perf_counter()
        virtual = 0
        while pend or decoder._live or decoder._arrivals:
            now_step = decoder.steps + virtual
            while pend and pend[0][3] <= now_step:
                try:
                    handles.append(decoder.submit(
                        pend[0][0], max_new_tokens=pend[0][2]))
                except SlotsExhausted:
                    break                          # backpressure: next leave
                pend.popleft()
            if decoder._live or decoder._arrivals:
                decoder.step()
            elif pend:
                virtual = pend[0][3] - decoder.steps  # idle fast-forward
        wall = time.perf_counter() - t0
        disp["cont"] = (int(dec._c_batches["decode"].value - b0),
                        decoder.steps)
        decoder.close()
        return useful / wall, handles

    # warmup: the stream executables + ONE ticked decode per distinct
    # table width any group's gmax in [min_b, new_tokens] can produce (a
    # width compiled mid-run would tax the ticked wall unfairly)
    dec.decode_stream(slots=slots, prompt_bucket=prompt,
                      max_new_tokens=new_tokens, page_size=page).warmup()
    widths = {}
    for m in range(min_b, new_tokens + 1):
        widths.setdefault(-(-(prompt + m) // page), m)
    for warm_nt in widths.values():
        wp = rngc.integers(0, vocab, (slots, prompt)).astype(np.int32)
        dec.decode(wp, max_new_tokens=warm_nt, kv_layout="paged",
                   page_size=page, batch_bucket=slots, prompt_bucket=prompt)
    _log("[bench] runner cont warm done")

    def step_compiles():
        return sum(getattr(w, "compiles", 0) for w in dec._wrappers
                   if "decode_step" in getattr(w, "name", ""))

    # attribution bracket (ISSUE 17): snapshot the useful-vs-wasted token
    # ledger and device-seconds counters around the measured A/B so the
    # round artifact carries goodput%% and device-cost-per-1k-tokens for
    # exactly the work the RUNNER_CONT numbers describe
    from mmlspark_tpu.observability.attribution import OUTCOMES
    att0 = {o: dec._c_tok_outcome.value(outcome=o) for o in OUTCOMES}
    dev_s0 = dec._c_device_s.value()
    gen0 = dec._c_decode_tokens.value

    # median of `reps` passes per engine (same protocol as the other
    # arms: single ~1s walls on this shared box swing 3x with neighbor
    # load, and the RATIO is the acceptance number)
    t_rates = []
    for _ in range(reps):
        t_rates.append(ticked_engine())
        _log(f"[bench] runner ticked tokens/s {t_rates[-1]:.1f}")
    t_rates.sort()
    t_tps = t_rates[len(t_rates) // 2]
    # the join-compile gate brackets the CONTINUOUS traces only: a ticked
    # compile (warmup gap) must never be misattributed to joins
    n_step0 = step_compiles()
    c_rates = []
    for _ in range(reps):
        c_tps, handles = continuous_engine()
        c_rates.append(c_tps)
        _log(f"[bench] runner continuous tokens/s {c_rates[-1]:.1f}")
    c_rates.sort()
    c_tps = c_rates[len(c_rates) // 2]
    # goodput + device cost over the bracket: useful share of every token
    # cell the ledger classified, and device-seconds per 1k real generated
    # tokens (the /fleet/capacity per-class number's bench ground truth)
    att = {o: dec._c_tok_outcome.value(outcome=o) - att0[o] for o in OUTCOMES}
    g_useful = att["useful"]
    g_wasted = sum(v for o, v in att.items() if o != "useful")
    goodput_pct = 100.0 * g_useful / max(g_useful + g_wasted, 1e-9)
    dev_s = dec._c_device_s.value() - dev_s0
    gen_tokens = dec._c_decode_tokens.value - gen0
    dev_per_1k = 1000.0 * dev_s / max(gen_tokens, 1e-9)
    _log(f"[bench] runner goodput ledger: useful {g_useful:.0f} wasted "
         f"{g_wasted:.0f} by-outcome "
         f"{ {o: round(v) for o, v in att.items() if v} } "
         f"device_s {dev_s:.3f} over {gen_tokens:.0f} tokens")
    print(f"RUNNER_GOODPUT {goodput_pct} {dev_per_1k} {int(bool(proxy))}",
          flush=True)
    # device work per useful token is the machine-independent half of the
    # story: the ticked drain burns slowest-member padding steps (every
    # step at full batch width) and full-width prefills, while the
    # continuous engine steps only live work and prefills each arrival
    # alone.  Token-forward units: prefill = rows*prompt, step = batch
    # width.  On the CPU proxy at this tiny shape, per-dispatch host
    # overhead flattens the wall ratio toward 1 — the 1.5x gate is an
    # on-chip number, where this compute ratio dominates the wall.
    t_tf = disp["ticked"][0] * slots * prompt + disp["ticked"][1] * slots
    c_tf = disp["cont"][0] * prompt + disp["cont"][1] * slots
    _log(f"[bench] runner cont device work (token-forwards): "
         f"ticked {t_tf} vs continuous {c_tf} "
         f"({t_tf / max(c_tf, 1):.2f}x saved)")
    # read the counter BEFORE the parity references below: their one-shot
    # bb=1 signatures legitimately compile and must not be charged to joins
    new_steps = step_compiles() - n_step0
    parity = 1
    for (p, _plen, budget, _a), h in list(zip(reqs, handles))[:3]:
        ref = dec.decode(p[None], max_new_tokens=budget,
                         kv_layout="paged", page_size=page)
        if list(ref.tokens[0]) != h.tokens:
            parity = 0
    print(f"RUNNER_CONT {t_tps} {c_tps} {c_tps / max(t_tps, 1e-9)} "
          f"{parity} {new_steps} {int(bool(proxy))}", flush=True)

    # --- prefix-cache cached-vs-cold TTFT A/B under template-sharing
    # arrivals (ISSUE 20): the SAME Poisson trace of template+suffix
    # prompts replayed twice through the ContinuousDecoder — cold
    # (prefix_cache=False, every join prefills the full prompt) and cached
    # (admission consults the PrefixIndex, joins prefill only the uncached
    # suffix).  Useful work is identical, so the TTFT-p99 ratio is the
    # skipped-prefill win; acceptance on-chip >= 1.3x (the CPU proxy
    # records parity + hit rate — host-side index bookkeeping there costs
    # comparable time to the tiny prefill it skips).  The replay also
    # counter-checks the zero-new-compile-keys rule across EVERY hit
    # length the trace produces.
    page_p = 4
    slots_p = 4 if proxy else 8
    n_preq = 16 if proxy else 40
    tpl_len = max(page_p * 3, prompt - 4)     # 3 shared pages per template
    suf_len = max(2, prompt - tpl_len)
    pref_budget = max(4, new_tokens // 2)
    rngx = np.random.default_rng(23)
    templates = [rngx.integers(0, vocab, tpl_len).astype(np.int32)
                 for _ in range(3)]
    preqs = []
    arrive_p = 0.0
    rate_p = 1.25 * slots_p / max(pref_budget, 1)
    for i in range(n_preq):
        arrive_p += rngx.exponential(1.0 / rate_p)
        p = np.concatenate([templates[i % len(templates)],
                            rngx.integers(0, vocab, suf_len).astype(np.int32)])
        preqs.append((p.astype(np.int32), pref_budget, int(arrive_p)))

    def prefix_engine(enabled: bool):
        decoder = dec.decode_stream(slots=slots_p, prompt_bucket=prompt,
                                    max_new_tokens=pref_budget,
                                    page_size=page_p, prefix_cache=enabled)
        pend = _deque(preqs)
        handles = []
        virtual = 0
        while pend or decoder._live or decoder._arrivals:
            now_step = decoder.steps + virtual
            while pend and pend[0][2] <= now_step:
                try:
                    handles.append(decoder.submit(
                        pend[0][0], max_new_tokens=pend[0][1]))
                except SlotsExhausted:
                    break
                pend.popleft()
            if decoder._live or decoder._arrivals:
                decoder.step()
            elif pend:
                virtual = pend[0][2] - decoder.steps
        ttfts = sorted(1000.0 * h.ttft_s for h in handles
                       if h.ttft_s is not None)
        stats = decoder.index.stats() if enabled else None
        decoder.close()
        return ttfts, stats, handles

    # warm EVERY signature the replay can touch (join prefill, sampler,
    # fused step, CoW page copy) so the compile bracket below measures
    # the hit path, not first-build compiles
    dec.decode_stream(slots=slots_p, prompt_bucket=prompt,
                      max_new_tokens=pref_budget, page_size=page_p,
                      prefix_cache=True).warmup()
    _log("[bench] runner prefix warm done")

    def all_compiles():
        return sum(getattr(w, "compiles", 0) for w in dec._wrappers)

    n_c0 = all_compiles()
    cold_ttfts: list = []
    for _ in range(reps):
        t, _s, _h = prefix_engine(False)
        cold_ttfts.extend(t)
    cached_ttfts: list = []
    pstats, phandles = None, []
    for _ in range(reps):
        t, pstats, phandles = prefix_engine(True)
        cached_ttfts.extend(t)
    new_px = all_compiles() - n_c0      # read BEFORE the parity one-shots
    cold_ttfts.sort()
    cached_ttfts.sort()
    cold_p99 = cold_ttfts[int(len(cold_ttfts) * 0.99)] if cold_ttfts else 0.0
    cach_p99 = cached_ttfts[int(len(cached_ttfts) * 0.99)] \
        if cached_ttfts else 0.0
    hit_rate = (pstats or {}).get("hit_rate_pct", 0.0)
    # retained pages pin the shared auto pool full — release them so the
    # cold parity one-shots (prefix_cache=False, so no reclaim path) can
    # allocate from the same pool
    idx_p = dec.prefix_cache(page_p)
    idx_p.evict_pages(idx_p.retained_pages(), reason="pressure")
    parity_p = 1
    for (p, budget, _a), h in list(zip(preqs, phandles))[:3]:
        ref = dec.decode(p[None], max_new_tokens=budget,
                         kv_layout="paged", page_size=page_p)
        if list(ref.tokens[0]) != h.tokens:
            parity_p = 0
    _log(f"[bench] runner prefix ttft p99 cold {cold_p99:.2f}ms cached "
         f"{cach_p99:.2f}ms hit_rate {hit_rate:.1f}% compiles {new_px}")
    print(f"RUNNER_PREFIX {cold_p99} {cach_p99} "
          f"{cold_p99 / max(cach_p99, 1e-9)} {hit_rate} {parity_p} "
          f"{new_px} {int(bool(proxy))}", flush=True)


def phase_ooc(n=200_000, f=50, iters=8, tiles=4, reps=3) -> None:
    """Out-of-core streamed-vs-in-memory A/B at a fits-in-memory shape —
    the OVERHEAD bound for the chunked pipeline (ISSUE 7 acceptance:
    streamed >= 0.9x in-memory when tiling buys nothing, with the
    prefetch-overlap %% reported so a miss is attributable to transfer
    stalls vs per-pass overhead).  Same trainer config both sides; the
    streamed run forces ``tiles`` tiles through ``tile_rows``.  Labels
    perturb per rep (relay result-cache busting, as phase_gbdt)."""
    from __graft_entry__ import enable_compilation_cache
    enable_compilation_cache()
    import numpy as np
    from mmlspark_tpu.lightgbm import GBDTParams, train, train_streamed

    rng = np.random.default_rng(0)
    X = rng.normal(size=(n, f)).astype(np.float32)
    y0 = (X[:, 0] + 0.5 * X[:, 1] + rng.normal(scale=0.3, size=n) > 0) \
        .astype(np.float32)
    nonce = [0]

    def fresh_y():
        nonce[0] += 1
        y = y0.copy()
        a = (37 * nonce[0]) % (n - 64)
        y[a:a + 64] = 1.0 - y[a:a + 64]
        return y

    pkw = dict(num_iterations=iters, objective="binary", max_depth=5)
    tile_rows = -(-n // max(1, tiles))
    t0 = time.perf_counter()
    train(X, fresh_y(), GBDTParams(**pkw))
    train_streamed(X, fresh_y(), GBDTParams(**pkw), tile_rows=tile_rows)
    _log(f"[bench] ooc warm(compile) {time.perf_counter() - t0:.0f}s")
    r_mem, r_str, overlaps = [], [], []
    for _ in range(reps):
        t0 = time.perf_counter()
        train(X, fresh_y(), GBDTParams(**pkw))
        r_mem.append(n * iters / max(time.perf_counter() - t0, 1e-9))
        t0 = time.perf_counter()
        res = train_streamed(X, fresh_y(), GBDTParams(**pkw),
                             tile_rows=tile_rows)
        r_str.append(n * iters / max(time.perf_counter() - t0, 1e-9))
        overlaps.append(res.extras["prefetch_overlap_pct"])
        _log(f"[bench] ooc rep inmem {r_mem[-1]:.0f} streamed {r_str[-1]:.0f}"
             f" overlap {overlaps[-1]:.1f}%")
    r_mem.sort(), r_str.sort(), overlaps.sort()
    mid = len(r_mem) // 2
    print(f"OOC_AB {r_mem[mid]} {r_str[mid]} "
          f"{r_str[mid] / max(r_mem[mid], 1e-9)} {overlaps[mid]} {tiles}",
          flush=True)

    # checkpoint-overhead arm (ISSUE 10 acceptance: <= 5% at this shape):
    # the SAME streamed config with periodic atomic checkpoints on, so the
    # cost of durability is a tracked number instead of a vibe.  Snapshot
    # serialization rides a background writer; what this measures is the
    # residual drag (snapshot list copies + the terminal blocking save).
    import shutil
    import tempfile
    ck_every = max(1, iters // 4)
    r_ck = []
    for _ in range(reps):
        ckd = tempfile.mkdtemp(prefix="ooc_ckpt_")
        try:
            t0 = time.perf_counter()
            train_streamed(X, fresh_y(), GBDTParams(**pkw),
                           tile_rows=tile_rows, checkpoint_dir=ckd,
                           checkpoint_every=ck_every, resume="never")
            r_ck.append(n * iters / max(time.perf_counter() - t0, 1e-9))
        finally:
            shutil.rmtree(ckd, ignore_errors=True)
        _log(f"[bench] ooc ckpt rep {r_ck[-1]:.0f}")
    r_ck.sort()
    overhead_pct = 100.0 * (1.0 - r_ck[mid] / max(r_str[mid], 1e-9))
    print(f"OOC_CKPT {r_ck[mid]} {overhead_pct} {ck_every}", flush=True)


def phase_resnet(batch=256, steps=8, hw=224, reps=3) -> None:
    """ResNet-50 featurize throughput (reference CNTKModel's flagship
    inference path).  Round-3/4 measured 2544 img/s at batch 32 with one
    relay dispatch per step — the ~10-100 ms per-dispatch relay latency
    dominated the compute, capping MFU at ~5% by this file's 4.09
    GFLOP/img convention (VERDICT r4 #5 quotes ~10% via 2x FLOP counting).
    Fixes here: batch 256 (MXU-filling), and the step loop moved INSIDE the
    jitted program (lax.scan over per-step input perturbations — ONE relay
    dispatch per timed rep, steps*batch images).  Each scan step perturbs
    the batch and every rep shifts the offset: first-sight args per
    dispatch, so the relay result-cache cannot serve repeats.  Prints
    images/sec and model FLOPs utilization (4.09 GFLOP/img fwd at 224^2,
    ~197 bf16 TFLOP/s peak per v5e chip)."""
    from __graft_entry__ import enable_compilation_cache
    enable_compilation_cache()
    import jax
    import jax.numpy as jnp
    from mmlspark_tpu.models import resnet50
    from mmlspark_tpu.ops import image as image_ops

    module = resnet50(num_classes=1000, dtype=jnp.bfloat16)
    variables = module.init(jax.random.PRNGKey(0),
                            jnp.zeros((1, 64, 64, 3), jnp.float32))
    x = jax.random.uniform(jax.random.PRNGKey(1), (batch, hw, hw, 3),
                           jnp.float32, 0, 255)

    @jax.jit
    def featurize_many(variables, x, step_offsets):
        def body(acc, s):
            f = module.apply(variables, image_ops.normalize(x + s),
                             features=True)
            return acc + f.astype(jnp.float32).mean(), None
        acc, _ = jax.lax.scan(body, jnp.float32(0.0), step_offsets)
        return acc

    offs = jnp.arange(steps, dtype=jnp.float32)
    t0 = time.perf_counter()
    float(featurize_many(variables, x, offs - 7.0))  # warm, forced fetch
    _log(f"[bench] resnet warm(compile) {time.perf_counter() - t0:.0f}s")
    rates = []
    for r in range(1, reps + 1):
        t0 = time.perf_counter()
        float(featurize_many(variables, x, offs + 0.1 * r))
        rates.append(batch * steps / (time.perf_counter() - t0))
        _log(f"[bench] resnet rep img/s {rates[-1]:.0f}")
    rates.sort()
    ips = rates[len(rates) // 2]
    mfu_pct = 100.0 * ips * 4.09e9 / 197e12
    print(f"IMAGES_SEC {ips} {mfu_pct}", flush=True)


def phase_ranker(n=200_000, f=50, group=100, iters_a=2, iters_b=8,
                 reps=3) -> None:
    """LambdaRank marginal rows/sec, median of ``reps`` — the lambda pass is
    device-resident (make_lambdarank_grad_fn), so this measures the fused
    iteration rate.  Labels perturb per call (relay result-cache busting,
    same as phase_gbdt)."""
    from __graft_entry__ import enable_compilation_cache
    enable_compilation_cache()
    import numpy as np
    from mmlspark_tpu.lightgbm import GBDTParams, train
    rng = np.random.default_rng(0)
    X = rng.normal(size=(n, f)).astype(np.float32)
    rel0 = (X[:, 0] + 0.3 * rng.normal(size=n) > 0.5).astype(np.float32) \
        + (X[:, 1] > 1.0)
    gp = np.arange(0, n + 1, group)
    p = dict(objective="lambdarank", max_depth=5)
    nonce = [0]

    def fresh_rel():
        nonce[0] += 1
        rel = rel0.copy()
        a = (53 * nonce[0]) % (n - 32)
        rel[a:a + 32] = 2.0 - rel[a:a + 32]
        return rel

    bc = {}
    train(X, fresh_rel(), GBDTParams(num_iterations=iters_a, **p),
          group_ptr=gp, bin_cache=bc)
    rates = []
    for _ in range(reps):
        t0 = time.perf_counter()
        train(X, fresh_rel(), GBDTParams(num_iterations=iters_a, **p),
              group_ptr=gp, bin_cache=bc)
        t_a = time.perf_counter() - t0
        t0 = time.perf_counter()
        train(X, fresh_rel(), GBDTParams(num_iterations=iters_b, **p),
              group_ptr=gp, bin_cache=bc)
        t_b = time.perf_counter() - t0
        rates.append(n * (iters_b - iters_a) / max(t_b - t_a, 1e-9))
    rates.sort()
    print(f"RANKER_RPS {rates[len(rates) // 2]}", flush=True)


def phase_serving(n_requests=1000) -> None:
    """Serving p50 latency over real HTTP: a fitted GBDT pipeline behind the
    continuous-mode server, single-row requests scored via the host-side
    booster walk over ONE persistent HTTP/1.1 connection (the client pattern
    the reference's continuous-mode claim assumes).  Pure host — no device
    involvement (reference claim: ~1 ms, docs/mmlspark-serving.md:10-11)."""
    import http.client
    import json as _json
    import numpy as np
    from mmlspark_tpu.core import DataFrame, Transformer
    from mmlspark_tpu.core.schema import vector_column
    from mmlspark_tpu.lightgbm import LightGBMClassifier
    from mmlspark_tpu.serving import PipelineServer

    rng = np.random.default_rng(0)
    X = rng.normal(size=(2000, 20))
    y = (X[:, 0] > 0).astype(float)
    df = DataFrame.from_dict({"features": vector_column(list(X)), "label": y})
    model = LightGBMClassifier().set_params(num_iterations=30,
                                            min_data_in_leaf=5).fit(df)

    class Scorer(Transformer):
        def _transform(self, frame):
            def per_part(p):
                feats = vector_column([np.asarray(v, np.float32)
                                       for v in p["request"]])
                out = model.transform(DataFrame.from_dict({"features": feats}))
                return {**p, "reply": out.collect()["prediction"]}
            return frame.map_partitions(per_part)

        def transform_schema(self, schema):
            return schema

    srv = PipelineServer(Scorer(), port=0, mode="continuous").start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=10)
        body = _json.dumps(list(np.asarray(X[0], float)))
        hdrs = {"Content-Type": "application/json"}
        for _ in range(50):  # warm
            conn.request("POST", srv.api_path, body, hdrs)
            conn.getresponse().read()
        lats = []
        for _ in range(n_requests):
            t0 = time.perf_counter()
            conn.request("POST", srv.api_path, body, hdrs)
            conn.getresponse().read()
            lats.append(time.perf_counter() - t0)
        lats.sort()
        print(f"SERVING_P50_MS {1000 * lats[len(lats) // 2]} "
              f"{1000 * lats[int(len(lats) * 0.95)]}", flush=True)

        # sustained concurrent load: 8 persistent connections back-to-back
        # (the reference's serving claims are about sustained throughput,
        # docs/mmlspark-serving.md:10-11); shared driver with the CI gate
        from mmlspark_tpu.serving import sustained_load
        res = sustained_load("127.0.0.1", srv.port, srv.api_path, body, hdrs)
        print(f"SERVING_LOAD {res['rps']} {res['p99_ms']}", flush=True)
    finally:
        srv.stop()

    # profiler overhead A/B on the ECHO microbench (ISSUE 15): a scorer
    # with no model cost, so the host-stack sampler's overhead has nowhere
    # to hide — the worst case for the <= 3% gate.  Measurement design
    # (validated against a null A/B on this class of host): per-batch
    # MEDIAN latency (throughput over a batch is swamped by contention
    # outliers), batches COUNTERBALANCED base/prof then prof/base (a null
    # pair showed ~5% monotone within-pair drift that a fixed order books
    # as phantom overhead), overhead from the pooled per-arm medians (a
    # per-pair ratio median stays drift-skewed at this pair count).
    class EchoScorer(Transformer):
        def _transform(self, frame):
            def per_part(p):
                return {**p, "reply": np.asarray(
                    [float(np.sum(v)) for v in p["request"]])}
            return frame.map_partitions(per_part)

        def transform_schema(self, schema):
            return schema

    from mmlspark_tpu.observability.profiling import SamplingProfiler
    esrv = PipelineServer(EchoScorer(), port=0, mode="continuous").start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", esrv.port, timeout=10)
        ebody = _json.dumps([1.0, 2.0, 3.0])

        def med_batch(n=60):
            lats = []
            for _ in range(n):
                t0 = time.perf_counter()
                conn.request("POST", esrv.api_path, ebody, hdrs)
                conn.getresponse().read()
                lats.append(time.perf_counter() - t0)
            lats.sort()
            return lats[n // 2]

        def prof_batch():
            sampler = SamplingProfiler()       # default hz — the gate's arm
            sampler.start()
            try:
                return med_batch()
            finally:
                sampler.stop()

        med_batch(100)                         # warm
        bases, profs = [], []
        for i in range(8):
            if i % 2 == 0:
                bases.append(med_batch())
                profs.append(prof_batch())
            else:
                profs.append(prof_batch())
                bases.append(med_batch())
        base_p50_ms = 1000.0 * sorted(bases)[len(bases) // 2]
        prof_p50_ms = 1000.0 * sorted(profs)[len(profs) // 2]
        overhead = 100.0 * (prof_p50_ms / base_p50_ms - 1.0)
        print(f"SERVING_PROFILER {base_p50_ms} {prof_p50_ms} {overhead}",
              flush=True)
    finally:
        esrv.stop()


def phase_cpu(n=200_000, f=200, reps=3) -> None:
    """CPU-executor baseline: identical trainer on the host CPU — run
    STRICTLY ALONE (VERDICT r4 weak #1: on a 1-core host any concurrent
    phase halves the denominator), median of ``reps`` marginal rates, with
    the host fingerprint printed next to the number so the artifact records
    what machine produced the denominator."""
    import json as _json
    import numpy as np
    from mmlspark_tpu.lightgbm import GBDTParams, train

    fp = {"nproc": os.cpu_count()}
    try:
        with open("/proc/cpuinfo") as fcpu:
            for line in fcpu:
                if line.startswith("model name"):
                    fp["cpu_model"] = line.split(":", 1)[1].strip()
                    break
        fp["loadavg_1m"] = round(os.getloadavg()[0], 2)
    except OSError:
        pass
    print(f"CPU_HOST {_json.dumps(fp)}", flush=True)

    rng = np.random.default_rng(0)
    X = rng.normal(size=(n, f)).astype(np.float32)
    y0 = (X[:, 0] + 0.5 * X[:, 1] + rng.normal(scale=0.3, size=n) > 0).astype(np.float32)
    nonce = [0]

    def fresh_y():  # same busting discipline as the TPU phases
        nonce[0] += 1
        y = y0.copy()
        a = (37 * nonce[0]) % (n - 64)
        y[a:a + 64] = 1.0 - y[a:a + 64]
        return y

    bc = {}   # identical binning memo as the TPU phase (symmetric marginal)
    train(X, fresh_y(), GBDTParams(num_iterations=1, objective="binary", max_depth=5),
          bin_cache=bc)
    rates = []
    for _ in range(reps):
        t0 = time.perf_counter()
        train(X, fresh_y(), GBDTParams(num_iterations=2, objective="binary", max_depth=5),
              bin_cache=bc)
        ta = time.perf_counter() - t0
        t0 = time.perf_counter()
        train(X, fresh_y(), GBDTParams(num_iterations=7, objective="binary", max_depth=5),
              bin_cache=bc)
        tb = time.perf_counter() - t0
        rates.append(n * 5 / max(tb - ta, 1e-9))
        _log(f"[bench] cpu rep rate {rates[-1]:.0f}")
    rates.sort()
    print(f"CPU_RPS {rates[len(rates) // 2]}", flush=True)


# --------------------------------------------------------------------------
# parent orchestration
# --------------------------------------------------------------------------

def _tpu_env() -> dict:
    return dict(os.environ)


def _cpu_env() -> dict:
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("TPU", "AXON", "PALLAS_AXON"))}
    env.pop("PYTHONPATH", None)  # drop sitecustomize TPU hooks
    env["JAX_PLATFORMS"] = "cpu"
    return env


def _spawn(phase: str, env: dict, extra_args=()) -> subprocess.Popen:
    # stderr merges into the captured stdout so the parent's streaming
    # reader can treat ANY child output (rep logs, jax warnings) as a sign
    # of life; every line is echoed to the parent's stderr for live logs
    return subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--phase", phase,
         *extra_args],
        cwd=_REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT)   # binary pipe: parent reads raw fd


def _collect_multi(proc: subprocess.Popen, markers, idle: float,
                   hard: float = 1500.0) -> dict:
    """Stream the child's merged output; return {marker: floats-or-raw}.

    Round-4 post-mortem: whole-phase kill deadlines landed MID-COMPILE and
    wedged the device relay for hours (RANKER killed at 300s -> every later
    TPU client blocked).  The parent therefore kills only on SILENCE: the
    ``idle`` window (sized to cover the longest observed compile) resets on
    every output line, so a child that is computing, compiling noisily, or
    printing reps is never killed; a child that produces nothing for
    ``idle`` seconds is either host-hung or behind a relay that is already
    wedged — killing it then cannot make the relay worse.  ``hard`` is the
    absolute backstop."""
    import selectors
    got = {}

    def parse(line):
        for m in markers:
            if line.startswith(m):
                rest = line[len(m):].strip()
                try:
                    got[m] = [float(v) for v in rest.split()]
                except ValueError:   # non-numeric payload (e.g. JSON)
                    got[m] = rest

    # raw-fd reads with manual line splitting: readline() on a buffered
    # wrapper can block on a partial line (disabling the deadline checks)
    # and slurps lines select() then never reports again
    fd = proc.stdout.fileno()
    os.set_blocking(fd, False)
    sel = selectors.DefaultSelector()
    sel.register(proc.stdout, selectors.EVENT_READ)
    buf = b""
    t_start = last = time.perf_counter()
    while True:
        now = time.perf_counter()
        if now - last > idle or now - t_start > hard:
            proc.kill()
            _log(f"[bench] phase {markers[0]} killed: "
                 f"{'silent ' + str(round(now - last)) + 's' if now - last > idle else 'hard cap'}")
            break
        if not sel.select(timeout=5.0):
            if proc.poll() is not None:
                break
            continue
        try:
            chunk = os.read(fd, 65536)
        except BlockingIOError:
            continue
        if chunk == b"":                     # EOF: child exited
            break
        last = time.perf_counter()
        sys.stderr.write(chunk.decode("utf-8", "replace"))
        sys.stderr.flush()
        buf += chunk
        *lines, buf = buf.split(b"\n")
        for raw in lines:
            parse(raw.decode("utf-8", "replace"))
    try:
        rem = proc.communicate(timeout=10)[0]
        for line in (buf + (rem or b"")).decode("utf-8", "replace").splitlines():
            parse(line)
    except Exception:  # noqa: BLE001
        pass
    return got


def _collect(proc: subprocess.Popen, marker: str, idle: float,
             hard: float = 1500.0, phase: str = ""):
    # the PHASE_METRICS marker rides every phase child (ISSUE 11); folding
    # happens here so single-marker call sites get it for free
    got = _collect_multi(proc, (marker, "PHASE_METRICS"), idle, hard)
    if phase:
        _record_phase_metrics(phase, got)
    val = got.get(marker)
    if val is None:
        _log(f"[bench] phase {marker} ended rc={proc.returncode} without result")
    return val


def _note(phase: str, msg: str) -> None:
    RESULT["extras"].setdefault("phase_notes", {})[phase] = msg


def _record_hist_ab(got: dict) -> bool:
    """Fold a hist_ab child's markers into extras; False when absent."""
    vals = got.get("HIST_AB_RATES")
    if isinstance(vals, str):
        return False
    if not vals or len(vals) < 3:
        return False
    ex = RESULT["extras"]
    ex["hist_ab_f32_rows_per_sec"] = round(vals[0], 1)
    ex["hist_ab_packed_rows_per_sec"] = round(vals[1], 1)
    ex["hist_ab_packed_speedup"] = round(vals[2], 3)
    fused = got.get("HIST_AB_FUSED")
    if fused and len(fused) >= 3:
        # fused Pallas frontier vs the separate packed pipeline (ISSUE 8):
        # same frontier work, one kernel vs four XLA dispatches
        ex["hist_ab_separate_rows_per_sec"] = round(fused[0], 1)
        ex["hist_ab_fused_rows_per_sec"] = round(fused[1], 1)
        ex["hist_ab_fused_speedup"] = round(fused[2], 3)
    mode = got.get("HIST_AB_MODE")
    if isinstance(mode, str) and mode.split():
        parts = mode.split()
        ex["hist_ab_mode"] = parts[0]
        if len(parts) >= 3:
            ex["hist_ab_shape"] = f"{parts[1]}x{parts[2]}"
    return True


def _record_ooc(got: dict) -> bool:
    """Fold an ooc child's OOC_AB marker into extras; False when absent."""
    vals = got.get("OOC_AB")
    if isinstance(vals, str) or not vals or len(vals) < 4:
        return False
    ex = RESULT["extras"]
    ex["ooc_inmemory_rows_per_sec"] = round(vals[0], 1)
    ex["ooc_streamed_rows_per_sec"] = round(vals[1], 1)
    ex["ooc_streamed_vs_inmemory"] = round(vals[2], 3)
    ex["ooc_prefetch_overlap_pct"] = round(vals[3], 2)
    if len(vals) >= 5:
        ex["ooc_tiles"] = int(vals[4])
    ck = got.get("OOC_CKPT")
    if not isinstance(ck, str) and ck and len(ck) >= 2:
        # durability-cost arm: streamed-with-checkpoints vs streamed
        ex["ooc_ckpt_streamed_rows_per_sec"] = round(ck[0], 1)
        ex["ckpt_overhead_pct"] = round(ck[1], 2)
        if len(ck) >= 3:
            ex["ooc_ckpt_every"] = int(ck[2])
    else:
        # the A/B landed but the checkpoint arm was cut (killed/timed out):
        # the missing acceptance number must be attributable, not silent
        _note("ooc", "checkpoint arm produced no OOC_CKPT marker; "
                     "ckpt_overhead_pct missing this round")
    return True


def _record_runner(got: dict) -> bool:
    """Fold a runner child's markers into extras; False when absent."""
    ok = False
    ex = RESULT["extras"]
    vals = got.get("RUNNER_AB")
    if vals and not isinstance(vals, str) and len(vals) >= 3:
        ex["runner_ab_legacy_rows_per_sec"] = round(vals[0], 1)
        ex["runner_ab_runner_rows_per_sec"] = round(vals[1], 1)
        ex["runner_vs_legacy"] = round(vals[2], 3)
        if vals[2] < 0.9:
            _note("runner", f"runner/legacy {vals[2]:.3f} below the 0.9x "
                            "overhead gate")
        ok = True
    dec = got.get("RUNNER_DECODE")
    if dec and not isinstance(dec, str) and len(dec) >= 1:
        ex["runner_decode_tokens_per_sec"] = round(dec[0], 1)
        if len(dec) >= 3:
            ex["runner_decode_shape"] = f"b{int(dec[1])}xt{int(dec[2])}"
        ok = True
    pg = got.get("RUNNER_PAGED")
    if pg and not isinstance(pg, str) and len(pg) >= 3:
        # paged-vs-dense decode A/B (ISSUE 12): on-chip gate paged >= 1.2x
        # dense tokens/sec; the CPU proxy (flag in field 6) carries
        # parity/accounting cover only, with the gate queued for the relay
        # round alongside runner_decode_tokens_per_sec
        ex["decode_dense_tokens_per_sec"] = round(pg[0], 1)
        ex["decode_paged_tokens_per_sec"] = round(pg[1], 1)
        ex["decode_paged_vs_dense"] = round(pg[2], 3)
        if len(pg) >= 5:
            ex["decode_page_occupancy_pct"] = round(pg[3], 2)
            ex["decode_hbm_bytes_per_seq"] = round(pg[4], 1)
        if len(pg) >= 6 and pg[5] >= 1:
            _note("runner", "paged-vs-dense measured on the CPU proxy "
                            "(parity + pool accounting cover; no HBM in "
                            "the loop) — the 1.2x on-chip gate rides the "
                            "queued relay round")
        elif pg[2] < 1.2:
            _note("runner", f"paged/dense {pg[2]:.3f} below the 1.2x "
                            "on-chip gate")
        ok = True
    ct = got.get("RUNNER_CONT")
    if ct and not isinstance(ct, str) and len(ct) >= 3:
        # continuous-vs-ticked decode A/B (ISSUE 13): on-chip gate
        # continuous >= 1.5x ticked tokens/sec under ragged Poisson
        # arrivals; joins must cause zero step-executable compiles either
        # way, and the CPU proxy records ratio + parity instead of gating
        ex["decode_ticked_tokens_per_sec"] = round(ct[0], 1)
        ex["decode_cont_tokens_per_sec"] = round(ct[1], 1)
        ex["decode_cont_vs_ticked"] = round(ct[2], 3)
        proxy_run = len(ct) >= 6 and ct[5] >= 1
        if len(ct) >= 4:
            ex["decode_cont_parity"] = "ok" if ct[3] >= 1 else "MISMATCH"
            if ct[3] < 1:
                _note("runner", "continuous decode tokens DIVERGED from "
                                "one-shot decode() — parity gate failed")
        if len(ct) >= 5:
            ex["decode_cont_join_step_compiles"] = int(ct[4])
            if ct[4] > 0:
                _note("runner", f"{int(ct[4])} step-executable compile(s) "
                                "during the continuous trace — joins must "
                                "not mint compile keys")
        if proxy_run:
            _note("runner", "continuous-vs-ticked measured on the CPU "
                            "proxy (ratio + parity cover) — the 1.5x "
                            "on-chip gate rides the queued relay round")
        elif ct[2] < 1.5:
            _note("runner", f"continuous/ticked {ct[2]:.3f} below the "
                            "1.5x on-chip gate")
        ok = True
    px = got.get("RUNNER_PREFIX")
    if px and not isinstance(px, str) and len(px) >= 4:
        # prefix-cache cached-vs-cold TTFT A/B (ISSUE 20): on-chip gate
        # cached TTFT p99 >= 1.3x better than cold under template-sharing
        # arrivals; the CPU proxy records parity + hit rate instead of
        # gating (host-side index bookkeeping there rivals the tiny
        # prefill it skips), and hits must mint zero new compile keys
        ex["decode_prefix_cold_ttft_p99_ms"] = round(px[0], 3)
        ex["decode_prefix_ttft_p99_ms"] = round(px[1], 3)
        ex["decode_prefix_vs_nocache"] = round(px[2], 3)
        ex["decode_prefix_hit_rate_pct"] = round(px[3], 2)
        proxy_px = len(px) >= 7 and px[6] >= 1
        if px[3] <= 0:
            _note("runner", "prefix-cache trace recorded a ZERO hit rate "
                            "— template-sharing arrivals must hit")
        if len(px) >= 5:
            ex["decode_prefix_parity"] = "ok" if px[4] >= 1 else "MISMATCH"
            if px[4] < 1:
                _note("runner", "prefix-cached decode tokens DIVERGED "
                                "from cold decode() — exactness gate "
                                "failed")
        if len(px) >= 6:
            ex["decode_prefix_hit_compiles"] = int(px[5])
            if px[5] > 0:
                _note("runner", f"{int(px[5])} executable compile(s) "
                                "during the prefix-cache replay — hits "
                                "must not mint compile keys")
        if proxy_px:
            _note("runner", "prefix cached-vs-cold measured on the CPU "
                            "proxy (parity + hit-rate cover) — the 1.3x "
                            "TTFT gate rides the queued relay round")
        elif px[2] < 1.3:
            _note("runner", f"prefix cached/cold TTFT {px[2]:.3f} below "
                            "the 1.3x on-chip gate")
        ok = True
    gp = got.get("RUNNER_GOODPUT")
    if gp and not isinstance(gp, str) and len(gp) >= 2:
        # goodput & cost attribution (ISSUE 17): useful-token share and
        # device-seconds per 1k generated tokens over the continuous A/B
        # bracket — the bench ground truth the /fleet/capacity per-class
        # cost number is judged against (agreement gate lives in tests)
        ex["decode_goodput_pct"] = round(gp[0], 2)
        ex["decode_device_s_per_1k_tokens"] = round(gp[1], 4)
        ok = True
    return ok


def _record_serving_profiler(got: dict) -> bool:
    """Fold the echo-serving profiler overhead A/B (ISSUE 15) into extras;
    False when the marker is absent.  Gate: the sampler ON at its default
    hz must stay within 3% of baseline — a miss leaves a phase note, so
    the artifact says WHY the number is missing its gate."""
    vals = got.get("SERVING_PROFILER")
    if isinstance(vals, str) or not vals or len(vals) < 3:
        return False
    ex = RESULT["extras"]
    ex["serving_echo_p50_ms"] = round(vals[0], 3)
    ex["serving_echo_profiled_p50_ms"] = round(vals[1], 3)
    ex["profiler_overhead_pct"] = round(vals[2], 2)
    if vals[2] > 3.0:
        _note("serving", f"profiler overhead {vals[2]:.2f}% exceeds the "
                         "3% echo-microbench gate")
    return True


def _record_gbdt_util(got: dict) -> bool:
    """Fold GBDT_UTIL (cost-analysis bytes/iter + HBM-roofline utilization
    %) into extras; False when the child had no cost analysis."""
    vals = got.get("GBDT_UTIL")
    if isinstance(vals, str) or not vals or len(vals) < 2:
        return False
    RESULT["extras"]["gbdt_hbm_bytes_per_iter"] = round(vals[0], 1)
    RESULT["extras"]["gbdt_achievable_util_pct"] = round(vals[1], 2)
    return True


def _health_gate(spawn=None, attempts: int = 3, idle: float = 150,
                 hard: float = 200, backoff_s: float = 15.0,
                 sleep=time.sleep):
    """Relay health gate with exponential backoff between attempts.

    BENCH_r05 lost every TPU phase to a single silent health child while
    later serving phases ran fine; PR 5's one immediate retry still lost
    2 of 5 rounds — an immediate retry lands on a relay that is mid-recovery
    and fails the same way.  Each failed attempt now waits
    ``backoff_s * 2**(attempt-1)`` (15s, 30s, ...) before the next probe so
    a relay that needs tens of seconds to come back gets them.  Returns
    (ok, attempts_used)."""
    spawn = spawn or (lambda: _spawn("health", _tpu_env()))
    for attempt in range(1, attempts + 1):
        got = _collect(spawn(), "HEALTH_OK", idle, hard=hard)
        if got is not None:
            return True, attempt
        if attempt < attempts:
            wait_s = backoff_s * 2 ** (attempt - 1)
            _log(f"[bench] health attempt {attempt} silent/failed; "
                 f"backing off {wait_s:.0f}s before retry")
            sleep(wait_s)
    return False, attempts


def main() -> None:
    wall0 = time.perf_counter()

    # Phase 0 — relay health gate (one retry; see _health_gate).
    tpu_ok, health_tries = _health_gate()
    _log(f"[bench] health: {'ok' if tpu_ok else 'FAILED'} "
         f"after {health_tries} attempt(s) "
         f"({time.perf_counter() - wall0:.0f}s)")
    if health_tries > 1 and tpu_ok:
        _note("health", "attempt 1 silent/failed; retry succeeded")
    if not tpu_ok:
        RESULT["extras"]["note"] = (
            "TPU device relay unreachable (health matmul did not complete "
            "in 150s over three backed-off attempts); TPU phases skipped, "
            "CPU baseline only")
        _emit()

    # Phase 1 — CPU-executor baseline, FIRST and STRICTLY ALONE (VERDICT r4
    # weak #1: concurrency halves the denominator on a 1-core host).  It is
    # host-only, so a sick relay cannot cost us the denominator either.
    got = _collect_multi(_spawn("cpu", _cpu_env()),
                         ("CPU_RPS", "CPU_HOST", "PHASE_METRICS"),
                         idle=350, hard=700)
    _record_phase_metrics("cpu", got)
    cpu_rps = 0.0
    if got.get("CPU_RPS"):
        cpu_rps = got["CPU_RPS"][0]
        RESULT["extras"]["cpu_executor_rows_per_sec"] = round(cpu_rps, 1)
    else:
        _note("cpu", "CPU baseline child died or stalled; no vs_baseline")
    if isinstance(got.get("CPU_HOST"), str):
        try:
            RESULT["extras"]["cpu_host"] = json.loads(got["CPU_HOST"])
        except ValueError:
            pass
    _emit()

    # Optional persistent warm relay (MMLSPARK_TPU_BENCH_WARM_RELAY=1): one
    # held health child keeps a live client on the relay for the whole run
    # so each phase child attaches warm instead of re-waking the relay — the
    # failure mode that cost r05 its TPU phases.  Spawned only after the
    # CPU baseline (which must run strictly alone) and killed in `finally`.
    warm_relay = None
    if tpu_ok and os.environ.get("MMLSPARK_TPU_BENCH_WARM_RELAY", "") \
            not in ("", "0"):
        warm_relay = _spawn("health", _tpu_env(), ["--hold", "1"])
        RESULT["extras"]["warm_relay"] = "held"
        _log("[bench] warm relay holder spawned")

    try:
        _run_measured_phases(tpu_ok, cpu_rps)
    finally:
        if warm_relay is not None:
            warm_relay.kill()
            _log("[bench] warm relay holder killed")
    _log(f"[bench] done in {time.perf_counter() - wall0:.0f}s")


def _run_measured_phases(tpu_ok: bool, cpu_rps: float) -> None:
    """Phases 2-5 (TPU measurements, A/B proxy, serving) — split from
    ``main`` so the warm-relay holder's kill rides one ``finally``."""
    tpu_rps = 0.0
    if tpu_ok:
        # Phase 2 — headline metric: GBDT rows/sec on the real chip (the
        # GBDT_UTIL marker rides along: cost-analysis bytes -> achievable-
        # utilization %, the tile-size tuning denominator).
        got = _collect_multi(_spawn("gbdt", _tpu_env()),
                             ("GBDT_RPS", "GBDT_UTIL", "PHASE_METRICS"),
                             idle=600, hard=1200)
        if got.get("GBDT_RPS") is None:
            # degraded fallback: quarter-size, same trainer
            _note("gbdt", "1M run stalled/overran; retried quarter-size")
            got = _collect_multi(_spawn("gbdt", _tpu_env(),
                                        ["--n", "250000", "--iters_b", "10",
                                         "--reps", "1"]),
                                 ("GBDT_RPS", "GBDT_UTIL",
                                  "PHASE_METRICS"), idle=300,
                                 hard=500)
            if got.get("GBDT_RPS"):
                RESULT["extras"]["note"] = (
                    "measured at 250k x 200 (1M run exceeded its deadline); "
                    "rows/sec is the steady-state marginal rate, ~linear in rows")
        _record_gbdt_util(got)
        _record_phase_metrics("gbdt", got)
        if got.get("GBDT_RPS"):
            tpu_rps = got["GBDT_RPS"][0]
            RESULT["value"] = round(tpu_rps, 1)
            if cpu_rps:
                RESULT["vs_baseline"] = round(tpu_rps / cpu_rps, 3)
        else:
            _note("gbdt", "both attempts failed; no TPU headline number")
        _emit()

        # Phase 2c — out-of-core streamed-vs-in-memory A/B on the chip
        # (overhead bound at a fits-in-HBM shape + prefetch overlap %).
        got = _collect_multi(_spawn("ooc", _tpu_env()),
                             ("OOC_AB", "OOC_CKPT", "PHASE_METRICS"),
                             idle=600, hard=1600)
        _record_phase_metrics("ooc", got)
        if not _record_ooc(got):
            _note("ooc", "TPU streamed A/B stalled/failed; CPU proxy will run")
        _emit()

        # Phase 2b — packed-int vs f32 histogram build A/B at the bench
        # shape (quantized-gradient acceptance: packed >= 1.5x the
        # 3-channel f32 build; ISSUE 5).
        got = _collect_multi(_spawn("hist_ab", _tpu_env()),
                             ("HIST_AB_RATES", "HIST_AB_MODE",
                              "HIST_AB_FUSED", "PHASE_METRICS"),
                             idle=600,
                             hard=1100)
        _record_phase_metrics("hist_ab", got)
        if not _record_hist_ab(got):
            _note("hist_ab", "TPU A/B stalled/failed; CPU proxy will run")
        _emit()

        # Phase 3 — LambdaRank iteration rate (device-resident lambdas).
        # Compile-aware deadline + one retry: the first attempt may spend
        # its window inside a fresh XLA compile (r4: killed at 300s
        # mid-compile, number lost).  A completed compile lands in the
        # persistent cache, so a second attempt is measurement-only.
        got = _collect(_spawn("ranker", _tpu_env()), "RANKER_RPS", idle=480,
                       hard=900, phase="ranker")
        if got is None:
            _note("ranker", "attempt 1 stalled (likely compile); retried")
            # the retry gets a LARGER idle window: if attempt 1 died inside
            # a silent fresh compile, a smaller window would deterministically
            # kill the retry mid-compile too (the relay-wedge scenario)
            got = _collect(_spawn("ranker", _tpu_env()), "RANKER_RPS",
                           idle=700, hard=1000, phase="ranker")
        if got:
            RESULT["extras"]["lambdarank_train_rows_per_sec_200kx50"] = \
                round(got[0], 1)
        else:
            _note("ranker", "both attempts failed; no lambdarank number")
        _emit()

        # Phase 4 — ResNet-50 featurize (same retry discipline).
        got = _collect(_spawn("resnet", _tpu_env()), "IMAGES_SEC", idle=420,
                       hard=800, phase="resnet")
        if got is None:
            _note("resnet", "attempt 1 stalled (likely compile); retried")
            got = _collect(_spawn("resnet", _tpu_env()), "IMAGES_SEC",
                           idle=600, hard=900, phase="resnet")
        if got:
            RESULT["extras"]["resnet50_featurize_images_per_sec_per_chip"] = \
                round(got[0], 1)
            if len(got) > 1:
                RESULT["extras"]["resnet50_featurize_mfu_pct"] = round(got[1], 1)
        else:
            _note("resnet", "both attempts failed; no featurize number")
        _emit()

        # Phase 4d — unified-runner A/B + KV-cached decode tokens/sec on the
        # chip (ISSUE 9: runner >= 0.9x the legacy glue it replaced, plus
        # the generative-serving number).
        got = _collect_multi(_spawn("runner", _tpu_env()),
                             ("RUNNER_AB", "RUNNER_DECODE", "RUNNER_PAGED",
                              "RUNNER_CONT", "RUNNER_PREFIX",
                              "RUNNER_GOODPUT", "PHASE_METRICS"),
                             idle=600, hard=1100)
        _record_phase_metrics("runner", got)
        if not _record_runner(got):
            _note("runner", "TPU runner A/B stalled/failed; CPU proxy will run")
        _emit()

    # Phase 4b — packed-histogram A/B CPU proxy: covers the relay-down case
    # (and a failed TPU attempt) so the round artifact always carries an
    # attribution number for the quantized pipeline.
    if "hist_ab_packed_speedup" not in RESULT["extras"]:
        got = _collect_multi(_spawn("hist_ab", _cpu_env(), ["--proxy", "1"]),
                             ("HIST_AB_RATES", "HIST_AB_MODE",
                              "HIST_AB_FUSED", "PHASE_METRICS"),
                             idle=300, hard=600)
        _record_phase_metrics("hist_ab", got)
        if not _record_hist_ab(got):
            _note("hist_ab", "CPU proxy A/B also failed; no packed number")
        _emit()

    # Phase 4c — out-of-core A/B CPU proxy (relay-down cover, same as the
    # hist_ab proxy): the round artifact always carries the streamed
    # overhead bound + prefetch-overlap number for the chunked pipeline.
    if "ooc_streamed_vs_inmemory" not in RESULT["extras"]:
        got = _collect_multi(_spawn("ooc", _cpu_env()),
                             ("OOC_AB", "OOC_CKPT", "PHASE_METRICS"),
                             idle=500, hard=1300)
        _record_phase_metrics("ooc", got)
        if not _record_ooc(got):
            _note("ooc", "CPU proxy streamed A/B also failed; no ooc number")
        _emit()

    # Phase 4e — runner A/B CPU proxy (relay-down cover): the round artifact
    # always carries the runner-overhead ratio + a decode tokens/sec number.
    if "runner_vs_legacy" not in RESULT["extras"]:
        got = _collect_multi(_spawn("runner", _cpu_env(), ["--proxy", "1"]),
                             ("RUNNER_AB", "RUNNER_DECODE", "RUNNER_PAGED",
                              "RUNNER_CONT", "RUNNER_PREFIX",
                              "RUNNER_GOODPUT", "PHASE_METRICS"),
                             idle=500, hard=900)
        _record_phase_metrics("runner", got)
        if not _record_runner(got):
            _note("runner", "CPU proxy runner A/B also failed; no runner number")
        _emit()

    # Phase 5 — serving latency + sustained load (pure host, CPU platform).
    sproc = _spawn("serving", _cpu_env())
    got = _collect_multi(sproc, ("SERVING_P50_MS", "SERVING_LOAD",
                                 "SERVING_PROFILER", "PHASE_METRICS"),
                         idle=200, hard=400)
    _record_phase_metrics("serving", got)
    if got.get("SERVING_P50_MS"):
        RESULT["extras"]["serving_http_p50_ms"] = round(got["SERVING_P50_MS"][0], 2)
        RESULT["extras"]["serving_http_p95_ms"] = round(got["SERVING_P50_MS"][1], 2)
    if got.get("SERVING_LOAD"):
        RESULT["extras"]["serving_sustained_rps_8conn"] = round(got["SERVING_LOAD"][0], 1)
        RESULT["extras"]["serving_sustained_p99_ms"] = round(got["SERVING_LOAD"][1], 2)
    if not _record_serving_profiler(got):
        _note("serving", "echo profiler A/B produced no SERVING_PROFILER "
                         "marker; profiler_overhead_pct missing this round")
    _emit()


if __name__ == "__main__":
    if "--phase" in sys.argv:
        args = sys.argv[sys.argv.index("--phase") + 1:]
        phase, rest = args[0], args[1:]
        kw = {}
        for i in range(0, len(rest) - 1, 2):
            kw[rest[i].lstrip("-")] = int(rest[i + 1])
        {"health": phase_health, "gbdt": phase_gbdt, "ranker": phase_ranker,
         "resnet": phase_resnet, "cpu": phase_cpu, "hist_ab": phase_hist_ab,
         "ooc": phase_ooc, "serving": phase_serving,
         "runner": phase_runner}[phase](**kw)
        if phase != "health":  # the health probe must stay marker-clean
            _emit_phase_metrics()
    else:
        main()
