"""DeepLearning - CIFAR10 Convolutional Network.

Equivalent of the reference's ``DeepLearning - CIFAR10 Convolutional
Network`` notebook: train a small convnet on CIFAR-shaped images with the
jitted optax loop, then serve it through the JaxModel transformer for
frame-level scoring.  Images are synthetic class-colored tiles (offline
stand-in with the CIFAR tensor shape)."""
import time

import numpy as np

from _common import setup


def make_cifar_like(n=2048, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.uniform(0, 1, (n, 32, 32, 3)).astype(np.float32)
    y = rng.integers(0, 4, n)
    for i in range(n):  # each class tints one channel/half
        c = y[i]
        if c < 3:
            X[i, :, :, c] += 0.8
        else:
            X[i, 16:, :, :] += 0.6
    return np.clip(X, 0, 2), y.astype(np.int32)


def main():
    setup()
    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    import optax
    from mmlspark_tpu.core import DataFrame
    from mmlspark_tpu.dl import JaxModel

    class ConvNet(nn.Module):
        @nn.compact
        def __call__(self, x):
            for feat in (16, 32):
                x = nn.relu(nn.Conv(feat, (3, 3), strides=(2, 2))(x))
            x = x.reshape((x.shape[0], -1))
            x = nn.relu(nn.Dense(64)(x))
            return nn.Dense(4)(x)

    X, y = make_cifar_like()
    cut = int(len(y) * 0.85)
    m = ConvNet()
    params = m.init(jax.random.PRNGKey(0), X[:1])
    tx = optax.adam(1e-3)
    opt = tx.init(params)

    @jax.jit
    def step(params, opt, xb, yb):
        def loss(p):
            return optax.softmax_cross_entropy_with_integer_labels(
                m.apply(p, xb), yb).mean()
        l, g = jax.value_and_grad(loss)(params)
        up, opt = tx.update(g, opt)
        return optax.apply_updates(params, up), opt, l

    t0 = time.perf_counter()
    bs = 256
    for epoch in range(6):
        for s in range(0, cut, bs):
            params, opt, l = step(params, opt, jnp.asarray(X[s:s + bs]),
                                  jnp.asarray(y[s:s + bs]))
    print(f"trained 6 epochs in {time.perf_counter() - t0:.1f}s, "
          f"final loss {float(l):.3f}")

    # frame-level scoring through the JaxModel transformer
    jm = JaxModel()
    jm.set_model(apply_fn=lambda v, b: m.apply(v, b), variables=params)
    jm.set_params(input_col="image", output_col="logits", batch_size=256,
                  input_shape=[32, 32, 3])
    col = np.empty(len(X) - cut, dtype=object)
    for i in range(len(col)):
        col[i] = X[cut + i]
    df = DataFrame.from_dict({"image": col})
    out = jm.transform(df).collect()["logits"]
    pred = np.asarray([np.argmax(v) for v in out])
    acc = float((pred == y[cut:]).mean())
    print(f"held-out accuracy: {acc:.3f}")
    assert acc > 0.9, acc
    print("CIFAR convnet OK")


if __name__ == "__main__":
    main()
