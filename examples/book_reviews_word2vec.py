"""TextAnalytics - Amazon Book Reviews with Word2Vec.

Equivalent of the reference's ``TextAnalytics - Amazon Book Reviews with
Word2Vec`` notebook: tokenizer + ``Word2Vec`` document embeddings feed a
small model zoo (several LightGBM configurations — the notebook's
LogisticRegression/RandomForest/GBT grid), ``FindBestModel`` picks the
winner on the test split by AUC, and ``ComputeModelStatistics`` reports
validation accuracy.  Review text is synthesized (zero egress) with the
same shape: free text + a 1-5 rating thresholded at > 3.
"""
import numpy as np

from _common import setup

GOOD = ["gripping", "masterpiece", "loved", "beautiful", "inspiring",
        "brilliant", "excellent", "wonderful"]
BAD = ["boring", "dull", "hated", "waste", "awful", "predictable",
       "terrible", "disappointing"]
NEUTRAL = ["book", "story", "chapter", "author", "plot", "character",
           "read", "pages", "series", "writing", "the", "a", "was", "it"]


def make_reviews(n=6000, seed=0):
    from mmlspark_tpu.core import DataFrame
    rng = np.random.default_rng(seed)
    texts = np.empty(n, dtype=object)
    rating = np.zeros(n)
    for i in range(n):
        r = int(rng.integers(1, 6))
        rating[i] = r
        words = list(rng.choice(NEUTRAL, rng.integers(8, 16)))
        pool, k = (GOOD, r - 3) if r > 3 else (BAD, 4 - r)
        for _ in range(max(1, k)):
            words.insert(int(rng.integers(0, len(words))),
                         str(rng.choice(pool)))
        texts[i] = " ".join(words)
    return DataFrame.from_dict({"text": texts, "rating": rating},
                               num_partitions=4)


def main():
    setup()
    from mmlspark_tpu.automl import FindBestModel
    from mmlspark_tpu.core import Pipeline
    from mmlspark_tpu.featurize import Word2Vec
    from mmlspark_tpu.lightgbm import LightGBMClassifier
    from mmlspark_tpu.train import ComputeModelStatistics, TrainClassifier

    data = make_reviews()
    processed = data.with_column(
        "label", lambda p: (np.asarray(p["rating"]) > 3).astype(float))
    train, test, validation = processed.random_split([0.60, 0.20, 0.20],
                                                     seed=42)

    # tokenizer + Word2Vec = the notebook's textFeaturizer pipeline
    word2vec = Word2Vec(input_col="text", output_col="features",
                        vector_size=32, max_iter=3, min_count=2, seed=42)
    featurizer = word2vec.fit(train)
    ptrain = featurizer.transform(train)
    ptest = featurizer.transform(test)
    pvalidation = featurizer.transform(validation)
    syn = featurizer.find_synonyms("loved", 3)
    print(f"synonyms of 'loved': {[w for w, _ in syn]}")

    # the notebook's hyperparameter grid -> TrainClassifier wrappers
    grid = [dict(num_iterations=it, learning_rate=lr)
            for it in (20, 40) for lr in (0.1, 0.3)]
    trained = [TrainClassifier().set_params(
        model=LightGBMClassifier().set_params(min_data_in_leaf=5, **hp),
        label_col="label").fit(ptrain) for hp in grid]

    best = FindBestModel().set_params(evaluation_metric="accuracy",
                                      models=trained).fit(ptest)
    print(f"grid accuracies on test: "
          f"{[round(v, 4) for v in best.get_evaluation_results()]}")
    print(f"best model test accuracy: "
          f"{float(best.get('best_model_metrics')):.4f}")

    predictions = best.transform(pvalidation)
    metrics = ComputeModelStatistics().set_params(
        evaluation_metric="classification", label_col="label",
        scores_col="prediction").transform(predictions).collect()
    acc = float(metrics["accuracy"][0])
    print(f"best model accuracy on validation = {100 * acc:.2f}%")
    assert acc > 0.85, acc
    print("book reviews with word2vec OK")


if __name__ == "__main__":
    main()
