"""LightGBM Regressor — Flight-Delays-style wide tabular regression.

Equivalent of the reference's Flight Delays regression notebook
(BASELINE.json config 2): ~1M-row wide tabular regression, rows shardable
over the device mesh (``shard_rows=True``).
"""
import time

import numpy as np

from _common import setup


def main():
    setup()
    from mmlspark_tpu.core import DataFrame
    from mmlspark_tpu.core.schema import vector_column
    from mmlspark_tpu.lightgbm import LightGBMRegressor

    rng = np.random.default_rng(0)
    n, d = 1_000_000, 50
    X = rng.normal(size=(n, d)).astype(np.float32)
    delay = (8 * X[:, 0] - 3 * X[:, 1] + 2 * np.abs(X[:, 2])
             + rng.normal(scale=2.0, size=n)).astype(np.float32)
    # dense 2-d vector column: no per-row object boxing at this scale
    df = DataFrame([{"features": X, "label": delay}])

    reg = LightGBMRegressor().set_params(num_iterations=50, learning_rate=0.1,
                                         num_leaves=31)
    t0 = time.perf_counter()
    model = reg.fit(df)
    dt = time.perf_counter() - t0
    print(f"trained 50 iters on {n:,} x {d} in {dt:.1f}s "
          f"-> {n * 50 / dt:,.0f} rows/s")
    pred = model.transform(df.limit(10000)).collect()["prediction"]
    mse = float(np.mean((pred - delay[:10000]) ** 2))
    print(f"train-slice MSE {mse:.3f} (noise floor ~4.0)")


if __name__ == "__main__":
    main()
