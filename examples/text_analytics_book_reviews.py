"""TextAnalytics - Amazon Book Reviews.

Equivalent of the reference's ``TextAnalytics - Amazon Book Reviews``
notebook: raw review text -> TextFeaturizer (tokenize, stop words, hashed
n-gram TF-IDF) -> classifier on the sparse features -> held-out accuracy.
Review text is generated from sentiment lexicons (offline stand-in with
the same star-label structure).
"""
import numpy as np

from _common import setup

POS = ["wonderful", "gripping", "brilliant", "loved", "masterpiece",
       "delightful", "excellent"]
NEG = ["boring", "awful", "tedious", "hated", "disappointing", "dull",
       "terrible"]
FILLER = ["the", "plot", "book", "chapter", "author", "story", "character",
          "ending", "prose", "pacing", "i", "found", "it", "was", "really"]


def make_reviews(n=2400, seed=0):
    rng = np.random.default_rng(seed)
    texts = np.empty(n, dtype=object)
    stars = np.zeros(n)
    for i in range(n):
        good = i % 2 == 0
        lex = POS if good else NEG
        words = list(rng.choice(FILLER, rng.integers(8, 16)))
        for _ in range(rng.integers(1, 4)):
            words.insert(int(rng.integers(0, len(words))),
                         str(rng.choice(lex)))
        texts[i] = " ".join(words)
        stars[i] = 5.0 if good else rng.integers(1, 3)
    return texts, (stars >= 4).astype(float)


def main():
    setup()
    from mmlspark_tpu.core import DataFrame
    from mmlspark_tpu.featurize import TextFeaturizer
    from mmlspark_tpu.vw import VowpalWabbitClassifier

    texts, y = make_reviews()
    df = DataFrame.from_dict({"text": texts, "label": y}, num_partitions=4)
    train, test = df.random_split([0.8, 0.2], seed=1)

    feat = TextFeaturizer().set_params(input_col="text", output_col="features",
                                       num_features=2048,
                                       use_stop_words_remover=True).fit(train)
    # hashed sparse features feed VW natively (the reference notebook's
    # linear-classifier-on-TF path)
    clf = VowpalWabbitClassifier().set_params(num_passes=10, num_bits=18)
    model = clf.fit(feat.transform(train))
    pred = model.transform(feat.transform(test)).collect()
    acc = float((np.asarray(pred["prediction"])
                 == np.asarray(pred["label"])).mean())
    print(f"held-out accuracy on hashed TF-IDF features: {acc:.3f}")
    assert acc > 0.9, acc
    print("book reviews OK")


if __name__ == "__main__":
    main()
