"""Interpretability - Tabular SHAP — explain a LightGBM income model.

Equivalent of the reference's ``Interpretability - Tabular SHAP explainer``
notebook: Adult-Census-shaped frame -> LightGBMClassifier -> KernelSHAP over
the raw tabular columns, checked against the booster's own exact TreeSHAP.
"""
import numpy as np

from _common import setup


def main():
    setup()
    from mmlspark_tpu.core import DataFrame
    from mmlspark_tpu.core.schema import vector_column
    from mmlspark_tpu.explainers import LocalExplainer
    from mmlspark_tpu.lightgbm import LightGBMClassifier

    rng = np.random.default_rng(0)
    n = 4000
    age = rng.uniform(17, 90, n)
    hours = rng.uniform(1, 99, n)
    edu = rng.integers(1, 16, n).astype(float)
    noise = rng.uniform(-1, 1, n)  # irrelevant column SHAP should zero out
    logit = 0.06 * (age - 38) + 0.05 * (hours - 40) + 0.35 * (edu - 9)
    y = (logit + rng.logistic(scale=0.6, size=n) > 0).astype(float)
    X = np.column_stack([age, hours, edu, noise])
    train_df = DataFrame.from_dict({"features": vector_column(list(X)),
                                    "label": y}, num_partitions=4)
    tabular_df = DataFrame.from_dict({"age": age, "hours": hours, "edu": edu,
                                      "noise": noise}, num_partitions=4)

    model = LightGBMClassifier().set_params(num_iterations=60, num_leaves=15,
                                            probability_col="probability")
    fitted = model.fit(train_df)

    # the explainer ASSEMBLES the tabular columns into the model's features
    # column per perturbed sample (reference TabularSHAP inputCols contract)
    explain_rows = tabular_df.limit(8)
    shap = LocalExplainer.KernelSHAP.tabular(
        model=fitted, input_cols=["age", "hours", "edu", "noise"],
        input_col="features", output_col="shap", target_col="probability",
        target_classes=[1], num_samples=300,
        background_data=tabular_df.limit(100))
    out = shap.transform(explain_rows).collect()
    phis = np.stack([np.asarray(v, float) for v in out["shap"]])
    mean_abs = np.abs(phis).mean(axis=0)
    print("mean |SHAP| per column:",
          dict(zip(["age", "hours", "edu", "noise"], mean_abs.round(4))))
    assert mean_abs[2] > mean_abs[3], "edu must out-attribute noise"

    # exact TreeSHAP from the booster agrees on the ranking
    tree_phi = fitted.booster.predict_contrib(X[:8])
    tree_rank = np.abs(tree_phi[:, :4]).mean(axis=0)
    print("TreeSHAP mean |phi|:", tree_rank.round(4))
    assert tree_rank[2] > tree_rank[3]
    print("tabular SHAP OK")


if __name__ == "__main__":
    main()
