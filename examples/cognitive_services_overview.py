"""Cognitive Services - Overview.

Equivalent of the reference's ``Cognitive Services - Overview`` notebook:
several cognitive transformers (sentiment, key phrases, translation,
anomaly detection) run as pipeline stages over frame columns, with
value-or-column ServiceParams, per-row error capture and the standard
subscription-key header plumbing.  The endpoint is a local echo mock
(zero-egress analogue of the Azure endpoints — the transformer side,
which is what this repo rebuilds, is identical).
"""
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from _common import setup


class EchoService:
    def __init__(self):
        outer = self

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                body = self.rfile.read(
                    int(self.headers.get("Content-Length", 0)))
                outer.requests.append({"path": self.path,
                                       "headers": dict(self.headers),
                                       "body": body})
                resp = json.dumps({"echo": json.loads(body or b"null"),
                                   "path": self.path}).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(resp)))
                self.end_headers()
                self.wfile.write(resp)

        self.requests = []
        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        threading.Thread(target=self.httpd.serve_forever, daemon=True).start()
        self.url = f"http://127.0.0.1:{self.httpd.server_port}"


def main():
    setup()
    from mmlspark_tpu.cognitive import (DetectLastAnomaly, KeyPhraseExtractor,
                                        TextSentiment, Translate)
    from mmlspark_tpu.core import DataFrame

    svc = EchoService()
    try:
        texts = np.array(["the service was excellent",
                          "slow and unhelpful support"], dtype=object)
        series = np.empty(2, dtype=object)
        for i in range(2):
            series[i] = [{"timestamp": f"2024-01-0{d+1}T00:00:00Z",
                          "value": float(d + i)} for d in range(5)]
        df = DataFrame.from_dict({"text": texts, "series": series})

        sent = TextSentiment(output_col="sentiment")
        sent.set("url", svc.url + "/text/analytics/v3.0/sentiment")
        sent.set("subscription_key", "key")
        sent.set_col("text", "text")

        phrases = KeyPhraseExtractor(output_col="phrases")
        phrases.set("url", svc.url + "/text/analytics/v3.0/keyPhrases")
        phrases.set("subscription_key", "key")
        phrases.set_col("text", "text")

        trans = Translate(output_col="translated")
        trans.set("url", svc.url + "/translate?api-version=3.0")
        trans.set("subscription_key", "key")
        trans.set_col("text", "text")
        trans.set("to_language", ["fr"])

        anom = DetectLastAnomaly(output_col="anomaly")
        anom.set("url", svc.url + "/anomalydetector/v1.0/timeseries/last/detect")
        anom.set("subscription_key", "key")
        anom.set_col("series", "series")

        out = df
        for stage in (sent, phrases, trans, anom):
            out = stage.transform(out)
        rows = out.collect()
        doc = rows["sentiment"][0]["echo"]["documents"][0]
        print("sentiment request doc:", doc)
        assert doc["text"] == texts[0]
        assert rows["phrases"][1]["echo"]["documents"][0]["text"] == texts[1]
        assert rows["translated"][0]["echo"] == [{"Text": texts[0]}]
        assert rows["anomaly"][0]["echo"]["granularity"] == "daily"
        keys = {r["headers"].get("Ocp-Apim-Subscription-Key")
                for r in svc.requests}
        assert keys == {"key"}
        print(f"{len(svc.requests)} service calls, 4 stages chained OK")
    finally:
        svc.httpd.shutdown()
        svc.httpd.server_close()


if __name__ == "__main__":
    main()
