"""Classification - Adult Census with Vowpal Wabbit.

Equivalent of the reference's ``Classification - Adult Census with Vowpal
Wabbit`` notebook: derive a numeric label from the income string, hash the
raw mixed-type columns with ``VowpalWabbitFeaturizer`` (string categoricals
hash directly — no one-hot pass), fit ``VowpalWabbitClassifier`` in a
``Pipeline``, and report ``ComputeModelStatistics``.
"""
import numpy as np

from _common import setup
from adult_census import make_census


def main():
    setup()
    from mmlspark_tpu.core import Pipeline
    from mmlspark_tpu.train import ComputeModelStatistics
    from mmlspark_tpu.vw import VowpalWabbitClassifier, VowpalWabbitFeaturizer

    data = make_census()
    # label = income contains "<" -> 0.0 else 1.0 (the notebook's withColumn)
    def add_label(df):
        inc = df.collect()["income"]
        return df.with_column("label",
                              np.asarray(["<" not in v for v in inc], float))

    train, test = data.random_split([0.75, 0.25], seed=123)
    train, test = add_label(train), add_label(test)
    print(f"train rows: {train.count()}")

    vw_featurizer = VowpalWabbitFeaturizer(
        input_cols=["education", "marital-status", "hours-per-week"],
        output_col="features")
    vw_model = VowpalWabbitClassifier().set_params(
        num_passes=10, label_col="label", loss_function="logistic")
    vw_pipeline = Pipeline([vw_featurizer, vw_model])

    vw_trained = vw_pipeline.fit(train)
    prediction = vw_trained.transform(test)
    metrics = ComputeModelStatistics().set_params(
        evaluation_metric="classification", label_col="label",
        scores_col="prediction").transform(prediction).collect()
    acc = float(metrics["accuracy"][0])
    print(f"accuracy={acc:.3f} f1={float(metrics['f1_score'][0]):.3f}")
    assert acc > 0.75, acc
    print("adult census with VW OK")


if __name__ == "__main__":
    main()
