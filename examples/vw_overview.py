"""Vowpal Wabbit - Overview.

Equivalent of the reference's ``Vowpal Wabbit - Overview`` notebook: the
full VW tour — heart-disease classification (featurizer + classifier +
ComputeModelStatistics), quantile-loss regression with interactions
(the notebook's ``-q ::`` Boston section), an SVMlight-style sparse
regression, and a contextual-bandit policy — on synthesized stand-ins for
the notebook's remote datasets (zero egress).
"""
import numpy as np

from _common import setup


def make_heart(n=4000, seed=0):
    from mmlspark_tpu.core import DataFrame
    rng = np.random.default_rng(seed)
    age = rng.uniform(29, 77, n)
    chol = rng.uniform(126, 564, n)
    thalach = rng.uniform(71, 202, n)          # max heart rate
    oldpeak = rng.uniform(0, 6.2, n)
    risk = (0.05 * (age - 50) + 0.004 * (chol - 240)
            - 0.02 * (thalach - 150) + 0.6 * oldpeak)
    target = (risk + rng.normal(scale=0.5, size=n) > 0.4).astype(float)
    return DataFrame.from_dict({"age": age, "chol": chol,
                                "thalach": thalach, "oldpeak": oldpeak,
                                "target": target}, num_partitions=4)


def main():
    setup()
    from mmlspark_tpu.core import DataFrame, Pipeline
    from mmlspark_tpu.train import ComputeModelStatistics
    from mmlspark_tpu.vw import (VowpalWabbitClassifier,
                                 VowpalWabbitContextualBandit,
                                 VowpalWabbitFeaturizer,
                                 VowpalWabbitInteractions,
                                 VowpalWabbitRegressor)

    # ---- 1. heart-disease classification (notebook part 1)
    df = make_heart()
    train, test = df.random_split([0.85, 0.15], seed=1)
    feat = VowpalWabbitFeaturizer(
        input_cols=["age", "chol", "thalach", "oldpeak"],
        output_col="features")
    clf = VowpalWabbitClassifier().set_params(num_passes=20,
                                              label_col="target")
    model = Pipeline([feat, clf]).fit(train)
    pred = model.transform(test)
    metrics = ComputeModelStatistics().set_params(
        evaluation_metric="classification", label_col="target",
        scores_col="prediction").transform(pred).collect()
    acc = float(metrics["accuracy"][0])
    print(f"heart disease: accuracy={acc:.3f} f1={float(metrics['f1_score'][0]):.3f}")
    assert acc > 0.75, acc

    # ---- 2. quantile regression with quadratic interactions (-q ::)
    rng = np.random.default_rng(3)
    n = 3000
    Xr = rng.normal(size=(n, 6)).astype(np.float32)
    yr = (Xr[:, 0] * Xr[:, 1] * 2.0 + Xr[:, 2] + 0.2 *
          rng.normal(size=n))                   # needs the interaction terms
    rdf = DataFrame.from_dict(
        {**{f"f{i}": Xr[:, i] for i in range(6)}, "target": yr})
    rtrain, rtest = rdf.random_split([0.75, 0.25], seed=42)
    rfeat = VowpalWabbitFeaturizer(
        input_cols=[f"f{i}" for i in range(6)], output_col="base")
    rq = VowpalWabbitInteractions(                # the notebook's -q ::
        input_cols=["base", "base"], output_col="features")
    vwr = VowpalWabbitRegressor().set_params(
        label_col="target", num_passes=60, loss_function="quantile",
        learning_rate=0.5, power_t=0.7)
    rmodel = Pipeline([rfeat, rq, vwr]).fit(rtrain)
    rscored = rmodel.transform(rtest)
    rmetrics = ComputeModelStatistics().set_params(
        evaluation_metric="regression", label_col="target",
        scores_col="prediction").transform(rscored).collect()
    print(f"interaction regression: MAE={float(rmetrics['mean_absolute_error'][0]):.3f}")

    # ---- 3. sparse (svmlight-style) regression (triazines section)
    n_sp, dims = 1500, 60
    feats = np.empty(n_sp, dtype=object)
    w_true = rng.normal(size=dims)
    targets = np.zeros(n_sp)
    for i in range(n_sp):
        idx = rng.choice(dims, 8, replace=False).astype(np.int32)
        val = rng.normal(size=8).astype(np.float32)
        targets[i] = w_true[idx] @ val + 0.1 * rng.normal()
        feats[i] = {"indices": idx, "values": val}
    sdf = DataFrame.from_dict({"features": feats, "label": targets})
    strain, stest = sdf.random_split([0.85, 0.15], seed=1)
    smodel = VowpalWabbitRegressor().set_params(
        num_passes=20, loss_function="squared").fit(strain)
    sscored = smodel.transform(stest)
    smetrics = ComputeModelStatistics().set_params(
        evaluation_metric="regression", label_col="label",
        scores_col="prediction").transform(sscored).collect()
    print(f"sparse regression: MAE={float(smetrics['mean_absolute_error'][0]):.3f}")

    # ---- 4. contextual bandit (vwcb section): epsilon-greedy over 3 actions
    n_cb = 2000
    ctx = rng.integers(0, 3, n_cb)              # user context id
    best_action = (ctx + 1) % 3                 # hidden optimal policy
    chosen = rng.integers(0, 3, n_cb)           # logged uniform behaviour
    cost = np.where(chosen == best_action, 0.0, 1.0)
    prob = np.full(n_cb, 1.0 / 3.0)
    act_col = np.empty(n_cb, dtype=object)
    shared_col = np.empty(n_cb, dtype=object)
    for i in range(n_cb):
        shared_col[i] = {"indices": np.asarray([int(ctx[i])], np.int32),
                         "values": np.asarray([1.0], np.float32)}
        # the (context x action) cross term rides in the action features —
        # what the reference wires via -q between shared/action namespaces
        act_col[i] = [{"indices": np.asarray([8 + a, 16 + int(ctx[i]) * 3 + a],
                                             np.int32),
                       "values": np.asarray([1.0, 1.0], np.float32)}
                      for a in range(3)]
    cdf = DataFrame.from_dict({
        "shared_features": shared_col, "action_features": act_col,
        "chosen_action": chosen.astype(np.float64) + 1,  # 1-based like VW
        "cost": cost, "probability": prob})
    cb = VowpalWabbitContextualBandit().set_params(
        num_passes=8, learning_rate=0.5)
    cb_model = cb.fit(cdf)
    scored = cb_model.transform(cdf).collect()["prediction"]
    picked = np.array([int(np.argmin(s)) for s in scored])
    regret = float((picked != best_action).mean())
    print(f"contextual bandit: policy regret={regret:.3f} (uniform=0.667)")
    assert regret < 0.35, regret
    print("vw overview OK")


if __name__ == "__main__":
    main()
