"""HyperParameterTuning - Fighting Breast Cancer — random-grid model search.

Equivalent of the reference's ``HyperParameterTuning`` notebook: the REAL
UCI breast-cancer dataset (committed CSV, tests/resources/datasets) ->
TuneHyperparameters over a LightGBM search space -> held-out metrics of the
best model.
"""
import os

import numpy as np

from _common import setup

CSV = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                   "tests", "resources", "datasets", "breast_cancer.csv")


def main():
    setup()
    from mmlspark_tpu.automl import (DiscreteHyperParam, GridSpace,
                                     HyperparamBuilder, RangeHyperParam,
                                     TuneHyperparameters)
    from mmlspark_tpu.core import DataFrame
    from mmlspark_tpu.core.schema import vector_column
    from mmlspark_tpu.lightgbm import LightGBMClassifier

    M = np.loadtxt(CSV, delimiter=",", skiprows=1)
    X, y = M[:, :-1], M[:, -1]
    rng = np.random.default_rng(7)
    order = rng.permutation(len(y))
    cut = int(len(y) * 0.75)
    tr, te = order[:cut], order[cut:]

    def frame(idx):
        return DataFrame.from_dict({"features": vector_column(list(X[idx])),
                                    "label": y[idx]}, num_partitions=2)

    space = HyperparamBuilder() \
        .add_hyperparam("num_leaves", DiscreteHyperParam([7, 15, 31])) \
        .add_hyperparam("num_iterations", DiscreteHyperParam([20, 40])) \
        .add_hyperparam("learning_rate", RangeHyperParam(0.05, 0.3)).build()

    tuner = TuneHyperparameters()
    tuner.set("models", LightGBMClassifier())
    tuner.set("param_space", GridSpace(space, points_per_range=2))
    tuner.set("parallelism", 2)
    best = tuner.fit(frame(tr))
    print("best params:", best.get("best_params"))
    print("best cv metric:", round(best.get("best_metric"), 4))

    pred = best.transform(frame(te)).collect()
    acc = float((np.asarray(pred["prediction"]) == y[te]).mean())
    print(f"held-out accuracy: {acc:.4f}")
    assert acc > 0.93, acc
    print("hyperparameter tuning OK")


if __name__ == "__main__":
    main()
