"""DeepLearning - BiLSTM entity extraction — sequence tagging via JaxModel.

Equivalent of the reference's ``DeepLearning - BiLSTM Medical Entity
Extraction`` notebook (BASELINE.json config 5): token sequences scored by a
BiLSTM tagger through the JaxModel runner; no pretrained weights offline, so
the model is trained briefly on synthetic entity patterns first.
"""
import time

import numpy as np

from _common import setup


def main():
    setup()
    import jax
    import jax.numpy as jnp
    import optax
    from mmlspark_tpu.core import DataFrame
    from mmlspark_tpu.dl import JaxModel
    from mmlspark_tpu.models import BiLSTMTagger

    V, T, L = 200, 3, 24  # vocab, tags (O / DRUG / DOSE), seq len
    rng = np.random.default_rng(0)

    def make_batch(n):
        toks = rng.integers(10, V, (n, L))
        tags = np.zeros((n, L), np.int32)
        for i in range(n):
            j = rng.integers(0, L - 2)
            toks[i, j] = 1          # DRUG marker token
            tags[i, j] = 1
            toks[i, j + 1] = 2      # DOSE marker token
            tags[i, j + 1] = 2
        return toks.astype(np.int32), tags

    module = BiLSTMTagger(vocab_size=V, num_tags=T, embed_dim=32, hidden=64,
                          num_layers=1)
    toks, tags = make_batch(256)
    variables = module.init(jax.random.PRNGKey(0), jnp.asarray(toks))
    tx = optax.adam(3e-3)
    opt_state = tx.init(variables["params"])

    @jax.jit
    def step(params, opt_state, toks, tags):
        def loss_fn(p):
            logits = module.apply({"params": p}, toks)
            return optax.softmax_cross_entropy_with_integer_labels(logits, tags).mean()
        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    params = variables["params"]
    for it in range(60):
        params, opt_state, loss = step(params, opt_state, jnp.asarray(toks),
                                       jnp.asarray(tags))
    print(f"trained tagger, final loss {float(loss):.4f}")

    # inference through the framework's runner
    test_toks, test_tags = make_batch(64)
    col = np.empty(64, dtype=object)
    for i in range(64):
        col[i] = test_toks[i]
    df = DataFrame.from_dict({"tokens": col}, num_partitions=2)
    runner = JaxModel().set_model(module=module, variables={"params": params})
    runner.set_params(input_col="tokens", output_col="tag_logits",
                      batch_size=32, input_dtype="int32")
    t0 = time.perf_counter()
    out = runner.transform(df)
    dt = time.perf_counter() - t0
    pred = np.stack([np.argmax(v, -1) for v in out.collect()["tag_logits"]])
    acc = float((pred == test_tags).mean())
    print(f"tagged {64 * L} tokens in {dt:.3f}s; token accuracy {acc:.3f}")


if __name__ == "__main__":
    main()
