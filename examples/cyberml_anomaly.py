"""CyberML - Anomalous Access Detection — collaborative-filtering anomalies.

Equivalent of the reference's ``CyberML - Anomalous Access Detection``
notebook (``cyber/anomaly/collaborative_filtering.py``): per-tenant
user->resource access logs -> AccessAnomaly (implicit-feedback sparse ALS)
-> high scores on cross-department accesses that never occur in training.
"""
import numpy as np

from _common import setup

DEPTS = {"eng": [f"srv{i}" for i in range(6)],
         "hr": [f"hrdb{i}" for i in range(4)],
         "fin": [f"ledger{i}" for i in range(4)]}


def make_access_log(seed=0, days=25):
    rng = np.random.default_rng(seed)
    rows = []
    users = [(f"u{u}", dept) for u, dept in
             enumerate(list(DEPTS) * 6)]  # 18 users across 3 departments
    for day in range(days):
        for uname, dept in users:
            for _ in range(rng.integers(2, 6)):
                rows.append({"tenant": "contoso", "user": uname,
                             "res": rng.choice(DEPTS[dept])})
    return rows


def main():
    setup()
    from mmlspark_tpu.core import DataFrame
    from mmlspark_tpu.cyber import AccessAnomaly

    rows = make_access_log()
    df = DataFrame.from_rows(rows)
    print(f"training on {len(rows)} access events")
    model = AccessAnomaly().set_params(rank=8, max_iter=10, seed=2).fit(df)

    probes = DataFrame.from_rows([
        {"tenant": "contoso", "user": "u0", "res": "srv1"},     # eng -> eng
        {"tenant": "contoso", "user": "u0", "res": "hrdb0"},    # eng -> hr!
        {"tenant": "contoso", "user": "u1", "res": "hrdb2"},    # hr -> hr
        {"tenant": "contoso", "user": "u1", "res": "ledger0"},  # hr -> fin!
    ])
    out = model.transform(probes).collect()
    scores = np.asarray(out["anomaly_score"], float)
    for i, r in enumerate(probes.collect()["res"]):
        print(f"{out['user'][i]} -> {r}: anomaly_score={scores[i]:.3f}")
    assert scores[1] > scores[0], "cross-dept access must score higher"
    assert scores[3] > scores[2]
    print("cyberML access anomaly OK")


if __name__ == "__main__":
    main()
