"""Pretrained-model featurization via ONNX import — the ImageFeaturizer
transfer-learning path (reference DeepLearning-TransferLearning notebook).

A torch CNN's weights are packed into a real ONNX wire-format artifact,
registered in the local model repo with the classifier head cut, and used to
featurize an image column; features match the source runtime numerically.
"""
import numpy as np

from _common import setup


def main():
    setup(force_cpu=True)
    import torch
    import torch.nn as tnn
    from mmlspark_tpu.core import DataFrame
    from mmlspark_tpu.dl import ImageFeaturizer, ModelDownloader
    from mmlspark_tpu.dl.onnx_wire import build_model, encode_node

    torch.manual_seed(0)
    m = tnn.Sequential(tnn.Conv2d(3, 16, 3, stride=2, padding=1),
                       tnn.BatchNorm2d(16), tnn.ReLU(),
                       tnn.AdaptiveAvgPool2d(1), tnn.Flatten(),
                       tnn.Linear(16, 10)).eval()
    conv, bn, _, _, _, lin = m
    t = lambda x: x.detach().numpy()  # noqa: E731
    init = {"cw": t(conv.weight), "cb": t(conv.bias), "bs": t(bn.weight),
            "bb": t(bn.bias), "bm": t(bn.running_mean),
            "bv": t(bn.running_var), "fw": t(lin.weight), "fb": t(lin.bias)}
    nodes = [
        encode_node("Conv", ["x", "cw", "cb"], ["c"], kernel_shape=[3, 3],
                    strides=[2, 2], pads=[1, 1, 1, 1]),
        encode_node("BatchNormalization", ["c", "bs", "bb", "bm", "bv"], ["b"],
                    epsilon=float(bn.eps)),
        encode_node("Relu", ["b"], ["r"]),
        encode_node("GlobalAveragePool", ["r"], ["g"]),
        encode_node("Flatten", ["g"], ["feat"], axis=1),
        encode_node("Gemm", ["feat", "fw", "fb"], ["y"], transB=1),
    ]
    onnx_bytes = build_model(nodes, init, [("x", [1, 3, 64, 64])],
                             [("y", [1, 10])])

    repo = "/tmp/mmlspark_tpu_zoo"
    dl = ModelDownloader(local_cache=repo)
    dl.import_onnx("DemoNet", onnx_bytes, cut_layers=1)  # cut Gemm -> features
    payload = dl.download_by_name("DemoNet")             # pretrained weights
    print("zoo models:", [s.name for s in dl.repo.list_models()])

    rng = np.random.default_rng(0)
    raw = rng.uniform(0, 1, (8, 64, 64, 3)).astype(np.float32)
    imgs = np.empty(8, dtype=object)
    for i in range(8):
        imgs[i] = raw[i]
    df = DataFrame.from_dict({"image": imgs})
    feat = ImageFeaturizer(input_col="image", output_col="features",
                           height=64, width=64, auto_convert=False,
                           batch_size=8).set_model(payload=payload)
    got = np.stack(list(feat.transform(df).to_pandas()["features"]))
    with torch.no_grad():
        trunc = tnn.Sequential(conv, bn, tnn.ReLU(), tnn.AdaptiveAvgPool2d(1),
                               tnn.Flatten())
        want = trunc(torch.from_numpy(raw.transpose(0, 3, 1, 2))).numpy()
    err = float(np.abs(got - want).max())
    print(f"features {got.shape}, max |err| vs torch = {err:.2e}")
    assert err < 1e-4


if __name__ == "__main__":
    main()
