"""Spark-Serving-equivalent demo: deploy a fitted pipeline as a web service.

Mirrors the reference's serving quickstart (``docs/mmlspark-serving.md``):
train a model, wrap it in a request->reply pipeline, serve it continuously,
and measure request latency.
"""
import json
import time
import urllib.request

import numpy as np

from _common import setup


def main():
    setup()
    from mmlspark_tpu.core import DataFrame, Transformer
    from mmlspark_tpu.core.schema import vector_column
    from mmlspark_tpu.lightgbm import LightGBMClassifier
    from mmlspark_tpu.serving import PipelineServer

    rng = np.random.default_rng(0)
    X = rng.normal(size=(2000, 8))
    y = (X[:, 0] - X[:, 1] > 0).astype(float)
    model = LightGBMClassifier().set_params(num_iterations=30).fit(
        DataFrame.from_dict({"features": vector_column(list(X)), "label": y}))

    class RequestToReply(Transformer):
        """request {features: [...]} -> reply {probability: p}."""

        def _transform(self, df):
            feats = np.empty(df.count(), dtype=object)
            for i, r in enumerate(df.collect()["request"]):
                feats[i] = np.asarray(r["features"], np.float64)
            scored = model.transform(DataFrame([{"features": feats}]))
            probs = scored.collect()["probability"]
            out = np.empty(len(probs), dtype=object)
            for i, p in enumerate(probs):
                out[i] = {"probability": float(p[1])}
            return df.with_column("reply", lambda part: out)

    server = PipelineServer(RequestToReply(), mode="continuous", port=0).start()
    print(f"serving at {server.address}")

    # warm + latency probe
    def call(vec):
        req = urllib.request.Request(
            server.address, data=json.dumps({"features": vec}).encode(),
            headers={"Content-Type": "application/json"})
        return json.loads(urllib.request.urlopen(req, timeout=10).read())

    call(list(X[0]))
    lat = []
    for i in range(50):
        t0 = time.perf_counter()
        resp = call(list(X[i % len(X)]))
        lat.append(1000 * (time.perf_counter() - t0))
    lat = np.asarray(lat)
    stats = json.loads(urllib.request.urlopen(
        f"http://127.0.0.1:{server.port}/stats").read())
    print(f"latency p50={np.percentile(lat, 50):.2f}ms "
          f"p95={np.percentile(lat, 95):.2f}ms; server stats: {stats}")
    server.stop()


if __name__ == "__main__":
    main()
