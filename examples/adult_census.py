"""Classification - Adult Census.

Equivalent of the reference's ``Classification - Adult Census`` notebook:
select a handful of raw mixed-type census columns, let ``TrainClassifier``
auto-featurize them (string categoricals included), score, report
``ComputeModelStatistics``, and persist the trained model.  The remote
AdultCensusIncome.parquet is unreachable offline, so the frame is a
synthesized stand-in with the same columns and label semantics.
"""
import os
import tempfile

import numpy as np

from _common import setup

EDUCATION = ["HS-grad", "Some-college", "Bachelors", "Masters", "Doctorate",
             "11th"]
EDU_YEARS = {"11th": 7, "HS-grad": 9, "Some-college": 10, "Bachelors": 13,
             "Masters": 14, "Doctorate": 16}
MARITAL = ["Married-civ-spouse", "Never-married", "Divorced", "Widowed"]


def make_census(n=8000, seed=123):
    from mmlspark_tpu.core import DataFrame
    rng = np.random.default_rng(seed)
    education = rng.choice(EDUCATION, n)
    marital = rng.choice(MARITAL, n)
    hours = np.clip(rng.normal(40, 12, n), 1, 99).round()
    score = (np.array([EDU_YEARS[e] for e in education]) * 0.35
             + (marital == "Married-civ-spouse") * 2.0
             + (hours - 40) * 0.06 + rng.normal(scale=1.2, size=n))
    income = np.where(score > 5.8, ">50K", "<=50K").astype(object)
    return DataFrame.from_dict({
        "education": education.astype(object),
        "marital-status": marital.astype(object),
        "hours-per-week": hours.astype(float),
        "income": income}, num_partitions=4)


def main():
    setup()
    from mmlspark_tpu.core import load, save
    from mmlspark_tpu.train import ComputeModelStatistics, TrainClassifier
    from mmlspark_tpu.lightgbm import LightGBMClassifier

    data = make_census()
    train, test = data.random_split([0.75, 0.25], seed=123)
    print(f"train rows: {train.count()}, test rows: {test.count()}")

    # TrainClassifier auto-featurizes mixed types and string labels
    # (reference: TrainClassifier(model=LogisticRegression(), ...))
    model = TrainClassifier().set_params(
        model=LightGBMClassifier().set_params(num_iterations=40,
                                              min_data_in_leaf=5),
        label_col="income", number_of_features=256).fit(train)

    prediction = model.transform(test)
    cols = prediction.collect()
    y = np.asarray([v == ">50K" for v in cols["income"]], float)
    scored = prediction.with_column("label_num", y)
    metrics = ComputeModelStatistics().set_params(
        label_col="label_num", scores_col="prediction",
        evaluation_metric="classification").transform(scored).collect()
    acc = float(metrics["accuracy"][0])
    print(f"accuracy={acc:.3f} precision={float(metrics['precision'][0]):.3f} "
          f"recall={float(metrics['recall'][0]):.3f}")
    assert acc > 0.8, acc

    # model.write().overwrite().save("AdultCensus.mml") analogue
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "AdultCensus.mml")
        save(model, path)
        reloaded = load(path)
        pred2 = reloaded.transform(test).collect()["prediction"]
        assert np.array_equal(np.asarray(pred2),
                              np.asarray(cols["prediction"]))
        print("model save/load round trip OK")
    print("adult census OK")


if __name__ == "__main__":
    main()
