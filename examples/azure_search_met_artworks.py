"""AzureSearchIndex - Met Artworks.

Equivalent of the reference's ``AzureSearchIndex - Met Artworks`` notebook:
a frame of artworks (metadata + featurized embedding) is pushed into a
search index in batches via AzureSearchWriter, then queried.  The service
is a local in-process mock index (zero-egress analogue) honouring the same
``@search.action: mergeOrUpload`` document batch protocol.
"""
import json
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

import numpy as np

from _common import setup

INDEX = {}


class MockSearchIndex(BaseHTTPRequestHandler):
    def log_message(self, *a):
        pass

    def do_POST(self):
        body = json.loads(self.rfile.read(
            int(self.headers.get("Content-Length", 0))).decode())
        for doc in body.get("value", []):
            assert doc.pop("@search.action") == "mergeOrUpload"
            INDEX[doc["id"]] = doc
        out = json.dumps({"value": [{"status": True}]}).encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(out)))
        self.end_headers()
        self.wfile.write(out)


def main():
    setup()
    from mmlspark_tpu.cognitive import AzureSearchWriter
    from mmlspark_tpu.core import DataFrame

    rng = np.random.default_rng(0)
    cultures = ["dutch", "japanese", "egyptian"]
    n = 90
    ids = np.array([f"met_{i}" for i in range(n)], dtype=object)
    culture = np.array([cultures[i % 3] for i in range(n)], dtype=object)
    title = np.array([f"artwork {i}" for i in range(n)], dtype=object)
    embedding = np.empty(n, dtype=object)
    for i in range(n):
        embedding[i] = rng.normal(size=8).round(3).tolist()
    df = DataFrame.from_dict({"id": ids, "culture": culture, "title": title,
                              "embedding": embedding}, num_partitions=3)

    httpd = HTTPServer(("127.0.0.1", 0), MockSearchIndex)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        codes = AzureSearchWriter.write(
            df, "mock-svc", "artworks", "key",
            url_override=f"http://127.0.0.1:{httpd.server_port}/index")
        print(f"batch status codes: {codes}")
        assert all(c == 200 for c in codes)
        assert len(INDEX) == n
        doc = INDEX["met_42"]
        print("indexed doc:", {k: doc[k] for k in ("id", "culture", "title")})
        assert doc["culture"] == cultures[42 % 3]
        # a 'query': filter the indexed docs by culture facet
        dutch = [d for d in INDEX.values() if d["culture"] == "dutch"]
        print(f"dutch artworks in index: {len(dutch)}")
        assert len(dutch) == n // 3
        print("search index OK")
    finally:
        httpd.shutdown()
        httpd.server_close()


if __name__ == "__main__":
    main()
