"""Classification - Before and After MMLSpark.

Equivalent of the reference's ``Before and After`` notebook: the same
mixed-type classification problem solved twice — the "before" way (manual
indexing, assembling, threshold post-processing) and the "after" way (one
TrainClassifier wrapping a learner, auto-featurization included) — landing
on the same quality with a fraction of the code.
"""
import numpy as np

from _common import setup


def make_reviews(n=3000, seed=0):
    rng = np.random.default_rng(seed)
    rating = rng.integers(1, 6, n).astype(float)
    length = rng.integers(5, 400, n).astype(float)
    channel = rng.choice(["web", "mobile", "store"], n)
    boost = np.where(channel == "store", 0.8, 0.0)
    y = (rating + 0.002 * length + boost
         + rng.normal(scale=0.8, size=n) > 3.6).astype(float)
    return rating, length, channel, y


def main():
    setup()
    from mmlspark_tpu.core import DataFrame
    from mmlspark_tpu.core.schema import vector_column
    from mmlspark_tpu.featurize import ValueIndexer
    from mmlspark_tpu.lightgbm import LightGBMClassifier
    from mmlspark_tpu.train import TrainClassifier

    rating, length, channel, y = make_reviews()
    df = DataFrame.from_dict({"rating": rating, "length": length,
                              "channel": np.array(channel, dtype=object),
                              "label": y}, num_partitions=4)
    train, test = df.random_split([0.8, 0.2], seed=1)

    # ---- BEFORE: manual indexing + manual assembly + manual scoring
    vi = ValueIndexer().set_params(input_col="channel",
                                   output_col="channel_idx").fit(train)

    def assemble(frame):
        d = frame.collect()
        X = np.column_stack([d["rating"], d["length"], d["channel_idx"]])
        return DataFrame.from_dict({"features": vector_column(list(X)),
                                    "label": d["label"]})

    before_model = LightGBMClassifier().set_params(num_iterations=40) \
        .fit(assemble(vi.transform(train)))
    pred_b = before_model.transform(assemble(vi.transform(test))).collect()
    acc_before = float((pred_b["prediction"] == pred_b["label"]).mean())

    # ---- AFTER: one wrapped estimator, featurization automatic
    after = TrainClassifier(
        LightGBMClassifier().set_params(num_iterations=40),
        label_col="label").fit(train)
    pred_a = after.transform(test).collect()
    acc_after = float((np.asarray(pred_a["prediction"])
                       == np.asarray(pred_a["label"])).mean())

    print(f"before (manual): acc={acc_before:.3f}")
    print(f"after (TrainClassifier): acc={acc_after:.3f}")
    assert acc_after > 0.8 and acc_after > acc_before - 0.03
    print("before/after OK")


if __name__ == "__main__":
    main()
