"""VowpalWabbit - Twitter sentiment — sparse hashed text classification.

Equivalent of the reference's Twitter sentiment VW notebook (BASELINE.json
config 4): text -> VowpalWabbitFeaturizer (murmur hashing, host C++ kernel)
-> VowpalWabbitClassifier (adaptive/normalized SGD on TPU).
"""
import time

import numpy as np

from _common import setup

POSITIVE = ["love", "great", "awesome", "fantastic", "happy", "best", "cool"]
NEGATIVE = ["hate", "awful", "terrible", "worst", "sad", "angry", "broken"]
FILLER = ["the", "a", "today", "lol", "just", "really", "so", "this", "that",
          "phone", "game", "movie", "weather", "traffic"]


def make_tweets(n=20000, seed=0):
    from mmlspark_tpu.core import DataFrame
    rng = np.random.default_rng(seed)
    texts = np.empty(n, dtype=object)
    labels = np.zeros(n)
    for i in range(n):
        pos = rng.random() < 0.5
        words = list(rng.choice(FILLER, rng.integers(4, 10)))
        pool = POSITIVE if pos else NEGATIVE
        for _ in range(int(rng.integers(1, 3))):
            words.insert(int(rng.integers(0, len(words))), str(rng.choice(pool)))
        texts[i] = " ".join(words)
        labels[i] = float(pos)
    return DataFrame.from_dict({"text": texts, "label": labels}, num_partitions=8)


def main():
    setup()
    from mmlspark_tpu.vw import VowpalWabbitFeaturizer, VowpalWabbitClassifier

    df = make_tweets()
    feat = VowpalWabbitFeaturizer(input_cols=["text"], output_col="features",
                                  num_bits=18, string_split_cols=["text"])
    t0 = time.perf_counter()
    hashed = feat.transform(df)
    print(f"hashed {df.count()} tweets in {time.perf_counter() - t0:.2f}s")
    train, test = hashed.random_split([0.8, 0.2], seed=1)
    clf = VowpalWabbitClassifier().set_params(num_bits=18, num_passes=3,
                                              learning_rate=0.5)
    t0 = time.perf_counter()
    model = clf.fit(train)
    print(f"trained in {time.perf_counter() - t0:.2f}s; stats:")
    print(model.get_performance_statistics().to_pandas().head())
    out = model.transform(test).collect()
    acc = float((out["prediction"] == out["label"]).mean())
    print(f"test accuracy: {acc:.3f}")


if __name__ == "__main__":
    main()
