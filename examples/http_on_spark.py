"""HttpOnSpark - Working with Arbitrary Web APIs.

Equivalent of the reference's ``HttpOnSpark`` notebook: a column of data
flows through HTTP calls to an external service as part of the pipeline
(reference ``SimpleHTTPTransformer``), with error rows captured instead of
failing the job.  The web API here is a local mock (zero-egress analogue
of the notebook's public endpoint).
"""
import json
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

import numpy as np

from _common import setup


class SentimentAPI(BaseHTTPRequestHandler):
    """POST {'text': ...} -> {'sentiment': score} (toy lexicon)."""

    def log_message(self, *a):
        pass

    def do_POST(self):
        body = json.loads(self.rfile.read(
            int(self.headers.get("Content-Length", 0))).decode())
        if self.path == "/flaky" and "bad" in body.get("text", ""):
            self.send_response(500)
            self.end_headers()
            return
        pos = sum(w in body.get("text", "") for w in ("good", "great", "love"))
        neg = sum(w in body.get("text", "") for w in ("bad", "awful", "hate"))
        out = json.dumps({"sentiment": pos - neg}).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(out)))
        self.end_headers()
        self.wfile.write(out)


def main():
    setup()
    from mmlspark_tpu.core import DataFrame
    from mmlspark_tpu.io import SimpleHTTPTransformer

    httpd = HTTPServer(("127.0.0.1", 0), SentimentAPI)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{httpd.server_port}"
    try:
        texts = ["a good great film", "an awful bad mess", "love this",
                 "plain neutral prose"]
        col = np.array([{"text": t} for t in texts], dtype=object)
        df = DataFrame.from_dict({"data": col}, num_partitions=2)
        t = SimpleHTTPTransformer(input_col="data", output_col="scored",
                                  url=url + "/score")
        out = t.transform(df).collect()
        scores = [v["sentiment"] for v in out["scored"]]
        print("sentiments:", scores)
        assert scores == [2, -2, 1, 0]

        # error rows are captured per-row, not fatal
        t2 = SimpleHTTPTransformer(input_col="data", output_col="scored",
                                   url=url + "/flaky")
        out2 = t2.transform(df).collect()
        errs = [e is not None for e in out2["errors"]]
        print("error mask:", errs)
        assert errs == [False, True, False, False]
        assert out2["scored"][0]["sentiment"] == 2
        print("HTTP-on-frame OK")
    finally:
        httpd.shutdown()
        httpd.server_close()


if __name__ == "__main__":
    main()
