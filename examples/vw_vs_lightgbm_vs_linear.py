"""Regression - Vowpal Wabbit vs. LightGBM vs. Linear Regressor.

Equivalent of the reference's three-way regression comparison notebook:
the same flight-delay-style frame trained by VowpalWabbitRegressor,
LightGBMRegressor and a linear model (VW with adaptive updates off = plain
SGD), compared on held-out L2/MAE.
"""
import numpy as np

from _common import setup


def make_delays(n=6000, seed=0):
    rng = np.random.default_rng(seed)
    dep_hour = rng.uniform(0, 24, n)
    distance = rng.uniform(100, 2500, n)
    carrier_q = rng.normal(size=n)
    weather = rng.uniform(0, 1, n)
    delay = (4.0 * np.sin(dep_hour / 24 * 2 * np.pi) + 0.004 * distance
             + 6.0 * weather ** 2 + 2.0 * carrier_q
             + rng.normal(scale=1.5, size=n))
    # unit-ish scales: the plain-SGD baseline (adaptive off) diverges on
    # raw distances in the thousands, exactly like classic VW without
    # normalized updates
    X = np.column_stack([dep_hour / 24.0, distance / 1000.0, carrier_q,
                         weather]).astype(np.float64)
    return X, delay


def main():
    setup()
    from mmlspark_tpu.core import DataFrame
    from mmlspark_tpu.core.schema import vector_column
    from mmlspark_tpu.lightgbm import LightGBMRegressor
    from mmlspark_tpu.vw import VowpalWabbitRegressor
    from mmlspark_tpu.vw.featurizer import VowpalWabbitFeaturizer

    X, y = make_delays()
    cut = int(len(y) * 0.8)

    def dense(idx):
        return DataFrame.from_dict({"features": vector_column(list(X[idx])),
                                    "label": y[idx]}, num_partitions=2)

    def sparse(idx):
        cols = {f"f{j}": X[idx, j] for j in range(X.shape[1])}
        df = DataFrame.from_dict({**cols, "label": y[idx]}, num_partitions=2)
        return VowpalWabbitFeaturizer(
            input_cols=list(cols), output_col="features").transform(df)

    tr, te = np.arange(cut), np.arange(cut, len(y))
    results = {}

    lgb = LightGBMRegressor().set_params(num_iterations=80, num_leaves=31) \
        .fit(dense(tr))
    results["LightGBM"] = np.asarray(
        lgb.transform(dense(te)).collect()["prediction"])

    vw = VowpalWabbitRegressor().set_params(num_passes=12, num_bits=18) \
        .fit(sparse(tr))
    results["VowpalWabbit"] = np.asarray(
        vw.transform(sparse(te)).collect()["prediction"])

    lin = VowpalWabbitRegressor().set_params(num_passes=12, num_bits=18,
                                             adaptive=False).fit(sparse(tr))
    results["LinearSGD"] = np.asarray(
        lin.transform(sparse(te)).collect()["prediction"])

    yte = y[te]
    l2 = {}
    for name, pred in results.items():
        l2[name] = float(np.mean((pred - yte) ** 2))
        mae = float(np.mean(np.abs(pred - yte)))
        print(f"{name:>12}: L2={l2[name]:.3f}  MAE={mae:.3f}")
    # trees capture the nonlinearities the linear models cannot
    assert l2["LightGBM"] < l2["VowpalWabbit"]
    assert l2["LightGBM"] < l2["LinearSGD"]
    print("three-way regression comparison OK")


if __name__ == "__main__":
    main()
