"""Regression - Flight Delays with DataCleaning.

Equivalent of the reference's ``Regression - Flight Delays with
DataCleaning`` notebook: ``DataConversion(convertTo="double")`` repairs
integer-typed schedule columns, ``DataConversion(convertTo="toCategorical")``
recodes the string carrier/time-block columns, ``TrainRegressor`` fits
ArrDelay, and both ``ComputeModelStatistics`` and per-row
``ComputePerInstanceStatistics`` report quality.
"""
import numpy as np

from _common import setup

CARRIERS = ["AA", "DL", "UA", "WN", "B6"]
BLOCKS = ["0600-0659", "0900-0959", "1200-1259", "1700-1759", "2100-2159"]


def make_flights(n=6000, seed=0):
    from mmlspark_tpu.core import DataFrame
    rng = np.random.default_rng(seed)
    month = rng.integers(1, 13, n)
    day_of_week = rng.integers(1, 8, n)
    carrier = rng.choice(CARRIERS, n)
    dep_blk = rng.choice(BLOCKS, n)
    crs_dep = np.array([int(b[:4]) for b in dep_blk]) + rng.integers(0, 59, n)
    carrier_delay = {"AA": 4.0, "DL": 1.0, "UA": 6.0, "WN": 3.0, "B6": 9.0}
    evening = np.array([int(b[:4]) >= 1700 for b in dep_blk])
    arr_delay = (np.array([carrier_delay[c] for c in carrier])
                 + evening * 11.0 + (day_of_week >= 6) * -2.5
                 + rng.gamma(2.0, 4.0, n) - 6.0)
    return DataFrame.from_dict({
        "Month": month.astype(np.int32),          # integer-typed on purpose:
        "DayOfWeek": day_of_week.astype(np.int32),  # DataConversion repairs
        "CRSDepTime": crs_dep.astype(np.int32),
        "Carrier": carrier.astype(object),
        "DepTimeBlk": dep_blk.astype(object),
        "ArrDelay": arr_delay}, num_partitions=4)


def main():
    setup()
    from mmlspark_tpu.featurize import DataConversion
    from mmlspark_tpu.lightgbm import LightGBMRegressor
    from mmlspark_tpu.train import (ComputeModelStatistics,
                                    ComputePerInstanceStatistics,
                                    TrainRegressor)

    flights = make_flights()
    print(f"records read: {flights.count()}")

    # the notebook's first cleaning pass: int schedule columns -> double
    flights = DataConversion().set_params(
        cols=["Month", "DayOfWeek", "CRSDepTime"],
        convert_to="double").transform(flights)
    assert isinstance(flights.collect()["Month"][0], float)

    train, test = flights.random_split([0.75, 0.25], seed=42)

    # second cleaning pass: string columns -> categorical codes
    conv = DataConversion().set_params(cols=["Carrier", "DepTimeBlk"],
                                       convert_to="toCategorical")
    train_cat = conv.transform(train)
    test_cat = conv.transform(test)

    model = TrainRegressor().set_params(
        model=LightGBMRegressor().set_params(num_iterations=60,
                                             min_data_in_leaf=10),
        label_col="ArrDelay").fit(train_cat)
    scored = model.transform(test_cat)

    metrics = ComputeModelStatistics().set_params(
        evaluation_metric="regression", label_col="ArrDelay",
        scores_col="prediction").transform(scored).collect()
    mae = float(metrics["mean_absolute_error"][0])
    print(f"MAE={mae:.2f} RMSE={float(metrics['root_mean_squared_error'][0]):.2f}")

    per_row = ComputePerInstanceStatistics().set_params(
        label_col="ArrDelay", scores_col="prediction").transform(scored)
    cols = per_row.collect()
    assert {"L1_loss", "L2_loss"} <= set(cols)
    print("per-instance rows:",
          [(round(float(cols['L1_loss'][i]), 2),
            round(float(cols['L2_loss'][i]), 2)) for i in range(3)])

    # the model must beat predicting the training mean
    base_mae = float(np.mean(np.abs(
        np.asarray(test.collect()["ArrDelay"])
        - float(np.mean(train.collect()["ArrDelay"])))))
    print(f"baseline (mean) MAE={base_mae:.2f}")
    assert mae < base_mae - 1.0, (mae, base_mae)
    print("flight delays with data cleaning OK")


if __name__ == "__main__":
    main()
