"""DeepLearning - Transfer Learning — ResNet-50 featurize + LightGBM head.

Equivalent of the reference's ``DeepLearning - Transfer Learning`` notebook
(BASELINE.json config 3): CIFAR-like images -> ImageFeaturizer (truncated
ResNet-50) -> LightGBMClassifier on the embeddings.
"""
import time

import numpy as np

from _common import setup


def main():
    setup()
    from mmlspark_tpu.core import DataFrame
    from mmlspark_tpu.dl import ImageFeaturizer, ModelDownloader
    from mmlspark_tpu.lightgbm import LightGBMClassifier

    rng = np.random.default_rng(0)
    n, hw = 512, 32
    imgs = np.empty(n, dtype=object)
    labels = np.zeros(n)
    for i in range(n):
        cls = i % 2
        base = rng.uniform(0, 255, (hw, hw, 3)).astype(np.float32)
        if cls:
            base[:, :, 0] = np.clip(base[:, :, 0] * 1.6, 0, 255)  # red-shifted class
        imgs[i] = base
        labels[i] = cls
    df = DataFrame.from_dict({"image": imgs, "label": labels}, num_partitions=4)

    payload = ModelDownloader().download_by_name("ResNet50", num_classes=10)
    featurizer = ImageFeaturizer()
    featurizer.set("model", payload)
    featurizer.set_params(input_col="image", output_col="features",
                          height=64, width=64, batch_size=64)
    t0 = time.perf_counter()
    feats = featurizer.transform(df)
    dt = time.perf_counter() - t0
    print(f"featurized {n} images in {dt:.2f}s -> {n / dt:.1f} images/s")

    train, test = feats.random_split([0.8, 0.2], seed=1)
    model = LightGBMClassifier().set_params(num_iterations=50,
                                            min_data_in_leaf=5).fit(train)
    pred = model.transform(test).collect()
    acc = float((pred["prediction"] == pred["label"]).mean())
    print(f"transfer-learning accuracy: {acc:.3f}")


if __name__ == "__main__":
    main()
