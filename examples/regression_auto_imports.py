"""Regression - Auto Imports (with data cleaning).

Equivalent of the reference's ``Regression - Auto Imports`` /
``Flight Delays with DataCleaning`` notebooks: a messy mixed-type frame
(missing numerics, string categoricals, wrong dtypes) is repaired with
SummarizeData -> DataConversion -> CleanMissingData -> ValueIndexer, then
TrainRegressor fits price, scored with ComputeModelStatistics.
"""
import numpy as np

from _common import setup


def make_autos(n=1500, seed=0):
    rng = np.random.default_rng(seed)
    hp = rng.uniform(48, 288, n)
    curb = rng.uniform(1500, 4000, n)
    mpg = rng.uniform(13, 49, n)
    make = rng.choice(["toyota", "bmw", "mazda", "volvo"], n)
    prestige = {"toyota": 0.0, "mazda": 0.0, "volvo": 3000.0, "bmw": 9000.0}
    price = (80 * hp + 3.2 * curb - 120 * mpg
             + np.array([prestige[m] for m in make])
             + rng.normal(scale=900, size=n))
    hp[rng.random(n) < 0.08] = np.nan          # missing horsepower
    hp_str = np.array([f"{v:.1f}" if np.isfinite(v) else "?" for v in hp],
                      dtype=object)            # ...and stored as strings
    return hp_str, curb, mpg, make, price


def main():
    setup()
    from mmlspark_tpu.core import DataFrame
    from mmlspark_tpu.featurize import (CleanMissingData, DataConversion,
                                        ValueIndexer)
    from mmlspark_tpu.lightgbm import LightGBMRegressor
    from mmlspark_tpu.stages import SummarizeData
    from mmlspark_tpu.train import ComputeModelStatistics, TrainRegressor

    hp_str, curb, mpg, make, price = make_autos()
    df = DataFrame.from_dict({
        "horsepower": hp_str, "curb_weight": curb, "city_mpg": mpg,
        "make": np.array(make, dtype=object), "price": price},
        num_partitions=3)

    # the notebook's first move: eyeball the damage
    summary = SummarizeData().transform(df).collect()
    print("summary columns:", list(summary)[:6])

    conv = DataConversion().set_params(cols=["horsepower"],
                                       convert_to="double")
    df2 = conv.transform(df)
    assert np.isnan(np.asarray(df2.collect()["horsepower"], float)).any()

    clean = CleanMissingData().set_params(input_cols=["horsepower"],
                                          cleaning_mode="Median").fit(df2)
    df3 = clean.transform(df2)
    assert not np.isnan(np.asarray(df3.collect()["horsepower"], float)).any()

    vi = ValueIndexer().set_params(input_col="make",
                                   output_col="make_idx").fit(df3)
    df4 = vi.transform(df3).drop("make")

    train, test = df4.random_split([0.8, 0.2], seed=1)
    model = TrainRegressor(
        LightGBMRegressor().set_params(num_iterations=80, num_leaves=31),
        label_col="price").fit(train)
    scored = model.transform(test)
    stats = ComputeModelStatistics().set_params(
        label_col="price", scores_col="prediction",
        evaluation_metric="regression").transform(scored).collect()
    r2 = float(stats["R^2"][0])
    print({k: round(float(v[0]), 3) for k, v in stats.items()})
    assert r2 > 0.9, r2
    print("auto imports regression OK")


if __name__ == "__main__":
    main()
