"""Interpretability - Image Explainers — ImageLIME + ImageSHAP.

Equivalent of the reference's ``Interpretability - Image Explainers``
notebook: images -> a classifier -> superpixel LIME and KernelSHAP weight
maps over the same superpixels.  Images are synthetic two-class frames
(bright patch in one quadrant) so the expected attribution is known.
"""
import numpy as np

from _common import setup


def make_images(n=64, hw=32, seed=0):
    rng = np.random.default_rng(seed)
    imgs = np.empty(n, dtype=object)
    labels = np.zeros(n)
    for i in range(n):
        img = rng.uniform(0, 60, (hw, hw, 3)).astype(np.float32)
        if i % 2:  # class 1: bright top-left patch
            img[: hw // 2, : hw // 2] += 160.0
            labels[i] = 1.0
        imgs[i] = img
    return imgs, labels


def main():
    setup()
    from mmlspark_tpu.core import DataFrame, Transformer
    from mmlspark_tpu.explainers import LocalExplainer

    imgs, labels = make_images()
    df = DataFrame.from_dict({"image": imgs, "label": labels},
                             num_partitions=2)

    class PatchModel(Transformer):
        """Stand-in classifier: P(class1) from top-left brightness (the
        notebook uses a pretrained network; the explainer contract is
        identical)."""

        def _transform(self, frame):
            def per_part(p):
                out = np.empty(len(p["image"]), dtype=object)
                for i, v in enumerate(p["image"]):
                    a = np.asarray(v, float)
                    q = a[: a.shape[0] // 2, : a.shape[1] // 2].mean() / 255.0
                    pr = 1 / (1 + np.exp(-10 * (q - 0.35)))
                    out[i] = np.asarray([1 - pr, pr])
                return {**p, "probability": out}
            return frame.map_partitions(per_part)

    model = PatchModel()
    one = df.limit(2)

    lime = LocalExplainer.LIME.image(
        model=model, input_col="image", output_col="weights",
        target_col="probability", target_classes=[1], num_samples=60,
        cell_size=8.0)
    lime_out = lime.transform(one).collect()

    shap = LocalExplainer.KernelSHAP.image(
        model=model, input_col="image", output_col="shap",
        target_col="probability", target_classes=[1], num_samples=60,
        cell_size=8.0)
    shap_out = shap.transform(one).collect()

    for name, out, col in (("LIME", lime_out, "weights"),
                           ("SHAP", shap_out, "shap")):
        segs = out["superpixels"][1]
        w = np.asarray(out[col][1], float)
        # attribution mass inside the bright quadrant must dominate
        hw = segs.shape[0]
        tl_segs = np.unique(segs[: hw // 2, : hw // 2])
        inside = np.abs(w[tl_segs]).sum()
        total = np.abs(w).sum() + 1e-12
        print(f"{name}: {len(w)} superpixels, top-left attribution share "
              f"{inside / total:.2f}")
        assert inside / total > 0.5, name
    print("image explainers OK")


if __name__ == "__main__":
    main()
