"""Shared example setup: run on the real TPU when present, else a CPU mesh."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def setup(force_cpu: bool = False):
    if force_cpu or os.environ.get("MMLSPARK_TPU_EXAMPLES_CPU"):
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", 8)
    import jax
    print(f"devices: {jax.devices()}")
