"""Exploring Art across Cultures — ConditionalKNN over embeddings.

Equivalent of the reference's ``ConditionalKNN - Exploring Art Across
Cultures`` notebook: artwork embeddings + (culture, medium) labels ->
ConditionalKNN, querying nearest works CONDITIONED on a target culture set
— the ball-tree prunes by label before distance (reference
``ConditionalBallTree.findMaximumInnerProducts``).
"""
import numpy as np

from _common import setup

CULTURES = ["dutch", "japanese", "egyptian", "french"]


def make_art(n_per=120, d=48, seed=0):
    """Per-culture Gaussian clusters in embedding space + a shared 'style'
    direction so cross-culture neighbours exist."""
    rng = np.random.default_rng(seed)
    X, culture, title = [], [], []
    for ci, c in enumerate(CULTURES):
        center = rng.normal(size=d) * 2.0
        for j in range(n_per):
            X.append(center + rng.normal(scale=0.7, size=d))
            culture.append(c)
            title.append(f"{c}_{j}")
    return np.asarray(X, np.float32), culture, title


def main():
    setup()
    from mmlspark_tpu.core import DataFrame
    from mmlspark_tpu.core.schema import vector_column
    from mmlspark_tpu.nn import ConditionalKNN

    X, culture, title = make_art()
    df = DataFrame.from_dict({
        "features": vector_column(list(X)),
        "values": np.array(title, dtype=object),
        "labels": np.array(culture, dtype=object)}, num_partitions=4)

    knn = ConditionalKNN().set_params(k=5, leaf_size=20,
                                      output_col="matches")
    model = knn.fit(df)

    # query: a dutch work, but ask for matches among japanese+egyptian only
    q = X[:3]
    cond = np.empty(3, dtype=object)
    for i in range(3):
        cond[i] = ["japanese", "egyptian"]
    qdf = DataFrame.from_dict({"features": vector_column(list(q)),
                               "conditioner": cond})
    out = model.transform(qdf).collect()["matches"]
    for i, matches in enumerate(out):
        got = {m["label"] for m in matches}
        print(f"query {i}: {len(matches)} matches, cultures={sorted(got)}")
        assert got <= {"japanese", "egyptian"}, got
        assert len(matches) == 5

    # unconditioned: same-culture works dominate the neighbourhood
    qdf2 = DataFrame.from_dict({"features": vector_column(list(q))})
    out2 = model.transform(qdf2).collect()["matches"]
    same = sum(m["label"] == "dutch" for ms in out2 for m in ms)
    print(f"unconditioned: {same}/15 matches are dutch")
    assert same >= 12
    print("conditional KNN OK")


if __name__ == "__main__":
    main()
