"""OpenCV - Pipeline Image Transformations.

Equivalent of the reference's ``OpenCV - Pipeline Image Transformations``
notebook: a frame of images flows through a chained ImageTransformer
(resize -> blur -> flip -> normalize), the augmenter doubles the set with
mirrored copies, and the unrolled vectors feed a downstream learner — all
as ONE jitted device chain per partition.
"""
import numpy as np

from _common import setup


def main():
    setup()
    from mmlspark_tpu.core import DataFrame
    from mmlspark_tpu.lightgbm import LightGBMClassifier
    from mmlspark_tpu.opencv import ImageSetAugmenter, ImageTransformer

    rng = np.random.default_rng(0)
    n, hw = 200, 24
    col = np.empty(n, dtype=object)
    labels = np.zeros(n)
    for i in range(n):
        img = rng.uniform(0, 200, (hw, hw, 3)).astype(np.float32)
        if i % 2:
            img[:, : hw // 2] += 55.0  # left-bright class
            labels[i] = 1.0
        col[i] = np.clip(img, 0, 255)
    df = DataFrame.from_dict({"image": col, "label": labels},
                             num_partitions=4)

    chain = ImageTransformer(input_col="image", output_col="proc") \
        .resize(16, 16).blur(3, 3, 1.0).normalize()
    processed = chain.transform(df)
    sample = processed.collect()["proc"][0]
    print(f"processed shape: {sample.shape}")
    assert sample.shape == (16, 16, 3)

    aug = ImageSetAugmenter().set_params(input_col="image", output_col="image")
    doubled = aug.transform(df)
    print(f"augmented rows: {doubled.count()} (from {df.count()})")
    assert doubled.count() == 2 * df.count()

    vec = ImageTransformer(input_col="image", output_col="features") \
        .resize(12, 12).unroll()
    feats = vec.transform(df)
    model = LightGBMClassifier().set_params(num_iterations=30, num_leaves=7,
                                            min_data_in_leaf=5).fit(feats)
    pred = model.transform(feats).collect()
    acc = float((np.asarray(pred["prediction"]) == labels).mean())
    print(f"downstream accuracy on unrolled pixels: {acc:.3f}")
    assert acc > 0.9, acc
    print("opencv pipeline OK")


if __name__ == "__main__":
    main()
