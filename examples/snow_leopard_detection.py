"""Snow Leopard Detection — featurize -> LightGBM -> LIME, one pipeline.

Equivalent of the reference's ``ModelInterpretation - Snow Leopard
Detection`` notebook: camera-trap-style images -> ImageFeaturizer (truncated
ResNet) -> LightGBMClassifier on embeddings -> ImageLIME over the SAME
fitted pipeline to localise what the model keys on.  This exercises stage
*interplay*: the LIME model under explanation is the composed
featurizer+classifier pipeline, not a toy scorer.
"""
import numpy as np

from _common import setup


def make_camera_traps(n=96, hw=32, seed=0):
    """Class 1 ('leopard') = bright high-contrast rosette blob in the centre
    region; class 0 = plain rocky background."""
    rng = np.random.default_rng(seed)
    imgs = np.empty(n, dtype=object)
    labels = np.zeros(n)
    for i in range(n):
        img = rng.uniform(40, 90, (hw, hw, 3)).astype(np.float32)
        if i % 2:
            cx, cy = rng.integers(10, hw - 10, 2)
            img[cx - 6: cx + 6, cy - 6: cy + 6] += \
                rng.uniform(90, 150, (12, 12, 3)).astype(np.float32)
            labels[i] = 1.0
        imgs[i] = np.clip(img, 0, 255)
    return imgs, labels


def main():
    setup()
    from mmlspark_tpu.core import DataFrame, Transformer
    from mmlspark_tpu.dl import ImageFeaturizer, ModelDownloader
    from mmlspark_tpu.explainers import LocalExplainer
    from mmlspark_tpu.lightgbm import LightGBMClassifier

    imgs, labels = make_camera_traps()
    df = DataFrame.from_dict({"image": imgs, "label": labels},
                             num_partitions=2)

    payload = ModelDownloader().download_by_name("ResNet18", num_classes=10)
    featurizer = ImageFeaturizer()
    featurizer.set("model", payload)
    featurizer.set_params(input_col="image", output_col="features",
                          height=32, width=32, batch_size=32)

    feats = featurizer.transform(df)
    clf = LightGBMClassifier().set_params(num_iterations=40, num_leaves=7,
                                          min_data_in_leaf=5,
                                          probability_col="probability")
    fitted = clf.fit(feats)
    scored = fitted.transform(feats).collect()
    acc = float((np.asarray(scored["prediction"]) == labels).mean())
    print(f"train accuracy on embeddings: {acc:.3f}")
    assert acc > 0.9, acc

    class Pipeline(Transformer):
        """featurize -> classify as ONE model: what LIME perturbs."""

        def _transform(self, frame):
            return fitted.transform(featurizer.transform(frame))

    leopard_rows = df.limit(2)
    lime = LocalExplainer.LIME.image(
        model=Pipeline(), input_col="image", output_col="weights",
        target_col="probability", target_classes=[1], num_samples=80,
        cell_size=8.0, regularization=0.0005)
    out = lime.transform(leopard_rows).collect()
    w = np.asarray(out["weights"][1], float)  # row 1 is a leopard frame
    segs = out["superpixels"][1]
    print(f"LIME over {len(w)} superpixels; strongest={np.abs(w).max():.4f}")
    assert len(w) == segs.max() + 1
    assert np.abs(w).max() > 0, "attribution must be non-degenerate"
    print("snow leopard composite pipeline OK")


if __name__ == "__main__":
    main()
