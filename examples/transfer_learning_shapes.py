"""Transfer learning with the COMMITTED trained backbone (ShapesResNet20).

The reference ships pretrained artifacts through ModelDownloader
(``downloader/ModelDownloader.scala:26-112``) and its transfer notebooks
probe frozen features.  This example loads the repo's genuinely-trained
checkpoint (``artifacts/model_repo/ShapesResNet20`` — trained in-tree by
``tools/train_backbone.py`` on the procedural shapes corpus) and runs the
committed transfer protocol on REAL data: UCI digit scans placed at random
position/scale on a 32x32 canvas; a logistic probe on the frozen pooled
features must beat the same probe on raw pixels by a stated margin — the
translation robustness a conv backbone is supposed to transfer.
"""
import os

import numpy as np

from _common import setup

MARGIN = 0.03   # stated margin: frozen features must beat raw pixels by >=3pts


def main():
    setup()
    import jax.numpy as jnp
    from sklearn.linear_model import LogisticRegression

    from mmlspark_tpu.dl import ModelDownloader
    from mmlspark_tpu.dl.procedural_shapes import digits_as_images

    repo = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "artifacts", "model_repo")
    assert os.path.isdir(os.path.join(repo, "ShapesResNet20")), (
        "trained artifact missing — run tools/train_backbone.py")
    payload = ModelDownloader(local_cache=repo).download_by_name("ShapesResNet20")

    Xd, yd = digits_as_images(jitter=True)
    feats = np.concatenate([
        np.asarray(payload.module.apply(payload.variables,
                                        jnp.asarray(Xd[a:a + 512]),
                                        features=True))
        for a in range(0, len(Xd), 512)])

    rng = np.random.default_rng(7)
    order = rng.permutation(len(yd))
    cut = int(len(yd) * 0.7)
    tr, te = order[:cut], order[cut:]

    probe = LogisticRegression(max_iter=2000).fit(feats[tr], yd[tr])
    transfer_acc = probe.score(feats[te], yd[te])
    raw = Xd.reshape(len(Xd), -1)
    raw_acc = LogisticRegression(max_iter=2000).fit(raw[tr], yd[tr]) \
        .score(raw[te], yd[te])

    print(f"jittered-digits probe: frozen features {transfer_acc:.3f} "
          f"vs raw pixels {raw_acc:.3f}")
    assert transfer_acc >= raw_acc + MARGIN, (
        f"transfer lift below stated margin: {transfer_acc:.3f} vs "
        f"{raw_acc:.3f} + {MARGIN}")
    print(f"transfer lift {100 * (transfer_acc - raw_acc):.1f}pts >= "
          f"{100 * MARGIN:.0f}pts  OK")


if __name__ == "__main__":
    main()
