"""Streaming speech transcription demo — the SpeechToTextSDK equivalent.

Mirrors the reference's speech notebooks: transcribe a wav column with
incremental hypotheses, attribute speakers in a conversation, and stream a
live session chunk-by-chunk through the serving engine.
"""
import json
import urllib.request

import numpy as np

from _common import setup


def tone(freq, seconds, sr=16000):
    t = np.arange(int(seconds * sr)) / sr
    return (0.4 * np.sin(2 * np.pi * freq * t)).astype(np.float32)


def main():
    setup(force_cpu=True)  # host-latency demo; chip not needed
    from mmlspark_tpu.core import DataFrame
    from mmlspark_tpu.cognitive import (ConversationTranscription,
                                        SpeechServingModel, SpeechToTextSDK,
                                        StreamingRecognizer)
    from mmlspark_tpu.io.audio import write_wav
    from mmlspark_tpu.serving import PipelineServer

    # 1. batch transcription over a wav column
    wavs = np.empty(2, dtype=object)
    wavs[0] = write_wav(np.concatenate([tone(220, 0.5), tone(880, 0.5)]), 16000)
    wavs[1] = write_wav(tone(440, 0.4), 16000)
    df = DataFrame.from_dict({"audio": wavs})
    stt = SpeechToTextSDK(input_col="audio", output_col="events", chunk_s=0.25)
    out = stt.transform(df).collect()
    for ev in out["events"][0]:
        print(f"  [{ev['status']:11s}] t={ev['offset']:.2f}s "
              f"text={ev['text']!r}")

    # 2. conversation transcription: speaker turns
    conv = np.empty(1, dtype=object)
    conv[0] = write_wav(np.concatenate([tone(150, 1.0), tone(3000, 1.0)]), 16000)
    ct = ConversationTranscription(input_col="audio", output_col="events",
                                   chunk_s=0.25)
    events = ct.transform(DataFrame.from_dict({"audio": conv})).collect()["events"][0]
    print("speaker turns:", [e["speaker"] for e in events])

    # 3. live session through the serving engine
    model = SpeechServingModel(StreamingRecognizer(chunk_s=0.2))
    srv = PipelineServer(model, port=0).start()
    audio = tone(660, 0.8)
    cs = model.recognizer.chunk_samples
    for i in range(0, len(audio), cs):
        body = json.dumps({"session": "live",
                           "chunk": audio[i:i + cs].tolist()}).encode()
        req = urllib.request.Request(srv.address, data=body,
                                     headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=15) as r:
            print("  live:", json.loads(r.read().decode())["status"])
    req = urllib.request.Request(
        srv.address, data=json.dumps({"session": "live", "final": True}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=15) as r:
        print("  final:", json.loads(r.read().decode())["status"])
    srv.stop()


if __name__ == "__main__":
    main()
