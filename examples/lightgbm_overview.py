"""LightGBM Overview — Adult-Census-style binary classification.

Equivalent of the reference's ``LightGBM - Overview`` notebook
(BASELINE.json config 1): mixed-type tabular frame -> TrainClassifier with a
LightGBMClassifier -> metrics.  Data is a seeded synthetic stand-in with the
Adult Census shape (offline environment).
"""
import numpy as np

from _common import setup


def make_adult_like(n=20000, seed=0):
    from mmlspark_tpu.core import DataFrame
    rng = np.random.default_rng(seed)
    age = rng.uniform(17, 90, n)
    hours = rng.uniform(1, 99, n)
    edu_num = rng.integers(1, 16, n).astype(float)
    workclass = rng.choice(["Private", "Self-emp", "Gov", "Other"], n)
    occupation = rng.choice(["Tech", "Craft", "Sales", "Exec", "Service"], n)
    logit = (0.04 * (age - 38) + 0.05 * (hours - 40) + 0.3 * (edu_num - 9)
             + (occupation == "Exec") * 0.8 + rng.logistic(scale=0.7, size=n))
    income = np.where(logit > 0.5, ">50K", "<=50K")
    return DataFrame.from_dict({
        "age": age, "hours_per_week": hours, "education_num": edu_num,
        "workclass": np.array(workclass, dtype=object),
        "occupation": np.array(occupation, dtype=object),
        "income": np.array(income, dtype=object),
    }, num_partitions=8)


def main():
    setup()
    import time
    from mmlspark_tpu.lightgbm import LightGBMClassifier
    from mmlspark_tpu.train import TrainClassifier, ComputeModelStatistics

    df = make_adult_like()
    train, test = df.random_split([0.85, 0.15], seed=1)
    clf = TrainClassifier(
        LightGBMClassifier().set_params(num_iterations=100, learning_rate=0.1,
                                        num_leaves=31),
        label_col="income")
    t0 = time.perf_counter()
    model = clf.fit(train)
    print(f"fit: {time.perf_counter() - t0:.2f}s "
          f"({train.count() / (time.perf_counter() - t0):.0f} rows/s end-to-end)")
    scored = model.transform(test)
    y = np.asarray([v == ">50K" for v in scored.collect()["income"]], float)
    scored = scored.with_column("label_num", y)
    stats = ComputeModelStatistics().set_params(
        label_col="label_num", scores_col="prediction",
        evaluation_metric="classification").transform(scored)
    print({k: v[0] for k, v in stats.collect().items() if k != "confusion_matrix"})


if __name__ == "__main__":
    main()
