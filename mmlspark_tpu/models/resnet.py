"""ResNet family in flax — the ImageFeaturizer backbone.

Reference capability: ``deep-learning/.../ImageFeaturizer.scala`` featurizes
images with a pretrained CNN whose head is truncated (``cutOutputLayers``).
The reference evaluates CNTK graphs; here the models are native flax modules
jit-compiled onto the TPU's MXU (NHWC layout, bf16-friendly), and "layer
cutting" is expressed by requesting intermediate outputs.

No pretrained weights ship in this environment (zero egress); weights are
randomly initialised or loaded from a local checkpoint via
``mmlspark_tpu.dl.ModelDownloader``.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class BottleneckBlock(nn.Module):
    filters: int
    strides: Tuple[int, int] = (1, 1)
    conv: ModuleDef = nn.Conv
    norm: ModuleDef = nn.BatchNorm

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1), use_bias=False)(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), self.strides, use_bias=False)(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters * 4, (1, 1), use_bias=False)(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1), self.strides,
                                 use_bias=False, name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class BasicBlock(nn.Module):
    filters: int
    strides: Tuple[int, int] = (1, 1)
    conv: ModuleDef = nn.Conv
    norm: ModuleDef = nn.BatchNorm

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides, use_bias=False)(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), use_bias=False)(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), self.strides,
                                 use_bias=False, name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    """NHWC ResNet.  ``__call__`` returns logits; ``features=True`` returns the
    pooled penultimate embedding (the featurizer path, = cutOutputLayers=1)."""

    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.float32
    cifar_stem: bool = False   # 3x3/1 stem, no maxpool (32x32-scale inputs)

    @nn.compact
    def __call__(self, x, train: bool = False, features: bool = False):
        conv = partial(nn.Conv, dtype=self.dtype)
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype)
        x = x.astype(self.dtype)
        if self.cifar_stem:
            x = conv(self.num_filters, (3, 3), (1, 1), padding=[(1, 1), (1, 1)],
                     use_bias=False, name="conv_init")(x)
        else:
            x = conv(self.num_filters, (7, 7), (2, 2),
                     padding=[(3, 3), (3, 3)], use_bias=False,
                     name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = nn.relu(x)
        if not self.cifar_stem:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(self.num_filters * 2 ** i, strides=strides,
                                   conv=conv, norm=norm)(x)
        x = jnp.mean(x, axis=(1, 2))  # global average pool -> (N, C)
        if features:
            return x.astype(jnp.float32)
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="head")(x)
        return x.astype(jnp.float32)


def resnet18(num_classes: int = 1000, dtype=jnp.float32) -> ResNet:
    return ResNet([2, 2, 2, 2], BasicBlock, num_classes, dtype=dtype)


def resnet34(num_classes: int = 1000, dtype=jnp.float32) -> ResNet:
    return ResNet([3, 4, 6, 3], BasicBlock, num_classes, dtype=dtype)


def resnet50(num_classes: int = 1000, dtype=jnp.float32) -> ResNet:
    return ResNet([3, 4, 6, 3], BottleneckBlock, num_classes, dtype=dtype)


def resnet101(num_classes: int = 1000, dtype=jnp.float32) -> ResNet:
    return ResNet([3, 4, 23, 3], BottleneckBlock, num_classes, dtype=dtype)


def cifar_resnet20(num_classes: int = 10, width: int = 32,
                   dtype=jnp.float32) -> ResNet:
    """CIFAR-scale ResNet-20 (He et al. §4.2 topology: 3 stages x 3 basic
    blocks, 3x3 stem, no maxpool) — the trainable-in-this-container backbone
    behind the committed model-repo checkpoint (ModelDownloader.scala:112
    ships pretrained artifacts; zero egress means ours is trained in-tree)."""
    return ResNet([3, 3, 3], BasicBlock, num_classes, num_filters=width,
                  cifar_stem=True, dtype=dtype)
