from .resnet import ResNet, resnet18, resnet34, resnet50, resnet101
from .bilstm import BiLSTMTagger, LSTMLayer

__all__ = ["ResNet", "resnet18", "resnet34", "resnet50", "resnet101",
           "BiLSTMTagger", "LSTMLayer"]
