from .resnet import ResNet, resnet18, resnet34, resnet50, resnet101
from .bilstm import BiLSTMTagger, LSTMLayer
from .transformer import TransformerEncoder, EncoderBlock, MultiHeadAttention
from .gbdt import GBDTBooster
from .runner import (ModelRunner, DecodeResult, PagePool,
                     ContinuousDecoder, StreamHandle, PagePoolExhausted,
                     SlotsExhausted, ShedReply, bucket_rows)

__all__ = ["ResNet", "resnet18", "resnet34", "resnet50", "resnet101",
           "BiLSTMTagger", "LSTMLayer", "TransformerEncoder", "EncoderBlock",
           "MultiHeadAttention", "GBDTBooster", "ModelRunner", "DecodeResult",
           "PagePool", "ContinuousDecoder", "StreamHandle",
           "PagePoolExhausted", "SlotsExhausted", "ShedReply",
           "bucket_rows"]
