"""GBDT booster artifact — trees as dense arrays, prediction as jitted gathers.

Reference: ``lightgbm/.../booster/LightGBMBooster.scala`` (JNI handle around a
native model: ``score:390``, ``predictLeaf:403``, ``featuresShap:414``,
``saveNativeModel:454``, ``getFeatureImportances:491``, ``mergeBooster:252``).

TPU-native redesign: a booster is a *pytree of fixed-shape arrays* — every
tree is an array-of-nodes with explicit child pointers, sized for
``num_leaves`` leaves and ``num_leaves - 1`` internal nodes.  This holds
LightGBM's leaf-wise (best-first) trees exactly (non-perfect shapes, nodes
in creation order) and level-wise perfect trees as the special case where
children follow BFS order.  Prediction is a vectorised pointer-chase:
``vmap`` over trees, ``lax.fori_loop`` over a static depth bound — leaves
self-loop, so no recursion or dynamic shapes, and XLA tiles the gathers
onto the VPU and fuses the final reduction.

Indexing: ``left_child[i] >= 0`` is an internal-node index; negative values
encode leaves as ``~leaf_id`` (LightGBM's own convention).  ``max_depth`` is
the walk bound: the deepest internal-node chain over all trees.  Multiclass
stores trees round-robin: tree t scores class t % num_class (LightGBM
convention).
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

import numpy as np

from ..core.serialize import Saveable

OBJECTIVES = ("regression", "regression_l1", "huber", "quantile", "binary",
              "multiclass", "lambdarank")


def _sigmoid(z):
    return 1.0 / (1.0 + np.exp(-z))


def perfect_tree_children(max_depth: int) -> tuple:
    """(left, right) child arrays of a perfect depth-D tree in BFS order:
    children of internal node i at 2i+1 / 2i+2; positions >= 2^D - 1 are
    leaves encoded ``~leaf_id``.  Level-wise trees and pre-round-3 saved
    artifacts (which had no child arrays) use exactly this layout."""
    I = 2 ** max_depth - 1
    lc = np.empty(I, np.int32)
    rc = np.empty(I, np.int32)
    for i in range(I):
        l, r = 2 * i + 1, 2 * i + 2
        lc[i] = l if l < I else ~(l - I)
        rc[i] = r if r < I else ~(r - I)
    return lc, rc


def children_depth_bound(left_child: np.ndarray, right_child: np.ndarray) -> int:
    """Longest internal-node chain over (T, M) child arrays — the static
    iteration count prediction walks need.  Child internal indices always
    exceed the parent's (creation order), so one forward pass suffices."""
    lc = np.asarray(left_child)
    rc = np.asarray(right_child)
    if lc.ndim == 1:
        lc, rc = lc[None], rc[None]
    T, M = lc.shape
    d = np.ones((T, M), np.int32)
    for i in range(M):
        for child in (lc[:, i], rc[:, i]):
            internal = child >= 0
            rows = np.nonzero(internal)[0]
            d[rows, child[rows]] = np.maximum(d[rows, child[rows]],
                                              d[rows, i] + 1)
    return int(d.max()) if M else 1


class GBDTBooster(Saveable):
    """Immutable fitted booster.  T trees, M = num_leaves - 1 internal node
    slots, L = num_leaves leaf slots.  Arrays:

    - left_child:    (T, M) int32 child pointer (>=0 internal, <0 = ~leaf_id)
    - right_child:   (T, M) int32
    - split_feature: (T, M) int32, -1 where the node doesn't split
    - threshold:     (T, M) float32 raw-value threshold (x <= thr goes left)
    - threshold_bin: (T, M) int32 binned threshold (bin <= t goes left)
    - split_gain:    (T, M) float32
    - internal_value:(T, M) float32 (-G/(H+l2) at the node; Saabas contribs)
    - internal_count:(T, M) float32 row counts
    - leaf_value:    (T, L) float32
    - leaf_count:    (T, L) float32
    - tree_weight:   (T,)   float32 (DART/RF weights; 1.0 for gbdt/goss)
    """

    def __init__(self, split_feature, threshold, threshold_bin, split_gain,
                 internal_value, internal_count, leaf_value, leaf_count,
                 tree_weight, *, max_depth: int, num_features: int,
                 objective: str = "regression", num_class: int = 1,
                 init_score: float = 0.0, average_output: bool = False,
                 feature_names: Optional[List[str]] = None,
                 best_iteration: int = -1, sigmoid: float = 1.0,
                 categorical_features: Optional[List[int]] = None,
                 left_child=None, right_child=None, cat_bitset=None):
        self.split_feature = np.asarray(split_feature, np.int32)
        if left_child is None:  # pre-round-3 artifact: perfect depth-D tree
            lc1, rc1 = perfect_tree_children(int(max_depth))
            T = self.split_feature.shape[0]
            left_child = np.tile(lc1, (T, 1))
            right_child = np.tile(rc1, (T, 1))
        self.left_child = np.asarray(left_child, np.int32)
        self.right_child = np.asarray(right_child, np.int32)
        self.threshold = np.asarray(threshold, np.float32)
        self.threshold_bin = np.asarray(threshold_bin, np.int32)
        self.split_gain = np.asarray(split_gain, np.float32)
        self.internal_value = np.asarray(internal_value, np.float32)
        self.internal_count = np.asarray(internal_count, np.float32)
        self.leaf_value = np.asarray(leaf_value, np.float32)
        self.leaf_count = np.asarray(leaf_count, np.float32)
        self.tree_weight = np.asarray(tree_weight, np.float32)
        self.max_depth = int(max_depth)
        self.num_features = int(num_features)
        self.objective = objective
        self.num_class = int(num_class)
        self.init_score = float(init_score)
        self.average_output = bool(average_output)  # rf mode
        self.feature_names = feature_names or [f"f{i}" for i in range(num_features)]
        self.best_iteration = int(best_iteration)
        self.sigmoid = float(sigmoid)
        # categorical splits (reference categorical support,
        # LightGBMBase.getCategoricalIndexes:168; NaN matches no category and
        # routes right).  Without ``cat_bitset``: one-vs-rest — threshold
        # holds the CATEGORY CODE and x == code -> left.  With it:
        # ``cat_bitset[t, m]`` is the (B,) LEFT category set of node m
        # (sorted-subset many-vs-many splits; onehot nodes carry their
        # single-bit set), and code-in-set -> left.
        self.categorical_features = sorted(int(i) for i in
                                           (categorical_features or []))
        self._is_cat = np.zeros(self.num_features, bool)
        if self.categorical_features:
            self._is_cat[self.categorical_features] = True
        self.cat_bitset = None if cat_bitset is None \
            else np.asarray(cat_bitset, bool)

    # ------------------------------------------------------------------ shape
    @property
    def num_trees(self) -> int:
        return self.split_feature.shape[0]

    @property
    def num_iterations(self) -> int:
        return self.num_trees // max(1, self.num_class if self.objective == "multiclass" else 1)

    @property
    def num_leaves(self) -> int:
        return self.leaf_value.shape[1]

    def resolve_cat_bitset(self, B: int) -> np.ndarray:
        """(T, M, B) LEFT category sets, width-normalized to B bins; for
        one-vs-rest boosters the stored codes become one-bit sets (the two
        decision rules are equivalent, so this is lossless)."""
        T, M = self.split_feature.shape
        out = np.zeros((T, M, B), bool)
        if self.cat_bitset is not None:
            W = min(B, self.cat_bitset.shape[-1])
            out[:, :, :W] = self.cat_bitset[:, :, :W]
            return out
        # codes >= B stay UNSET (they can never match a bin of width B);
        # clipping them to B-1 would silently remap out-of-range categories
        # onto the last bin when merge() mixes boosters of unequal widths
        is_cat_node = (self.split_feature >= 0) & \
            self._is_cat[np.maximum(self.split_feature, 0)] & \
            (self.threshold_bin < B)
        t_i, m_i = np.nonzero(is_cat_node)
        out[t_i, m_i, self.threshold_bin[t_i, m_i]] = True
        return out

    # ------------------------------------------------------------------ predict
    def _walk_leaves(self, X: np.ndarray, use_trees: Optional[slice] = None) -> np.ndarray:
        """(n, T') leaf index per tree.  Device gather-walk for batch scoring;
        pure-numpy walk for small batches (the serving regime: avoids the
        per-call device transfer + dispatch, keeping request latency in the
        low milliseconds as the reference's continuous serving does).

        Node ids start at 0 (the root) and chase ``left/right_child``
        pointers; negative ids are leaves (``~leaf_id``) and self-loop, so a
        fixed ``max_depth``-iteration walk resolves every (possibly
        non-perfect, leaf-wise) tree."""
        import jax
        import jax.numpy as jnp
        sf = self.split_feature
        th = self.threshold
        lca, rca = self.left_child, self.right_child
        cbs = self.cat_bitset
        if use_trees is not None:
            sf, th = sf[use_trees], th[use_trees]
            lca, rca = lca[use_trees], rca[use_trees]
            cbs = cbs[use_trees] if cbs is not None else None
        D = max(1, self.max_depth)
        n_rows = X.shape[0]
        T = sf.shape[0]
        if n_rows * T <= 1 << 17:  # small: numpy vectorized walk
            Xn = np.nan_to_num(np.asarray(X, np.float64), nan=-np.inf)
            node = np.zeros((n_rows, T), np.int64)
            t_idx = np.arange(T)[None, :]
            r_idx = np.arange(n_rows)[:, None]
            isc_all = self._is_cat
            for _ in range(D):
                j = np.maximum(node, 0)
                f = sf[t_idx, j]
                thr = th[t_idx, j]
                xv = Xn[r_idx, np.maximum(f, 0)]
                isc = isc_all[np.maximum(f, 0)]
                # categorical codes compare after rounding, matching the
                # round() used at binning time (2.9999 trains as code 3)
                if cbs is not None:
                    Bb = cbs.shape[-1]
                    code = np.where(np.isfinite(xv), np.round(xv), -1.0)
                    memb = ((code >= 0) & (code < Bb)
                            & cbs[t_idx, j,
                                  np.clip(code, 0, Bb - 1).astype(np.int64)])
                    go_right = (f >= 0) & np.where(isc, ~memb, xv > thr)
                else:
                    go_right = (f >= 0) & np.where(isc, np.round(xv) != thr,
                                                   xv > thr)
                child = np.where(go_right, rca[t_idx, j], lca[t_idx, j])
                node = np.where(node >= 0, child, node)
            return (~node).astype(np.int64)

        use_bitset = cbs is not None and bool(self._is_cat.any())

        from ..observability.compute import instrumented_jit

        @instrumented_jit(name="models.gbdt_walk")
        def walk(X, sf, th, lca, rca, cat, cbs_a):
            n = X.shape[0]
            Xn = jnp.nan_to_num(X, nan=-jnp.inf)  # missing routes left

            def one_tree(sf_t, th_t, lc_t, rc_t, cbs_t):
                node = jnp.zeros((n,), jnp.int32)

                def body(d, node):
                    j = jnp.maximum(node, 0)
                    f = sf_t[j]
                    thr = th_t[j]
                    x = Xn[jnp.arange(n), jnp.maximum(f, 0)]
                    if use_bitset:
                        Bb = cbs_t.shape[-1]
                        code = jnp.where(jnp.isfinite(x), jnp.round(x), -1.0)
                        memb = ((code >= 0) & (code < Bb)
                                & cbs_t[j, jnp.clip(code, 0, Bb - 1)
                                        .astype(jnp.int32)])
                        cat_right = ~memb
                    else:
                        cat_right = jnp.round(x) != thr
                    go_right = (f >= 0) & jnp.where(cat[jnp.maximum(f, 0)],
                                                    cat_right, x > thr)
                    child = jnp.where(go_right, rc_t[j], lc_t[j])
                    return jnp.where(node >= 0, child, node)

                node = jax.lax.fori_loop(0, D, body, node)
                return ~node

            return jax.vmap(one_tree)(sf, th, lca, rca, cbs_a).T  # (n, T)

        cbs_dev = jnp.asarray(cbs) if use_bitset \
            else jnp.zeros((T, 1, 1), bool)
        return np.asarray(walk(jnp.asarray(X, jnp.float32), jnp.asarray(sf),
                               jnp.asarray(th), jnp.asarray(lca),
                               jnp.asarray(rca), jnp.asarray(self._is_cat),
                               cbs_dev))

    def predict_leaf(self, X: np.ndarray) -> np.ndarray:
        """Reference ``predictLeaf`` (LightGBMBooster.scala:403)."""
        return self._walk_leaves(np.asarray(X, np.float32))

    def raw_scores(self, X: np.ndarray, num_iteration: int = -1) -> np.ndarray:
        """(n, num_class) raw margins (reference ``score`` raw path)."""
        T = self.num_trees
        if num_iteration and num_iteration > 0:
            k = self.num_class if self.objective == "multiclass" else 1
            T = min(T, num_iteration * k)
        leaves = self._walk_leaves(np.asarray(X, np.float32), slice(0, T))
        # vals[i, t] = leaf_value[t, leaves[i, t]]
        vals = np.take_along_axis(self.leaf_value[:T].T, leaves, axis=0)  # (n, T)
        vals = vals * self.tree_weight[None, :T]
        k = self.num_class if self.objective == "multiclass" else 1
        n = X.shape[0]
        out = np.zeros((n, k), np.float64)
        for c in range(k):
            sel = vals[:, c::k]
            out[:, c] = sel.sum(axis=1)
            if self.average_output:
                w = self.tree_weight[c::k][: sel.shape[1]]
                out[:, c] = out[:, c] / max(1e-12, w.sum())
        return out + self.init_score

    def predict(self, X: np.ndarray, num_iteration: int = -1) -> np.ndarray:
        """Transformed scores: prob for binary (n,), softmax (n,K) for
        multiclass, exp(raw) for log-link objectives (poisson/tweedie),
        raw for regression/ranking."""
        raw = self.raw_scores(X, num_iteration)
        if self.objective == "binary":
            return _sigmoid(self.sigmoid * raw[:, 0])
        if self.objective == "multiclass":
            z = raw - raw.max(axis=1, keepdims=True)
            e = np.exp(z)
            return e / e.sum(axis=1, keepdims=True)
        if self.objective in ("poisson", "tweedie", "gamma"):
            return np.exp(np.clip(raw[:, 0], -30, 30))
        return raw[:, 0]

    def predict_contrib(self, X: np.ndarray, method: str = "tree_shap") -> np.ndarray:
        """Per-feature contributions (n, F+1), last col = expected value.

        method="tree_shap": exact path-dependent TreeSHAP (reference
        ``featuresShap:414`` parity).  method="saabas": fast path-delta
        attribution; both are additive (rows sum to the raw score).
        """
        if method == "tree_shap":
            return tree_shap(self, X)
        X = np.asarray(X, np.float32)
        n, F = X.shape
        D = max(1, self.max_depth)
        M = self.split_feature.shape[1]
        out = np.zeros((n, F + 1), np.float64)
        Xn = np.nan_to_num(X, nan=-np.inf)
        k = self.num_class if self.objective == "multiclass" else 1
        if k > 1:
            raise ValueError("predict_contrib supports single-score models; "
                             "slice trees per class for multiclass")
        out[:, F] = self.init_score
        rows = np.arange(n)
        for t in range(self.num_trees):
            w = self.tree_weight[t]
            lca, rca = self.left_child[t], self.right_child[t]
            node = np.zeros(n, np.int64)
            cur_val = np.full(n, self.internal_value[t, 0], np.float64)
            out[:, F] += w * self.internal_value[t, 0]
            for _ in range(D):
                active = node >= 0
                j = np.maximum(node, 0)
                f = self.split_feature[t, j]
                thr = self.threshold[t, j]
                xv = Xn[rows, np.maximum(f, 0)]
                isc = self._is_cat[np.maximum(f, 0)]
                if self.cat_bitset is not None:
                    Bb = self.cat_bitset.shape[-1]
                    code = np.where(np.isfinite(xv), np.round(xv), -1.0)
                    memb = ((code >= 0) & (code < Bb)
                            & self.cat_bitset[t, j,
                                              np.clip(code, 0, Bb - 1)
                                              .astype(np.int64)])
                    go_right = (f >= 0) & np.where(isc, ~memb, xv > thr)
                else:
                    go_right = (f >= 0) & np.where(isc, np.round(xv) != thr,
                                                   xv > thr)
                nxt = np.where(go_right, rca[j], lca[j])
                nxt_val = np.where(
                    nxt >= 0,
                    self.internal_value[t, np.clip(nxt, 0, M - 1)],
                    self.leaf_value[t, np.clip(~nxt, 0, self.num_leaves - 1)])
                attributed = active & (f >= 0)
                delta = np.where(attributed, w * (nxt_val - cur_val), 0.0)
                np.add.at(out, (rows, np.where(attributed, f, F)), delta)
                cur_val = np.where(attributed, nxt_val, cur_val)
                node = np.where(active, nxt, node)
        return out

    # ------------------------------------------------------------------ utils
    def feature_importance(self, importance_type: str = "split") -> np.ndarray:
        """Reference ``getFeatureImportances:491``: 'split' counts or 'gain'."""
        F = self.num_features
        out = np.zeros(F, np.float64)
        mask = self.split_feature >= 0
        feats = self.split_feature[mask]
        if importance_type == "split":
            np.add.at(out, feats, 1.0)
        elif importance_type == "gain":
            np.add.at(out, feats, self.split_gain[mask])
        else:
            raise ValueError("importance_type must be 'split' or 'gain'")
        return out

    def merge(self, other: "GBDTBooster") -> "GBDTBooster":
        """Concatenate trees (reference ``mergeBooster:252`` batch training)."""
        assert self.num_leaves == other.num_leaves and self.num_class == other.num_class
        assert self.categorical_features == other.categorical_features
        cat = lambda a, b: np.concatenate([a, b], axis=0)
        merged_bitset = None
        if self.cat_bitset is not None or other.cat_bitset is not None:
            W = max(b.cat_bitset.shape[-1] for b in (self, other)
                    if b.cat_bitset is not None)
            merged_bitset = cat(self.resolve_cat_bitset(W),
                                other.resolve_cat_bitset(W))
        return GBDTBooster(
            cat(self.split_feature, other.split_feature),
            cat(self.threshold, other.threshold),
            cat(self.threshold_bin, other.threshold_bin),
            cat(self.split_gain, other.split_gain),
            cat(self.internal_value, other.internal_value),
            cat(self.internal_count, other.internal_count),
            cat(self.leaf_value, other.leaf_value),
            cat(self.leaf_count, other.leaf_count),
            cat(self.tree_weight, other.tree_weight),
            left_child=cat(self.left_child, other.left_child),
            right_child=cat(self.right_child, other.right_child),
            max_depth=max(self.max_depth, other.max_depth),
            num_features=self.num_features,
            objective=self.objective, num_class=self.num_class,
            init_score=self.init_score, average_output=self.average_output,
            feature_names=self.feature_names, sigmoid=self.sigmoid,
            categorical_features=self.categorical_features,
            cat_bitset=merged_bitset)

    # ------------------------------------------------------------------ serde
    _META = ("max_depth", "num_features", "objective", "num_class", "init_score",
             "average_output", "feature_names", "best_iteration", "sigmoid",
             "categorical_features")
    _ARRAYS = ("split_feature", "threshold", "threshold_bin", "split_gain",
               "internal_value", "internal_count", "leaf_value", "leaf_count",
               "tree_weight", "left_child", "right_child")
    # optional arrays: absent on boosters without sorted-subset splits (and
    # on pre-round-3 artifacts)
    _OPT_ARRAYS = ("cat_bitset",)

    def _present_arrays(self):
        return self._ARRAYS + tuple(k for k in self._OPT_ARRAYS
                                    if getattr(self, k) is not None)

    def to_string(self) -> str:
        """Model as a JSON string (reference native model string serde,
        ``saveNativeModel:454`` / ``modelString`` params)."""
        d = {k: getattr(self, k) for k in self._META}
        arrays = {k: getattr(self, k).tolist() for k in self._ARRAYS}
        if self.cat_bitset is not None:
            # pack the (T, M, B) membership to uint8 words: 32x smaller JSON
            packed = np.packbits(self.cat_bitset, axis=-1)
            arrays["cat_bitset_packed"] = packed.tolist()
            d["cat_bitset_bins"] = int(self.cat_bitset.shape[-1])
        d["arrays"] = arrays
        return json.dumps(d)

    @staticmethod
    def from_string(s: str) -> "GBDTBooster":
        d = json.loads(s)
        arrays = {k: np.asarray(v) for k, v in d.pop("arrays").items()}
        packed = arrays.pop("cat_bitset_packed", None)
        nbits = d.pop("cat_bitset_bins", 0)
        if packed is not None:
            arrays["cat_bitset"] = np.unpackbits(
                packed.astype(np.uint8), axis=-1)[..., :nbits].astype(bool)
        return GBDTBooster(**arrays, **d)

    def save(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)
        np.savez(os.path.join(path, "trees.npz"),
                 **{k: getattr(self, k) for k in self._present_arrays()})
        with open(os.path.join(path, "meta.json"), "w") as f:
            json.dump({k: getattr(self, k) for k in self._META}, f)

    @classmethod
    def load(cls, path: str) -> "GBDTBooster":
        with open(os.path.join(path, "meta.json")) as f:
            meta = json.load(f)
        with np.load(os.path.join(path, "trees.npz")) as z:
            # pre-round-3 artifacts lack child arrays (perfect trees only)
            arrays = {k: z[k]
                      for k in cls._ARRAYS + cls._OPT_ARRAYS if k in z.files}
        return cls(**arrays, **meta)


# ---------------------------------------------------------------------------
# Path-dependent TreeSHAP (Lundberg Algorithm 2) over array-of-nodes trees
# ---------------------------------------------------------------------------

class _ShapPath:
    __slots__ = ("d", "z", "o", "w")

    def __init__(self, d, z, o, w):
        self.d, self.z, self.o, self.w = d, z, o, w


def _extend(path, pz, po, pi):
    # value-copy the elements: the recursion branches share parent paths
    path = [_ShapPath(p.d, p.z, p.o, p.w) for p in path] + \
        [_ShapPath(pi, pz, po, 1.0 if len(path) == 0 else 0.0)]
    l = len(path) - 1
    for i in range(l - 1, -1, -1):
        path[i + 1].w += po * path[i].w * (i + 1) / (l + 1)
        path[i].w = pz * path[i].w * (l - i) / (l + 1)
    return path


def _unwind(path, i):
    l = len(path) - 1
    path = [(_ShapPath(p.d, p.z, p.o, p.w)) for p in path]
    o, z = path[i].o, path[i].z
    nxt = path[l].w
    for j in range(l - 1, -1, -1):
        if o != 0:
            tmp = path[j].w
            path[j].w = nxt * (l + 1) / ((j + 1) * o)
            nxt = tmp - path[j].w * z * (l - j) / (l + 1)
        else:
            path[j].w = path[j].w * (l + 1) / (z * (l - j))
    for j in range(i, l):
        path[j].d, path[j].z, path[j].o = path[j + 1].d, path[j + 1].z, path[j + 1].o
    path.pop()
    return path


def _unwound_sum(path, i):
    l = len(path) - 1
    o, z = path[i].o, path[i].z
    total = 0.0
    if o != 0:
        nxt = path[l].w
        for j in range(l - 1, -1, -1):
            tmp = nxt / ((j + 1) * o)
            total += tmp
            nxt = path[j].w - tmp * z * (l - j)
    else:
        for j in range(l - 1, -1, -1):
            total += path[j].w / (z * (l - j))
    return total * (l + 1)


def _tree_shap_one(x, phi, t, booster: "GBDTBooster"):
    """Accumulate SHAP values of tree t for instance x into phi (F+1,).
    Nodes: j >= 0 internal (children via left/right_child), j < 0 leaf ~j."""
    sf = booster.split_feature[t]
    th = booster.threshold[t]
    lca = booster.left_child[t]
    rca = booster.right_child[t]
    ic = booster.internal_count[t]
    lv = booster.leaf_value[t]
    lc = booster.leaf_count[t]
    w = float(booster.tree_weight[t])

    def cover(j):
        return float(ic[j]) if j >= 0 else float(lc[~j])

    def value(j):
        return float(lv[~j])  # only leaves are valued in the recursion

    total_cover = max(float(lc.sum()), 1e-12)
    phi[-1] += w * float((lv * lc).sum()) / total_cover  # E[f] under covers

    def recurse(j, path, pz, po, pi):
        path = _extend(path, pz, po, pi)
        if j < 0:  # leaf
            for i in range(1, len(path)):
                phi[path[i].d] += w * _unwound_sum(path, i) * \
                    (path[i].o - path[i].z) * value(j)
            return
        f = int(sf[j])
        left, right = int(lca[j]), int(rca[j])
        if f < 0:
            # pass-through node: everything goes left
            recurse(left, path, 1.0, 1.0, -2)
            return
        xv = x[f]
        if booster._is_cat[f]:
            if not np.isfinite(xv):
                goes_left = False
            elif booster.cat_bitset is not None:
                code = int(round(xv))
                Bb = booster.cat_bitset.shape[-1]
                goes_left = bool(0 <= code < Bb
                                 and booster.cat_bitset[t, j, code])
            else:
                goes_left = round(xv) == th[j]
        else:
            goes_left = not (xv > th[j])    # NaN compares False -> left
        hot, cold = (left, right) if goes_left else (right, left)
        rj = max(cover(j), 1e-12)
        hz, cz = cover(hot) / rj, cover(cold) / rj
        iz, io = 1.0, 1.0
        # undo previous occurrence of this feature on the path
        for k in range(1, len(path)):
            if path[k].d == f:
                iz, io = path[k].z, path[k].o
                path = _unwind(path, k)
                break
        recurse(hot, path, iz * hz, io, f)
        recurse(cold, path, iz * cz, 0.0, f)

    recurse(0, [], 1.0, 1.0, -1)


def tree_shap(booster: "GBDTBooster", X: np.ndarray) -> np.ndarray:
    """(n, F+1) exact path-dependent SHAP values (last col = expected value).
    Reference parity: ``featuresShap`` (LightGBMBooster.scala:414)."""
    X = np.asarray(X, np.float64)
    n, F = X.shape
    if booster.num_class > 1 and booster.objective == "multiclass":
        raise ValueError("slice trees per class for multiclass SHAP")
    out = np.zeros((n, F + 1), np.float64)
    out[:, F] += booster.init_score
    for i in range(n):
        for t in range(booster.num_trees):
            _tree_shap_one(X[i], out[i], t, booster)
    return out
