"""Transformer encoder — the long-context model family.

Goes beyond the reference (whose only sequence model is a per-row BiLSTM,
SURVEY.md §5.7): a flax encoder whose attention can run dense, blockwise
(memory-efficient single device), or as ring attention over the ``seq`` mesh
axis for sequences longer than one device's HBM
(``parallel.ring_attention``).

Generative scoring (ISSUE 9): the encoder doubles as a causal LM
(``causal=True, pool="none", num_classes=vocab_size``) with an explicit
KV cache threaded through ``__call__(tokens, positions=..., kv_cache=...)``.
The cache is a plain pytree — one ``(k, v)`` pair of static-shape
``(batch, cache_len, heads, head_dim)`` slots per layer (``init_cache``) —
so prefill and the single-token decode step are ordinary pure functions the
``ModelRunner`` lowers ONCE each: the decode loop re-dispatches one compiled
executable per token instead of recompiling per step (the lower-once/
execute-many contract, PAPERS arxiv 1810.09868; the Gemma-on-TPU serving
comparison in PAPERS.md is the reference point for the shape of the cache).
Per-sequence write positions make ragged prompts exact: each sequence's new
k/v land at ITS next slot, and attention masks keys strictly by absolute
position, so padded prompt tails are overwritten before any real query can
attend to them (see docs/runner.md, "Decode correctness").

Paged decode (ISSUE 12): the same cached attention also runs over a PAGED
cache — pool slabs of ``(num_pages, page_size, heads, head_dim)``
(``init_paged_cache``) addressed through a per-sequence ``page_table``
(B, W) int32, the serving pattern the TPU-vs-GPU Gemma study in PAPERS.md
benchmarks.  The write scatters into ``(table[pos // ps], pos % ps)``; the
read gathers each sequence's pages back into position order, so gathered
slot s is absolute position s and the SAME strict ``s <= q_pos``
admissibility mask applies — prefill logits are identical to the dense
path.  Page 0 is the reserved trash page: pad rows and any write whose
logical page is unallocated land there (unallocated table entries are 0)
and no real sequence is ever given it, so garbage writes cannot corrupt
live pages; pad-tail writes into a sequence's own allocated last page are
past its frontier and overwritten by decode steps before they become
admissible, the same argument as dense (docs/runner.md).
"""
from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import jax.numpy as jnp

from ..parallel import ring_attention as ra


def _cache_update(cache_kv, k_new, v_new, positions):
    """Scatter this call's per-token k/v into the dense cache slots.

    ``cache_kv`` = (k, v) each (B, S, H, D); ``k_new``/``v_new`` (B, L, H, D);
    ``positions`` (B, L) absolute slot per token — per-sequence, so ragged
    batches write each sequence at its own frontier."""
    ck, cv = cache_kv
    bidx = jnp.arange(ck.shape[0])[:, None]            # (B, 1)
    ck = ck.at[bidx, positions].set(k_new.astype(ck.dtype))
    cv = cv.at[bidx, positions].set(v_new.astype(cv.dtype))
    return ck, cv


def _paged_cache_update(cache_kv, k_new, v_new, positions, page_table):
    """Scatter this call's per-token k/v into shared POOL pages.

    ``cache_kv`` = (k, v) each (num_pages, page_size, H, D) — pool-level,
    shared by every sequence; ``page_table`` (B, W) int32 maps a sequence's
    logical page j (absolute positions [j*page_size, (j+1)*page_size)) to
    its physical pool page.  Unallocated table entries are 0, the reserved
    trash page, so pad rows and pad-tail prompt positions write garbage
    into a page no real sequence ever reads.

    Offset-prefill contract (ISSUE 20): ``positions`` need not start at 0
    — a prefix-cache hit prefills only the uncached suffix with positions
    offset past the shared prefix, against a table already naming the
    cached pages.  Positions whose logical page falls PAST the table's
    width are routed to the trash page explicitly: a raw gather would
    clamp them to column W-1, and under prefix sharing that column's page
    can be live shared state owned by other sequences."""
    ck, cv = cache_kv
    page_size = ck.shape[1]
    W = page_table.shape[1]
    bidx = jnp.arange(page_table.shape[0])[:, None]    # (B, 1)
    logical = positions // page_size                   # (B, L) logical page
    phys = jnp.where(logical < W,                      # (B, L) physical page
                     page_table[bidx, jnp.minimum(logical, W - 1)], 0)
    slot = positions % page_size                       # (B, L) slot in page
    ck = ck.at[phys, slot].set(k_new.astype(ck.dtype))
    cv = cv.at[phys, slot].set(v_new.astype(cv.dtype))
    return ck, cv


class MultiHeadAttention(nn.Module):
    num_heads: int
    head_dim: int
    attention_mode: str = "dense"      # dense | blockwise | ring
    causal: bool = False
    block_size: int = 512
    seq_axis: str = "seq"
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, positions=None, kv_cache=None, page_table=None):
        B, L, _ = x.shape
        H, D = self.num_heads, self.head_dim
        qkv = nn.Dense(3 * H * D, dtype=self.dtype, name="qkv")(x)
        if kv_cache is not None:
            # KV-cached path (prefill when L = prompt bucket, decode when
            # L = 1).  Dense only: blockwise/ring tile over the query axis
            # and cannot address per-sequence cache slots.
            if self.attention_mode != "dense":
                raise ValueError(
                    "kv_cache requires attention_mode='dense' (got "
                    f"{self.attention_mode!r}); blockwise/ring serve the "
                    "full-sequence paths only")
            if positions is None:
                raise ValueError("kv_cache requires explicit positions")
            q, k, v = jnp.split(qkv.reshape(B, L, 3, H, D), 3, axis=2)
            q, k, v = q[:, :, 0], k[:, :, 0], v[:, :, 0]   # (B, L, H, D)
            if page_table is not None:
                # paged path: k/v land in pool pages addressed through the
                # table; the read gathers each sequence's pages back into
                # (B, W*page_size, H, D), where gathered slot s IS absolute
                # position s (logical page j covers [j*ps, (j+1)*ps)), so
                # the admissibility mask below is identical to dense.
                ck, cv = _paged_cache_update(kv_cache, k, v, positions,
                                             page_table)
                W, page_size = page_table.shape[1], ck.shape[1]
                keys = ck[page_table].reshape(B, W * page_size, H, D)
                vals = cv[page_table].reshape(B, W * page_size, H, D)
            else:
                ck, cv = _cache_update(kv_cache, k, v, positions)
                keys, vals = ck, cv
            s = jnp.einsum("blhd,bshd->bhls", q, keys) / jnp.sqrt(D)
            # keys admissible strictly by absolute position: slot s serves
            # query l iff s <= positions[b, l].  Slots past a sequence's
            # frontier hold zeros or stale pad-token k/v, but every decode
            # step writes its token at the frontier BEFORE attending, so
            # admissible slots are always freshly written.  (Paged: slots
            # whose logical page is unallocated sit past every frontier by
            # construction, so the trash page is never admissible.)
            key_pos = jnp.arange(keys.shape[1])[None, None, None, :]
            admissible = key_pos <= positions[:, None, :, None]
            s = jnp.where(admissible, s, -1e30)
            out = jnp.einsum("bhls,bshd->blhd", nn.softmax(s, axis=-1),
                             vals.astype(s.dtype))
            out = out.reshape(B, L, H * D)
            return nn.Dense(x.shape[-1], dtype=self.dtype,
                            name="proj")(out), (ck, cv)
        q, k, v = jnp.split(qkv.reshape(B, L, 3, H, D).transpose(2, 0, 3, 1, 4), 3)
        q, k, v = q[0], k[0], v[0]                    # (B, H, L, D)
        if self.attention_mode == "ring":
            # inside shard_map the seq axis name is live; outside it falls
            # back to blockwise
            try:
                out = ra.ring_attention(q, k, v, axis_name=self.seq_axis,
                                        causal=self.causal)
            except NameError:
                out = ra.blockwise_attention(q, k, v, self.block_size, self.causal)
        elif self.attention_mode == "blockwise":
            out = ra.blockwise_attention(q, k, v, self.block_size, self.causal)
        else:
            s = (q @ k.swapaxes(-1, -2)) / jnp.sqrt(D)
            if self.causal:
                mask = jnp.tril(jnp.ones((L, L), bool))
                s = jnp.where(mask, s, -1e30)
            out = jnp.einsum("bhqk,bhkd->bhqd", nn.softmax(s, axis=-1), v)
        out = out.transpose(0, 2, 1, 3).reshape(B, L, H * D)
        return nn.Dense(x.shape[-1], dtype=self.dtype, name="proj")(out)


class EncoderBlock(nn.Module):
    num_heads: int
    head_dim: int
    mlp_dim: int
    attention_mode: str = "dense"
    causal: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, positions=None, kv_cache=None, page_table=None):
        h = nn.LayerNorm(dtype=self.dtype)(x)
        attn = MultiHeadAttention(self.num_heads, self.head_dim,
                                  self.attention_mode, self.causal,
                                  dtype=self.dtype)
        if kv_cache is not None:
            h, kv_cache = attn(h, positions=positions, kv_cache=kv_cache,
                               page_table=page_table)
        else:
            h = attn(h)
        x = x + h
        h = nn.LayerNorm(dtype=self.dtype)(x)
        h = nn.Dense(self.mlp_dim, dtype=self.dtype)(h)
        h = nn.gelu(h)
        h = nn.Dense(x.shape[-1], dtype=self.dtype)(h)
        x = x + h
        return (x, kv_cache) if kv_cache is not None else x


class TransformerEncoder(nn.Module):
    """Token transformer; ``features=True`` returns per-token embeddings."""

    vocab_size: int
    num_classes: int = 2
    embed_dim: int = 256
    num_heads: int = 4
    num_layers: int = 4
    mlp_dim: int = 512
    max_len: int = 32768
    attention_mode: str = "dense"
    causal: bool = False
    pool: str = "mean"                 # mean | none (per-token)
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, tokens, train: bool = False, features: bool = False,
                 positions=None, kv_cache=None, page_table=None):
        B, L = tokens.shape
        x = nn.Embed(self.vocab_size, self.embed_dim, dtype=self.dtype)(tokens)
        pos = self.param("pos_embed", nn.initializers.normal(0.02),
                         (1, self.max_len, self.embed_dim))
        if positions is not None:
            # explicit global positions: required under sequence parallelism
            # (local shard starts at axis_index * L_local) and under KV-cached
            # decode (each sequence's token sits at its own frontier)
            x = x + jnp.take(pos[0], positions, axis=0).astype(self.dtype)
        else:
            x = x + pos[:, :L].astype(self.dtype)
        head_dim = self.embed_dim // self.num_heads
        new_cache = []
        for i in range(self.num_layers):
            block = EncoderBlock(self.num_heads, head_dim, self.mlp_dim,
                                 self.attention_mode, self.causal,
                                 dtype=self.dtype, name=f"block_{i}")
            if kv_cache is not None:
                x, layer_kv = block(x, positions=positions,
                                    kv_cache=kv_cache[i],
                                    page_table=page_table)
                new_cache.append(layer_kv)
            else:
                x = block(x)
        x = nn.LayerNorm(dtype=self.dtype)(x)
        if features:
            x = x.astype(jnp.float32)
            return (x, tuple(new_cache)) if kv_cache is not None else x
        if self.pool == "mean" and kv_cache is None:
            x = x.mean(axis=1)
        logits = nn.Dense(self.num_classes, dtype=self.dtype, name="head")(x)
        logits = logits.astype(jnp.float32)  # (B, C) / (B, L, C) pool="none"
        return (logits, tuple(new_cache)) if kv_cache is not None else logits

    def init_cache(self, batch: int, cache_len: int):
        """Zeroed KV-cache pytree: ``num_layers`` pairs of static-shape
        ``(batch, cache_len, heads, head_dim)`` slots.  Plain data, no
        params — build it host-side once per decode signature and thread it
        through ``__call__(..., kv_cache=...)``.  ``cache_len`` bounds
        prompt + generated tokens and is part of the compile signature."""
        if cache_len > self.max_len:
            raise ValueError(f"cache_len {cache_len} exceeds max_len "
                             f"{self.max_len} (positional table bound)")
        head_dim = self.embed_dim // self.num_heads
        shape = (batch, cache_len, self.num_heads, head_dim)
        return tuple((jnp.zeros(shape, self.dtype), jnp.zeros(shape, self.dtype))
                     for _ in range(self.num_layers))

    def init_paged_cache(self, num_pages: int, page_size: int):
        """Zeroed PAGED KV-cache pytree: ``num_layers`` pairs of
        ``(num_pages, page_size, heads, head_dim)`` pool slabs, shared by
        every sequence through a per-sequence page table (see
        ``models/runner.py::PagePool``).  Page 0 is reserved as the trash
        page for pad rows and pad-tail prompt writes, so a usable pool
        needs ``num_pages >= 2``.  Unlike ``init_cache``, the pool is sized
        by TOTAL tokens across sequences, not ``batch * cache_len`` — the
        memory model that lets concurrency scale with actual lengths."""
        if num_pages < 2:
            raise ValueError(f"num_pages {num_pages} < 2: page 0 is the "
                             "reserved trash page, so a usable pool needs "
                             "at least one allocatable page")
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        head_dim = self.embed_dim // self.num_heads
        shape = (num_pages, page_size, self.num_heads, head_dim)
        return tuple((jnp.zeros(shape, self.dtype), jnp.zeros(shape, self.dtype))
                     for _ in range(self.num_layers))
