"""Transformer encoder — the long-context model family.

Goes beyond the reference (whose only sequence model is a per-row BiLSTM,
SURVEY.md §5.7): a flax encoder whose attention can run dense, blockwise
(memory-efficient single device), or as ring attention over the ``seq`` mesh
axis for sequences longer than one device's HBM
(``parallel.ring_attention``).
"""
from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import jax.numpy as jnp

from ..parallel import ring_attention as ra


class MultiHeadAttention(nn.Module):
    num_heads: int
    head_dim: int
    attention_mode: str = "dense"      # dense | blockwise | ring
    causal: bool = False
    block_size: int = 512
    seq_axis: str = "seq"
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        B, L, _ = x.shape
        H, D = self.num_heads, self.head_dim
        qkv = nn.Dense(3 * H * D, dtype=self.dtype, name="qkv")(x)
        q, k, v = jnp.split(qkv.reshape(B, L, 3, H, D).transpose(2, 0, 3, 1, 4), 3)
        q, k, v = q[0], k[0], v[0]                    # (B, H, L, D)
        if self.attention_mode == "ring":
            # inside shard_map the seq axis name is live; outside it falls
            # back to blockwise
            try:
                out = ra.ring_attention(q, k, v, axis_name=self.seq_axis,
                                        causal=self.causal)
            except NameError:
                out = ra.blockwise_attention(q, k, v, self.block_size, self.causal)
        elif self.attention_mode == "blockwise":
            out = ra.blockwise_attention(q, k, v, self.block_size, self.causal)
        else:
            s = (q @ k.swapaxes(-1, -2)) / jnp.sqrt(D)
            if self.causal:
                mask = jnp.tril(jnp.ones((L, L), bool))
                s = jnp.where(mask, s, -1e30)
            out = jnp.einsum("bhqk,bhkd->bhqd", nn.softmax(s, axis=-1), v)
        out = out.transpose(0, 2, 1, 3).reshape(B, L, H * D)
        return nn.Dense(x.shape[-1], dtype=self.dtype, name="proj")(out)


class EncoderBlock(nn.Module):
    num_heads: int
    head_dim: int
    mlp_dim: int
    attention_mode: str = "dense"
    causal: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        h = nn.LayerNorm(dtype=self.dtype)(x)
        h = MultiHeadAttention(self.num_heads, self.head_dim,
                               self.attention_mode, self.causal,
                               dtype=self.dtype)(h)
        x = x + h
        h = nn.LayerNorm(dtype=self.dtype)(x)
        h = nn.Dense(self.mlp_dim, dtype=self.dtype)(h)
        h = nn.gelu(h)
        h = nn.Dense(x.shape[-1], dtype=self.dtype)(h)
        return x + h


class TransformerEncoder(nn.Module):
    """Token transformer; ``features=True`` returns per-token embeddings."""

    vocab_size: int
    num_classes: int = 2
    embed_dim: int = 256
    num_heads: int = 4
    num_layers: int = 4
    mlp_dim: int = 512
    max_len: int = 32768
    attention_mode: str = "dense"
    causal: bool = False
    pool: str = "mean"                 # mean | none (per-token)
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, tokens, train: bool = False, features: bool = False,
                 positions=None):
        B, L = tokens.shape
        x = nn.Embed(self.vocab_size, self.embed_dim, dtype=self.dtype)(tokens)
        pos = self.param("pos_embed", nn.initializers.normal(0.02),
                         (1, self.max_len, self.embed_dim))
        if positions is not None:
            # explicit global positions: required under sequence parallelism,
            # where the local shard starts at axis_index * L_local
            x = x + jnp.take(pos[0], positions, axis=0).astype(self.dtype)
        else:
            x = x + pos[:, :L].astype(self.dtype)
        head_dim = self.embed_dim // self.num_heads
        for i in range(self.num_layers):
            x = EncoderBlock(self.num_heads, head_dim, self.mlp_dim,
                             self.attention_mode, self.causal,
                             dtype=self.dtype, name=f"block_{i}")(x)
        x = nn.LayerNorm(dtype=self.dtype)(x)
        if features:
            return x.astype(jnp.float32)
        if self.pool == "mean":
            x = x.mean(axis=1)
        logits = nn.Dense(self.num_classes, dtype=self.dtype, name="head")(x)
        return logits.astype(jnp.float32)  # (B, C) or (B, L, C) for pool="none"
