"""Cross-request prefix cache — radix index over page-granular token chunks
(ISSUE 20).

Millions of requests share system prompts and templates, so most prefill
work recomputes KV state already resident in the :class:`~.runner.PagePool`.
This module is the index half of the prefix-caching tentpole: a radix trie
over **page-granular token chunks** of completed prompts, mapping each chunk
to the resident physical page holding its k/v.  The pool half (refcounts,
pin-on-hit, copy-on-write splits) lives in ``models/runner.py``.

Match rule
----------
Lookup walks full ``page_size``-token chunks from the root — a chunk matches
only byte-exactly, so a hit is always **page-aligned**.  The final partial
page of a retained prompt is kept as a *tail* on its last full-chunk node;
lookup extends a full-chunk match token-wise into the tail, so two prompts
sharing a template that ends mid-page still share that page (the divergent
write there is what the pool's copy-on-write split handles).  The covered
length is always capped at ``len(prompt) - 1``: the final prompt position is
the logits source for the first generated token and is always recomputed,
which keeps the suffix prefill non-empty (same executable signature, ~one
token of device work on a full hit) and the greedy tokens bit-identical to a
cold decode.

Lifecycle
---------
- **retain** (:meth:`PrefixIndex.release`): a finished request's pages are
  handed to the index instead of the free list — the index takes over the
  request's reference, so retention is free (no copy) and an entry's page
  can simultaneously back live requests (refcount > 1).
- **pin** (:meth:`PrefixIndex.lookup`): a hit pins the matched pages under
  the index lock, atomically with respect to eviction — an entry is never
  evicted out from under an admission that just matched it.
- **evict**: entries are evicted leaf-first in LRU order from a bounded
  ``budget_pages`` budget, and on demand under pool pressure
  (:meth:`evict_pages`).  Eviction drops only the INDEX's reference; a page
  shared with a live request stays resident until that request frees it —
  eviction under pressure can never yank a live page table's pages.
- **flush** (``reason="pool_replaced"``): ``PagePool.resized()`` flushes the
  index before building its successor — index entries name physical page
  ids of the old pool's slabs, and a dangling entry surviving a resize
  would hand freed page ids out against the replacement's memory.

Locking: the index lock is always taken BEFORE the pool lock (lookup pins,
release/evict free — both under the index lock).  Nothing in the pool calls
back into the index, so the sanitizer-checked lock order is acyclic.
"""
from __future__ import annotations

import itertools
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..utils.concurrency import make_lock

__all__ = ["PrefixIndex", "prefix_instruments"]


def prefix_instruments(registry=None) -> Dict[str, Any]:
    """Register (idempotently) and return the prefix-cache metric families.
    ``ModelRunner`` construction calls this so the families exist — and the
    telemetry-coverage sweep gates on them — even for runners that never
    enable the cache; :class:`PrefixIndex` binds the children."""
    if registry is None:
        from ..observability import get_registry
        registry = get_registry()
    return {
        "hits": registry.counter(
            "mmlspark_prefix_hits_total",
            "admission lookups that matched a cached prefix (>= 1 page)",
            labels=("runner",)),
        "misses": registry.counter(
            "mmlspark_prefix_misses_total",
            "admission lookups that matched nothing — full prefill",
            labels=("runner",)),
        "evictions": registry.counter(
            "mmlspark_prefix_evictions_total",
            "retained pages evicted from the prefix index, by reason "
            "(lru = budget, pressure = pool reclaim, pool_replaced = "
            "resize flush)", labels=("runner", "reason")),
        "cow_splits": registry.counter(
            "mmlspark_prefix_cow_splits_total",
            "shared pages split copy-on-write at the first divergent "
            "token write", labels=("runner",)),
        "hit_tokens": registry.counter(
            "mmlspark_prefix_hit_tokens_total",
            "prompt tokens whose prefill was skipped via a cached prefix "
            "(the cost ledger's prefill_cached lane)", labels=("runner",)),
        "hit_rate": registry.gauge(
            "mmlspark_prefix_hit_rate_pct",
            "lifetime prefix-lookup hit rate (hits / lookups)",
            labels=("runner",)),
        "retained": registry.gauge(
            "mmlspark_prefix_retained_pages",
            "pages currently retained by the prefix index (bounded by the "
            "budget_pages knob)", labels=("runner",)),
    }


class _Node:
    """One page-granular chunk of a retained prompt: ``chunk`` (the token
    bytes) under ``parent`` maps to physical ``page``; ``tail`` optionally
    holds the retained prompt's final partial page as ``(page, tokens)``."""

    __slots__ = ("id", "key", "chunk", "page", "parent", "nchildren",
                 "tail", "last_used")

    def __init__(self, nid: int, key, chunk: bytes, page: int, parent,
                 now: float):
        self.id = nid
        self.key = key
        self.chunk = chunk
        self.page = int(page)
        self.parent = parent           # _Node or None (root-level)
        self.nchildren = 0
        self.tail: Optional[Tuple[int, Tuple[int, ...]]] = None
        self.last_used = now


class PrefixIndex:
    """Radix trie mapping page-granular prompt chunks to resident pages.

    One index per :class:`~.runner.PagePool` (``pool.prefix_index``),
    created by ``ModelRunner.prefix_cache``.  All methods are thread-safe;
    pool operations (pin/free) happen under the index lock, index-lock ->
    pool-lock order."""

    def __init__(self, pool, *, budget_pages: int = 64,
                 name: str = "model", registry=None,
                 clock: Callable[[], float] = time.monotonic):
        if budget_pages < 1:
            raise ValueError(f"budget_pages must be >= 1, got {budget_pages}")
        self.pool = pool
        self.budget_pages = int(budget_pages)
        self.name = name
        self._clock = clock
        self._lock = make_lock("PrefixIndex._lock")
        self._ids = itertools.count(1)     # node id 0 is the root
        self._nodes: Dict[Tuple[int, bytes], _Node] = {}
        self._root_tail: Optional[Tuple[int, Tuple[int, ...]]] = None
        self._root_tail_t = 0.0
        self._retained = 0                 # pages held by entries + tails
        self._hits = 0
        self._misses = 0
        inst = prefix_instruments(registry)
        self._c_hits = inst["hits"].labels(runner=name)
        self._c_misses = inst["misses"].labels(runner=name)
        self._c_evict = inst["evictions"]
        self._c_cow = inst["cow_splits"].labels(runner=name)
        self._c_hit_tokens = inst["hit_tokens"].labels(runner=name)
        self._g_hit_rate = inst["hit_rate"]
        self._g_retained = inst["retained"]
        self._book_gauges_locked()

    # ------------------------------------------------------------- booking
    def _book_gauges_locked(self) -> None:
        total = self._hits + self._misses
        rate = 100.0 * self._hits / total if total else 0.0
        self._g_hit_rate.set(rate, runner=self.name)
        self._g_retained.set(float(self._retained), runner=self.name)

    def book_cow(self, n: int = 1) -> None:
        """Book copy-on-write splits (called by the pool-side split that
        routes a divergent write to a private copy)."""
        self._c_cow.inc(n)

    # -------------------------------------------------------------- lookup
    def lookup(self, tokens) -> Tuple[List[int], int]:
        """Longest cached prefix of ``tokens``: returns ``(pages,
        covered)`` where ``pages`` back prompt positions ``[0, covered)``
        and are PINNED on the caller's behalf (free them, or hand them to
        :meth:`release`, when the request terminates).  ``covered`` is
        capped at ``len(tokens) - 1`` — the final prompt position is always
        recomputed (see module docstring), so a miss returns ``([], 0)``
        and a full hit leaves a one-token suffix."""
        toks = np.asarray(tokens, dtype=np.int32).ravel()
        length = int(toks.size)
        ps = self.pool.page_size
        with self._lock:
            now = self._clock()
            pages: List[int] = []
            covered = 0
            node: Optional[_Node] = None
            pid = 0
            for ci in range(length // ps):
                nxt = self._nodes.get(
                    (pid, toks[ci * ps:(ci + 1) * ps].tobytes()))
                if nxt is None:
                    break
                node, pid = nxt, nxt.id
                nxt.last_used = now
                pages.append(nxt.page)
                covered += ps
            # tail extension: the retained prompt's final partial page —
            # matched token-wise, so divergence mid-page still shares the
            # agreeing slots (the CoW leg recomputes the rest)
            tail = node.tail if node is not None else self._root_tail
            if tail is not None and covered == len(pages) * ps \
                    and covered < length:
                tpage, ttoks = tail
                rem = toks[covered:]
                k = 0
                while k < len(ttoks) and k < rem.size \
                        and int(rem[k]) == ttoks[k]:
                    k += 1
                if k > 0:
                    pages.append(tpage)
                    covered += k
                    if node is not None:
                        node.last_used = now
                    else:
                        self._root_tail_t = now
            covered = min(covered, length - 1)
            if covered <= 0:
                pages, covered = [], 0
            else:
                pages = pages[:-(-covered // ps)]
            if pages:
                # pin under the index lock: atomic against eviction
                self.pool.pin(pages)
                self._hits += 1
                self._c_hits.inc()
                self._c_hit_tokens.inc(covered)
            else:
                self._misses += 1
                self._c_misses.inc()
            self._book_gauges_locked()
            return list(pages), int(covered)

    # ------------------------------------------------------------ retention
    def release(self, tokens, pages) -> None:
        """Terminal hand-off: ``pages`` back the k/v of ``tokens`` (the
        prompt plus every generated token that was fed back — the final
        sampled token's k/v is never written).  New chunks transfer the
        caller's page reference to the index; chunks already retained
        (including the very pages this request pinned at admission) drop
        the caller's reference instead.  Anything left over is freed.
        Enforces the LRU budget afterwards."""
        toks = np.asarray(tokens, dtype=np.int32).ravel()
        length = int(toks.size)
        pages = [int(p) for p in pages]
        ps = self.pool.page_size
        nfull = min(length // ps, len(pages))
        with self._lock:
            now = self._clock()
            node: Optional[_Node] = None
            pid = 0
            surplus: List[int] = []
            for ci in range(nfull):
                chunk = toks[ci * ps:(ci + 1) * ps].tobytes()
                key = (pid, chunk)
                ex = self._nodes.get(key)
                if ex is not None:
                    # chunk already cached (often literally the page we
                    # pinned at admission): drop OUR reference
                    surplus.append(pages[ci])
                    ex.last_used = now
                    node, pid = ex, ex.id
                else:
                    nid = next(self._ids)
                    fresh = _Node(nid, key, chunk, pages[ci], node, now)
                    self._nodes[key] = fresh
                    if node is not None:
                        node.nchildren += 1
                    self._retained += 1   # reference transferred to us
                    node, pid = fresh, nid
            rest = pages[nfull:]
            tail_toks = toks[nfull * ps:]
            if tail_toks.size > 0 and rest:
                holder = node.tail if node is not None else self._root_tail
                if holder is None:
                    tail = (rest[0], tuple(int(t) for t in tail_toks))
                    if node is not None:
                        node.tail = tail
                    else:
                        self._root_tail, self._root_tail_t = tail, now
                    self._retained += 1
                    rest = rest[1:]
                # else: an equivalent-or-diverged tail is already retained
                # (first-wins); our copy is surplus
            surplus.extend(rest)
            if surplus:
                self.pool.free(surplus)
            self._enforce_budget_locked()
            self._book_gauges_locked()

    # ------------------------------------------------------------- eviction
    def _evict_node_locked(self, node: _Node, reason: str) -> int:
        """Remove one leaf entry (page + any tail), freeing the index's
        references.  Returns pages whose refcount hit zero (actual
        free-list gain — a page shared with a live request stays
        resident)."""
        freed = [node.page]
        if node.tail is not None:
            freed.append(node.tail[0])
            node.tail = None
        del self._nodes[node.key]
        if node.parent is not None:
            node.parent.nchildren -= 1
        gained = sum(1 for p in freed if self.pool.refcount(p) == 1)
        self._retained -= len(freed)
        self.pool.free(freed)
        self._c_evict.labels(runner=self.name, reason=reason).inc(len(freed))
        return gained

    def _evict_root_tail_locked(self, reason: str) -> int:
        tail, self._root_tail = self._root_tail, None
        gained = 1 if self.pool.refcount(tail[0]) == 1 else 0
        self._retained -= 1
        self.pool.free([tail[0]])
        self._c_evict.labels(runner=self.name, reason=reason).inc(1)
        return gained

    def _lru_candidates_locked(self):
        cands = [(n.last_used, 0, n) for n in self._nodes.values()
                 if n.nchildren == 0]
        if self._root_tail is not None:
            cands.append((self._root_tail_t, 1, None))
        cands.sort(key=lambda c: (c[0], c[1]))
        return cands

    def _enforce_budget_locked(self, reason: str = "lru") -> None:
        while self._retained > self.budget_pages:
            cands = self._lru_candidates_locked()
            if not cands:
                break
            _, _, node = cands[0]
            if node is None:
                self._evict_root_tail_locked(reason)
            else:
                self._evict_node_locked(node, reason)

    def evict_pages(self, n: int, reason: str = "pressure") -> int:
        """Evict LRU entries until ``n`` pages actually return to the free
        list (refcount-0 retentions), or nothing evictable remains.
        Returns the free-list gain — callers retry their allocation only
        when it is > 0."""
        gained = 0
        with self._lock:
            while gained < n:
                cands = self._lru_candidates_locked()
                if not cands:
                    break
                _, _, node = cands[0]
                if node is None:
                    gained += self._evict_root_tail_locked(reason)
                else:
                    gained += self._evict_node_locked(node, reason)
            self._book_gauges_locked()
        return gained

    def flush(self, reason: str = "pool_replaced") -> int:
        """Evict EVERYTHING (booked under ``reason``) — the pool-resize
        seam: no entry may survive into a successor pool's page-id space.
        Returns pages released."""
        with self._lock:
            freed: List[int] = []
            for node in self._nodes.values():
                freed.append(node.page)
                if node.tail is not None:
                    freed.append(node.tail[0])
            if self._root_tail is not None:
                freed.append(self._root_tail[0])
                self._root_tail = None
            self._nodes.clear()
            self._retained = 0
            if freed:
                self.pool.free(freed)
                self._c_evict.labels(runner=self.name,
                                     reason=reason).inc(len(freed))
            self._book_gauges_locked()
            return len(freed)

    def rebind(self, pool) -> None:
        """Point the (flushed) index at a successor pool — called by
        ``PagePool.resized()`` after the flush."""
        with self._lock:
            if self._nodes or self._root_tail is not None:
                raise RuntimeError("rebind of a non-empty prefix index — "
                                   "flush() first (entries name the OLD "
                                   "pool's physical pages)")
            self.pool = pool

    # ---------------------------------------------------------------- intro
    def retained_pages(self) -> int:
        with self._lock:
            return self._retained

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            total = self._hits + self._misses
            return {
                "hits": self._hits,
                "misses": self._misses,
                "hit_rate_pct": round(100.0 * self._hits / total, 2)
                if total else 0.0,
                "retained_pages": self._retained,
                "budget_pages": self.budget_pages,
                "nodes": len(self._nodes),
            }
