"""Unified model runner — lower-once StableHLO execution for every model.

The paper's second capability pillar (ROADMAP "Unified StableHLO model
runner"): one subsystem that takes an in-tree model (resnet, transformer,
bilstm), an ONNX import (``dl/onnx_import.py``), or any pure
``apply(variables, batch)`` callable, lowers it **once per (local device
set, bucketed batch shape)** through ``instrumented_jit`` into a cached
executable, and serves it behind two fronts:

- **batch transform** — :meth:`ModelRunner.apply_batch` owns the padding/
  bucketing/unpadding that ``dl/jax_model.py``, ``dl/image_featurizer.py``
  and the serving scorers each hand-rolled before this PR (power-of-two
  latency buckets: a 1-row request pads to 1, not ``batch_size``);
- **low-latency serving** — :meth:`ModelRunner.scorer` returns a
  ``Transformer`` that ``PipelineServer`` (and the streaming facade) score
  through: the server's continuous-mode drain admits requests into one
  in-flight batch, and the runner buckets that batch onto an already-lowered
  executable, so steady-state latency never pays a compile.

On top of it, generative scoring is a first-class workload:
:meth:`ModelRunner.decode` runs a KV-cached batched decode loop — one
prefill executable per (batch bucket, prompt bucket, cache length) plus ONE
single-token step executable re-dispatched every token, with per-sequence
lengths so ragged prompts decode exactly (``models/transformer.py`` owns
the cache math; docs/runner.md states the correctness argument).

Lowering contract (the lower-once/execute-many precedent is the Julia→TPU
full-compilation work, PAPERS arxiv 1810.09868): every executable is keyed
by (device set, bucket shape) and built exactly once; compile counts ride
``mmlspark_jit_compile_total{fn="runner.<name>*"}`` so a recompile storm
across ragged batch sizes is impossible by construction and visible on
``/debug/compile`` if an input ever escapes the buckets.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from ..core import DataFrame, Transformer
from ..core.schema import ColumnType

__all__ = ["ModelRunner", "DecodeResult", "bucket_rows"]

#: fronts a batch can arrive through; metric label values
FRONTS = ("transform", "serving", "decode")


def bucket_rows(m: int, batch_size: int) -> int:
    """Power-of-two latency bucket for an ``m``-row chunk: a 1-row serving
    request pads to 1, not ``batch_size``; full chunks use ``batch_size``
    itself.  Each bucket lowers once and is cached."""
    if m >= batch_size:
        return batch_size
    return min(batch_size, 1 << (max(1, m) - 1).bit_length())


def _pad_rows(x: np.ndarray, target: int) -> np.ndarray:
    """Pad the leading dim to ``target`` by repeating the last row (cheap,
    and keeps the padded rows numerically tame for any model)."""
    m = x.shape[0]
    if m == target:
        return x
    pad = np.repeat(x[-1:], target - m, axis=0)
    return np.concatenate([x, pad], axis=0)


@dataclass
class DecodeResult:
    """One batched decode: ``tokens[b, t]`` is the t-th generated token of
    sequence b; ``logits`` (collect_logits=True) holds the distribution
    that produced each token; ``steps`` counts device dispatches (prefill
    excluded); ``lengths`` echoes the prompt lengths the loop honoured."""
    tokens: np.ndarray                 # (B, T) int32
    lengths: np.ndarray                # (B,) prompt lengths
    steps: int
    logits: Optional[np.ndarray] = None  # (B, T, V) float32


class ModelRunner:
    """Compile-once execution cache + batch/serving/decode fronts.

    Accepts any of:

    - ``payload`` — an object exposing ``pure_apply`` / ``variables`` (and
      optionally ``module``): ``FlaxModelPayload``, ``OnnxModelPayload``;
    - ``module=`` + ``variables=`` — a flax module (resnet, transformer,
      bilstm); ``apply_kwargs`` forward to ``module.apply``;
    - ``apply_fn=`` + ``variables=`` — a raw pure ``(variables, batch)``
      callable.

    ``name`` labels every metric series and compile-report entry this
    runner books — keep it low-cardinality (a model family, not a uid).
    """

    def __init__(self, payload=None, *, module=None, variables=None,
                 apply_fn: Optional[Callable] = None,
                 apply_kwargs: Optional[Dict[str, Any]] = None,
                 name: str = "model", batch_size: int = 64,
                 registry=None):
        if payload is not None:
            self._pure = payload.pure_apply
            self.variables = payload.variables
            self.module = getattr(payload, "module", None)
        elif apply_fn is not None:
            self._pure = apply_fn
            self.variables = variables
            self.module = module
        elif module is not None:
            kw = dict(apply_kwargs or {})

            def _pure(vs, batch, _m=module, _kw=kw):
                return _m.apply(vs, batch, **_kw)

            self._pure = _pure
            self.variables = variables
            self.module = module
        else:
            raise ValueError("need a payload, a module, or an apply_fn")
        self.name = name
        self.batch_size = int(batch_size)
        from ..observability import get_registry
        self.registry = registry if registry is not None else get_registry()
        #: (kind, device_key, *shape) -> executable; every entry lowered once
        self._executables: Dict[Tuple, Callable] = {}
        #: name -> InstrumentedJit wrappers this runner created (compile
        #: introspection for tests and compile_stats)
        self._wrappers: list = []
        self._lock = threading.Lock()
        reg = self.registry
        c_batches = reg.counter(
            "mmlspark_runner_batches_total",
            "device dispatches per runner by front",
            labels=("runner", "front"))
        c_rows = reg.counter(
            "mmlspark_runner_rows_total",
            "real (unpadded) rows scored per runner by front",
            labels=("runner", "front"))
        self._c_batches = {f: c_batches.labels(runner=name, front=f)
                          for f in FRONTS}
        self._c_rows = {f: c_rows.labels(runner=name, front=f)
                        for f in FRONTS}
        self._c_pad = reg.counter(
            "mmlspark_runner_pad_rows_total",
            "padding rows added by bucketing (wasted device work)",
            labels=("runner",)).labels(runner=name)
        self._c_decode_steps = reg.counter(
            "mmlspark_runner_decode_steps_total",
            "single-token decode-step dispatches",
            labels=("runner",)).labels(runner=name)
        self._c_decode_tokens = reg.counter(
            "mmlspark_runner_decode_tokens_total",
            "tokens generated (real sequences only)",
            labels=("runner",)).labels(runner=name)

    # ------------------------------------------------------------- lowering
    @staticmethod
    def _device_key() -> Tuple:
        """The local device set the executables are specialized to; a mesh
        change (tests swapping in mesh8, a late-attached accelerator)
        re-keys instead of serving a stale placement."""
        from ..parallel import get_active_mesh
        mesh = get_active_mesh()
        return tuple(int(d.id) for d in mesh.devices.flat)

    def _instrumented(self, fn: Callable, suffix: str = "", **jit_kwargs):
        from ..observability.compute import instrumented_jit
        wrapper = instrumented_jit(
            fn, name=f"runner.{self.name}{suffix}",
            registry=self.registry, **jit_kwargs)
        self._wrappers.append(wrapper)
        return wrapper

    def executable(self, bucket_n: int, feat_shape: Tuple[int, ...]):
        """The compiled apply for one (device set, bucketed batch shape) —
        built on first use, a dict hit forever after.  Multi-device meshes
        shard the batch dim over ``data`` with params replicated (inference
        DP); multi-host processes stage their host-local batch as a global
        array explicitly (jit refuses host-local numpy for non-replicated
        shardings; every process holds the SAME batch under the executor
        model — identical partition per call)."""
        key = ("apply", self._device_key(), int(bucket_n), tuple(feat_shape))
        fn = self._executables.get(key)
        if fn is not None:
            return fn
        with self._lock:
            fn = self._executables.get(key)
            if fn is not None:
                return fn
            import jax
            from ..parallel import batch_sharded, get_active_mesh, replicated
            mesh = get_active_mesh()
            n_dev = mesh.devices.size
            if n_dev > 1 and bucket_n % n_dev == 0:
                sharded = self._instrumented(
                    self._pure,
                    in_shardings=(replicated(mesh), batch_sharded(mesh)),
                    out_shardings=replicated(mesh))
                if jax.process_count() > 1:
                    bsh = batch_sharded(mesh)

                    def fn(variables, chunk, _inner=sharded, _s=bsh):
                        garr = jax.make_array_from_callback(
                            chunk.shape, _s, lambda idx: chunk[idx])
                        return _inner(variables, garr)
                else:
                    fn = sharded
            else:
                fn = self._instrumented(self._pure)
            self._executables[key] = fn
        return fn

    def compile_stats(self) -> Dict[str, Any]:
        """Introspection for tests and ops: executables cached by key plus
        the underlying compile count (one per signature by contract)."""
        return {
            "executables": sorted(
                "/".join(str(p) for p in k) for k in self._executables),
            "compiles": sum(getattr(w, "compiles", 0)
                            for w in self._wrappers),
        }

    # ------------------------------------------------------------ batch front
    def apply_batch(self, x: np.ndarray, front: str = "transform",
                    batch_size: Optional[int] = None) -> np.ndarray:
        """Score a stacked host batch of any row count: chunk to
        ``batch_size``, pad each chunk to its power-of-two bucket, run the
        cached executable, unpad, concatenate.  This is the ONE copy of the
        pad/bucket glue the per-model transformers used to hand-roll."""
        bs = int(batch_size or self.batch_size)
        n = x.shape[0]
        if n == 0:
            return np.empty((0,), dtype=np.float32)
        variables = self.variables
        outs = []
        pad_total = 0
        for start in range(0, n, bs):
            chunk = x[start:start + bs]
            m = chunk.shape[0]
            bucket = bucket_rows(m, bs)
            pad_total += bucket - m
            chunk = _pad_rows(chunk, bucket)
            fn = self.executable(bucket, chunk.shape[1:])
            outs.append(np.asarray(fn(variables, chunk))[:m])
            self._c_batches[front].inc()
        self._c_rows[front].inc(n)
        if pad_total:
            self._c_pad.inc(pad_total)
        return np.concatenate(outs, axis=0)

    # ---------------------------------------------------------- serving front
    def scorer(self, input_col: str = "request", reply_col: str = "reply",
               prepare: Optional[Callable] = None,
               encode: Optional[Callable] = None,
               mode: str = "score", **decode_kwargs) -> "Transformer":
        """A ``Transformer`` front for ``PipelineServer`` / the streaming
        facade.  ``mode="score"`` stacks request rows (via ``prepare``,
        default ``np.asarray(..., float32)``) and scores them through
        :meth:`apply_batch`; ``mode="decode"`` treats each request as a
        token-id prompt and returns generated token lists from
        :meth:`decode` (``decode_kwargs`` forward, e.g.
        ``max_new_tokens=``).  The server's continuous-mode drain is the
        admission window: whatever is in flight when the scorer runs
        becomes ONE bucketed device batch."""
        if mode not in ("score", "decode"):
            raise ValueError("scorer mode must be score|decode")
        return _RunnerScorer(self, input_col, reply_col, prepare, encode,
                             mode, decode_kwargs)

    # ------------------------------------------------------------ decode front
    def _decode_executables(self, batch_b: int, prompt_b: int,
                            cache_len: int):
        """(prefill, step) executables for one decode signature.  Prefill is
        keyed by (batch bucket, prompt bucket, cache length); the step by
        (batch bucket, cache length) only — its input shapes are constant
        across the whole generation loop, so EVERY token of EVERY request
        at this signature re-dispatches one compiled program."""
        import jax.numpy as jnp
        module = self.module
        dkey = self._device_key()
        kp = ("prefill", dkey, batch_b, prompt_b, cache_len)
        ks = ("step", dkey, batch_b, cache_len)
        prefill = self._executables.get(kp)
        step = self._executables.get(ks)
        if prefill is not None and step is not None:
            return prefill, step
        with self._lock:
            prefill = self._executables.get(kp)
            if prefill is None:
                def _prefill(variables, toks, positions, lengths, cache,
                             _m=module):
                    logits, cache = _m.apply(variables, toks,
                                             positions=positions,
                                             kv_cache=cache)
                    # last REAL token's logits per sequence — gathered
                    # on-device so the (B, P, V) tensor never crosses to host
                    last = jnp.take_along_axis(
                        logits, (lengths - 1)[:, None, None], axis=1)[:, 0]
                    return last, cache

                prefill = self._executables[kp] = self._instrumented(
                    _prefill, suffix=".prefill")
            step = self._executables.get(ks)
            if step is None:
                def _step(variables, tok, positions, cache, _m=module):
                    logits, cache = _m.apply(variables, tok,
                                             positions=positions,
                                             kv_cache=cache)
                    return logits[:, 0], cache

                step = self._executables[ks] = self._instrumented(
                    _step, suffix=".decode_step")
        return prefill, step

    def decode(self, prompts: np.ndarray, lengths=None,
               max_new_tokens: int = 16, eos_id: Optional[int] = None,
               sample_fn: Optional[Callable] = None,
               collect_logits: bool = False,
               batch_bucket: Optional[int] = None,
               prompt_bucket: Optional[int] = None,
               cache_len: Optional[int] = None) -> DecodeResult:
        """KV-cached batched autoregressive generation.

        ``prompts`` is ``(B, P)`` int32 (rows padded to the longest prompt);
        ``lengths`` gives each sequence's true prompt length so ragged
        batches decode exactly — each sequence writes and reads the cache at
        ITS own frontier.  Buckets: ``B`` pads to a power-of-two row bucket,
        ``P`` to a power-of-two prompt bucket, and the cache length defaults
        to the next power of two covering prompt + new tokens — three static
        shapes, so one prefill compile and one step compile serve every
        request at the signature.  ``sample_fn(logits) -> tokens`` defaults
        to greedy argmax; ``eos_id`` freezes finished sequences (and ends
        the loop early once ALL are finished)."""
        if self.module is None or not hasattr(self.module, "init_cache"):
            raise TypeError(
                "decode() needs a module with init_cache (a KV-cache-capable "
                "model, e.g. models.TransformerEncoder with causal=True, "
                "pool='none'); this runner wraps "
                f"{type(self.module).__name__ if self.module else 'a raw apply_fn'}")
        import jax.numpy as jnp
        prompts = np.asarray(prompts, np.int32)
        if prompts.ndim != 2:
            raise ValueError("prompts must be (batch, prompt_len) int32")
        B, P = prompts.shape
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        lengths = (np.full(B, P, np.int32) if lengths is None
                   else np.asarray(lengths, np.int32))
        if lengths.shape != (B,) or lengths.min() < 1 or lengths.max() > P:
            raise ValueError("lengths must be (batch,) in [1, prompt_len]")
        B_b = batch_bucket or 1 << (B - 1).bit_length()
        P_b = prompt_bucket or 1 << (P - 1).bit_length()
        if B_b < B or P_b < P:
            raise ValueError("bucket smaller than the batch/prompt it serves")
        S = cache_len or 1 << (P_b + max_new_tokens - 1).bit_length()
        if S < P_b + max_new_tokens:
            raise ValueError("cache_len must cover prompt_bucket + "
                             "max_new_tokens")
        toks = np.zeros((B_b, P_b), np.int32)
        toks[:B, :P] = prompts
        lens = np.concatenate([lengths, np.ones(B_b - B, np.int32)])
        self._c_pad.inc((B_b - B) * P_b + B * (P_b - P))
        prefill, step = self._decode_executables(B_b, P_b, S)
        variables = self.variables
        cache = self.module.init_cache(B_b, S)
        positions = np.broadcast_to(np.arange(P_b, dtype=np.int32),
                                    (B_b, P_b))
        last, cache = prefill(variables, jnp.asarray(toks),
                              jnp.asarray(positions), jnp.asarray(lens),
                              cache)
        self._c_batches["decode"].inc()
        sample = sample_fn or (lambda lg: np.argmax(lg, axis=-1))
        out_tokens = np.zeros((B_b, max_new_tokens), np.int32)
        out_logits = [] if collect_logits else None
        # pad rows are born finished: their garbage samples must never hold
        # the eos early-exit open (or inflate the step counters)
        finished = np.zeros(B_b, bool)
        finished[B:] = True
        steps = 0
        for t in range(max_new_tokens):
            lg = np.asarray(last)                      # (B_b, V) host fetch
            if collect_logits:
                out_logits.append(lg)
            tok = np.asarray(sample(lg), np.int32)
            if eos_id is not None:
                tok = np.where(finished, eos_id, tok)
                finished |= tok == eos_id
            out_tokens[:, t] = tok
            if t == max_new_tokens - 1 or \
                    (eos_id is not None and bool(finished.all())):
                break
            # token t sits at absolute position lengths + t; the step
            # writes it at that frontier and returns logits for t+1
            pos = (lens + t).astype(np.int32)[:, None]
            last, cache = step(variables, jnp.asarray(tok[:, None]),
                               jnp.asarray(pos), cache)
            steps += 1
            self._c_decode_steps.inc()
        n_generated = t + 1
        self._c_decode_tokens.inc(B * n_generated)
        self._c_rows["decode"].inc(B)
        logits = (np.stack(out_logits, axis=1)[:B] if collect_logits
                  else None)
        return DecodeResult(tokens=out_tokens[:B, :n_generated],
                            lengths=lengths, steps=steps, logits=logits)


class _RunnerScorer(Transformer):
    """Private serving front: built by :meth:`ModelRunner.scorer`, scored by
    ``PipelineServer`` / the streaming facade.  Not a registered stage —
    it is constructed programmatically around a live runner, never from
    params, so it stays out of codegen/fuzzing by the ``_`` convention."""

    def __init__(self, runner: ModelRunner, input_col: str, reply_col: str,
                 prepare: Optional[Callable], encode: Optional[Callable],
                 mode: str, decode_kwargs: Dict[str, Any]):
        super().__init__()
        self.runner = runner
        self.input_col, self.reply_col = input_col, reply_col
        self.prepare = prepare or (lambda v: np.asarray(v, np.float32))
        self.encode = encode or (lambda y: y)
        self.mode = mode
        self.decode_kwargs = dict(decode_kwargs)

    def _transform(self, df: DataFrame) -> DataFrame:
        def per_part(p):
            col = p[self.input_col]
            n = len(col)
            out = np.empty(n, dtype=object)
            if n == 0:
                return {**p, self.reply_col: out}
            if self.mode == "decode":
                prompts = [np.asarray(v, np.int32).reshape(-1) for v in col]
                lengths = np.asarray([len(q) for q in prompts], np.int32)
                P = int(lengths.max())
                stacked = np.zeros((n, P), np.int32)
                for i, q in enumerate(prompts):
                    stacked[i, :len(q)] = q
                res = self.runner.decode(stacked, lengths=lengths,
                                         **self.decode_kwargs)
                for i in range(n):
                    out[i] = self.encode(res.tokens[i])
            else:
                x = np.stack([self.prepare(v) for v in col])
                y = self.runner.apply_batch(x, front="serving")
                for i in range(n):
                    out[i] = self.encode(y[i])
            return {**p, self.reply_col: out}

        return df.map_partitions(per_part)

    def transform_schema(self, schema):
        schema.require(self.input_col)
        return schema.add(self.reply_col, ColumnType.VECTOR)
