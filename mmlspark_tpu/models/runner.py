"""Unified model runner — lower-once StableHLO execution for every model.

The paper's second capability pillar (ROADMAP "Unified StableHLO model
runner"): one subsystem that takes an in-tree model (resnet, transformer,
bilstm), an ONNX import (``dl/onnx_import.py``), or any pure
``apply(variables, batch)`` callable, lowers it **once per (local device
set, bucketed batch shape)** through ``instrumented_jit`` into a cached
executable, and serves it behind two fronts:

- **batch transform** — :meth:`ModelRunner.apply_batch` owns the padding/
  bucketing/unpadding that ``dl/jax_model.py``, ``dl/image_featurizer.py``
  and the serving scorers each hand-rolled before this PR (power-of-two
  latency buckets: a 1-row request pads to 1, not ``batch_size``);
- **low-latency serving** — :meth:`ModelRunner.scorer` returns a
  ``Transformer`` that ``PipelineServer`` (and the streaming facade) score
  through: the server's continuous-mode drain admits requests into one
  in-flight batch, and the runner buckets that batch onto an already-lowered
  executable, so steady-state latency never pays a compile.

On top of it, generative scoring is a first-class workload:
:meth:`ModelRunner.decode` runs a KV-cached batched decode loop — one
prefill executable per (batch bucket, prompt bucket, cache geometry) plus
ONE single-token step executable re-dispatched every token, with
per-sequence lengths so ragged prompts decode exactly
(``models/transformer.py`` owns the cache math; docs/runner.md states the
correctness argument).  ISSUE 12 rebuilt the decode memory model: the step
executables DONATE the cache (and finished-mask) buffers so per-token
dispatch updates slots in place instead of allocating a fresh cache per
layer per token; the default greedy/eos path samples + freezes on device
(one (B,) token fetch per step, never the (B, V) logits); and
``kv_layout="paged"`` replaces the dense per-sequence reservation with
fixed-size pages from a shared :class:`PagePool` plus a per-sequence page
table, so hundreds of concurrent sequences share cache HBM by ACTUAL
length — the serving pattern the TPU-vs-GPU Gemma study in PAPERS.md
benchmarks, and the memory substrate the continuous-batching ROADMAP item
admits requests into.  The paged step is keyed on (batch bucket, page
size, table width): cache length stops being a compile key, collapsing the
per-``cache_len`` executable fan-out.

Lowering contract (the lower-once/execute-many precedent is the Julia→TPU
full-compilation work, PAPERS arxiv 1810.09868): every executable is keyed
by (device set, bucket shape) and built exactly once; compile counts ride
``mmlspark_jit_compile_total{fn="runner.<name>*"}`` so a recompile storm
across ragged batch sizes is impossible by construction and visible on
``/debug/compile`` if an input ever escapes the buckets.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from ..core import DataFrame, Transformer
from ..core.schema import ColumnType

__all__ = ["ModelRunner", "DecodeResult", "PagePool", "bucket_rows"]

#: fronts a batch can arrive through; metric label values
FRONTS = ("transform", "serving", "decode")


def bucket_rows(m: int, batch_size: int) -> int:
    """Power-of-two latency bucket for an ``m``-row chunk: a 1-row serving
    request pads to 1, not ``batch_size``; full chunks use ``batch_size``
    itself.  Each bucket lowers once and is cached."""
    if m >= batch_size:
        return batch_size
    return min(batch_size, 1 << (max(1, m) - 1).bit_length())


def _pad_rows(x: np.ndarray, target: int) -> np.ndarray:
    """Pad the leading dim to ``target`` by repeating the last row (cheap,
    and keeps the padded rows numerically tame for any model)."""
    m = x.shape[0]
    if m == target:
        return x
    pad = np.repeat(x[-1:], target - m, axis=0)
    return np.concatenate([x, pad], axis=0)


def _greedy_freeze(logits, finished, eos_id):
    """On-device greedy sampling + eos freeze — the ONE copy of the rule
    shared by the fused decode step and the prefill sampler: frozen
    sequences keep emitting ``eos_id``, and emitting it freezes."""
    import jax.numpy as jnp
    tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if eos_id is not None:
        tok = jnp.where(finished, eos_id, tok)
        finished = finished | (tok == eos_id)
    return tok, finished


def _cached_apply(module, variables, toks, positions, table, cache):
    """One call shape for every decode executable: ``table`` is ``None`` on
    the dense layout (an empty pytree — part of the jit signature, no
    tracing cost) and the kwarg is withheld so modules that only know
    ``init_cache`` keep working."""
    kw = {} if table is None else {"page_table": table}
    return module.apply(variables, toks, positions=positions,
                        kv_cache=cache, **kw)


@dataclass
class DecodeResult:
    """One batched decode: ``tokens[b, t]`` is the t-th generated token of
    sequence b; ``logits`` (collect_logits=True) holds the distribution
    that produced each token; ``steps`` counts device dispatches (prefill
    excluded); ``lengths`` echoes the prompt lengths the loop honoured;
    ``extras`` surfaces the resolved cache geometry — kv_layout,
    real_tokens (unfrozen steps only), cache_bytes_per_seq, and for the
    paged layout page_size / table_width / pages_peak /
    page_occupancy_pct — so callers (``mixed_load``'s decode class, the
    bench A/B) can report tokens/sec against the memory the decode
    actually held."""
    tokens: np.ndarray                 # (B, T) int32
    lengths: np.ndarray                # (B,) prompt lengths
    steps: int
    logits: Optional[np.ndarray] = None  # (B, T, V) float32
    extras: Optional[Dict[str, Any]] = None


class PagePool:
    """Fixed-size KV-cache page allocator — the shared-HBM memory model
    behind ``ModelRunner.decode(kv_layout="paged")`` (ISSUE 12 tentpole).

    The pool owns ``num_pages`` pages of ``page_size`` token slots each,
    materialized on device as ``module.init_paged_cache`` slabs of
    ``(num_pages, page_size, heads, head_dim)`` per layer, plus the
    host-side free list that hands pages to sequences: allocate by TRUE
    prompt length at prefill, extend one page at a time when a decode
    frontier crosses a page boundary, free on eos/completion.  Page 0 is
    the reserved trash page (pad rows and unallocated table entries point
    there; it is never handed out), so ``capacity == num_pages - 1``.
    Sequences therefore share cache HBM by actual length instead of
    reserving ``batch × max_len`` slots each — the occupancy and
    high-water gauges make the claim observable on ``/metrics``.

    The device slabs are BORROWED by one decode loop at a time (the step
    executables donate them in place, so two concurrent borrowers would
    consume each other's buffers); :meth:`borrow_cache` blocks until the
    previous borrower returns.  The accounting half (allocate/extend/free/
    occupancy) is lock-protected and usable standalone — sizing studies
    never have to build device slabs.
    """

    #: booking ops — each books pages moved, not call count
    OPS = ("allocate", "extend", "free")

    def __init__(self, module=None, num_pages: int = 0, page_size: int = 64,
                 *, name: str = "pool", registry=None):
        if num_pages < 2:
            raise ValueError(f"num_pages {num_pages} < 2: page 0 is the "
                             "reserved trash page, so a usable pool needs "
                             "at least one allocatable page")
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.module = module
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self._name = name
        #: free physical pages; page 0 (trash) is never in this list
        self._free = list(range(self.num_pages - 1, 0, -1))
        self._cond = threading.Condition(threading.Lock())
        self._cache = None          # built lazily, rebuilt if dropped
        self._cache_nbytes = 0
        self._borrowed = False
        self.high_water = 0
        #: True when the owning runner sized this pool implicitly (from a
        #: decode's worst case) — such pools may be grown for a larger
        #: batch; an explicitly budgeted pool is never resized behind the
        #: caller's back
        self.auto_sized = False
        from ..observability import get_registry
        reg = registry if registry is not None else get_registry()
        self._registry = reg
        # page_size is in the label set because one runner keeps a pool
        # PER page size — without it the pools would stomp one another's
        # occupancy series
        ops = reg.counter(
            "mmlspark_runner_page_ops_total",
            "KV page-pool pages moved by op (allocate/extend/free)",
            labels=("runner", "page_size", "op"))
        self._c_ops = {op: ops.labels(runner=name,
                                      page_size=str(self.page_size), op=op)
                       for op in self.OPS}
        self._g_used = reg.gauge(
            "mmlspark_runner_page_pool_used_pages",
            "KV pages currently held by live sequences",
            labels=("runner", "page_size"))
        self._g_hw = reg.gauge(
            "mmlspark_runner_page_pool_high_water_pages",
            "max KV pages ever simultaneously held",
            labels=("runner", "page_size"))
        self._book("allocate", 0)   # gauges live from construction

    # ---------------------------------------------------------- accounting
    @property
    def capacity(self) -> int:
        """Allocatable pages (the trash page is not allocatable)."""
        return self.num_pages - 1

    def token_capacity(self) -> int:
        """Total token slots the pool can hold across all sequences."""
        return self.capacity * self.page_size

    def pages_in_use(self) -> int:
        return self.capacity - len(self._free)

    def occupancy_pct(self) -> float:
        return 100.0 * self.pages_in_use() / max(self.capacity, 1)

    def _book(self, op: str, n: int) -> None:
        """Book one pool operation: the op counter plus the occupancy and
        high-water gauges (called under the pool lock)."""
        used = self.pages_in_use()
        if used > self.high_water:
            self.high_water = used
        self._c_ops[op].inc(n)
        ps = str(self.page_size)
        self._g_used.set(float(used), runner=self._name, page_size=ps)
        self._g_hw.set(float(self.high_water), runner=self._name,
                       page_size=ps)

    def allocate(self, n: int, op: str = "allocate"):
        """Hand out ``n`` pages (prefill sizing: ``ceil(true_len / page_
        size)`` per sequence).  Raises when the budget is exhausted —
        admission control, not silent overcommit."""
        with self._cond:
            if n > len(self._free):
                raise RuntimeError(
                    f"page pool exhausted: need {n} page(s), "
                    f"{len(self._free)} free of {self.capacity} "
                    f"(page_size={self.page_size}) — free finished "
                    "sequences, shrink the batch, or size the pool larger")
            pages = [self._free.pop() for _ in range(n)]
            self._book(op, n)
            return pages

    def extend(self, n: int = 1):
        """Allocate at a decode page-boundary crossing (same free list,
        booked as ``op="extend"`` so growth is attributable)."""
        return self.allocate(n, op="extend")

    def free(self, pages) -> None:
        """Return pages to the pool (eos/completion).  Freed pages are not
        zeroed: stale k/v in a reused page sits past the new owner's
        frontier until overwritten, so it is never admissible."""
        pages = [int(p) for p in pages]
        if any(p <= 0 or p >= self.num_pages for p in pages):
            raise ValueError(f"free() of invalid page in {pages} "
                             "(page 0 is the reserved trash page)")
        with self._cond:
            self._free.extend(pages)
            self._book("free", len(pages))

    # ------------------------------------------------------- device slabs
    def page_nbytes(self) -> int:
        """Device bytes per page across all layers (0 until slabs built)."""
        return self._cache_nbytes // self.num_pages if self._cache_nbytes \
            else 0

    def borrow_cache(self):
        """Take exclusive ownership of the device slabs (building them on
        first use), blocking while another decode holds them — the step
        executables donate the buffers, so exactly one loop may own them."""
        if self.module is None:
            raise TypeError("this PagePool was built without a module — "
                            "accounting only, no device slabs")
        with self._cond:
            while self._borrowed:
                self._cond.wait()
            self._borrowed = True
            cache = self._cache
            self._cache = None
        if cache is None:
            try:
                cache = self.module.init_paged_cache(self.num_pages,
                                                     self.page_size)
                import jax
                self._cache_nbytes = sum(
                    int(l.nbytes) for l in jax.tree_util.tree_leaves(cache))
            except Exception:
                # a failed slab build (HBM exhaustion) must not leave the
                # pool borrowed forever — every later borrower would block
                self.return_cache(None)
                raise
        return cache

    def resized(self, num_pages: int) -> "PagePool":
        """A fresh pool with the same module/page size/metric identity but
        ``num_pages`` pages.  Refuses while sequences hold pages or a
        decode holds the slabs — resizing would orphan them."""
        with self._cond:
            if self._borrowed or self.pages_in_use():
                raise RuntimeError(
                    f"cannot resize a busy page pool ({self.pages_in_use()} "
                    "page(s) held, borrowed="
                    f"{self._borrowed}) — wait for in-flight decodes")
        pool = PagePool(self.module, num_pages, self.page_size,
                        name=self._name, registry=self._registry)
        pool.auto_sized = self.auto_sized
        return pool

    def return_cache(self, cache) -> None:
        """Give the slabs back (pass ``None`` after a failed loop — the
        donated buffer state is unknown, so the next borrower rebuilds)."""
        with self._cond:
            self._borrowed = False
            self._cache = cache
            self._cond.notify()


class ModelRunner:
    """Compile-once execution cache + batch/serving/decode fronts.

    Accepts any of:

    - ``payload`` — an object exposing ``pure_apply`` / ``variables`` (and
      optionally ``module``): ``FlaxModelPayload``, ``OnnxModelPayload``;
    - ``module=`` + ``variables=`` — a flax module (resnet, transformer,
      bilstm); ``apply_kwargs`` forward to ``module.apply``;
    - ``apply_fn=`` + ``variables=`` — a raw pure ``(variables, batch)``
      callable.

    ``name`` labels every metric series and compile-report entry this
    runner books — keep it low-cardinality (a model family, not a uid).
    """

    def __init__(self, payload=None, *, module=None, variables=None,
                 apply_fn: Optional[Callable] = None,
                 apply_kwargs: Optional[Dict[str, Any]] = None,
                 name: str = "model", batch_size: int = 64,
                 registry=None):
        if payload is not None:
            self._pure = payload.pure_apply
            self.variables = payload.variables
            self.module = getattr(payload, "module", None)
        elif apply_fn is not None:
            self._pure = apply_fn
            self.variables = variables
            self.module = module
        elif module is not None:
            kw = dict(apply_kwargs or {})

            def _pure(vs, batch, _m=module, _kw=kw):
                return _m.apply(vs, batch, **_kw)

            self._pure = _pure
            self.variables = variables
            self.module = module
        else:
            raise ValueError("need a payload, a module, or an apply_fn")
        self.name = name
        self.batch_size = int(batch_size)
        from ..observability import get_registry
        self.registry = registry if registry is not None else get_registry()
        #: (kind, device_key, *shape) -> executable; every entry lowered once
        self._executables: Dict[Tuple, Callable] = {}
        #: name -> InstrumentedJit wrappers this runner created (compile
        #: introspection for tests and compile_stats)
        self._wrappers: list = []
        self._lock = threading.Lock()
        reg = self.registry
        c_batches = reg.counter(
            "mmlspark_runner_batches_total",
            "device dispatches per runner by front",
            labels=("runner", "front"))
        c_rows = reg.counter(
            "mmlspark_runner_rows_total",
            "real (unpadded) rows scored per runner by front",
            labels=("runner", "front"))
        self._c_batches = {f: c_batches.labels(runner=name, front=f)
                          for f in FRONTS}
        self._c_rows = {f: c_rows.labels(runner=name, front=f)
                        for f in FRONTS}
        self._c_pad = reg.counter(
            "mmlspark_runner_pad_rows_total",
            "padding rows added by bucketing (wasted device work)",
            labels=("runner",)).labels(runner=name)
        self._c_decode_steps = reg.counter(
            "mmlspark_runner_decode_steps_total",
            "single-token decode-step dispatches",
            labels=("runner",)).labels(runner=name)
        self._c_decode_tokens = reg.counter(
            "mmlspark_runner_decode_tokens_total",
            "per-sequence real generated tokens (unfrozen steps only; "
            "eos-frozen tails and pad rows are not generated work)",
            labels=("runner",)).labels(runner=name)
        # page-pool surface (paged decode): families registered at
        # construction so the telemetry-coverage sweep gates on them even
        # for runners that never decode; PagePool binds the children
        # (page_size in the labels: one runner keeps a pool per page size)
        reg.counter("mmlspark_runner_page_ops_total",
                    "KV page-pool pages moved by op (allocate/extend/free)",
                    labels=("runner", "page_size", "op"))
        reg.gauge("mmlspark_runner_page_pool_used_pages",
                  "KV pages currently held by live sequences",
                  labels=("runner", "page_size"))
        reg.gauge("mmlspark_runner_page_pool_high_water_pages",
                  "max KV pages ever simultaneously held",
                  labels=("runner", "page_size"))
        #: (device key, page size) -> shared PagePool for paged decode
        self._pools: Dict[Tuple, PagePool] = {}
        #: resolved geometry of the most recent decode (DecodeResult.extras)
        self.last_decode_extras: Optional[Dict[str, Any]] = None

    # ------------------------------------------------------------- lowering
    @staticmethod
    def _device_key() -> Tuple:
        """The local device set the executables are specialized to; a mesh
        change (tests swapping in mesh8, a late-attached accelerator)
        re-keys instead of serving a stale placement."""
        from ..parallel import get_active_mesh
        mesh = get_active_mesh()
        return tuple(int(d.id) for d in mesh.devices.flat)

    def _instrumented(self, fn: Callable, suffix: str = "", **jit_kwargs):
        from ..observability.compute import instrumented_jit
        wrapper = instrumented_jit(
            fn, name=f"runner.{self.name}{suffix}",
            registry=self.registry, **jit_kwargs)
        self._wrappers.append(wrapper)
        return wrapper

    def executable(self, bucket_n: int, feat_shape: Tuple[int, ...]):
        """The compiled apply for one (device set, bucketed batch shape) —
        built on first use, a dict hit forever after.  Multi-device meshes
        shard the batch dim over ``data`` with params replicated (inference
        DP); multi-host processes stage their host-local batch as a global
        array explicitly (jit refuses host-local numpy for non-replicated
        shardings; every process holds the SAME batch under the executor
        model — identical partition per call)."""
        key = ("apply", self._device_key(), int(bucket_n), tuple(feat_shape))
        fn = self._executables.get(key)
        if fn is not None:
            return fn
        with self._lock:
            fn = self._executables.get(key)
            if fn is not None:
                return fn
            import jax
            from ..parallel import batch_sharded, get_active_mesh, replicated
            mesh = get_active_mesh()
            n_dev = mesh.devices.size
            if n_dev > 1 and bucket_n % n_dev == 0:
                sharded = self._instrumented(
                    self._pure,
                    in_shardings=(replicated(mesh), batch_sharded(mesh)),
                    out_shardings=replicated(mesh))
                if jax.process_count() > 1:
                    bsh = batch_sharded(mesh)

                    def fn(variables, chunk, _inner=sharded, _s=bsh):
                        garr = jax.make_array_from_callback(
                            chunk.shape, _s, lambda idx: chunk[idx])
                        return _inner(variables, garr)
                else:
                    fn = sharded
            else:
                fn = self._instrumented(self._pure)
            self._executables[key] = fn
        return fn

    def compile_stats(self) -> Dict[str, Any]:
        """Introspection for tests and ops: executables cached by key plus
        the underlying compile count (one per signature by contract)."""
        return {
            "executables": sorted(
                "/".join(str(p) for p in k) for k in self._executables),
            "compiles": sum(getattr(w, "compiles", 0)
                            for w in self._wrappers),
        }

    # ------------------------------------------------------------ batch front
    def apply_batch(self, x: np.ndarray, front: str = "transform",
                    batch_size: Optional[int] = None) -> np.ndarray:
        """Score a stacked host batch of any row count: chunk to
        ``batch_size``, pad each chunk to its power-of-two bucket, run the
        cached executable, unpad, concatenate.  This is the ONE copy of the
        pad/bucket glue the per-model transformers used to hand-roll."""
        bs = int(batch_size or self.batch_size)
        n = x.shape[0]
        if n == 0:
            return np.empty((0,), dtype=np.float32)
        variables = self.variables
        outs = []
        pad_total = 0
        for start in range(0, n, bs):
            chunk = x[start:start + bs]
            m = chunk.shape[0]
            bucket = bucket_rows(m, bs)
            pad_total += bucket - m
            chunk = _pad_rows(chunk, bucket)
            fn = self.executable(bucket, chunk.shape[1:])
            outs.append(np.asarray(fn(variables, chunk))[:m])
            self._c_batches[front].inc()
        self._c_rows[front].inc(n)
        if pad_total:
            self._c_pad.inc(pad_total)
        return np.concatenate(outs, axis=0)

    # ---------------------------------------------------------- serving front
    def scorer(self, input_col: str = "request", reply_col: str = "reply",
               prepare: Optional[Callable] = None,
               encode: Optional[Callable] = None,
               mode: str = "score", **decode_kwargs) -> "Transformer":
        """A ``Transformer`` front for ``PipelineServer`` / the streaming
        facade.  ``mode="score"`` stacks request rows (via ``prepare``,
        default ``np.asarray(..., float32)``) and scores them through
        :meth:`apply_batch`; ``mode="decode"`` treats each request as a
        token-id prompt and returns generated token lists from
        :meth:`decode` (``decode_kwargs`` forward — ``max_new_tokens=``,
        ``eos_id=``, and the cache layout: ``kv_layout="paged"`` with
        ``page_size=``/``pool=`` serves the drain from shared page-pool
        HBM by actual sequence length, instead of the dense per-sequence
        max-length reservation; the resolved geometry rides
        ``DecodeResult.extras`` / ``runner.last_decode_extras`` so
        ``mixed_load``'s decode class can report tokens/sec against it).
        The server's continuous-mode drain is the admission window:
        whatever is in flight when the scorer runs becomes ONE bucketed
        device batch."""
        if mode not in ("score", "decode"):
            raise ValueError("scorer mode must be score|decode")
        return _RunnerScorer(self, input_col, reply_col, prepare, encode,
                             mode, decode_kwargs)

    # ------------------------------------------------------------ decode front
    def page_pool(self, page_size: int = 64,
                  num_pages: Optional[int] = None) -> Optional["PagePool"]:
        """The runner's shared :class:`PagePool` for ``page_size`` —
        created on first use (sized by ``num_pages``; a paged decode
        without an explicit pool sizes it to its own worst case and grows
        it for larger batches) and reused by every later paged decode at
        this page size, so the occupancy/high-water gauges describe the
        shared cache HBM, not one call.  Passing ``num_pages`` when a pool
        already exists RESIZES it (the explicit-budget escape hatch;
        raises while sequences hold pages).  Returns ``None`` when no pool
        exists yet and ``num_pages`` was not given."""
        key = (self._device_key(), int(page_size))
        with self._lock:
            pool = self._pools.get(key)
            if num_pages is not None:
                if pool is None:
                    pool = self._pools[key] = PagePool(
                        self.module, num_pages, page_size, name=self.name,
                        registry=self.registry)
                elif pool.num_pages != int(num_pages):
                    pool = self._pools[key] = pool.resized(int(num_pages))
                pool.auto_sized = False
            return pool

    def _auto_pool(self, page_size: int, need_pages: int) -> PagePool:
        """The implicit pool for a paged decode that brought no budget:
        create at this call's worst case, or GROW an earlier auto-sized
        pool that a larger batch has outrun (an explicitly budgeted pool
        is never resized — its exhaustion is admission control).  Growth
        is best-effort: if another decode holds pages right now, the
        existing pool serves and may legitimately run out."""
        key = (self._device_key(), int(page_size))
        with self._lock:
            pool = self._pools.get(key)
            if pool is None:
                pool = self._pools[key] = PagePool(
                    self.module, need_pages, page_size, name=self.name,
                    registry=self.registry)
                pool.auto_sized = True
            elif pool.auto_sized and pool.num_pages < need_pages:
                try:
                    pool = self._pools[key] = pool.resized(need_pages)
                except RuntimeError:
                    pass                      # busy: keep the current pool
            return pool

    def _decode_executables(self, batch_b: int, prompt_b: int,
                            cache_len: Optional[int] = None, *,
                            page_size: Optional[int] = None,
                            table_w: Optional[int] = None,
                            fused: bool = False,
                            eos_id: Optional[int] = None):
        """(prefill, step) executables for one decode signature.

        Dense: prefill keys on (batch bucket, prompt bucket, cache length),
        the step on (batch bucket, cache length) only.  Paged: prefill keys
        on (batch bucket, prompt bucket, page size, table width) and the
        step on (batch bucket, page size, table width) — cache LENGTH is no
        longer a compile key, so decode signatures that differ only in
        reservation collapse onto one step executable.  Either way the
        step's input shapes are constant across the whole generation loop:
        EVERY token of EVERY request at the signature re-dispatches one
        compiled program.

        Donation contract (ISSUE 12): prefill donates the cache buffers it
        consumes, and the step donates the cache (and, on the fused path,
        the finished mask) so the per-token dispatch updates slots in place
        instead of allocating a fresh (B, S, H, D) per layer per token.
        The host loop must treat every donated argument as CONSUMED — it
        rebinds ``cache``/``finished`` from the step's outputs and never
        touches the stale references (the donation-safety regression test
        pins this).  ``fused=True`` builds the greedy/eos fast-path step
        that samples + freezes on device and returns the (B,) next token
        instead of (B, V) logits; ``eos_id`` is baked into that executable
        (part of its key — low-cardinality by construction)."""
        import jax.numpy as jnp
        module = self.module
        dkey = self._device_key()
        paged = page_size is not None
        if paged:
            kp = ("prefill_paged", dkey, batch_b, prompt_b, page_size,
                  table_w)
            ks = ("step_paged", dkey, batch_b, page_size, table_w)
        else:
            kp = ("prefill", dkey, batch_b, prompt_b, cache_len)
            ks = ("step", dkey, batch_b, cache_len)
        if fused:
            ks = ks + ("fused", eos_id)
        prefill = self._executables.get(kp)
        step = self._executables.get(ks)
        if prefill is not None and step is not None:
            return prefill, step
        sfx = "_paged" if paged else ""
        with self._lock:
            prefill = self._executables.get(kp)
            if prefill is None:
                def _prefill(variables, toks, positions, lengths, table,
                             cache, _m=module):
                    logits, cache = _cached_apply(_m, variables, toks,
                                                  positions, table, cache)
                    # last REAL token's logits per sequence — gathered
                    # on-device so the (B, P, V) tensor never crosses to
                    # host
                    last = jnp.take_along_axis(
                        logits, (lengths - 1)[:, None, None], axis=1)[:, 0]
                    return last, cache

                prefill = self._executables[kp] = self._instrumented(
                    _prefill, suffix=f".prefill{sfx}", donate_argnums=(5,))
            step = self._executables.get(ks)
            if step is None:
                if fused:
                    def _step(variables, tok, positions, table, finished,
                              cache, _m=module, _eos=eos_id):
                        logits, cache = _cached_apply(
                            _m, variables, tok[:, None], positions[:, None],
                            table, cache)
                        nxt, finished = _greedy_freeze(logits[:, 0],
                                                       finished, _eos)
                        return nxt, finished, cache

                    step = self._instrumented(
                        _step, suffix=f".decode_step{sfx}",
                        donate_argnums=(4, 5))
                else:
                    def _step(variables, tok, positions, table, cache,
                              _m=module):
                        logits, cache = _cached_apply(_m, variables, tok,
                                                      positions, table,
                                                      cache)
                        return logits[:, 0], cache

                    step = self._instrumented(
                        _step, suffix=f".decode_step{sfx}",
                        donate_argnums=(4,))
                self._executables[ks] = step
        return prefill, step

    def _sample_executable(self, batch_b: int, eos_id: Optional[int]):
        """On-device greedy sampler for the fused fast path: argmax + eos
        freeze without the (B, V) prefill logits ever crossing to host.
        Donates the finished mask (aliased to the output mask); the logits
        have no same-shaped output to alias, so donating them would only
        warn."""
        key = ("sample", self._device_key(), batch_b, eos_id)
        fn = self._executables.get(key)
        if fn is not None:
            return fn
        with self._lock:
            fn = self._executables.get(key)
            if fn is None:
                def _sample(last, finished, _eos=eos_id):
                    return _greedy_freeze(last, finished, _eos)

                fn = self._executables[key] = self._instrumented(
                    _sample, suffix=".decode_sample", donate_argnums=(1,))
        return fn

    def decode(self, prompts: np.ndarray, lengths=None,
               max_new_tokens: int = 16, eos_id: Optional[int] = None,
               sample_fn: Optional[Callable] = None,
               collect_logits: bool = False,
               batch_bucket: Optional[int] = None,
               prompt_bucket: Optional[int] = None,
               cache_len: Optional[int] = None,
               kv_layout: str = "dense",
               page_size: int = 64,
               pool: Optional[PagePool] = None) -> DecodeResult:
        """KV-cached batched autoregressive generation.

        ``prompts`` is ``(B, P)`` int32 (rows padded to the longest prompt);
        ``lengths`` gives each sequence's true prompt length so ragged
        batches decode exactly — each sequence writes and reads the cache at
        ITS own frontier.  Buckets: ``B`` pads to a power-of-two row bucket
        and ``P`` to a power-of-two prompt bucket.

        Cache memory (``kv_layout``): ``"dense"`` reserves one
        ``(cache_len,)`` slot row per sequence up front (``cache_len``
        defaults to the next power of two covering prompt + new tokens);
        ``"paged"`` allocates fixed-size pages from a shared
        :class:`PagePool` by ACTUAL length — ``ceil(true_len/page_size)``
        pages at prefill, one more at each page-boundary crossing, freed on
        eos — so concurrency scales with the tokens actually held, not
        ``B × max_len`` (pass ``pool=`` to share an explicitly sized
        budget; otherwise the runner's implicit pool for ``page_size`` is
        used, created at this call's worst case and grown when a larger
        batch outruns it).

        Sampling: ``sample_fn(logits) -> tokens`` defaults to greedy
        argmax; ``eos_id`` freezes finished sequences (and ends the loop
        early once ALL are finished).  When ``sample_fn`` is None and
        ``collect_logits`` is False, sampling + eos freezing run ON DEVICE
        and the step executables donate the cache/finished buffers: the
        common path fetches one (B,) token per step instead of the (B, V)
        logits, and the cache is updated in place instead of reallocated
        per token.

        Paged + eos caveat: once a frozen row's pages are freed its later
        logits are unspecified (its tokens are forced to ``eos_id``, and a
        ``sample_fn``'s output for frozen rows is discarded, so tokens are
        unaffected).  ``collect_logits=True`` keeps frozen rows' pages
        live instead, so the recorded distributions match the dense
        layout within the committed tolerance at every step."""
        if self.module is None or not hasattr(self.module, "init_cache"):
            raise TypeError(
                "decode() needs a module with init_cache (a KV-cache-capable "
                "model, e.g. models.TransformerEncoder with causal=True, "
                "pool='none'); this runner wraps "
                f"{type(self.module).__name__ if self.module else 'a raw apply_fn'}")
        import jax
        import jax.numpy as jnp
        prompts = np.asarray(prompts, np.int32)
        if prompts.ndim != 2:
            raise ValueError("prompts must be (batch, prompt_len) int32")
        B, P = prompts.shape
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if kv_layout not in ("dense", "paged"):
            raise ValueError("kv_layout must be dense|paged")
        paged = kv_layout == "paged" or pool is not None
        lengths = (np.full(B, P, np.int32) if lengths is None
                   else np.asarray(lengths, np.int32))
        if lengths.shape != (B,) or lengths.min() < 1 or lengths.max() > P:
            raise ValueError("lengths must be (batch,) in [1, prompt_len]")
        B_b = batch_bucket or 1 << (B - 1).bit_length()
        P_b = prompt_bucket or 1 << (P - 1).bit_length()
        if B_b < B or P_b < P:
            raise ValueError("bucket smaller than the batch/prompt it serves")
        # greedy/eos fast path: sample + freeze on device (donated buffers)
        fused = sample_fn is None and not collect_logits
        toks = np.zeros((B_b, P_b), np.int32)
        toks[:B, :P] = prompts
        lens = np.concatenate([lengths, np.ones(B_b - B, np.int32)])
        self._c_pad.inc((B_b - B) * P_b + B * (P_b - P))
        variables = self.variables

        table = None
        seq_pages: list = []
        if paged:
            if not hasattr(self.module, "init_paged_cache"):
                raise TypeError(
                    "kv_layout='paged' needs a module with init_paged_cache "
                    "(e.g. models.TransformerEncoder); "
                    f"{type(self.module).__name__} has none")
            if cache_len is not None:
                raise ValueError(
                    "cache_len is a dense-layout parameter (it sizes the "
                    "per-sequence reservation); the paged layout sizes "
                    "cache by pages — use page_size/pool instead")
            if pool is not None:
                page_size = pool.page_size
            page_size = int(page_size)
            if page_size < 1:
                raise ValueError("page_size must be >= 1")
            table_w = -(-(P_b + max_new_tokens) // page_size)
            max_len = getattr(self.module, "max_len", None)
            if max_len is not None and P_b + max_new_tokens > max_len:
                raise ValueError(
                    f"prompt_bucket + max_new_tokens = "
                    f"{P_b + max_new_tokens} exceeds the module's max_len "
                    f"{max_len} (positional table bound)")
            if pool is None:
                pool = self._auto_pool(page_size, B_b * table_w + 1)
            prefill, step = self._decode_executables(
                B_b, P_b, page_size=page_size, table_w=table_w,
                fused=fused, eos_id=eos_id)
            table = np.zeros((B_b, table_w), np.int32)
            seq_pages = [[] for _ in range(B_b)]
            try:
                # allocate by TRUE length — pad rows (and unallocated table
                # entries) stay on the trash page and never hold pool pages
                for b in range(B):
                    n_pages = -(-int(lengths[b]) // page_size)
                    pgs = pool.allocate(n_pages)
                    seq_pages[b] = list(pgs)
                    table[b, :n_pages] = pgs
                cache = pool.borrow_cache()
            except Exception:
                # a failed allocation or slab build must not leak the pages
                # already handed to earlier rows (borrow_cache resets its
                # own borrowed flag on failure)
                leftover = [p for pgs in seq_pages for p in pgs]
                if leftover:
                    pool.free(leftover)
                raise
            pages_prefill = sum(len(p) for p in seq_pages)
            peak_pages = pool.pages_in_use()
        else:
            S = cache_len or 1 << (P_b + max_new_tokens - 1).bit_length()
            if S < P_b + max_new_tokens:
                raise ValueError(
                    f"cache_len {S} is below prompt_bucket + max_new_tokens "
                    f"= {P_b + max_new_tokens}: the dense layout reserves "
                    "one full (cache_len,) slot row per sequence up front, "
                    "so the reservation must cover the longest possible "
                    "generation — raise cache_len, or switch to "
                    "kv_layout='paged' to size by actual length instead")
            prefill, step = self._decode_executables(
                B_b, P_b, cache_len=S, fused=fused, eos_id=eos_id)
            cache = self.module.init_cache(B_b, S)
            cache_nbytes = sum(int(l.nbytes)
                               for l in jax.tree_util.tree_leaves(cache))
        positions = np.broadcast_to(np.arange(P_b, dtype=np.int32),
                                    (B_b, P_b))
        sample = sample_fn or (lambda lg: np.argmax(lg, axis=-1))
        out_tokens = np.zeros((B_b, max_new_tokens), np.int32)
        out_logits = [] if collect_logits else None
        # pad rows are born finished: their garbage samples must never hold
        # the eos early-exit open (or inflate the step/token counters)
        finished = np.zeros(B_b, bool)
        finished[B:] = True
        steps = 0
        real_tokens = 0
        ok = False
        # every executable shares one signature; table is None (an empty
        # pytree) on the dense layout, and the device copy is re-uploaded
        # only when extend/free dirties it
        table_dev = jnp.asarray(table) if paged else None
        table_dirty = False
        try:
            last, cache = prefill(
                variables, jnp.asarray(toks), jnp.asarray(positions),
                jnp.asarray(lens), table_dev, cache)
            self._c_batches["decode"].inc()
            if fused:
                tok_d, fin_d = self._sample_executable(B_b, eos_id)(
                    last, jnp.asarray(finished))
            for t in range(max_new_tokens):
                if fused:
                    # the ONLY host fetches on the fast path: the (B,) token
                    # ids + (B,) finished flags; logits stay on device
                    tok = np.asarray(tok_d)
                    fin_now = np.asarray(fin_d)
                else:
                    lg = np.asarray(last)                  # (B_b, V) fetch
                    if collect_logits:
                        out_logits.append(lg)
                    tok = np.asarray(sample(lg), np.int32)
                    if eos_id is not None:
                        tok = np.where(finished, eos_id, tok)
                        fin_now = finished | (tok == eos_id)
                    else:
                        fin_now = finished
                # tokens emitted while a sequence was already frozen are eos
                # padding, not generated work (ISSUE 12 bugfix: the old
                # B * n_generated charge inflated fleet tokens/sec and the
                # autoscale signal on early-finishing batches)
                real_tokens += B - int(finished[:B].sum())
                out_tokens[:, t] = tok
                if paged and eos_id is not None and not collect_logits:
                    # free on eos: pages return to the pool mid-flight; the
                    # frozen row keeps stepping, but its zeroed table rows
                    # point every further write at the trash page (its
                    # post-freeze logits become unspecified — tokens are
                    # forced to eos either way).  collect_logits keeps
                    # frozen rows live instead, so the recorded
                    # distributions match the dense layout exactly.
                    for b in np.nonzero(fin_now[:B] & ~finished[:B])[0]:
                        if seq_pages[b]:
                            pool.free(seq_pages[b])
                            seq_pages[b] = []
                            table[b, :] = 0
                            table_dirty = True
                finished = fin_now
                if t == max_new_tokens - 1 or \
                        (eos_id is not None and bool(finished.all())):
                    break
                # token t sits at absolute position lengths + t; the step
                # writes it at that frontier and returns logits for t+1
                # (host path) or the sampled token t+1 (fused path)
                pos = (lens + t).astype(np.int32)
                if paged:
                    # extend at page boundaries: the write position must be
                    # backed by a real page BEFORE the step dispatches.
                    # Frozen rows stop extending once freed — except under
                    # collect_logits, where they stay live (logits parity)
                    for b in range(B):
                        if finished[b] and not collect_logits:
                            continue
                        pi = int(pos[b]) // page_size
                        if pi >= len(seq_pages[b]):
                            new_page = pool.extend()[0]
                            seq_pages[b].append(new_page)
                            table[b, pi] = new_page
                            table_dirty = True
                    peak_pages = max(peak_pages, pool.pages_in_use())
                    if table_dirty:
                        # re-upload only when extend/free actually changed
                        # the table — steady-state steps reuse the resident
                        # copy (the table arg is never donated)
                        table_dev = jnp.asarray(table)
                        table_dirty = False
                if fused:
                    # donated dispatch: fin_d/cache are CONSUMED here — the
                    # loop rebinds all three outputs and must never touch
                    # the stale references again
                    tok_d, fin_d, cache = step(variables, tok_d,
                                               jnp.asarray(pos), table_dev,
                                               fin_d, cache)
                else:
                    last, cache = step(variables, jnp.asarray(tok[:, None]),
                                       jnp.asarray(pos[:, None]), table_dev,
                                       cache)
                steps += 1
                self._c_decode_steps.inc()
            ok = True
        finally:
            if paged:
                leftover = [p for pgs in seq_pages for p in pgs]
                if leftover:
                    pool.free(leftover)
                # after a mid-step failure the donated slab state is
                # unknown — drop it so the next borrower rebuilds zeros
                pool.return_cache(cache if ok else None)
        n_generated = t + 1
        self._c_decode_tokens.inc(real_tokens)
        self._c_rows["decode"].inc(B)
        extras: Dict[str, Any] = {
            "kv_layout": "paged" if paged else "dense",
            "real_tokens": real_tokens,
            "batch_bucket": B_b,
        }
        if paged:
            extras.update(
                page_size=page_size, table_width=table_w,
                pool_pages=pool.capacity, pages_prefill=pages_prefill,
                pages_peak=peak_pages,
                page_occupancy_pct=round(
                    100.0 * peak_pages / max(pool.capacity, 1), 2),
                cache_bytes_per_seq=pool.page_nbytes() * peak_pages
                / max(B, 1))
        else:
            extras.update(cache_len=S,
                          cache_bytes_per_seq=cache_nbytes / max(B, 1))
        self.last_decode_extras = extras
        logits = (np.stack(out_logits, axis=1)[:B] if collect_logits
                  else None)
        return DecodeResult(tokens=out_tokens[:B, :n_generated],
                            lengths=lengths, steps=steps, logits=logits,
                            extras=extras)


class _RunnerScorer(Transformer):
    """Private serving front: built by :meth:`ModelRunner.scorer`, scored by
    ``PipelineServer`` / the streaming facade.  Not a registered stage —
    it is constructed programmatically around a live runner, never from
    params, so it stays out of codegen/fuzzing by the ``_`` convention."""

    def __init__(self, runner: ModelRunner, input_col: str, reply_col: str,
                 prepare: Optional[Callable], encode: Optional[Callable],
                 mode: str, decode_kwargs: Dict[str, Any]):
        super().__init__()
        self.runner = runner
        self.input_col, self.reply_col = input_col, reply_col
        self.prepare = prepare or (lambda v: np.asarray(v, np.float32))
        self.encode = encode or (lambda y: y)
        self.mode = mode
        self.decode_kwargs = dict(decode_kwargs)

    def _transform(self, df: DataFrame) -> DataFrame:
        def per_part(p):
            col = p[self.input_col]
            n = len(col)
            out = np.empty(n, dtype=object)
            if n == 0:
                return {**p, self.reply_col: out}
            if self.mode == "decode":
                prompts = [np.asarray(v, np.int32).reshape(-1) for v in col]
                lengths = np.asarray([len(q) for q in prompts], np.int32)
                P = int(lengths.max())
                stacked = np.zeros((n, P), np.int32)
                for i, q in enumerate(prompts):
                    stacked[i, :len(q)] = q
                res = self.runner.decode(stacked, lengths=lengths,
                                         **self.decode_kwargs)
                for i in range(n):
                    out[i] = self.encode(res.tokens[i])
            else:
                x = np.stack([self.prepare(v) for v in col])
                y = self.runner.apply_batch(x, front="serving")
                for i in range(n):
                    out[i] = self.encode(y[i])
            return {**p, self.reply_col: out}

        return df.map_partitions(per_part)

    def transform_schema(self, schema):
        schema.require(self.input_col)
        return schema.add(self.reply_col, ColumnType.VECTOR)
