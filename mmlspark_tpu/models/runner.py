"""Unified model runner — lower-once StableHLO execution for every model.

The paper's second capability pillar (ROADMAP "Unified StableHLO model
runner"): one subsystem that takes an in-tree model (resnet, transformer,
bilstm), an ONNX import (``dl/onnx_import.py``), or any pure
``apply(variables, batch)`` callable, lowers it **once per (local device
set, bucketed batch shape)** through ``instrumented_jit`` into a cached
executable, and serves it behind two fronts:

- **batch transform** — :meth:`ModelRunner.apply_batch` owns the padding/
  bucketing/unpadding that ``dl/jax_model.py``, ``dl/image_featurizer.py``
  and the serving scorers each hand-rolled before this PR (power-of-two
  latency buckets: a 1-row request pads to 1, not ``batch_size``);
- **low-latency serving** — :meth:`ModelRunner.scorer` returns a
  ``Transformer`` that ``PipelineServer`` (and the streaming facade) score
  through: the server's continuous-mode drain admits requests into one
  in-flight batch, and the runner buckets that batch onto an already-lowered
  executable, so steady-state latency never pays a compile.

On top of it, generative scoring is a first-class workload:
:meth:`ModelRunner.decode` runs a KV-cached batched decode loop — one
prefill executable per (batch bucket, prompt bucket, cache geometry) plus
ONE single-token step executable re-dispatched every token, with
per-sequence lengths so ragged prompts decode exactly
(``models/transformer.py`` owns the cache math; docs/runner.md states the
correctness argument).  ISSUE 12 rebuilt the decode memory model: the step
executables DONATE the cache (and finished-mask) buffers so per-token
dispatch updates slots in place instead of allocating a fresh cache per
layer per token; the default greedy/eos path samples + freezes on device
(one (B,) token fetch per step, never the (B, V) logits); and
``kv_layout="paged"`` replaces the dense per-sequence reservation with
fixed-size pages from a shared :class:`PagePool` plus a per-sequence page
table, so hundreds of concurrent sequences share cache HBM by ACTUAL
length — the serving pattern the TPU-vs-GPU Gemma study in PAPERS.md
benchmarks, and the memory substrate the continuous-batching ROADMAP item
admits requests into.  The paged step is keyed on (batch bucket, page
size, table width): cache length stops being a compile key, collapsing the
per-``cache_len`` executable fan-out.

Lowering contract (the lower-once/execute-many precedent is the Julia→TPU
full-compilation work, PAPERS arxiv 1810.09868): every executable is keyed
by (device set, bucket shape) and built exactly once; compile counts ride
``mmlspark_jit_compile_total{fn="runner.<name>*"}`` so a recompile storm
across ragged batch sizes is impossible by construction and visible on
``/debug/compile`` if an input ever escapes the buckets.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..core import DataFrame, Transformer
from ..utils.concurrency import make_condition, make_lock
from ..core.schema import ColumnType

__all__ = ["ModelRunner", "DecodeResult", "PagePool", "ContinuousDecoder",
           "StreamHandle", "PagePoolExhausted", "SlotsExhausted", "ShedReply",
           "bucket_rows"]

#: fronts a batch can arrive through; metric label values
FRONTS = ("transform", "serving", "decode")


class PagePoolExhausted(RuntimeError):
    """The page pool cannot cover an allocation — admission control, not a
    crash.  ``shed`` duck-types the serving layer's shed path (serving maps
    it to 503 + Retry-After without importing this module)."""
    shed = True


class SlotsExhausted(RuntimeError):
    """No free decode slot for a new arrival — the continuous engine's
    admission-control twin of :class:`PagePoolExhausted`."""
    shed = True


class EngineDraining(RuntimeError):
    """The decoder is draining (graceful shutdown, ISSUE 16): no new
    joins — existing slots run to eos/budget, arrivals shed retryably."""
    shed = True
    shed_reason = "draining"


class EngineUnavailable(RuntimeError):
    """The continuous decode engine cannot take this request right now —
    restart backoff in progress, or the runner is quarantined after
    repeated stalls (ISSUE 16).  A retryable shed (another worker can
    serve it), not a failure: ``shed`` duck-types the serving 503 path."""
    shed = True

    def __init__(self, msg: str, reason: str, retry_after_s: float = 1.0):
        super().__init__(msg)
        self.shed_reason = reason
        self.retry_after_s = float(retry_after_s)


class ShedReply:
    """Per-row shed sentinel: a scorer that must refuse ONE row of a batch
    (mid-decode page denial) returns this in the reply column, and the
    serving layer maps it to 503 + Retry-After.  Duck-typed on
    ``shed_reason`` so serving never imports the models package."""

    __slots__ = ("shed_reason", "retry_after_s")

    def __init__(self, reason: str, retry_after_s: Optional[float] = None):
        self.shed_reason = reason
        self.retry_after_s = retry_after_s


def bucket_rows(m: int, batch_size: int) -> int:
    """Power-of-two latency bucket for an ``m``-row chunk: a 1-row serving
    request pads to 1, not ``batch_size``; full chunks use ``batch_size``
    itself.  Each bucket lowers once and is cached."""
    if m >= batch_size:
        return batch_size
    return min(batch_size, 1 << (max(1, m) - 1).bit_length())


def _pad_rows(x: np.ndarray, target: int) -> np.ndarray:
    """Pad the leading dim to ``target`` by repeating the last row (cheap,
    and keeps the padded rows numerically tame for any model)."""
    m = x.shape[0]
    if m == target:
        return x
    pad = np.repeat(x[-1:], target - m, axis=0)
    return np.concatenate([x, pad], axis=0)


def _greedy_freeze(logits, finished, eos_id):
    """On-device greedy sampling + eos freeze — the ONE copy of the rule
    shared by the fused decode step and the prefill sampler: frozen
    sequences keep emitting ``eos_id``, and emitting it freezes."""
    import jax.numpy as jnp
    tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if eos_id is not None:
        tok = jnp.where(finished, eos_id, tok)
        finished = finished | (tok == eos_id)
    return tok, finished


def _cached_apply(module, variables, toks, positions, table, cache):
    """One call shape for every decode executable: ``table`` is ``None`` on
    the dense layout (an empty pytree — part of the jit signature, no
    tracing cost) and the kwarg is withheld so modules that only know
    ``init_cache`` keep working."""
    kw = {} if table is None else {"page_table": table}
    return module.apply(variables, toks, positions=positions,
                        kv_cache=cache, **kw)


@dataclass
class DecodeResult:
    """One batched decode: ``tokens[b, t]`` is the t-th generated token of
    sequence b; ``logits`` (collect_logits=True) holds the distribution
    that produced each token; ``steps`` counts device dispatches (prefill
    excluded); ``lengths`` echoes the prompt lengths the loop honoured;
    ``extras`` surfaces the resolved cache geometry — kv_layout,
    real_tokens (unfrozen steps only), cache_bytes_per_seq, and for the
    paged layout page_size / table_width / pages_peak /
    page_occupancy_pct — so callers (``mixed_load``'s decode class, the
    bench A/B) can report tokens/sec against the memory the decode
    actually held."""
    tokens: np.ndarray                 # (B, T) int32
    lengths: np.ndarray                # (B,) prompt lengths
    steps: int
    logits: Optional[np.ndarray] = None  # (B, T, V) float32
    extras: Optional[Dict[str, Any]] = None


class PagePool:
    """Fixed-size KV-cache page allocator — the shared-HBM memory model
    behind ``ModelRunner.decode(kv_layout="paged")`` (ISSUE 12 tentpole).

    The pool owns ``num_pages`` pages of ``page_size`` token slots each,
    materialized on device as ``module.init_paged_cache`` slabs of
    ``(num_pages, page_size, heads, head_dim)`` per layer, plus the
    host-side free list that hands pages to sequences: allocate by TRUE
    prompt length at prefill, extend one page at a time when a decode
    frontier crosses a page boundary, free on eos/completion.  Page 0 is
    the reserved trash page (pad rows and unallocated table entries point
    there; it is never handed out), so ``capacity == num_pages - 1``.
    Sequences therefore share cache HBM by actual length instead of
    reserving ``batch × max_len`` slots each — the occupancy and
    high-water gauges make the claim observable on ``/metrics``.

    The device slabs are BORROWED by one decode loop at a time (the step
    executables donate them in place, so two concurrent borrowers would
    consume each other's buffers); :meth:`borrow_cache` blocks until the
    previous borrower returns.  The accounting half (allocate/extend/free/
    occupancy) is lock-protected and usable standalone — sizing studies
    never have to build device slabs.
    """

    #: booking ops — each books pages moved, not call count ("denied"
    #: books pages REFUSED: the admission-control outcome, ISSUE 13).
    #: "pin" books refcount increments on shared pages (prefix hits),
    #: "cow" books private copies minted by copy-on-write splits (ISSUE 20)
    OPS = ("allocate", "extend", "free", "denied", "pin", "cow")

    def __init__(self, module=None, num_pages: int = 0, page_size: int = 64,
                 *, name: str = "pool", registry=None,
                 clock: Callable[[], float] = time.monotonic):
        if num_pages < 2:
            raise ValueError(f"num_pages {num_pages} < 2: page 0 is the "
                             "reserved trash page, so a usable pool needs "
                             "at least one allocatable page")
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.module = module
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self._name = name
        #: free physical pages; page 0 (trash) is never in this list
        self._free = list(range(self.num_pages - 1, 0, -1))
        #: per-page refcounts (ISSUE 20): a page is on exactly one side —
        #: in ``_free`` with no entry here, or held with refcount >= 1.
        #: ``free()`` decrements and only returns the page at zero, so a
        #: prefix-shared page survives any one holder's release
        self._ref: Dict[int, int] = {}
        #: the prefix index retaining pages in this pool, if any (set by
        #: ``ModelRunner.prefix_cache``); ``resized()`` flushes it so a
        #: successor pool can never be handed a dangling page id
        self.prefix_index = None
        self._cond = make_condition("PagePool._cond")
        self._cache = None          # built lazily, rebuilt if dropped
        self._cache_nbytes = 0
        self._borrowed = False
        self.high_water = 0
        #: True when the owning runner sized this pool implicitly (from a
        #: decode's worst case) — such pools may be grown for a larger
        #: batch; an explicitly budgeted pool is never resized behind the
        #: caller's back
        self.auto_sized = False
        from ..observability import get_registry
        reg = registry if registry is not None else get_registry()
        self._registry = reg
        # page_size is in the label set because one runner keeps a pool
        # PER page size — without it the pools would stomp one another's
        # occupancy series
        ops = reg.counter(
            "mmlspark_runner_page_ops_total",
            "KV page-pool pages moved by op (allocate/extend/free)",
            labels=("runner", "page_size", "op"))
        self._c_ops = {op: ops.labels(runner=name,
                                      page_size=str(self.page_size), op=op)
                       for op in self.OPS}
        self._g_used = reg.gauge(
            "mmlspark_runner_page_pool_used_pages",
            "KV pages currently held by live sequences",
            labels=("runner", "page_size"))
        self._g_hw = reg.gauge(
            "mmlspark_runner_page_pool_high_water_pages",
            "max KV pages ever simultaneously held",
            labels=("runner", "page_size"))
        # page-seconds integral (ISSUE 17): pages held x wall time,
        # integrated exactly at the alloc/extend/free edges — the memory
        # half of the per-request cost ledger, and the pool-level total
        # the per-request integrals must sum to
        self._clock = clock
        self._page_seconds = 0.0
        self._t_integral = self._clock()
        self._c_pagesec = reg.counter(
            "mmlspark_runner_page_seconds_total",
            "KV page-seconds consumed (pages held x wall time, integrated "
            "at pool-op edges)", labels=("runner", "page_size")).labels(
                runner=name, page_size=str(self.page_size))
        self._book("allocate", 0)   # gauges live from construction

    # ---------------------------------------------------------- accounting
    @property
    def capacity(self) -> int:
        """Allocatable pages (the trash page is not allocatable)."""
        return self.num_pages - 1

    def token_capacity(self) -> int:
        """Total token slots the pool can hold across all sequences."""
        return self.capacity * self.page_size

    def pages_in_use(self) -> int:
        return self.capacity - len(self._free)

    def occupancy_pct(self) -> float:
        return 100.0 * self.pages_in_use() / max(self.capacity, 1)

    def _integrate_locked(self) -> None:
        """Advance the page-seconds integral to now (called under the pool
        lock, BEFORE the free-list mutation — the interval just ended was
        held at the pre-edge page count)."""
        now = self._clock()
        delta = self.pages_in_use() * max(0.0, now - self._t_integral)
        self._t_integral = now
        if delta > 0:
            self._page_seconds += delta
            self._c_pagesec.inc(delta)

    def page_seconds(self) -> float:
        """Cumulative pages-held x wall-time integral, current to now."""
        with self._cond:
            self._integrate_locked()
            return self._page_seconds

    def _book(self, op: str, n: int) -> None:
        """Book one pool operation: the op counter plus the occupancy and
        high-water gauges (called under the pool lock)."""
        used = self.pages_in_use()
        if used > self.high_water:
            self.high_water = used
        self._c_ops[op].inc(n)
        ps = str(self.page_size)
        self._g_used.set(float(used), runner=self._name, page_size=ps)
        self._g_hw.set(float(self.high_water), runner=self._name,
                       page_size=ps)

    def allocate(self, n: int, op: str = "allocate", shared=None):
        """Hand out ``n`` fresh pages (prefill sizing: ``ceil(true_len /
        page_size)`` per sequence).  ``shared`` (ISSUE 20) names already-
        resident pages to PIN instead of copy — each gains a refcount and
        rides ahead of the fresh pages in the returned list, so a prefix
        hit allocates only its suffix.  Atomic: a refused fresh allocation
        unpins ``shared`` before raising.  Raises when the budget is
        exhausted — admission control, not silent overcommit."""
        shared = [int(p) for p in shared] if shared else []
        with self._cond:
            self._integrate_locked()
            if n > len(self._free):
                # book the refusal before raising: the denied outcome is
                # the admission-control signal dashboards alert on
                self._book("denied", n)
                raise PagePoolExhausted(
                    f"page pool exhausted: need {n} page(s), "
                    f"{len(self._free)} free of {self.capacity} "
                    f"(page_size={self.page_size}) — free finished "
                    "sequences, shrink the batch, or size the pool larger")
            if shared:
                self._pin_locked(shared)
            pages = [self._free.pop() for _ in range(n)]
            for p in pages:
                self._ref[p] = 1
            self._book(op, n)
            return shared + pages

    def extend(self, n: int = 1):
        """Allocate at a decode page-boundary crossing (same free list,
        booked as ``op="extend"`` so growth is attributable)."""
        return self.allocate(n, op="extend")

    def _pin_locked(self, pages) -> None:
        for p in pages:
            r = self._ref.get(p)
            if r is None:
                raise ValueError(f"pin of page {p} which is not allocated")
            self._ref[p] = r + 1
        self._book("pin", len(pages))

    def pin(self, pages) -> None:
        """Add a reference to already-resident pages (prefix-cache hit):
        the pinned pages are shared, and ``free()`` from any one holder
        only drops that holder's reference."""
        pages = [int(p) for p in pages]
        with self._cond:
            self._integrate_locked()
            self._pin_locked(pages)

    def refcount(self, page: int) -> int:
        """Current reference count of ``page`` (0 when free)."""
        with self._cond:
            return self._ref.get(int(page), 0)

    def shortfall(self, n: int) -> int:
        """Free-list deficit for an ``n``-page allocation (0 when it would
        succeed) — no booking, no side effects.  Callers use it to evict
        refcount-0 prefix retentions BEFORE an allocate, keeping the
        index-lock -> pool-lock order deadlock-free."""
        with self._cond:
            return max(0, int(n) - len(self._free))

    def free(self, pages) -> None:
        """Drop one reference per page (eos/completion); a page returns to
        the free list only at refcount zero, so freeing a prefix-shared
        page never yanks it from the other holders.  Freed pages are not
        zeroed: stale k/v in a reused page sits past the new owner's
        frontier until overwritten, so it is never admissible."""
        pages = [int(p) for p in pages]
        if any(p <= 0 or p >= self.num_pages for p in pages):
            raise ValueError(f"free() of invalid page in {pages} "
                             "(page 0 is the reserved trash page)")
        with self._cond:
            self._integrate_locked()
            for p in pages:
                r = self._ref.get(p)
                if r is None:
                    raise ValueError(f"double free of page {p}")
                if r > 1:
                    self._ref[p] = r - 1
                else:
                    del self._ref[p]
                    self._free.append(p)
            self._book("free", len(pages))

    # ------------------------------------------------------- device slabs
    def page_nbytes(self) -> int:
        """Device bytes per page across all layers (0 until slabs built)."""
        return self._cache_nbytes // self.num_pages if self._cache_nbytes \
            else 0

    def borrow_cache(self):
        """Take exclusive ownership of the device slabs (building them on
        first use), blocking while another decode holds them — the step
        executables donate the buffers, so exactly one loop may own them."""
        if self.module is None:
            raise TypeError("this PagePool was built without a module — "
                            "accounting only, no device slabs")
        with self._cond:
            while self._borrowed:
                self._cond.wait()
            self._borrowed = True
            cache = self._cache
            self._cache = None
        if cache is None:
            try:
                cache = self.module.init_paged_cache(self.num_pages,
                                                     self.page_size)
                import jax
                self._cache_nbytes = sum(
                    int(l.nbytes) for l in jax.tree_util.tree_leaves(cache))
            except Exception:
                # a failed slab build (HBM exhaustion) must not leave the
                # pool borrowed forever — every later borrower would block
                self.return_cache(None)
                raise
        return cache

    def resized(self, num_pages: int) -> "PagePool":
        """A fresh pool with the same module/page size/metric identity but
        ``num_pages`` pages.  Refuses while sequences hold pages or a
        decode holds the slabs — resizing would orphan them.

        A prefix index retaining pages here is FLUSHED first (booked
        ``evicted{reason="pool_replaced"}``) and rebound to the successor:
        its entries name physical page ids of THIS pool's slabs, and an
        index surviving a resize un-flushed would hand those ids out
        against the replacement's slabs — freed-page aliasing (ISSUE 20
        regression)."""
        idx = self.prefix_index
        if idx is not None:
            # outside the pool lock: flush frees pages back through
            # free(), which takes it (index-lock -> pool-lock order)
            idx.flush(reason="pool_replaced")
        with self._cond:
            if self._borrowed or self.pages_in_use():
                raise RuntimeError(
                    f"cannot resize a busy page pool ({self.pages_in_use()} "
                    "page(s) held, borrowed="
                    f"{self._borrowed}) — wait for in-flight decodes")
        pool = PagePool(self.module, num_pages, self.page_size,
                        name=self._name, registry=self._registry,
                        clock=self._clock)
        pool.auto_sized = self.auto_sized
        if idx is not None:
            idx.rebind(pool)
            pool.prefix_index = idx
            self.prefix_index = None
        return pool

    def return_cache(self, cache) -> None:
        """Give the slabs back (pass ``None`` after a failed loop — the
        donated buffer state is unknown, so the next borrower rebuilds)."""
        with self._cond:
            self._borrowed = False
            self._cache = cache
            self._cond.notify()


class ModelRunner:
    """Compile-once execution cache + batch/serving/decode fronts.

    Accepts any of:

    - ``payload`` — an object exposing ``pure_apply`` / ``variables`` (and
      optionally ``module``): ``FlaxModelPayload``, ``OnnxModelPayload``;
    - ``module=`` + ``variables=`` — a flax module (resnet, transformer,
      bilstm); ``apply_kwargs`` forward to ``module.apply``;
    - ``apply_fn=`` + ``variables=`` — a raw pure ``(variables, batch)``
      callable.

    ``name`` labels every metric series and compile-report entry this
    runner books — keep it low-cardinality (a model family, not a uid).
    """

    #: sampled block_until_ready cadence for the decode dispatch/device
    #: split (the PR 6 Trainer pattern brought to the decode hot loop):
    #: every Nth step pays one forced sync so the device-time series costs
    #: 1/N of the async overlap; 0 disables the device phase entirely
    DEVICE_TIME_EVERY_DEFAULT = 32

    def __init__(self, payload=None, *, module=None, variables=None,
                 apply_fn: Optional[Callable] = None,
                 apply_kwargs: Optional[Dict[str, Any]] = None,
                 name: str = "model", batch_size: int = 64,
                 registry=None, device_time_every: Optional[int] = None):
        if payload is not None:
            self._pure = payload.pure_apply
            self.variables = payload.variables
            self.module = getattr(payload, "module", None)
        elif apply_fn is not None:
            self._pure = apply_fn
            self.variables = variables
            self.module = module
        elif module is not None:
            kw = dict(apply_kwargs or {})

            def _pure(vs, batch, _m=module, _kw=kw):
                return _m.apply(vs, batch, **_kw)

            self._pure = _pure
            self.variables = variables
            self.module = module
        else:
            raise ValueError("need a payload, a module, or an apply_fn")
        self.name = name
        self.batch_size = int(batch_size)
        from ..observability import get_registry
        self.registry = registry if registry is not None else get_registry()
        #: (kind, device_key, *shape) -> executable; every entry lowered once
        self._executables: Dict[Tuple, Callable] = {}
        #: name -> InstrumentedJit wrappers this runner created (compile
        #: introspection for tests and compile_stats)
        self._wrappers: list = []
        self._lock = make_lock("ModelRunner._lock")
        reg = self.registry
        c_batches = reg.counter(
            "mmlspark_runner_batches_total",
            "device dispatches per runner by front",
            labels=("runner", "front"))
        c_rows = reg.counter(
            "mmlspark_runner_rows_total",
            "real (unpadded) rows scored per runner by front",
            labels=("runner", "front"))
        self._c_batches = {f: c_batches.labels(runner=name, front=f)
                          for f in FRONTS}
        self._c_rows = {f: c_rows.labels(runner=name, front=f)
                        for f in FRONTS}
        self._c_pad = reg.counter(
            "mmlspark_runner_pad_rows_total",
            "padding rows added by bucketing (wasted device work)",
            labels=("runner",)).labels(runner=name)
        self._c_decode_steps = reg.counter(
            "mmlspark_runner_decode_steps_total",
            "single-token decode-step dispatches",
            labels=("runner",)).labels(runner=name)
        self._c_decode_tokens = reg.counter(
            "mmlspark_runner_decode_tokens_total",
            "per-sequence real generated tokens (unfrozen steps only; "
            "eos-frozen tails and pad rows are not generated work)",
            labels=("runner",)).labels(runner=name)
        # decode-loop dispatch/device split (ISSUE 15): dispatch = host
        # time to enqueue each step program, device = sampled
        # block_until_ready wait every device_time_every steps — the
        # numbers that prove (or refute) "dispatch-bound"
        if device_time_every is None:
            device_time_every = self.DEVICE_TIME_EVERY_DEFAULT
        self.device_time_every = max(0, int(device_time_every))
        h_phase = reg.histogram(
            "mmlspark_runner_decode_phase_seconds",
            "decode-step breakdown: dispatch (host enqueue) vs device "
            "(sampled block_until_ready wait)", labels=("runner", "phase"))
        self._h_phase_dispatch = h_phase.labels(runner=name,
                                                phase="dispatch")
        self._h_phase_device = h_phase.labels(runner=name, phase="device")
        # page-pool surface (paged decode): families registered at
        # construction so the telemetry-coverage sweep gates on them even
        # for runners that never decode; PagePool binds the children
        # (page_size in the labels: one runner keeps a pool per page size)
        reg.counter("mmlspark_runner_page_ops_total",
                    "KV page-pool pages moved by op (allocate/extend/free)",
                    labels=("runner", "page_size", "op"))
        reg.gauge("mmlspark_runner_page_pool_used_pages",
                  "KV pages currently held by live sequences",
                  labels=("runner", "page_size"))
        reg.gauge("mmlspark_runner_page_pool_high_water_pages",
                  "max KV pages ever simultaneously held",
                  labels=("runner", "page_size"))
        reg.counter("mmlspark_runner_page_seconds_total",
                    "KV page-seconds consumed (pages held x wall time, "
                    "integrated at pool-op edges)",
                    labels=("runner", "page_size"))
        # continuous-engine surface (ISSUE 13): families registered at
        # construction so the telemetry sweep gates on them even for
        # runners that never open a decode stream; ContinuousDecoder binds
        # the children
        reg.counter("mmlspark_runner_slots_joined_total",
                    "requests spliced into the in-flight decode batch",
                    labels=("runner",))
        reg.counter("mmlspark_runner_slots_left_total",
                    "slots released by outcome (ok/denied/expired/cancelled)",
                    labels=("runner", "outcome"))
        reg.gauge("mmlspark_runner_slot_occupancy_pct",
                  "reserved+live decode slots as % of the in-flight bucket",
                  labels=("runner",))
        reg.histogram("mmlspark_runner_ttft_seconds",
                      "submit-to-first-token latency of continuous decode",
                      labels=("runner",))
        # tail-tolerance surface (ISSUE 16): stall + supervised-restart
        # families registered at construction so the telemetry sweep gates
        # on them even for runners that never stall; the stall watchdog
        # and the scorer's restart supervisor bind/book the children
        self._c_stalls = reg.counter(
            "mmlspark_runner_stalls_total",
            "device dispatches that exceeded the stall watchdog timeout",
            labels=("runner",)).labels(runner=name)
        reg.counter(
            "mmlspark_engine_restarts_total",
            "supervised decode-engine rebuilds after an abort/stall",
            labels=("runner",))
        # goodput/cost-attribution surface (ISSUE 17): the useful-vs-
        # wasted token ledger plus the amortized device-seconds counter —
        # all host-side accounting, never a compile key
        from ..observability.attribution import attribution_instruments
        _att = attribution_instruments(reg)
        self._c_tok_outcome = _att["tokens"]
        self._c_device_s = _att["device"]
        # prefix-cache surface (ISSUE 20): hit/miss/eviction/CoW counters,
        # saved-prefill tokens, and the hit-rate / retained-pages gauges —
        # registered at construction so the telemetry-coverage sweep gates
        # on them even for runners that never enable the cache;
        # PrefixIndex binds the children
        from .prefix_cache import prefix_instruments
        prefix_instruments(reg)
        #: (device key, page size) -> shared PagePool for paged decode
        self._pools: Dict[Tuple, PagePool] = {}
        #: resolved geometry of the most recent decode (DecodeResult.extras)
        self.last_decode_extras: Optional[Dict[str, Any]] = None
        # flight-recorder roster (ISSUE 15): the postmortem dump walks the
        # registry's live runners for their last decode geometry — a
        # WeakSet, so enrolment never pins a discarded runner
        from ..observability.flightrecorder import _roster
        _roster(reg, "_model_runners").add(self)

    # ------------------------------------------------------------- lowering
    @staticmethod
    def _device_key() -> Tuple:
        """The local device set the executables are specialized to; a mesh
        change (tests swapping in mesh8, a late-attached accelerator)
        re-keys instead of serving a stale placement."""
        from ..parallel import get_active_mesh
        mesh = get_active_mesh()
        return tuple(int(d.id) for d in mesh.devices.flat)

    def _instrumented(self, fn: Callable, suffix: str = "", **jit_kwargs):
        from ..observability.compute import instrumented_jit
        wrapper = instrumented_jit(
            fn, name=f"runner.{self.name}{suffix}",
            registry=self.registry, **jit_kwargs)
        self._wrappers.append(wrapper)
        return wrapper

    def executable(self, bucket_n: int, feat_shape: Tuple[int, ...]):
        """The compiled apply for one (device set, bucketed batch shape) —
        built on first use, a dict hit forever after.  Multi-device meshes
        shard the batch dim over ``data`` with params replicated (inference
        DP); multi-host processes stage their host-local batch as a global
        array explicitly (jit refuses host-local numpy for non-replicated
        shardings; every process holds the SAME batch under the executor
        model — identical partition per call)."""
        key = ("apply", self._device_key(), int(bucket_n), tuple(feat_shape))
        fn = self._executables.get(key)
        if fn is not None:
            return fn
        with self._lock:
            fn = self._executables.get(key)
            if fn is not None:
                return fn
            import jax
            from ..parallel import batch_sharded, get_active_mesh, replicated
            mesh = get_active_mesh()
            n_dev = mesh.devices.size
            if n_dev > 1 and bucket_n % n_dev == 0:
                sharded = self._instrumented(
                    self._pure,
                    in_shardings=(replicated(mesh), batch_sharded(mesh)),
                    out_shardings=replicated(mesh))
                if jax.process_count() > 1:
                    bsh = batch_sharded(mesh)

                    def fn(variables, chunk, _inner=sharded, _s=bsh):
                        garr = jax.make_array_from_callback(
                            chunk.shape, _s, lambda idx: chunk[idx])
                        return _inner(variables, garr)
                else:
                    fn = sharded
            else:
                fn = self._instrumented(self._pure)
            self._executables[key] = fn
        return fn

    def compile_stats(self) -> Dict[str, Any]:
        """Introspection for tests and ops: executables cached by key plus
        the underlying compile count (one per signature by contract)."""
        return {
            "executables": sorted(
                "/".join(str(p) for p in k) for k in self._executables),
            "compiles": sum(getattr(w, "compiles", 0)
                            for w in self._wrappers),
        }

    # ------------------------------------------------------------ batch front
    def apply_batch(self, x: np.ndarray, front: str = "transform",
                    batch_size: Optional[int] = None) -> np.ndarray:
        """Score a stacked host batch of any row count: chunk to
        ``batch_size``, pad each chunk to its power-of-two bucket, run the
        cached executable, unpad, concatenate.  This is the ONE copy of the
        pad/bucket glue the per-model transformers used to hand-roll."""
        bs = int(batch_size or self.batch_size)
        n = x.shape[0]
        if n == 0:
            return np.empty((0,), dtype=np.float32)
        variables = self.variables
        outs = []
        pad_total = 0
        for start in range(0, n, bs):
            chunk = x[start:start + bs]
            m = chunk.shape[0]
            bucket = bucket_rows(m, bs)
            pad_total += bucket - m
            chunk = _pad_rows(chunk, bucket)
            fn = self.executable(bucket, chunk.shape[1:])
            outs.append(np.asarray(fn(variables, chunk))[:m])
            self._c_batches[front].inc()
        self._c_rows[front].inc(n)
        if pad_total:
            self._c_pad.inc(pad_total)
        return np.concatenate(outs, axis=0)

    # ---------------------------------------------------------- serving front
    def scorer(self, input_col: str = "request", reply_col: str = "reply",
               prepare: Optional[Callable] = None,
               encode: Optional[Callable] = None,
               mode: str = "score", continuous: bool = False,
               report_ttft: bool = False, supervisor=None,
               **decode_kwargs) -> "Transformer":
        """A ``Transformer`` front for ``PipelineServer`` / the streaming
        facade.  ``mode="score"`` stacks request rows (via ``prepare``,
        default ``np.asarray(..., float32)``) and scores them through
        :meth:`apply_batch`; ``mode="decode"`` treats each request as a
        token-id prompt and returns generated token lists from
        :meth:`decode` (``decode_kwargs`` forward — ``max_new_tokens=``,
        ``eos_id=``, and the cache layout: ``kv_layout="paged"`` with
        ``page_size=``/``pool=`` serves the drain from shared page-pool
        HBM by actual sequence length, instead of the dense per-sequence
        max-length reservation; the resolved geometry rides
        ``DecodeResult.extras`` / ``runner.last_decode_extras`` so
        ``mixed_load``'s decode class can report tokens/sec against it).
        The server's continuous-mode drain is the admission window:
        whatever is in flight when the scorer runs becomes ONE bucketed
        device batch.

        ``continuous=True`` (decode mode only, ISSUE 13) upgrades the drain
        from batch ticks to SLOT-level continuous batching: the scorer owns
        a :class:`ContinuousDecoder` (``decode_kwargs`` become
        :meth:`decode_stream` kwargs — ``slots=``, ``prompt_bucket=``,
        ``max_new_tokens=``, ``eos_id=``, ``page_size=``, ``pool=``) and
        exposes ``continuous_submit`` so ``PipelineServer``/the streaming
        facade admit each request into a free slot of the in-flight batch
        the moment it is drained — no flush tick, and a finished sequence
        replies while the batch keeps decoding.  Admission failure (no free
        slot, page pool exhausted) sheds with 503 + Retry-After.
        ``supervisor`` (continuous only, ISSUE 16) overrides the default
        :class:`~mmlspark_tpu.utils.resilience.RestartSupervisor` gating
        engine rebuilds (backoff/quarantine policy, injectable clock).
        ``report_ttft=True`` wraps decode replies as ``{"tokens",
        "ttft_ms"}`` — the in-band first-token latency ``mixed_load``'s
        ``ttft_p99_ms`` gate reads (for the ticked drain there is no
        client-visible token before the batch resolves, so its honest TTFT
        is the full latency)."""
        if mode not in ("score", "decode"):
            raise ValueError("scorer mode must be score|decode")
        return _RunnerScorer(self, input_col, reply_col, prepare, encode,
                             mode, decode_kwargs, continuous=continuous,
                             report_ttft=report_ttft, supervisor=supervisor)

    # ------------------------------------------------------------ decode front
    def page_pool(self, page_size: int = 64,
                  num_pages: Optional[int] = None) -> Optional["PagePool"]:
        """The runner's shared :class:`PagePool` for ``page_size`` —
        created on first use (sized by ``num_pages``; a paged decode
        without an explicit pool sizes it to its own worst case and grows
        it for larger batches) and reused by every later paged decode at
        this page size, so the occupancy/high-water gauges describe the
        shared cache HBM, not one call.  Passing ``num_pages`` when a pool
        already exists RESIZES it (the explicit-budget escape hatch;
        raises while sequences hold pages).  Returns ``None`` when no pool
        exists yet and ``num_pages`` was not given."""
        key = (self._device_key(), int(page_size))
        with self._lock:
            pool = self._pools.get(key)
            if num_pages is not None:
                if pool is None:
                    pool = self._pools[key] = PagePool(
                        self.module, num_pages, page_size, name=self.name,
                        registry=self.registry)
                elif pool.num_pages != int(num_pages):
                    pool = self._pools[key] = pool.resized(int(num_pages))
                pool.auto_sized = False
            return pool

    def _auto_pool(self, page_size: int, need_pages: int) -> PagePool:
        """The implicit pool for a paged decode that brought no budget:
        create at this call's worst case, or GROW an earlier auto-sized
        pool that a larger batch has outrun (an explicitly budgeted pool
        is never resized — its exhaustion is admission control).  Growth
        is best-effort: if another decode holds pages right now, the
        existing pool serves and may legitimately run out."""
        key = (self._device_key(), int(page_size))
        with self._lock:
            pool = self._pools.get(key)
            if pool is None:
                pool = self._pools[key] = PagePool(
                    self.module, need_pages, page_size, name=self.name,
                    registry=self.registry)
                pool.auto_sized = True
            elif pool.auto_sized and pool.num_pages < need_pages:
                try:
                    pool = self._pools[key] = pool.resized(need_pages)
                except RuntimeError:
                    pass                      # busy: keep the current pool
            return pool

    def prefix_cache(self, page_size: int = 64, *,
                     budget_pages: int = 64, pool: Optional[PagePool] = None):
        """Get-or-create the :class:`~.prefix_cache.PrefixIndex` attached
        to ``pool`` (default: the runner's shared pool for ``page_size``,
        created minimal if absent — a later decode grows it).  The index
        rides the pool (``pool.prefix_index``), so ``resized()`` /
        auto-grow flush-and-rebind it in one place.  ``budget_pages``
        applies only at creation; call this before the first cached decode
        to size the retention budget."""
        from .prefix_cache import PrefixIndex
        if pool is None:
            pool = self._auto_pool(int(page_size), 2)
        if pool.prefix_index is None:
            pool.prefix_index = PrefixIndex(
                pool, budget_pages=budget_pages, name=self.name,
                registry=self.registry)
        return pool.prefix_index

    @staticmethod
    def _alloc_with_reclaim(pool: PagePool, index, n: int,
                            op: str = "allocate", shared=None):
        """``pool.allocate`` with one prefix-eviction retry: under pool
        pressure the index's refcount-0 retentions are reclaimable memory,
        evicted LRU (``reason="pressure"``) BEFORE the allocation is
        denied.  Caller-level so the lock order stays index -> pool."""
        if index is not None and n > 0:
            short = pool.shortfall(n)
            if short:
                index.evict_pages(short, reason="pressure")
        return pool.allocate(n, op=op, shared=shared)

    def _cow_executable(self):
        """Device-side page copy for copy-on-write splits: clone one
        physical page's k/v rows (every layer) from ``src`` into ``dst``.
        src/dst are traced scalars and the slabs are donated, so the copy
        is in-place-update-shaped and mints no per-page compile keys (one
        executable per pool geometry)."""
        key = ("cow_copy", self._device_key())
        fn = self._executables.get(key)
        if fn is not None:
            return fn
        with self._lock:
            fn = self._executables.get(key)
            if fn is None:
                def _cow(cache, src, dst):
                    out = []
                    for k, v in cache:
                        out.append((k.at[dst].set(k[src]),
                                    v.at[dst].set(v[src])))
                    return tuple(out)

                fn = self._executables[key] = self._instrumented(
                    _cow, suffix=".cow_copy", donate_argnums=(0,))
        return fn

    def _cow_split_page(self, pool: PagePool, index, cache, donor: int):
        """Split a shared page before a divergent write lands on it: mint
        a private copy (device page clone), drop the caller's reference on
        the donor, book the split.  Returns ``(cache, new_page)`` — the
        caller updates its page-table row and pages list.  Raises
        :class:`PagePoolExhausted` (after a pressure-eviction retry) when
        no page is mintable — the caller sheds the row like any other
        mid-flight denial."""
        import jax.numpy as jnp
        new_page = self._alloc_with_reclaim(pool, index, 1, op="cow")[0]
        cache = self._cow_executable()(cache, jnp.int32(donor),
                                       jnp.int32(new_page))
        pool.free([donor])
        if index is not None:
            index.book_cow()
        return cache, new_page

    def _decode_executables(self, batch_b: int, prompt_b: int,
                            cache_len: Optional[int] = None, *,
                            page_size: Optional[int] = None,
                            table_w: Optional[int] = None,
                            fused: bool = False,
                            eos_id: Optional[int] = None):
        """(prefill, step) executables for one decode signature.

        Dense: prefill keys on (batch bucket, prompt bucket, cache length),
        the step on (batch bucket, cache length) only.  Paged: prefill keys
        on (batch bucket, prompt bucket, page size, table width) and the
        step on (batch bucket, page size, table width) — cache LENGTH is no
        longer a compile key, so decode signatures that differ only in
        reservation collapse onto one step executable.  Either way the
        step's input shapes are constant across the whole generation loop:
        EVERY token of EVERY request at the signature re-dispatches one
        compiled program.

        Donation contract (ISSUE 12): prefill donates the cache buffers it
        consumes, and the step donates the cache (and, on the fused path,
        the finished mask) so the per-token dispatch updates slots in place
        instead of allocating a fresh (B, S, H, D) per layer per token.
        The host loop must treat every donated argument as CONSUMED — it
        rebinds ``cache``/``finished`` from the step's outputs and never
        touches the stale references (the donation-safety regression test
        pins this).  ``fused=True`` builds the greedy/eos fast-path step
        that samples + freezes on device and returns the (B,) next token
        instead of (B, V) logits; ``eos_id`` is baked into that executable
        (part of its key — low-cardinality by construction)."""
        import jax.numpy as jnp
        module = self.module
        dkey = self._device_key()
        paged = page_size is not None
        if paged:
            kp = ("prefill_paged", dkey, batch_b, prompt_b, page_size,
                  table_w)
            ks = ("step_paged", dkey, batch_b, page_size, table_w)
        else:
            kp = ("prefill", dkey, batch_b, prompt_b, cache_len)
            ks = ("step", dkey, batch_b, cache_len)
        if fused:
            ks = ks + ("fused", eos_id)
        prefill = self._executables.get(kp)
        step = self._executables.get(ks)
        if prefill is not None and step is not None:
            return prefill, step
        sfx = "_paged" if paged else ""
        with self._lock:
            prefill = self._executables.get(kp)
            if prefill is None:
                def _prefill(variables, toks, positions, lengths, table,
                             cache, _m=module):
                    logits, cache = _cached_apply(_m, variables, toks,
                                                  positions, table, cache)
                    # last REAL token's logits per sequence — gathered
                    # on-device so the (B, P, V) tensor never crosses to
                    # host
                    last = jnp.take_along_axis(
                        logits, (lengths - 1)[:, None, None], axis=1)[:, 0]
                    return last, cache

                prefill = self._executables[kp] = self._instrumented(
                    _prefill, suffix=f".prefill{sfx}", donate_argnums=(5,))
            step = self._executables.get(ks)
            if step is None:
                if fused:
                    def _step(variables, tok, positions, table, finished,
                              cache, _m=module, _eos=eos_id):
                        logits, cache = _cached_apply(
                            _m, variables, tok[:, None], positions[:, None],
                            table, cache)
                        nxt, finished = _greedy_freeze(logits[:, 0],
                                                       finished, _eos)
                        return nxt, finished, cache

                    step = self._instrumented(
                        _step, suffix=f".decode_step{sfx}",
                        donate_argnums=(4, 5))
                else:
                    def _step(variables, tok, positions, table, cache,
                              _m=module):
                        logits, cache = _cached_apply(_m, variables, tok,
                                                      positions, table,
                                                      cache)
                        return logits[:, 0], cache

                    step = self._instrumented(
                        _step, suffix=f".decode_step{sfx}",
                        donate_argnums=(4,))
                self._executables[ks] = step
        return prefill, step

    def _sample_executable(self, batch_b: int, eos_id: Optional[int]):
        """On-device greedy sampler for the fused fast path: argmax + eos
        freeze without the (B, V) prefill logits ever crossing to host.
        Donates the finished mask (aliased to the output mask); the logits
        have no same-shaped output to alias, so donating them would only
        warn."""
        key = ("sample", self._device_key(), batch_b, eos_id)
        fn = self._executables.get(key)
        if fn is not None:
            return fn
        with self._lock:
            fn = self._executables.get(key)
            if fn is None:
                def _sample(last, finished, _eos=eos_id):
                    return _greedy_freeze(last, finished, _eos)

                fn = self._executables[key] = self._instrumented(
                    _sample, suffix=".decode_sample", donate_argnums=(1,))
        return fn

    def decode(self, prompts: np.ndarray, lengths=None,
               max_new_tokens: int = 16, eos_id: Optional[int] = None,
               sample_fn: Optional[Callable] = None,
               collect_logits: bool = False,
               batch_bucket: Optional[int] = None,
               prompt_bucket: Optional[int] = None,
               cache_len: Optional[int] = None,
               kv_layout: str = "dense",
               page_size: int = 64,
               pool: Optional[PagePool] = None,
               prefix_cache: bool = False,
               watchdog=None) -> DecodeResult:
        """KV-cached batched autoregressive generation.

        ``prompts`` is ``(B, P)`` int32 (rows padded to the longest prompt);
        ``lengths`` gives each sequence's true prompt length so ragged
        batches decode exactly — each sequence writes and reads the cache at
        ITS own frontier.  Buckets: ``B`` pads to a power-of-two row bucket
        and ``P`` to a power-of-two prompt bucket.

        Cache memory (``kv_layout``): ``"dense"`` reserves one
        ``(cache_len,)`` slot row per sequence up front (``cache_len``
        defaults to the next power of two covering prompt + new tokens);
        ``"paged"`` allocates fixed-size pages from a shared
        :class:`PagePool` by ACTUAL length — ``ceil(true_len/page_size)``
        pages at prefill, one more at each page-boundary crossing, freed on
        eos — so concurrency scales with the tokens actually held, not
        ``B × max_len`` (pass ``pool=`` to share an explicitly sized
        budget; otherwise the runner's implicit pool for ``page_size`` is
        used, created at this call's worst case and grown when a larger
        batch outruns it).

        Sampling: ``sample_fn(logits) -> tokens`` defaults to greedy
        argmax; ``eos_id`` freezes finished sequences (and ends the loop
        early once ALL are finished).  When ``sample_fn`` is None and
        ``collect_logits`` is False, sampling + eos freezing run ON DEVICE
        and the step executables donate the cache/finished buffers: the
        common path fetches one (B,) token per step instead of the (B, V)
        logits, and the cache is updated in place instead of reallocated
        per token.

        Paged + eos caveat: once a frozen row's pages are freed its later
        logits are unspecified (its tokens are forced to ``eos_id``, and a
        ``sample_fn``'s output for frozen rows is discarded, so tokens are
        unaffected).  ``collect_logits=True`` keeps frozen rows' pages
        live instead, so the recorded distributions match the dense
        layout within the committed tolerance at every step.

        ``prefix_cache=True`` (paged + greedy only, ISSUE 20) consults the
        runner's :class:`~.prefix_cache.PrefixIndex` at admission: each
        row's cached prefix pages are PINNED instead of re-prefilled, only
        the suffix is allocated, and the prefill runs position-offset over
        the uncached suffix on the SAME executable signature (positions
        are traced data — zero new compile keys per hit length).  Rows
        that complete ok are retained into the index for the next
        request's hit.  Greedy tokens stay bit-identical to a cold
        decode — docs/runner.md "Prefix caching" states the argument."""
        if self.module is None or not hasattr(self.module, "init_cache"):
            raise TypeError(
                "decode() needs a module with init_cache (a KV-cache-capable "
                "model, e.g. models.TransformerEncoder with causal=True, "
                "pool='none'); this runner wraps "
                f"{type(self.module).__name__ if self.module else 'a raw apply_fn'}")
        import jax
        import jax.numpy as jnp
        prompts = np.asarray(prompts, np.int32)
        if prompts.ndim != 2:
            raise ValueError("prompts must be (batch, prompt_len) int32")
        B, P = prompts.shape
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if kv_layout not in ("dense", "paged"):
            raise ValueError("kv_layout must be dense|paged")
        paged = kv_layout == "paged" or pool is not None
        lengths = (np.full(B, P, np.int32) if lengths is None
                   else np.asarray(lengths, np.int32))
        if lengths.shape != (B,) or lengths.min() < 1 or lengths.max() > P:
            raise ValueError("lengths must be (batch,) in [1, prompt_len]")
        B_b = batch_bucket or 1 << (B - 1).bit_length()
        P_b = prompt_bucket or 1 << (P - 1).bit_length()
        if B_b < B or P_b < P:
            raise ValueError("bucket smaller than the batch/prompt it serves")
        # greedy/eos fast path: sample + freeze on device (donated buffers)
        fused = sample_fn is None and not collect_logits
        if prefix_cache and not paged:
            raise ValueError("prefix_cache=True needs kv_layout='paged' — "
                             "the cache shares resident PagePool pages")
        if prefix_cache and (sample_fn is not None or collect_logits):
            raise ValueError(
                "prefix_cache=True supports the greedy fused path only: "
                "cached positions' logits are never recomputed, so a "
                "sample_fn / collect_logits caller would observe a "
                "different distribution surface than a cold decode")
        toks = np.zeros((B_b, P_b), np.int32)
        toks[:B, :P] = prompts
        lens = np.concatenate([lengths, np.ones(B_b - B, np.int32)])
        self._c_pad.inc((B_b - B) * P_b + B * (P_b - P))
        variables = self.variables

        table = None
        seq_pages: list = []
        index = None
        #: per-row cached prompt positions (prefix hit) — 0 without a hit;
        #: prefill positions offset past these, the step loop keeps TRUE
        #: lengths (cached k/v is read through the shared pages)
        shared_n = np.zeros(B_b, np.int32)
        if paged:
            if not hasattr(self.module, "init_paged_cache"):
                raise TypeError(
                    "kv_layout='paged' needs a module with init_paged_cache "
                    "(e.g. models.TransformerEncoder); "
                    f"{type(self.module).__name__} has none")
            if cache_len is not None:
                raise ValueError(
                    "cache_len is a dense-layout parameter (it sizes the "
                    "per-sequence reservation); the paged layout sizes "
                    "cache by pages — use page_size/pool instead")
            if pool is not None:
                page_size = pool.page_size
            page_size = int(page_size)
            if page_size < 1:
                raise ValueError("page_size must be >= 1")
            table_w = -(-(P_b + max_new_tokens) // page_size)
            max_len = getattr(self.module, "max_len", None)
            if max_len is not None and P_b + max_new_tokens > max_len:
                raise ValueError(
                    f"prompt_bucket + max_new_tokens = "
                    f"{P_b + max_new_tokens} exceeds the module's max_len "
                    f"{max_len} (positional table bound)")
            if pool is None:
                pool = self._auto_pool(page_size, B_b * table_w + 1)
            if prefix_cache:
                index = self.prefix_cache(page_size, pool=pool)
            prefill, step = self._decode_executables(
                B_b, P_b, page_size=page_size, table_w=table_w,
                fused=fused, eos_id=eos_id)
            table = np.zeros((B_b, table_w), np.int32)
            seq_pages = [[] for _ in range(B_b)]
            try:
                # allocate by TRUE length — pad rows (and unallocated table
                # entries) stay on the trash page and never hold pool pages.
                # With the prefix cache, cached prefix pages are pinned by
                # lookup() and only the suffix is freshly allocated; the
                # row's prompt tokens shift left to the uncached suffix
                for b in range(B):
                    if index is not None:
                        cpages, covered = index.lookup(
                            prompts[b, :int(lengths[b])])
                    else:
                        cpages, covered = [], 0
                    n_pages = -(-int(lengths[b]) // page_size)
                    try:
                        pgs = self._alloc_with_reclaim(
                            pool, index, n_pages - len(cpages))
                    except Exception:
                        if cpages:
                            pool.free(cpages)   # drop the lookup pins
                        raise
                    seq_pages[b] = list(cpages) + list(pgs)
                    table[b, :n_pages] = seq_pages[b]
                    if covered:
                        shared_n[b] = covered
                        suffix = int(lengths[b]) - covered
                        toks[b, :] = 0
                        toks[b, :suffix] = prompts[b, covered:int(lengths[b])]
                cache = pool.borrow_cache()
            except Exception:
                # a failed allocation or slab build must not leak the pages
                # already handed to earlier rows (borrow_cache resets its
                # own borrowed flag on failure)
                leftover = [p for pgs in seq_pages for p in pgs]
                if leftover:
                    pool.free(leftover)
                raise
            if index is not None:
                # copy-on-write guard over the prefill write range: a
                # suffix (or pad-tail) write landing on a refcount>1 page
                # would corrupt the other holders' admissible slots — mint
                # a private copy first (mid-page tail sharing is the one
                # admission shape that produces this; see prefix_cache.py)
                try:
                    for b in range(B):
                        lo = int(shared_n[b]) // page_size
                        hi = (int(lengths[b]) - 1) // page_size
                        for pi in range(lo, hi + 1):
                            pg = seq_pages[b][pi]
                            if pool.refcount(pg) > 1:
                                cache, newp = self._cow_split_page(
                                    pool, index, cache, pg)
                                seq_pages[b][pi] = newp
                                table[b, pi] = newp
                except Exception:
                    for pgs in seq_pages:
                        if pgs:
                            pool.free(pgs)
                    seq_pages = [[] for _ in range(B_b)]
                    pool.return_cache(None)
                    raise
            pages_prefill = sum(len(p) for p in seq_pages)
            peak_pages = pool.pages_in_use()
        else:
            S = cache_len or 1 << (P_b + max_new_tokens - 1).bit_length()
            if S < P_b + max_new_tokens:
                raise ValueError(
                    f"cache_len {S} is below prompt_bucket + max_new_tokens "
                    f"= {P_b + max_new_tokens}: the dense layout reserves "
                    "one full (cache_len,) slot row per sequence up front, "
                    "so the reservation must cover the longest possible "
                    "generation — raise cache_len, or switch to "
                    "kv_layout='paged' to size by actual length instead")
            prefill, step = self._decode_executables(
                B_b, P_b, cache_len=S, fused=fused, eos_id=eos_id)
            cache = self.module.init_cache(B_b, S)
            cache_nbytes = sum(int(l.nbytes)
                               for l in jax.tree_util.tree_leaves(cache))
        # prefill positions offset past each row's cached prefix (all-zero
        # offsets without a hit — identical to the cold layout); the gather
        # lengths are SUFFIX lengths so the last-real-token logits come
        # from the final uncached position.  Positions/lengths are traced
        # data, so hit lengths mint no compile keys by construction.
        positions = (shared_n[:, None]
                     + np.arange(P_b, dtype=np.int32)[None, :])
        plens = (lens - shared_n).astype(np.int32)
        sample = sample_fn or (lambda lg: np.argmax(lg, axis=-1))
        out_tokens = np.zeros((B_b, max_new_tokens), np.int32)
        out_logits = [] if collect_logits else None
        # pad rows are born finished: their garbage samples must never hold
        # the eos early-exit open (or inflate the step/token counters)
        finished = np.zeros(B_b, bool)
        finished[B:] = True
        steps = 0
        real_tokens = 0
        #: per-row unfrozen emissions — the useful-vs-wasted ledger needs
        #: a denied row's pre-denial tokens attributable (host-side only)
        row_tokens = np.zeros(B, np.int64)
        #: row -> tokens emitted when its pool extend was DENIED (ISSUE 13
        #: bugfix: a budgeted pool exhausting mid-decode freezes the row and
        #: yields a clean partial result instead of raising out of the loop)
        denied_at: Dict[int, int] = {}
        ok = False
        # every executable shares one signature; table is None (an empty
        # pytree) on the dense layout, and the device copy is re-uploaded
        # only when extend/free dirties it
        table_dev = jnp.asarray(table) if paged else None
        table_dirty = False
        # dispatch/device split (ISSUE 15, the PR 6 Trainer pattern on the
        # decode hot loop): dispatch = host time to enqueue each step,
        # device = sampled block_until_ready wait every Nth step; the loop
        # runs under an ambient profiler phase so host-stack samples
        # attribute to the decode loop by name
        from ..observability.tracing import (Span, _enter_phase,
                                             _exit_phase, current_trace_id,
                                             export_span)
        dte = self.device_time_every
        dispatch_s_total = device_s_total = 0.0
        t_loop0 = time.perf_counter()
        if watchdog is not None:
            # stall watchdog (ISSUE 16): one armed section spans prefill +
            # the whole token loop, with a per-iteration heartbeat after
            # each host fetch — the timeout bounds any SINGLE dispatch/
            # fetch (the hang shapes), never the loop's total wall time.
            # Build one via stall_watchdog() to book stalls + flight dumps.
            watchdog.arm("runner.decode")
        _phase = _enter_phase("runner.decode")
        try:
            last, cache = prefill(
                variables, jnp.asarray(toks), jnp.asarray(positions),
                jnp.asarray(plens), table_dev, cache)
            self._c_batches["decode"].inc()
            if fused:
                tok_d, fin_d = self._sample_executable(B_b, eos_id)(
                    last, jnp.asarray(finished))
            for t in range(max_new_tokens):
                if fused:
                    # the ONLY host fetches on the fast path: the (B,) token
                    # ids + (B,) finished flags; logits stay on device
                    tok = np.asarray(tok_d)
                    fin_now = np.asarray(fin_d)
                    if denied_at:
                        # the device-resident finished mask never learns of
                        # a host-side page denial — fold it back in, or the
                        # denied row thaws next iteration (re-inflating the
                        # decode-tokens counter and holding the eos
                        # early-exit open forever)
                        fin_now = fin_now.copy()
                        for b in denied_at:
                            fin_now[b] = True
                else:
                    lg = np.asarray(last)                  # (B_b, V) fetch
                    if collect_logits:
                        out_logits.append(lg)
                    tok = np.asarray(sample(lg), np.int32)
                    if eos_id is not None:
                        tok = np.where(finished, eos_id, tok)
                        fin_now = finished | (tok == eos_id)
                    else:
                        fin_now = finished
                if watchdog is not None:
                    watchdog.heartbeat()   # this step's host fetch returned
                # tokens emitted while a sequence was already frozen are eos
                # padding, not generated work (ISSUE 12 bugfix: the old
                # B * n_generated charge inflated fleet tokens/sec and the
                # autoscale signal on early-finishing batches)
                real_tokens += B - int(finished[:B].sum())
                row_tokens += ~finished[:B]
                out_tokens[:, t] = tok
                if paged and eos_id is not None and not collect_logits:
                    # free on eos: pages return to the pool mid-flight; the
                    # frozen row keeps stepping, but its zeroed table rows
                    # point every further write at the trash page (its
                    # post-freeze logits become unspecified — tokens are
                    # forced to eos either way).  collect_logits keeps
                    # frozen rows live instead, so the recorded
                    # distributions match the dense layout exactly.
                    for b in np.nonzero(fin_now[:B] & ~finished[:B])[0]:
                        if seq_pages[b]:
                            pool.free(seq_pages[b])
                            seq_pages[b] = []
                            table[b, :] = 0
                            table_dirty = True
                finished = fin_now
                if t == max_new_tokens - 1 or \
                        ((eos_id is not None or denied_at)
                         and bool(finished.all())):
                    break
                # token t sits at absolute position lengths + t; the step
                # writes it at that frontier and returns logits for t+1
                # (host path) or the sampled token t+1 (fused path)
                pos = (lens + t).astype(np.int32)
                if paged:
                    # extend at page boundaries: the write position must be
                    # backed by a real page BEFORE the step dispatches.
                    # Frozen rows stop extending once freed — except under
                    # collect_logits, where they stay live (logits parity)
                    for b in range(B):
                        if b in denied_at or \
                                (finished[b] and not collect_logits):
                            continue
                        pi = int(pos[b]) // page_size
                        needs_page = pi >= len(seq_pages[b])
                        needs_cow = (not needs_page and index is not None
                                     and pool.refcount(seq_pages[b][pi]) > 1)
                        if needs_page or needs_cow:
                            try:
                                if needs_cow:
                                    # the paged step detected a write
                                    # landing on a refcount>1 page: route
                                    # it to a freshly allocated private
                                    # copy — table row updated, donor
                                    # refcount decremented (ISSUE 20 CoW)
                                    cache, new_page = self._cow_split_page(
                                        pool, index, cache, seq_pages[b][pi])
                                else:
                                    new_page = self._alloc_with_reclaim(
                                        pool, index, 1, op="extend")[0]
                            except PagePoolExhausted:
                                # mid-decode exhaustion of a budgeted pool
                                # is admission control: freeze the row,
                                # release its pages for the survivors, and
                                # return its generation so far (the denial
                                # is already booked as op="denied"; serving
                                # maps the row to a 503 shed)
                                denied_at[b] = t + 1
                                if not finished.flags.writeable:
                                    # the fused path's finished vector is a
                                    # read-only view of the device fetch
                                    finished = finished.copy()
                                finished[b] = True
                                if seq_pages[b]:
                                    pool.free(seq_pages[b])
                                    seq_pages[b] = []
                                table[b, :] = 0
                                table_dirty = True
                                continue
                            if needs_cow:
                                seq_pages[b][pi] = new_page
                            else:
                                seq_pages[b].append(new_page)
                            table[b, pi] = new_page
                            table_dirty = True
                    peak_pages = max(peak_pages, pool.pages_in_use())
                    if table_dirty:
                        # re-upload only when extend/free actually changed
                        # the table — steady-state steps reuse the resident
                        # copy (the table arg is never donated)
                        table_dev = jnp.asarray(table)
                        table_dirty = False
                t_disp0 = time.perf_counter()
                if fused:
                    # donated dispatch: fin_d/cache are CONSUMED here — the
                    # loop rebinds all three outputs and must never touch
                    # the stale references again
                    tok_d, fin_d, cache = step(variables, tok_d,
                                               jnp.asarray(pos), table_dev,
                                               fin_d, cache)
                else:
                    last, cache = step(variables, jnp.asarray(tok[:, None]),
                                       jnp.asarray(pos[:, None]), table_dev,
                                       cache)
                disp_s = time.perf_counter() - t_disp0
                dispatch_s_total += disp_s
                self._h_phase_dispatch.observe(disp_s)
                steps += 1
                self._c_decode_steps.inc()
                if dte and steps % dte == 0:
                    # sampled only: the forced sync ends async pipelining
                    # for this step, so the device series costs 1/N of the
                    # dispatch/execute overlap
                    t_dev0 = time.perf_counter()
                    jax.block_until_ready(tok_d if fused else last)
                    dev_s = time.perf_counter() - t_dev0
                    device_s_total += dev_s
                    self._h_phase_device.observe(dev_s)
            ok = True
        finally:
            _exit_phase(_phase)
            if watchdog is not None:
                watchdog.disarm()
            if paged:
                # retention (ISSUE 20): an ok row's pages hold valid k/v
                # for its prompt + every fed-back token (the final sampled
                # token is never written) — hand them to the prefix index
                # as the next request's hit instead of the free list.
                # Denied/eos-freed rows and failed loops free as before.
                for b in range(B_b):
                    pgs = seq_pages[b]
                    if not pgs:
                        continue
                    if (index is not None and ok and b < B
                            and b not in denied_at):
                        n_gen = int(t) + 1
                        ids = np.concatenate(
                            [prompts[b, :int(lengths[b])],
                             out_tokens[b, :max(n_gen - 1, 0)]])
                        index.release(ids, pgs)
                    else:
                        pool.free(pgs)
                    seq_pages[b] = []
                # after a mid-step failure the donated slab state is
                # unknown — drop it so the next borrower rebuilds zeros
                pool.return_cache(cache if ok else None)
        n_generated = t + 1
        # a denied row's post-denial slots hold whatever the trash-page
        # dispatches produced — overwrite with eos padding so the partial
        # result is clean up to (and silent past) its truncation point
        for b, cut in denied_at.items():
            out_tokens[b, cut:] = eos_id if eos_id is not None else 0
        self._c_decode_tokens.inc(real_tokens)
        self._c_rows["decode"].inc(B)
        # useful-vs-wasted ledger (ISSUE 17): every cell of the padded
        # batch emitted this call lands in exactly one outcome bucket, so
        # useful + wasted == B_b x iterations — a conservation law, not an
        # estimate.  Denied rows' pre-denial tokens were real device work
        # the caller only received truncated; pad cells cover bucket
        # padding AND frozen rows still riding the fused step.
        denied_tokens = int(sum(int(row_tokens[b]) for b in denied_at))
        useful_tokens = int(real_tokens) - denied_tokens
        pad_cells = B_b * n_generated - int(real_tokens)
        if useful_tokens:
            self._c_tok_outcome.inc(useful_tokens, outcome="useful")
        if denied_tokens:
            self._c_tok_outcome.inc(denied_tokens, outcome="denied_row")
        if pad_cells:
            self._c_tok_outcome.inc(pad_cells, outcome="pad_row")
        # attributed device-seconds: host-observed step wall time (enqueue
        # + the sampled residual device wait) — the cost denominator the
        # capacity model divides tokens into
        device_s_attr = dispatch_s_total + device_s_total
        self._c_device_s.inc(device_s_attr)
        extras: Dict[str, Any] = {
            "kv_layout": "paged" if paged else "dense",
            "real_tokens": real_tokens,
            "batch_bucket": B_b,
            "dispatch_s": round(dispatch_s_total, 6),
            "device_s": round(device_s_total, 6),
            "attribution": {"useful": useful_tokens,
                            "denied_row": denied_tokens,
                            "pad_row": pad_cells,
                            "device_s_attributed": round(device_s_attr, 6)},
        }
        # one span per decode call carrying the split (never per token —
        # the export ring is bounded); joins the ambient trace when the
        # call rides a served request
        span = Span("runner.decode", trace_id=current_trace_id(),
                    start_s=t_loop0,
                    attributes={"runner": self.name, "steps": steps,
                                "dispatch_s": round(dispatch_s_total, 6),
                                "device_s": round(device_s_total, 6),
                                "device_time_every": dte})
        span.finish(time.perf_counter())
        export_span(span, self.registry)
        if denied_at:
            extras["denied_rows"] = sorted(denied_at)
            extras["denied_at"] = {int(b): int(c)
                                   for b, c in sorted(denied_at.items())}
        if paged:
            extras.update(
                page_size=page_size, table_width=table_w,
                pool_pages=pool.capacity, pages_prefill=pages_prefill,
                pages_peak=peak_pages,
                page_occupancy_pct=round(
                    100.0 * peak_pages / max(pool.capacity, 1), 2),
                cache_bytes_per_seq=pool.page_nbytes() * peak_pages
                / max(B, 1))
            if index is not None:
                extras["prefix"] = {
                    "cached_tokens": int(shared_n[:B].sum()),
                    "hit_rows": int((shared_n[:B] > 0).sum()),
                    **index.stats()}
        else:
            extras.update(cache_len=S,
                          cache_bytes_per_seq=cache_nbytes / max(B, 1))
        self.last_decode_extras = extras
        logits = (np.stack(out_logits, axis=1)[:B] if collect_logits
                  else None)
        return DecodeResult(tokens=out_tokens[:B, :n_generated],
                            lengths=lengths, steps=steps, logits=logits,
                            extras=extras)

    # --------------------------------------------------------- stall watchdog
    def stall_watchdog(self, stall_timeout_s: float,
                       clock: Callable[[], float] = time.monotonic,
                       on_stall: Optional[Callable] = None):
        """A :class:`~mmlspark_tpu.utils.resilience.Watchdog` wired to this
        runner's stall telemetry (ISSUE 16): an armed section overrunning
        ``stall_timeout_s`` books ``mmlspark_runner_stalls_total`` and
        fires a flight-recorder postmortem dump on the stall edge
        (``trigger="stall"`` — the engine state BEFORE recovery tears it
        down), then chains the caller's ``on_stall(label, elapsed_s)``
        (the continuous engine hangs its poison-abort there).  Pass the
        result to :meth:`decode`'s ``watchdog=``, or let
        ``decode_stream(stall_timeout_s=...)`` build one internally."""
        from ..utils.resilience import Watchdog

        def _trip(label: str, elapsed: float) -> None:
            self._c_stalls.inc()
            try:
                from ..observability.flightrecorder import get_flight_recorder
                get_flight_recorder(self.registry).dump(trigger="stall")
            except Exception:  # noqa: BLE001 — the dump must never block
                pass           # stall recovery
            if on_stall is not None:
                on_stall(label, elapsed)

        return Watchdog(stall_timeout_s, clock=clock, on_stall=_trip,
                        name=self.name)

    # ------------------------------------------------------ continuous front
    def decode_stream(self, *, slots: int = 4, prompt_bucket: int = 16,
                      max_new_tokens: int = 16,
                      eos_id: Optional[int] = None, page_size: int = 64,
                      pool: Optional[PagePool] = None,
                      clock: Optional[Callable[[], float]] = None,
                      stall_timeout_s: Optional[float] = None,
                      prefix_cache: bool = False
                      ) -> "ContinuousDecoder":
        """A persistent in-flight decode loop over the paged pool (ISSUE 13
        tentpole): a fixed ``slots``-wide batch whose per-slot state (page-
        table row, length, finished flag) supports slot-level JOIN (a new
        arrival prefills into freshly allocated pages and splices into the
        running batch between steps) and LEAVE (eos/budget frees the slot's
        pages mid-flight; the slot is immediately admissible again).

        The stream reuses the ONE-SHOT executables at its geometry — the
        PR 12 step is keyed on (batch bucket, page size, table width), and
        each join prefills the arrival ALONE at the one-shot
        (1, prompt_bucket) prefill signature into its own pages — so
        admission introduces NO new compile keys (``warmup()`` covers all
        three signatures) and greedy tokens stay bit-identical to
        :meth:`decode`.  Greedy/eos fast path only
        (``sample_fn``/``collect_logits`` stay one-shot).

        With ``prefix_cache=True`` (ISSUE 20) admission consults the
        pool's :class:`~.prefix_cache.PrefixIndex`: a join allocates only
        the uncached suffix pages and prefills only the uncached positions
        (positions offset past the shared prefix — traced data, so joins
        STILL cannot mint a compile key), and a finished request's pages
        are retained in the index instead of freed, funding the next
        arrival's hit.  Greedy tokens stay bit-identical to cold-cache
        :meth:`decode` across hit/partial-hit/miss/CoW traffic.

        Drive it with :meth:`ContinuousDecoder.submit` + either
        :meth:`ContinuousDecoder.start` (background engine thread — what
        serving uses) or manual :meth:`ContinuousDecoder.step` calls
        (deterministic tests)."""
        return ContinuousDecoder(self, slots=slots,
                                 prompt_bucket=prompt_bucket,
                                 max_new_tokens=max_new_tokens,
                                 eos_id=eos_id, page_size=page_size,
                                 pool=pool, clock=clock,
                                 stall_timeout_s=stall_timeout_s,
                                 prefix_cache=prefix_cache)


class StreamHandle:
    """One request in flight on a :class:`ContinuousDecoder`.

    Lifecycle: ``queued`` (slot + prompt pages reserved at submit) →
    ``live`` (spliced into the batch; ``t_first_s``/``ttft_s`` set) → a
    terminal outcome: ``ok`` (eos or token budget), ``denied`` (page pool
    exhausted mid-flight — the generation so far is on ``tokens``),
    ``expired`` (deadline passed mid-flight), ``cancelled`` (decoder
    closed) or ``error`` (engine failure).  ``done`` fires at the terminal
    transition; ``on_done(handle)`` (if given) runs on the engine thread
    right after it."""

    __slots__ = ("prompt", "length", "max_new_tokens", "deadline_s",
                 "on_done", "slot", "tokens", "status", "done",
                 "t_submit_s", "t_first_s", "pages", "trace_id", "cost",
                 "prompt_hash", "covered")

    def __init__(self, prompt: np.ndarray, length: int, max_new_tokens: int,
                 deadline_s: Optional[float], on_done: Optional[Callable],
                 trace_id: Optional[str] = None,
                 prompt_hash: Optional[str] = None):
        self.prompt = prompt
        self.length = int(length)
        self.max_new_tokens = int(max_new_tokens)
        self.deadline_s = deadline_s
        self.on_done = on_done
        # the request's trace id (ISSUE 15 satellite): the TTFT histogram
        # observation carries it as an exemplar, so a p99 TTFT outlier on
        # /metrics resolves to the exact request via /trace/<id> even
        # though the observation books on the ENGINE thread, which has no
        # ambient span
        self.trace_id = trace_id
        self.slot = -1
        self.tokens: List[int] = []
        self.status = "queued"
        self.done = threading.Event()
        self.t_submit_s = 0.0
        self.t_first_s: Optional[float] = None
        self.pages: List[int] = []
        # per-request cost ledger (ISSUE 17) — attached at submit; engine
        # edges mutate it, the terminal outcome classifies its tokens
        self.cost = None
        # prefix-cache seam (ISSUE 20): the admission-time prompt hash the
        # serving layer passed through (observability only — the index
        # matches on token content), and how many leading prompt positions
        # the index covered (0 = cold; the join prefills only the suffix)
        self.prompt_hash = prompt_hash
        self.covered = 0

    @property
    def ttft_s(self) -> Optional[float]:
        """Submit-to-first-token latency (None until the join prefill)."""
        if self.t_first_s is None:
            return None
        return max(0.0, self.t_first_s - self.t_submit_s)

    def result(self, timeout: Optional[float] = None) -> DecodeResult:
        """Block until terminal and return a one-row :class:`DecodeResult`
        (partial for denied/expired/cancelled outcomes)."""
        if not self.done.wait(timeout):
            raise TimeoutError("decode stream request still in flight")
        toks = np.asarray(self.tokens, np.int32).reshape(1, -1)
        return DecodeResult(
            tokens=toks,
            lengths=np.asarray([self.length], np.int32),
            steps=max(0, len(self.tokens) - 1),
            extras={"status": self.status, "ttft_s": self.ttft_s})


class ContinuousDecoder:
    """Slot-level continuous batching on the paged KV pool (ISSUE 13).

    A fixed in-flight batch of ``slots`` rows decodes on ONE fused step
    executable; requests join free slots between steps and leave (freeing
    their pages) the moment they finish, so tokens/sec tracks the arrival
    process instead of the slowest member of a drained batch.  Per-slot
    state is the paged-decode substrate from PR 12: a page-table row, a
    true length, and a finished flag — empty slots are pad rows (finished,
    table row on the trash page).

    Join = a (1, prompt_bucket) prefill of the arrival alone into its
    freshly allocated pages, between steps — device work proportional to
    the arrival, never the batch width, and live rows' pages untouched
    (the prefill's table names only the joiner's pages).  Because every
    signature is exactly a one-shot :meth:`ModelRunner.decode` executable
    (and :meth:`warmup` pre-compiles all three), admission can never
    compile — the no-new-compile-keys rule the bench A/B counter-checks.

    Admission control at :meth:`submit`: no free slot raises
    :class:`SlotsExhausted`; the prompt's pages are allocated up front so
    pool exhaustion raises :class:`PagePoolExhausted` (booked as
    ``op="denied"``) — serving maps both to 503 + Retry-After.  A
    mid-flight extend denial resolves that slot as ``denied`` with its
    partial generation.

    Metrics: ``mmlspark_runner_slots_{joined,left}_total``,
    ``mmlspark_runner_slot_occupancy_pct``, and the
    ``mmlspark_runner_ttft_seconds`` histogram, all labelled by runner.

    Threading: ``submit`` is thread-safe; :meth:`step` must have ONE
    driver — the :meth:`start` engine thread, or a single test/bench loop.
    The decoder borrows the pool's device slabs at the first join and
    returns them at :meth:`close` (one-shot paged decodes on the same pool
    block until then, by the PR 12 borrow contract).
    """

    OUTCOMES = ("ok", "denied", "expired", "cancelled", "error")

    def __init__(self, runner: ModelRunner, *, slots: int = 4,
                 prompt_bucket: int = 16, max_new_tokens: int = 16,
                 eos_id: Optional[int] = None, page_size: int = 64,
                 pool: Optional[PagePool] = None,
                 clock: Optional[Callable[[], float]] = None,
                 stall_timeout_s: Optional[float] = None,
                 prefix_cache: bool = False):
        module = runner.module
        if module is None or not hasattr(module, "init_paged_cache"):
            raise TypeError(
                "decode_stream() needs a module with init_paged_cache "
                "(e.g. models.TransformerEncoder with causal=True)")
        if slots < 1 or prompt_bucket < 1 or max_new_tokens < 1:
            raise ValueError("slots, prompt_bucket and max_new_tokens "
                             "must all be >= 1")
        self.runner = runner
        self.slots = int(slots)
        self.prompt_bucket = int(prompt_bucket)
        self.max_new_tokens = int(max_new_tokens)
        self.eos_id = eos_id
        self.clock = clock or time.monotonic
        if pool is not None:
            page_size = pool.page_size
        if page_size < 1:
            raise ValueError("page_size must be >= 1")
        self.page_size = int(page_size)
        self.table_w = -(-(self.prompt_bucket + self.max_new_tokens)
                         // self.page_size)
        max_len = getattr(module, "max_len", None)
        if max_len is not None and \
                self.prompt_bucket + self.max_new_tokens > max_len:
            raise ValueError(
                f"prompt_bucket + max_new_tokens = "
                f"{self.prompt_bucket + self.max_new_tokens} exceeds the "
                f"module's max_len {max_len} (positional table bound)")
        self._explicit_pool = pool is not None
        self.pool = pool if pool is not None else runner._auto_pool(
            self.page_size, self.slots * self.table_w + 1)
        # cross-request prefix cache (ISSUE 20): the index rides the POOL
        # (resized()/auto-grow flush-and-rebind it there), the decoder
        # only holds the reference — _adopt_current_pool_locked re-reads
        # it whenever the idle stream re-binds to a replaced pool
        self._prefix_enabled = bool(prefix_cache)
        self.index = runner.prefix_cache(
            self.page_size, pool=self.pool) if prefix_cache else None
        # the one-shot executables AT THE STREAM GEOMETRY — shared cache
        # entries, so a warmed one-shot decode warms the stream and vice
        # versa, and joins can never mint a new compile key.  The step
        # runs at the full batch bucket; joins prefill each arrival ALONE
        # at the (1, prompt_bucket) signature — device work proportional
        # to the arrival, not the batch width (a full-width join prefill
        # costs slots× the compute per join), with the same one-shot
        # bit-parity by row independence.
        _, self._step = runner._decode_executables(
            self.slots, self.prompt_bucket, page_size=self.page_size,
            table_w=self.table_w, fused=True, eos_id=eos_id)
        self._prefill1, _ = runner._decode_executables(
            1, self.prompt_bucket, page_size=self.page_size,
            table_w=self.table_w, fused=True, eos_id=eos_id)
        self._sample1 = runner._sample_executable(1, eos_id)
        # per-slot state: empty slots behave as pad rows
        self._tok = np.zeros(self.slots, np.int32)
        self._fin = np.ones(self.slots, bool)
        self._lens = np.ones(self.slots, np.int32)
        self._emitted = np.zeros(self.slots, np.int32)
        self._table = np.zeros((self.slots, self.table_w), np.int32)
        self._table_dev = None
        self._table_dirty = True
        #: device-resident copies of _tok/_fin for the steady state — the
        #: previous step's outputs feed the next dispatch directly (as the
        #: one-shot fused loop does); a join/leave invalidates them so the
        #: next dispatch re-uploads the mutated host state
        self._tok_dev = None
        self._fin_dev = None
        self._handles: List[Optional[StreamHandle]] = [None] * self.slots
        self._free: List[int] = list(range(self.slots - 1, -1, -1))
        self._arrivals: "deque[StreamHandle]" = deque()
        self._cond = make_condition("ContinuousDecoder._cond")
        self._cache = None
        self._live = 0
        self._closed = False
        self._poisoned = False
        self._torn = False
        self._draining = False
        #: why the engine died ("stall"/"error"; None while alive or after
        #: a clean close) — the serving seam reads it to map stall-aborted
        #: handles to a retryable 503 instead of a 500 (ISSUE 16)
        self.abort_reason: Optional[str] = None
        self._thread: Optional[threading.Thread] = None
        # dispatch hang watchdog (ISSUE 16): armed around every engine
        # dispatch+fetch; a trip books the stall, dumps the flight
        # recorder (runner.stall_watchdog wires both), then poison-aborts
        # this engine from the monitor thread
        self.watchdog = None if stall_timeout_s is None else \
            runner.stall_watchdog(stall_timeout_s, clock=self.clock,
                                  on_stall=self._stall_abort)
        self.steps = 0       # fused step dispatches (join prefills excluded)
        self.joined = 0
        self.left = 0
        reg, name = runner.registry, runner.name
        self._name = name
        self._c_joined = reg.counter(
            "mmlspark_runner_slots_joined_total",
            "requests spliced into the in-flight decode batch",
            labels=("runner",)).labels(runner=name)
        fam_left = reg.counter(
            "mmlspark_runner_slots_left_total",
            "slots released by outcome (ok/denied/expired/cancelled)",
            labels=("runner", "outcome"))
        self._c_left = {o: fam_left.labels(runner=name, outcome=o)
                        for o in self.OUTCOMES}
        self._g_occ = reg.gauge(
            "mmlspark_runner_slot_occupancy_pct",
            "reserved+live decode slots as % of the in-flight bucket",
            labels=("runner",))
        self._h_ttft = reg.histogram(
            "mmlspark_runner_ttft_seconds",
            "submit-to-first-token latency of continuous decode",
            labels=("runner",)).labels(runner=name)
        # attribution plane (ISSUE 17): the decoder books token outcomes
        # and attributed device-seconds on the runner's shared families —
        # all host-side, so the ledger can never mint a compile key
        from ..observability.attribution import RequestCost, ENGINE_OUTCOME_MAP
        self._RequestCost = RequestCost
        self._outcome_map = ENGINE_OUTCOME_MAP
        self._c_tok_outcome = runner._c_tok_outcome
        self._c_device_s = runner._c_device_s
        self._book_occupancy()
        # flight-recorder roster (ISSUE 15): the postmortem dump reads the
        # live slot table + pool occupancy from here — WeakSet-held, so a
        # closed and discarded stream drops out on its own
        from ..observability.flightrecorder import _roster
        _roster(reg, "_decode_streams").add(self)

    # -------------------------------------------------------------- admission
    @property
    def closed(self) -> bool:
        """True once :meth:`close` ran or the engine aborted — a closed
        decoder refuses submits; callers holding one should rebuild."""
        return self._closed

    @property
    def draining(self) -> bool:
        """True once :meth:`drain` started: no new joins, existing slots
        running to completion."""
        return self._draining

    def occupancy(self) -> int:
        """Slots reserved or live (free slots are ``slots - occupancy``)."""
        with self._cond:
            return self.slots - len(self._free)

    def _book_occupancy(self) -> None:
        """Occupancy gauge — called with ``_cond`` held."""
        occ = self.slots - len(self._free)
        self._g_occ.set(100.0 * occ / self.slots, runner=self._name)

    def _adopt_current_pool_locked(self) -> None:
        """A FULLY idle stream re-binds to the runner's CURRENT implicit
        pool for its page size (``_cond`` held): ``page_pool(num_pages=)``
        resizes and ``_auto_pool`` growth REPLACE the runner's pool
        object, and a stream that kept the old reference would allocate
        from an orphaned budget (the operator's resize silently not
        applying) while both pools stomp one occupancy series.  Only when
        zero slots are reserved and the slabs are returned, so in-flight
        state never spans two pools; a stream built on an explicit
        ``pool=`` keeps it — that budget is the caller's contract."""
        if self._explicit_pool or self._cache is not None \
                or self._live or self._arrivals \
                or len(self._free) != self.slots:
            return
        current = self.runner._pools.get(
            (self.runner._device_key(), self.page_size))
        if current is not None and current is not self.pool:
            self.pool = current
            if self._prefix_enabled:
                # the index rode the old pool through resized()'s
                # flush-and-rebind (or needs creating on a fresh pool) —
                # either way the pool's attached index is authoritative
                self.index = self.runner.prefix_cache(
                    self.page_size, pool=current)

    def submit(self, prompt, *, max_new_tokens: Optional[int] = None,
               deadline_s: Optional[float] = None,
               on_done: Optional[Callable] = None,
               trace_id: Optional[str] = None,
               prompt_hash: Optional[str] = None) -> StreamHandle:
        """Admit one request: reserve a free slot and allocate its prompt
        pages NOW (the admission decision), splice into the batch at the
        next step boundary.  Raises :class:`SlotsExhausted` /
        :class:`PagePoolExhausted` when the engine is full — admission
        control, the serving layer's 503 signal.

        With the stream's prefix cache enabled, admission consults the
        index first: the longest page-aligned cached prefix is pinned
        (shared, refcounted) and only the SUFFIX pages are freshly
        allocated — the join then prefills only the uncached positions.
        ``prompt_hash`` is the serving seam's request identity (ISSUE 20)
        — recorded on the handle for ``/debug/requests``; the index
        itself matches on token content, so hash collisions cannot
        corrupt decode."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        length = int(prompt.size)
        if not 1 <= length <= self.prompt_bucket:
            raise ValueError(
                f"prompt length {length} outside [1, "
                f"{self.prompt_bucket}] (the stream's prompt bucket)")
        budget = (self.max_new_tokens if max_new_tokens is None
                  else int(max_new_tokens))
        if not 1 <= budget <= self.max_new_tokens:
            raise ValueError(
                f"max_new_tokens {budget} outside [1, "
                f"{self.max_new_tokens}] (the stream's table bound)")
        n_pages = -(-length // self.page_size)
        with self._cond:
            if self._closed:
                raise RuntimeError("decoder is closed")
            if self._draining:
                # graceful drain (ISSUE 16): existing slots run to
                # eos/budget, new arrivals shed retryably — another
                # worker (or this one after restart) takes them
                raise EngineDraining(
                    "decoder is draining — no new joins")
            self._adopt_current_pool_locked()
            if not self._free:
                raise SlotsExhausted(
                    f"no free decode slot ({self.slots} in flight) — "
                    "retry after a sequence finishes, or run more slots")
            # pages allocated inside the slot reservation so the two
            # admission resources can never disagree (denied is booked by
            # the pool before the raise).  Prefix lookup first: cached
            # prefix pages are PINNED (shared), only the suffix is fresh —
            # lock order decoder._cond -> index._lock -> pool._cond.
            covered = 0
            cpages: List[int] = []
            if self.index is not None:
                cpages, covered = self.index.lookup(prompt)
            try:
                pages = list(cpages) + list(self.runner._alloc_with_reclaim(
                    self.pool, self.index, n_pages - len(cpages)))
            except Exception:
                if cpages:
                    self.pool.free(cpages)
                raise
            slot = self._free.pop()
            handle = StreamHandle(prompt, length, budget, deadline_s,
                                  on_done, trace_id=trace_id,
                                  prompt_hash=prompt_hash)
            handle.slot = slot
            handle.pages = pages
            handle.covered = covered
            handle.t_submit_s = self.clock()
            handle.cost = self._RequestCost(prefill_tokens=length)
            handle.cost.prefill_cached = covered
            handle.cost.page_edge(handle.t_submit_s, len(handle.pages))
            self._arrivals.append(handle)
            self._book_occupancy()
            self._cond.notify_all()
        return handle

    # ----------------------------------------------------------------- engine
    def _borrow(self) -> None:
        if self._cache is None:
            self._cache = self.pool.borrow_cache()

    def _return_cache_if_idle(self) -> None:
        """Hand the borrowed slabs back while the engine is EMPTY (no live
        slot, no queued arrival): an idle engine holds no pages, so its
        slab contents are irrelevant — returning them lets one-shot paged
        decodes (and other streams on the same pool) interleave instead of
        blocking on the borrow until :meth:`close`.  The next join simply
        re-borrows."""
        if self._cache is None:
            return
        with self._cond:
            if self._arrivals:
                return
        cache, self._cache = self._cache, None
        self.pool.return_cache(cache)

    def warmup(self) -> None:
        """Compile the join-prefill/sampler/step executables with
        all-trash dispatches (zero page tables: no pool pages held, no
        slot state touched), so the first real join never pays a compile.
        The signatures are shared with one-shot :meth:`ModelRunner.decode`
        at this geometry, so a warmed one-shot also warms the stream."""
        import jax.numpy as jnp
        self._borrow()
        S, P_b = self.slots, self.prompt_bucket
        variables = self.runner.variables
        try:
            positions = jnp.broadcast_to(jnp.arange(P_b, dtype=jnp.int32),
                                         (1, P_b))
            table1 = jnp.zeros((1, self.table_w), jnp.int32)
            last, self._cache = self._prefill1(
                variables, jnp.zeros((1, P_b), jnp.int32), positions,
                jnp.ones(1, jnp.int32), table1, self._cache)
            self._sample1(last, jnp.ones(1, bool))
            _t, _f, self._cache = self._step(
                variables, jnp.zeros(S, jnp.int32),
                jnp.zeros(S, jnp.int32),
                jnp.zeros((S, self.table_w), jnp.int32),
                jnp.ones(S, bool), self._cache)
            if self.index is not None:
                # CoW page-copy at this pool geometry: warmed by a trash
                # self-copy (page 0 -> page 0, no real page touched) so
                # the first real split under hit traffic never compiles
                self._cache = self.runner._cow_executable()(
                    self._cache, jnp.int32(0), jnp.int32(0))
        except Exception:
            with self._cond:  # same lock as close()/_abort readers (CCY002)
                self._poisoned = True  # donated slab state unknown (see step)
            raise
        if self._live == 0:
            self._return_cache_if_idle()

    def step(self) -> int:
        """One engine round: splice queued arrivals (join prefill), advance
        every live slot one fused step, release finished slots (leave).
        ONE driver only — the :meth:`start` thread or a single test/bench
        loop.  Returns the number of live slots remaining.

        The round runs under the ``runner.decode.step`` ambient phase
        (ISSUE 15): host-stack samples from ``/debug/profile`` attribute
        the engine thread's time to the decode step loop by name — a span
        per round would flood the export ring at token cadence, the phase
        table costs two dict writes."""
        from ..observability.tracing import _enter_phase, _exit_phase
        with self._cond:
            joiners = list(self._arrivals)
            self._arrivals.clear()
        leavers: List[StreamHandle] = []
        _phase = _enter_phase("runner.decode.step")
        try:
            if joiners:
                self._join(joiners, leavers)
            if self._live:
                self._advance(leavers)
        except Exception:
            # a failed dispatch leaves the donated slab state unknown —
            # poison the borrow so close()/abort return None and the next
            # borrower rebuilds zeros instead of consuming a dead buffer;
            # under the engine lock: close() on another thread reads the
            # flag deciding return-vs-drop of the borrowed slabs (CCY002)
            with self._cond:
                self._poisoned = True
            raise
        finally:
            _exit_phase(_phase)
        self._finish(leavers)
        if self._live == 0:
            self._return_cache_if_idle()
        return self._live

    def _finish(self, leavers: List[StreamHandle]) -> None:
        for h in leavers:
            h.done.set()
            if h.on_done is not None:
                try:
                    h.on_done(h)
                except Exception:  # noqa: BLE001 — a reply callback must
                    pass           # never kill the shared engine

    def _join(self, joiners: List[StreamHandle],
              leavers: List[StreamHandle]) -> None:
        """Splice arrivals into their reserved slots.  Each joiner
        prefills ALONE at the (1, prompt_bucket) signature into its
        freshly allocated pool pages — per-row computation depends only
        on that row's pages and mask, so the tokens are bit-identical to
        one-shot prefill while the device work is proportional to the
        ARRIVAL, not the batch width (a full-width join prefill costs
        slots× the compute per join and dominated the trace's device
        passes); live rows' pages are untouched because the prefill's
        table argument only names the joiner's pages."""
        import jax.numpy as jnp
        runner = self.runner
        self._borrow()
        P_b, W = self.prompt_bucket, self.table_w
        ps = self.page_size
        positions = np.broadcast_to(np.arange(P_b, dtype=np.int32),
                                    (1, P_b))
        pos_dev = jnp.asarray(positions)
        for h in joiners:
            s = h.slot
            off = int(h.covered)
            if off and self.index is not None:
                # admission CoW guard (ISSUE 20): the suffix prefill
                # scatters positions [off, length) — a refcount>1 page in
                # that range (the partially-covered tail page of a
                # mid-page hit) must be split to a private copy BEFORE
                # the write lands on state other requests share
                try:
                    for pi in range(off // ps, (h.length - 1) // ps + 1):
                        if pi < len(h.pages) and \
                                self.pool.refcount(h.pages[pi]) > 1:
                            self._cache, newp = runner._cow_split_page(
                                self.pool, self.index, self._cache,
                                h.pages[pi])
                            h.pages[pi] = newp
                except PagePoolExhausted:
                    # admission-time denial: the arrival never joined —
                    # its pages fund the survivors, the client sees the
                    # same retryable verdict as a mid-flight denial
                    self._cancel_arrival(h, "denied", leavers)
                    continue
            suffix = h.length - off
            toks = np.zeros((1, P_b), np.int32)
            toks[0, :suffix] = h.prompt[off:]
            jtable = np.zeros((1, W), np.int32)
            n = len(h.pages)
            jtable[0, :n] = h.pages
            self._table[s, :] = 0
            self._table[s, :n] = h.pages
            self._table_dirty = True
            self._handles[s] = h
            if self.watchdog is not None:
                self.watchdog.arm("runner.decode.join")
            # positions offset past the cached prefix: traced DATA at the
            # same (1, prompt_bucket) signature, so a hit join reuses the
            # cold join's executable — no new compile key per hit length
            last, self._cache = self._prefill1(
                runner.variables, jnp.asarray(toks),
                jnp.asarray(positions + off) if off else pos_dev,
                jnp.asarray([suffix], np.int32), jnp.asarray(jtable),
                self._cache)
            tok_d, fin_d = self._sample1(last, jnp.zeros(1, bool))
            tok0 = int(np.asarray(tok_d)[0])
            fin0 = bool(np.asarray(fin_d)[0])
            if self.watchdog is not None:
                self.watchdog.disarm()
            runner._c_batches["decode"].inc()
            now = self.clock()
            h.status = "live"
            h.t_first_s = now
            # exemplar: the engine thread has no ambient span, so the
            # request's trace id rides the handle (ISSUE 15 satellite —
            # a TTFT outlier must resolve to its trace)
            self._h_ttft.observe(max(0.0, now - h.t_submit_s), h.trace_id)
            self._c_joined.inc()
            self.joined += 1
            self._live += 1
            self._lens[s] = h.length
            self._emitted[s] = 1
            self._tok[s] = tok0
            self._fin[s] = fin0
            self._tok_dev = None     # splice mutated host state
            self._fin_dev = None
            h.tokens.append(tok0)
            if h.cost is not None:
                h.cost.decode_tokens += 1
            runner._c_decode_tokens.inc()
            runner._c_rows["decode"].inc()
            if fin0 or h.max_new_tokens <= 1:
                self._release(s, "ok", leavers)

    def _advance(self, leavers: List[StreamHandle]) -> None:
        """One fused step over the batch: deadline leaves first (never
        spend a dispatch on a dead client), page-boundary extends (a
        denial leaves the slot with its partial generation), then the
        SAME donated step executable one-shot decode dispatches."""
        import jax.numpy as jnp
        runner = self.runner
        now = self.clock()
        for s, h in enumerate(self._handles):
            if h is not None and h.deadline_s is not None \
                    and now > h.deadline_s:
                self._release(s, "expired", leavers)
        if not self._live:
            return
        pos = np.zeros(self.slots, np.int32)
        for s, h in enumerate(self._handles):
            if h is None:
                continue
            p = int(self._lens[s] + self._emitted[s] - 1)
            pos[s] = p
            pi = p // self.page_size
            needs_page = pi >= len(h.pages)
            # step-site CoW guard (ISSUE 20): this step writes position p;
            # if the page holding p is shared (refcount>1 — the request's
            # generation diverging from a retained/shared prefix mid-page)
            # the write must land on a private copy
            needs_cow = (not needs_page and self.index is not None
                         and self.pool.refcount(h.pages[pi]) > 1)
            if needs_page or needs_cow:
                try:
                    if needs_cow:
                        self._cache, new_page = self.runner._cow_split_page(
                            self.pool, self.index, self._cache, h.pages[pi])
                    else:
                        new_page = self.runner._alloc_with_reclaim(
                            self.pool, self.index, 1, op="extend")[0]
                except PagePoolExhausted:
                    # mid-flight denial: the slot leaves with what it has
                    # (op="denied" already booked by the pool), its pages
                    # fund the survivors
                    self._release(s, "denied", leavers)
                    continue
                if needs_cow:
                    h.pages[pi] = new_page
                else:
                    h.pages.append(new_page)
                    if h.cost is not None:
                        h.cost.page_edge(now, 1)
                self._table[s, pi] = new_page
                self._table_dirty = True
        if not self._live:
            return
        if self._table_dirty or self._table_dev is None:
            self._table_dev = jnp.asarray(self._table)
            self._table_dirty = False
        tok_in = self._tok_dev if self._tok_dev is not None \
            else jnp.asarray(self._tok)
        fin_in = self._fin_dev if self._fin_dev is not None \
            else jnp.asarray(self._fin)
        if self.watchdog is not None:
            # the armed section covers the dispatch AND the host fetch
            # below — both are the hang shapes (a wedged relay stalls the
            # fetch; a dead runtime stalls the enqueue)
            self.watchdog.arm("runner.decode.step")
        t_disp0 = time.perf_counter()
        tok_d, fin_d, self._cache = self._step(
            runner.variables, tok_in, jnp.asarray(pos),
            self._table_dev, fin_in, self._cache)
        # dispatch/device split (ISSUE 15): the step call above is the
        # host enqueue; the token fetch below IS the device wait — already
        # a sync, so sampling it costs nothing extra
        disp_s = time.perf_counter() - t_disp0
        runner._h_phase_dispatch.observe(disp_s)
        # fin_in was donated (consumed) by the dispatch: rebind both device
        # copies to the step's outputs; a release below invalidates them
        self._tok_dev, self._fin_dev = tok_d, fin_d
        t_dev0 = time.perf_counter()
        tok, fin = np.asarray(tok_d), np.asarray(fin_d)
        # the fetch IS the device wait (already a sync) — measuring it
        # every step costs one clock read, so the attribution charge below
        # uses the true per-step device time, not a sampled estimate
        dev_s = time.perf_counter() - t_dev0
        if self.watchdog is not None:
            self.watchdog.disarm()
        self.steps += 1
        dte = runner.device_time_every
        if dte and self.steps % dte == 0:
            runner._h_phase_device.observe(dev_s)
        runner._c_decode_steps.inc()
        # attribution (ISSUE 17): the whole step's host-observed device
        # work (enqueue + device wait) is amortized over the slots that
        # had a live request behind them at dispatch; the rest of the
        # batch width was pad cells — dispatched-but-wasted by definition
        live = self._live
        step_s = disp_s + dev_s
        share = step_s / live if live else 0.0
        if step_s > 0:
            self._c_device_s.inc(step_s)
        pad = self.slots - live
        if pad > 0:
            self._c_tok_outcome.inc(pad, outcome="pad_row")
        for s, h in enumerate(self._handles):
            if h is None:
                continue
            self._tok[s] = tok[s]
            self._fin[s] = bool(fin[s])
            self._emitted[s] += 1
            h.tokens.append(int(tok[s]))
            if h.cost is not None:
                h.cost.decode_tokens += 1
                h.cost.device_s += share
            runner._c_decode_tokens.inc()
            if self._fin[s] or len(h.tokens) >= h.max_new_tokens:
                self._release(s, "ok", leavers)

    def _release(self, s: int, outcome: str,
                 leavers: List[StreamHandle]) -> None:
        """Leave: free the slot's pages mid-flight, reset it to pad-row
        state (trash table row, finished), and hand the slot back to
        admission — the batch keeps stepping around it."""
        h = self._handles[s]
        self._handles[s] = None
        h.status = outcome
        if h.cost is not None:
            # terminal classification: every token this request generated
            # lands in exactly one outcome bucket — the conservation law
            h.cost.close_pages(self.clock())
            if h.cost.decode_tokens > 0:
                self._c_tok_outcome.inc(h.cost.decode_tokens,
                                        outcome=self._outcome_map[outcome])
        if h.pages:
            if outcome == "ok" and self.index is not None and h.tokens:
                # prefix retention (ISSUE 20): a cleanly finished request
                # donates its pages to the index keyed by every position
                # actually WRITTEN — prompt + generated[:-1] (the final
                # sampled token's k/v was never scattered) — turning this
                # prefill into the next arrival's hit.  The index takes
                # ownership of the references; budget-surplus pages free.
                ids = np.concatenate(
                    [h.prompt, np.asarray(h.tokens[:-1], np.int32)])
                self.index.release(ids, h.pages)
            else:
                self.pool.free(h.pages)
            h.pages = []
        self._table[s, :] = 0
        self._table_dirty = True
        self._fin[s] = True
        self._tok[s] = 0
        self._lens[s] = 1
        self._emitted[s] = 0
        self._tok_dev = None     # host state mutated: next dispatch
        self._fin_dev = None     # re-uploads instead of reusing device copies
        self._c_left[outcome].inc()
        self.left += 1
        self._live -= 1
        leavers.append(h)
        with self._cond:
            self._free.append(s)
            self._book_occupancy()
            self._cond.notify_all()

    # ------------------------------------------------------------- postmortem
    def debug_state(self) -> Dict[str, Any]:
        """JSON-able engine state for the flight recorder (ISSUE 15): the
        slot table, per-slot progress, and pool occupancy — the state that
        otherwise dies with a crashed/preempted worker.  Read under the
        admission lock so a dump mid-join sees a consistent table."""
        with self._cond:
            slots = []
            for s in range(self.slots):
                h = self._handles[s]
                slots.append({
                    "slot": s,
                    "live": h is not None,
                    "status": None if h is None else h.status,
                    "length": int(self._lens[s]),
                    "emitted": int(self._emitted[s]),
                    "finished": bool(self._fin[s]),
                    "pages": list(map(int, self._table[s]))})
            state = {
                "runner": self._name,
                "slots": self.slots,
                "occupancy": self.slots - len(self._free),
                "live": self._live,
                "queued_arrivals": len(self._arrivals),
                "steps": self.steps,
                "joined": self.joined,
                "left": self.left,
                "closed": self._closed,
                "draining": self._draining,
                "abort_reason": self.abort_reason,
                "slot_table": slots,
            }
        if self.watchdog is not None:
            state["watchdog"] = self.watchdog.as_dict()
        state["pool"] = {
            "page_size": self.pool.page_size,
            "capacity": self.pool.capacity,
            "pages_in_use": self.pool.pages_in_use(),
            "occupancy_pct": round(self.pool.occupancy_pct(), 2)}
        if self.index is not None:
            state["prefix_cache"] = self.index.stats()
        return state

    # -------------------------------------------------------------- lifecycle
    def start(self) -> "ContinuousDecoder":
        """Run the engine on a background thread: steps while any slot is
        live, sleeps on the condition otherwise."""
        with self._cond:
            if self._torn:
                raise RuntimeError("decoder is closed — build a fresh "
                                   "stream (decode_stream()) instead")
            if self._thread is not None:
                return self
            self._closed = False
            self._thread = threading.Thread(
                target=self._run, daemon=True,
                name=f"mmlspark-decode-stream-{self._name}")
            self._thread.start()
        if self.watchdog is not None:
            # monitor thread mode: a test driving step() manually on a
            # FakeClock skips start() and polls watchdog.check() itself
            self.watchdog.start()
        return self

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._closed and not self._arrivals \
                        and self._live == 0:
                    self._cond.wait(0.1)
                if self._closed:
                    return
            try:
                self.step()
            except Exception:  # noqa: BLE001 — a poisoned step must not
                self._abort()  # strand clients on done.wait
                raise

    def _stall_abort(self, label: str, elapsed: float) -> None:
        """Watchdog trip (runs on the MONITOR thread — the engine thread
        is stuck inside the hung dispatch): mark the abort as a stall
        FIRST, so the on_done callbacks the teardown fires read it and
        shed 503 ``shed_engine_stall`` instead of erroring 500, then
        poison-abort — in-flight handles resolve, pages free, and the
        borrowed slabs drop (donated state is unknown while a dispatch is
        wedged inside them)."""
        self.abort_reason = "stall"
        self._abort()

    def _abort(self) -> None:
        """Engine failure: resolve every queued/live handle as ``error``
        and drop the borrowed slabs (donated state unknown — the next
        borrower rebuilds zeros)."""
        if self.abort_reason is None:
            self.abort_reason = "error"
        with self._cond:
            self._closed = True
            self._poisoned = True
            # the engine thread is exiting through this very call: clear
            # the handle so close() does not block joining ourselves
            self._thread = None
            self._cond.notify_all()
        self._teardown("error")

    def _teardown(self, outcome: str) -> None:
        """Release every queued/live handle with ``outcome`` and return
        (or drop, when poisoned) the borrowed slabs.  Claimed exactly once
        — ``_abort`` on the engine thread and ``close()`` on the caller
        can otherwise race the release loop into double-freed pages and a
        twice-listed free slot."""
        with self._cond:
            if self._torn:
                return
            self._torn = True
            arrivals = list(self._arrivals)
            self._arrivals.clear()
        leavers: List[StreamHandle] = []
        for h in arrivals:
            self._cancel_arrival(h, outcome, leavers)
        for s, h in enumerate(self._handles):
            if h is not None:
                self._release(s, outcome, leavers)
        self._finish(leavers)
        cache, self._cache = self._cache, None
        if cache is not None:
            self.pool.return_cache(None if self._poisoned else cache)
        if self.watchdog is not None:
            # the engine is gone — nothing left to watch.  stop() is safe
            # from the monitor thread itself (stall-abort path): it sets
            # the stop event without self-joining.
            self.watchdog.disarm()
            self.watchdog.stop()

    def _cancel_arrival(self, h: StreamHandle, outcome: str,
                        leavers: List[StreamHandle]) -> None:
        h.status = outcome
        if h.cost is not None:
            # a cancelled arrival never joined: zero decode tokens, so no
            # outcome booking — only its reserved page-seconds close out
            h.cost.close_pages(self.clock())
        if h.pages:
            self.pool.free(h.pages)
            h.pages = []
        self._c_left[outcome].inc()
        self.left += 1
        leavers.append(h)
        with self._cond:
            self._free.append(h.slot)
            self._book_occupancy()

    def drain(self, timeout_s: Optional[float] = None,
              poll_s: float = 0.05) -> bool:
        """Graceful wind-down (ISSUE 16): stop admitting — ``submit``
        sheds :class:`EngineDraining` from here on — let queued arrivals
        and live slots run to eos/budget/deadline, then :meth:`close`.

        Returns True when every slot finished inside ``timeout_s`` (None
        = wait indefinitely), False when the timeout cut the wait short —
        ``close()`` then cancels the survivors (partial tokens stay on
        their handles).  Needs the :meth:`start` engine thread (or a
        concurrent external ``step()`` driver) to make progress; the wait
        keys on ALL slots returning to the free list, so a join in flight
        between the arrival snapshot and its splice can never be stranded
        by the close racing it."""
        with self._cond:
            self._draining = True
        deadline = None if timeout_s is None else self.clock() + timeout_s
        drained = False
        with self._cond:
            while not self._torn:
                if len(self._free) == self.slots and not self._arrivals:
                    drained = True
                    break
                if deadline is not None and self.clock() >= deadline:
                    break
                self._cond.wait(poll_s)
        self.close()
        return drained

    def close(self) -> None:
        """Stop the engine, cancel queued arrivals and live slots (partial
        tokens stay on their handles), free their pages, and return the
        borrowed device slabs to the pool.  A closed decoder is final —
        holders rebuild (``_RunnerScorer._ensure_decoder`` does)."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
            thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=60)
        self._teardown("cancelled")


def _resolve_takes_cost(resolve: Callable) -> bool:
    """Whether a serving ``resolve`` callback accepts the ``cost=`` kwarg
    (ISSUE 17).  Introspected per request terminal — the server's resolve
    closure is fresh each call — so older callers (the streaming facade,
    out-of-tree fronts) keep working unchanged."""
    import inspect
    try:
        sig = inspect.signature(resolve)
    except (TypeError, ValueError):
        return False
    for p in sig.parameters.values():
        if p.kind is inspect.Parameter.VAR_KEYWORD or p.name == "cost":
            return True
    return False


class _RunnerScorer(Transformer):
    """Private serving front: built by :meth:`ModelRunner.scorer`, scored by
    ``PipelineServer`` / the streaming facade.  Not a registered stage —
    it is constructed programmatically around a live runner, never from
    params, so it stays out of codegen/fuzzing by the ``_`` convention."""

    def __init__(self, runner: ModelRunner, input_col: str, reply_col: str,
                 prepare: Optional[Callable], encode: Optional[Callable],
                 mode: str, decode_kwargs: Dict[str, Any],
                 continuous: bool = False, report_ttft: bool = False,
                 supervisor=None):
        super().__init__()
        self.runner = runner
        self.input_col, self.reply_col = input_col, reply_col
        self.prepare = prepare or (lambda v: np.asarray(v, np.float32))
        self.encode = encode or (lambda y: y)
        self.mode = mode
        self.decode_kwargs = dict(decode_kwargs)
        self.continuous = bool(continuous)
        self.report_ttft = bool(report_ttft)
        self._decoder: Optional[ContinuousDecoder] = None
        self._dec_lock = make_lock("_RunnerScorer._dec_lock")
        #: duck-typed health signal (ISSUE 16): PipelineServer's /health
        #: reads it — a quarantined runner flips it False so the fleet's
        #: probes evict the worker
        self.serving_healthy = True
        self.supervisor = None
        if self.continuous:
            if mode != "decode":
                raise ValueError("continuous=True requires mode='decode' "
                                 "(scoring rows already admit into the "
                                 "server's in-flight drain)")
            # instance attribute, not a class method: its PRESENCE is the
            # protocol — PipelineServer/streaming route entries here only
            # when the model exposes it, so a score-mode scorer (or any
            # other Transformer) never matches
            self.continuous_submit = self._continuous_submit
            # supervised engine recovery (ISSUE 16): rebuilds after an
            # abort ride capped exponential backoff; repeated stalls
            # quarantine the runner (serving_healthy -> False)
            from ..utils.resilience import RestartSupervisor
            self.supervisor = supervisor if supervisor is not None else \
                RestartSupervisor(
                    clock=self.decode_kwargs.get("clock") or time.monotonic)
            self._pending_restart = False
            self._c_restarts = runner.registry.counter(
                "mmlspark_engine_restarts_total",
                "supervised decode-engine rebuilds after an abort/stall",
                labels=("runner",)).labels(runner=runner.name)

    # ---------------------------------------------------- continuous protocol
    def _ensure_decoder(self) -> ContinuousDecoder:
        with self._dec_lock:
            dec = self._decoder
            if dec is not None and not dec.closed:
                return dec
            if dec is not None:
                # the engine died under us (poisoned dispatch, stall
                # abort): the first observer books the death; the backoff
                # below gates every rebuilder, so a request storm cannot
                # thrash rebuild-abort cycles (ISSUE 16)
                self._decoder = None
                self.supervisor.note_failure(dec.abort_reason or "error")
                self._pending_restart = True
            if self.supervisor.quarantined:
                # repeated stalls inside the window: stop restarting and
                # flip /health unhealthy — TopologyService probes evict
                # this worker; the fleet routes around it
                self.serving_healthy = False
                raise EngineUnavailable(
                    "decode engine quarantined after repeated stalls",
                    reason="engine_quarantined",
                    retry_after_s=self.supervisor.retry_after_s())
            wait = self.supervisor.retry_after_s()
            if wait > 0:
                raise EngineUnavailable(
                    f"decode engine restarting; backoff {wait:.2f}s left",
                    reason="engine_restarting",
                    retry_after_s=max(0.1, wait))
            self._decoder = self.runner.decode_stream(
                **self.decode_kwargs).start()
            if self._pending_restart:
                self._pending_restart = False
                self.supervisor.note_restart()
                self._c_restarts.inc()
            return self._decoder

    def continuous_close(self) -> None:
        """Stop the owned decode stream (PipelineServer.stop() calls this
        when present); a later request lazily reopens it."""
        with self._dec_lock:
            decoder, self._decoder = self._decoder, None
        if decoder is not None:
            decoder.close()
            if self.supervisor is not None:
                # a clean operator close is engine health, not failure —
                # the backoff exponent resets
                self.supervisor.note_success()

    def continuous_drain(self, timeout_s: Optional[float] = None) -> bool:
        """Graceful wind-down of the owned stream (ISSUE 16): no new
        joins, existing slots run to eos/budget, then close.  Returns
        True when every in-flight slot finished inside ``timeout_s``.  A
        later request lazily reopens a fresh engine (a drain is a clean
        close — no restart backoff)."""
        with self._dec_lock:
            decoder, self._decoder = self._decoder, None
        if decoder is None:
            return True
        drained = decoder.drain(timeout_s=timeout_s)
        if self.supervisor is not None:
            self.supervisor.note_success()
        return drained

    def _reply_body(self, tokens, ttft_s: Optional[float]):
        body = self.encode(np.asarray(tokens, np.int32))
        if isinstance(body, np.ndarray):
            # the default identity encode would otherwise reach the HTTP
            # writer as an ndarray and serialize as a numpy string repr
            body = body.tolist()
        if self.report_ttft:
            body = {"tokens": body,
                    "ttft_ms": None if ttft_s is None
                    else round(1000.0 * ttft_s, 3)}
        return body

    def _continuous_submit(self, payload, resolve, queue_age_s=0.0,
                           deadline_budget_s=None, trace_id=None,
                           prompt_hash=None) -> None:
        """The serving seam (ISSUE 13): admit ONE request into the
        in-flight batch.  ``resolve(reply=, status=, verdict=,
        retry_after_s=, ttft_s=)`` fires on the engine thread at the
        request's terminal outcome; admission failures raise out of here
        with ``.shed`` set so the caller sheds 503 + Retry-After.

        The caller's timing crosses the seam DOMAIN-FREE — ``queue_age_s``
        (time already spent queued at the caller) and
        ``deadline_budget_s`` (seconds of budget remaining) are relative,
        never absolute timestamps, so a server on an injectable clock and
        a decoder on ``time.monotonic`` can never be compared against each
        other.  Reported TTFT = queue age + the engine's
        submit-to-first-token.  ``trace_id`` (ISSUE 15) threads the
        request's trace through to the engine so the TTFT histogram's
        exemplar names it — the resolve path runs on the engine thread,
        where no ambient span exists to supply one.  ``prompt_hash``
        (ISSUE 20) is the admission seam's stable prompt identity,
        recorded on the stream handle for ``/debug/requests``."""
        decoder = self._ensure_decoder()
        prompt = np.asarray(payload, np.int32).reshape(-1)
        deadline_s = None if deadline_budget_s is None \
            else decoder.clock() + max(0.0, deadline_budget_s)
        pre_s = max(0.0, queue_age_s or 0.0)
        takes_cost = _resolve_takes_cost(resolve)

        def on_done(h: StreamHandle) -> None:
            # cost pass-through (ISSUE 17): the caller's queue wait lands
            # on the ledger at terminal time (race-free — on_done runs
            # once, on the engine thread) and rides resolve when the
            # caller's closure accepts it
            kw = {}
            if h.cost is not None:
                h.cost.queue_s = pre_s
                if takes_cost:
                    kw["cost"] = h.cost
            if h.status == "ok":
                ttft_s = None if h.ttft_s is None else pre_s + h.ttft_s
                resolve(reply=self._reply_body(h.tokens, ttft_s),
                        status=200, verdict="ok", ttft_s=ttft_s, **kw)
            elif h.status == "denied":
                resolve(reply={"error": "shed: page pool exhausted "
                                        "mid-decode"},
                        status=503, verdict="shed_page_pool",
                        retry_after_s=1.0, **kw)
            elif h.status == "expired":
                resolve(reply={"error": "deadline expired mid-decode"},
                        status=504, verdict="deadline_expired_decoding",
                        **kw)
            elif decoder.abort_reason == "stall":
                # the watchdog killed a hung dispatch under this request:
                # the prompt is fine and another worker (or this engine
                # after its supervised restart) can serve it — a
                # retryable 503, not a 500 (ISSUE 16)
                resolve(reply={"error": "shed: decode engine stalled"},
                        status=503, verdict="shed_engine_stall",
                        retry_after_s=1.0, **kw)
            else:  # cancelled / error — the engine went away under us
                resolve(reply={"error": f"decode {h.status}"},
                        status=500, verdict="error", **kw)

        decoder.submit(prompt, deadline_s=deadline_s, on_done=on_done,
                       trace_id=trace_id, prompt_hash=prompt_hash)

    # ------------------------------------------------------------- batch path
    def _decode_batch(self, col, n: int, out: np.ndarray, age) -> None:
        """Ticked/batch decode: one one-shot decode over the drained rows.
        Mid-decode page denials surface per row as :class:`ShedReply`
        (serving maps them to 503); ``report_ttft`` wraps replies with the
        honest ticked TTFT — the full latency (queue age at drain + decode
        wall, both RELATIVE durations so the server's clock domain never
        leaks in), since no token is client-visible before the batch
        resolves."""
        t0 = time.monotonic()
        prompts = [np.asarray(v, np.int32).reshape(-1) for v in col]
        lengths = np.asarray([len(q) for q in prompts], np.int32)
        P = int(lengths.max())
        stacked = np.zeros((n, P), np.int32)
        for i, q in enumerate(prompts):
            stacked[i, :len(q)] = q
        res = self.runner.decode(stacked, lengths=lengths,
                                 **self.decode_kwargs)
        denied = set((res.extras or {}).get("denied_rows", ()))
        wall_s = time.monotonic() - t0
        for i in range(n):
            if i in denied:
                out[i] = ShedReply("page pool exhausted mid-decode")
            elif age is not None:
                out[i] = self._reply_body(
                    res.tokens[i], max(0.0, float(age[i])) + wall_s)
            else:
                out[i] = self._reply_body(res.tokens[i], None)

    def _decode_batch_continuous(self, col, n: int, out: np.ndarray,
                                 age) -> None:
        """Batch front of a continuous scorer (streaming fallback, batch
        transform): rows ride the live stream — submit each into a slot,
        waiting for a free one when the batch is wider than the engine —
        so the executable cache, pool accounting and metrics stay one
        story."""
        decoder = self._ensure_decoder()
        handles: List[Optional[StreamHandle]] = [None] * n
        outstanding: List[StreamHandle] = []
        for i in range(n):
            prompt = np.asarray(col[i], np.int32).reshape(-1)
            while True:
                try:
                    handles[i] = decoder.submit(prompt)
                    outstanding.append(handles[i])
                    break
                except SlotsExhausted:
                    # the batch is wider than the engine (or concurrent
                    # serving traffic holds every slot): wait for capacity
                    # instead of shedding our own batch
                    if outstanding:
                        outstanding.pop(0).done.wait()
                    else:
                        time.sleep(0.005)
                except PagePoolExhausted as ex:
                    out[i] = ShedReply(str(ex))
                    break
        for i in range(n):
            h = handles[i]
            if h is None:
                continue
            h.done.wait()
            if h.status == "ok":
                pre_s = max(0.0, float(age[i])) if age is not None else 0.0
                out[i] = self._reply_body(
                    h.tokens, None if h.ttft_s is None
                    else pre_s + h.ttft_s)
            else:
                out[i] = ShedReply(f"decode {h.status}")

    def _transform(self, df: DataFrame) -> DataFrame:
        def per_part(p):
            col = p[self.input_col]
            n = len(col)
            out = np.empty(n, dtype=object)
            if n == 0:
                return {**p, self.reply_col: out}
            age = p.get("_enq_age_s") if hasattr(p, "get") else None
            if self.mode == "decode" and self.continuous:
                self._decode_batch_continuous(col, n, out, age)
            elif self.mode == "decode":
                self._decode_batch(col, n, out, age)
            else:
                x = np.stack([self.prepare(v) for v in col])
                y = self.runner.apply_batch(x, front="serving")
                for i in range(n):
                    out[i] = self.encode(y[i])
            return {**p, self.reply_col: out}

        return df.map_partitions(per_part)

    def transform_schema(self, schema):
        schema.require(self.input_col)
        return schema.add(self.reply_col, ColumnType.VECTOR)
