"""BiLSTM sequence tagger — the medical-entity-extraction model family.

Reference capability: ``notebooks/DeepLearning - BiLSTM Medical Entity
Extraction.ipynb`` evaluates a pretrained CNTK BiLSTM per row.  Here it is a
flax module whose recurrence is a ``lax.scan``-based LSTM (compiler-friendly
control flow, static shapes); long sequences can additionally be sharded over
the ``seq`` mesh axis via ``parallel.ring_attention`` blockwise primitives.
"""
from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp


class LSTMLayer(nn.Module):
    """One directional LSTM over (batch, time, feat) via flax's scan-based RNN."""
    hidden: int
    reverse: bool = False

    @nn.compact
    def __call__(self, xs):
        rnn = nn.RNN(nn.OptimizedLSTMCell(self.hidden),
                     reverse=self.reverse, keep_order=True)
        return rnn(xs)


class BiLSTMTagger(nn.Module):
    """Embedding -> stacked BiLSTM -> per-token classification head."""

    vocab_size: int
    num_tags: int
    embed_dim: int = 128
    hidden: int = 256
    num_layers: int = 2
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, tokens, train: bool = False, features: bool = False):
        # tokens: (batch, time) int32
        x = nn.Embed(self.vocab_size, self.embed_dim, dtype=self.dtype)(tokens)
        for i in range(self.num_layers):
            fwd = LSTMLayer(self.hidden, reverse=False, name=f"fwd_{i}")(x)
            bwd = LSTMLayer(self.hidden, reverse=True, name=f"bwd_{i}")(x)
            x = jnp.concatenate([fwd, bwd], axis=-1)
        if features:
            return x.astype(jnp.float32)
        logits = nn.Dense(self.num_tags, dtype=self.dtype, name="head")(x)
        return logits.astype(jnp.float32)
