"""Constants (reference ``LightGBMConstants.scala``).

Most of the reference's constants govern its socket rendezvous (ports,
retries, timeouts) which the mesh bootstrap replaces; the training-semantics
constants survive with the same names.
"""

DEFAULT_LISTEN_TIMEOUT_S = 600.0      # reference DefaultListenTimeout
NETWORK_RETRIES = 3                   # reference NetworkRetries (mesh init retry)
INITIAL_DELAY_MS = 100
DEFAULT_LOCAL_LISTEN_PORT = 12400     # kept for API parity; unused on mesh
MAX_PORT = 65535

DATA_PARALLEL = "data_parallel"
VOTING_PARALLEL = "voting_parallel"
FEATURE_PARALLEL = "feature_parallel"
SERIAL = "serial"

IGNORE_STATUS = "ignore"              # driver rendezvous line protocol tokens
FINISHED_STATUS = "finished"          # (bootstrap-era; documented for parity)
