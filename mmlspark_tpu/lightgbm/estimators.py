"""LightGBM-compatible estimators over the TPU GBDT core.

Reference: ``lightgbm/src/main/scala/.../LightGBMClassifier.scala`` (:209),
``LightGBMRegressor.scala``, ``LightGBMRanker.scala`` and the shared param
surface (``params/TrainParams.scala`` ~90 tunables; the high-traffic subset is
exposed here with the same names/semantics).  The Spark-side machinery the
reference needs — partition coalescing, driver rendezvous, barrier
mapPartitions (``LightGBMBase.scala:43-489``) — collapses on TPU to: gather
the frame's columns, shard rows over the device mesh, run the jitted boosting
loop (``core.train``); histogram psum over ICI replaces ``LGBM_NetworkInit``.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional

import numpy as np

from ..core import (ComplexParam, DataFrame, Estimator, HasFeaturesCol,
                    HasLabelCol, HasPredictionCol, HasProbabilityCol,
                    HasRawPredictionCol, HasWeightCol, Model, Param)
from ..core.schema import ColumnType, stack_vector_column
from ..models.gbdt import GBDTBooster
from . import core as gbdt_core
from .core import GBDTParams


def _shared_params(cls):
    """Attach the shared LightGBM param surface (TrainParams.scala names)."""
    specs = [
        ("num_iterations", "number of boosting iterations", "int", 100),
        ("learning_rate", "shrinkage rate", "float", 0.1),
        ("num_leaves", "max leaves per tree (leaf-wise best-first growth, "
                       "LightGBM numLeaves semantics)", "int", 31),
        ("max_depth", "max tree depth; set alone it selects level-wise "
                      "depth growth, with num_leaves it caps leaf-wise depth",
         "int", None),
        ("max_bin", "max histogram bins per feature", "int", 255),
        ("boosting_type", "gbdt|rf|dart|goss", "string", "gbdt"),
        ("lambda_l1", "L1 regularization", "float", 0.0),
        ("lambda_l2", "L2 regularization", "float", 0.0),
        ("min_data_in_leaf", "min rows per leaf", "int", 20),
        ("min_sum_hessian_in_leaf", "min hessian per leaf", "float", 1e-3),
        ("min_gain_to_split", "min split gain", "float", 0.0),
        ("bagging_fraction", "row subsample fraction", "float", 1.0),
        ("bagging_freq", "bagging frequency (0=off)", "int", 0),
        ("feature_fraction", "feature subsample fraction", "float", 1.0),
        ("top_rate", "GOSS large-gradient keep rate", "float", 0.2),
        ("other_rate", "GOSS small-gradient sample rate", "float", 0.1),
        ("drop_rate", "DART tree drop rate", "float", 0.1),
        ("max_drop", "DART max dropped trees", "int", 50),
        ("skip_drop", "DART skip probability", "float", 0.5),
        ("max_delta_step", "max leaf output", "float", 0.0),
        ("early_stopping_round", "stop if no valid improvement", "int", 0),
        ("metric", "eval metric name ('' = objective default)", "string", ""),
        ("validation_indicator_col", "bool column marking validation rows", "string", None),
        ("model_string", "warm-start model string", "string", None),
        ("num_batches", "split training into sequential batches "
                        "(LightGBMBase.scala:46-61)", "int", 0),
        ("growth", "tree growth strategy: leaf (LightGBM best-first) | "
                   "level (depth-wise) | auto (leaf unless only max_depth "
                   "is set)", "string", "auto"),
        ("seed", "random seed", "int", 0),
        ("parallelism", "data_parallel (full histogram psum) | "
                        "voting_parallel (top-k feature voting, O(k*B) comm) "
                        "| serial", "string", "data_parallel"),
        ("top_k", "voting_parallel: local top-k features voted per node "
                  "(reference TrainParams topK)", "int", 20),
        ("shard_rows", "shard rows over the active device mesh", "bool", False),
        ("categorical_features", "feature indices treated as categorical "
         "(one-vs-rest below max_cat_to_onehot cardinality, sorted-subset "
         "many-vs-many above; reference getCategoricalIndexes, "
         "LightGBMBase.scala:168)", "list", None),
        ("max_cat_to_onehot", "cardinality threshold below which categorical "
         "features split one-vs-rest instead of sorted-subset", "int", 4),
        ("cat_smooth", "grad/hess ratio smoothing when ordering categories "
         "for subset splits", "double", 10.0),
        ("cat_l2", "extra L2 regularization applied when scoring "
         "sorted-subset categorical splits", "double", 10.0),
        ("max_cat_threshold", "max categories on the smaller side of a "
         "sorted-subset split", "int", 32),
        ("use_quantized_grad", "quantized training (LightGBM 4.x): "
         "stochastically round per-row grad/hess to integer levels once "
         "per iteration and build packed integer histograms, rescaling "
         "only at split-gain time; unset = auto (on for accelerator "
         "backends, off on CPU; MMLSPARK_TPU_HIST_QUANT=0/1 overrides)",
         "bool", None),
        ("num_grad_quant_bins", "quantization levels for grad/hess under "
         "quantized training (reference name; 4-128, reference default 4 — "
         "16 here holds every repo accuracy gate)", "int", 16),
        ("checkpoint_dir", "directory for periodic atomic booster "
         "checkpoints: the run snapshots booster + iteration + PRNG state "
         "every checkpoint_every iterations and auto-resumes from the "
         "newest valid snapshot (docs/RESILIENCE.md: training fault "
         "tolerance)", "string", None),
        ("checkpoint_every", "checkpoint cadence in boosting iterations "
         "(0 = off; requires checkpoint_dir)", "int", 0),
        ("monitor_port", "serve live training telemetry over HTTP while "
         "fit() runs: GET /progress (step, rows/sec, ETA, loss tail), "
         "/metrics, /debug/dump, /debug/profile (0 = ephemeral port; "
         "unset = no server; docs/OBSERVABILITY.md: training plane)",
         "int", None),
        ("monitor_stall_timeout_s", "arm the training stall watchdog with "
         "a FIXED timeout in seconds instead of the EWMA-scaled default "
         "(a trip books mmlspark_training_stalls_total and writes a "
         "train_stall flight dump); setting this alone enables the "
         "watchdog without the HTTP server", "double", None),
    ]
    for name, doc, dtype, default in specs:
        setattr(cls, name, Param(name, doc, dtype, default))
    # re-run metaclass param collection
    cls._params = {**{p.name: p for p in cls.params()},
                   **{s[0]: getattr(cls, s[0]) for s in specs}}
    return cls


class _LightGBMBase(Estimator, HasFeaturesCol, HasLabelCol, HasWeightCol):
    """Shared train plumbing (reference ``LightGBMBase.train:43``)."""

    _objective: str = "regression"

    def _gbdt_params(self, num_class: int = 1) -> GBDTParams:
        max_depth = self.get("max_depth")
        growth = self.get("growth")
        if growth == "auto" and max_depth and not self.is_set("num_leaves"):
            # max_depth ALONE selects level-wise growth (the fast bench
            # mode); an explicitly set num_leaves keeps LightGBM leaf-wise
            # growth with max_depth as the depth cap, and the default
            # num_leaves=31 without a depth is leaf-wise too
            growth = "level"
        p = GBDTParams(
            num_iterations=self.get("num_iterations"),
            learning_rate=self.get("learning_rate"),
            num_leaves=self.get("num_leaves"),
            max_depth=max_depth or 0,
            growth=growth,
            max_bin=self.get("max_bin"),
            objective=self._objective,
            num_class=num_class,
            boosting_type=self.get("boosting_type"),
            lambda_l1=self.get("lambda_l1"), lambda_l2=self.get("lambda_l2"),
            min_data_in_leaf=self.get("min_data_in_leaf"),
            min_sum_hessian_in_leaf=self.get("min_sum_hessian_in_leaf"),
            min_gain_to_split=self.get("min_gain_to_split"),
            bagging_fraction=self.get("bagging_fraction"),
            bagging_freq=self.get("bagging_freq"),
            feature_fraction=self.get("feature_fraction"),
            top_rate=self.get("top_rate"), other_rate=self.get("other_rate"),
            drop_rate=self.get("drop_rate"), max_drop=self.get("max_drop"),
            skip_drop=self.get("skip_drop"),
            max_delta_step=self.get("max_delta_step"),
            early_stopping_round=self.get("early_stopping_round"),
            metric=self.get("metric"), seed=self.get("seed"),
            categorical_features=tuple(self.get("categorical_features") or ())
            or None,
            max_cat_to_onehot=self.get("max_cat_to_onehot"),
            cat_smooth=self.get("cat_smooth"), cat_l2=self.get("cat_l2"),
            max_cat_threshold=self.get("max_cat_threshold"),
            voting_k=self.get("top_k")
            if self.get("parallelism") == "voting_parallel" else 0,
            use_quantized_grad=self.get("use_quantized_grad"),
            num_grad_quant_bins=self.get("num_grad_quant_bins"))
        return p

    def _collect_xyw(self, df: DataFrame):
        data = df.collect()
        X = stack_vector_column(data[self.get("features_col")])
        y = np.asarray(data[self.get("label_col")], np.float64)
        w_col = self.get("weight_col")
        w = np.asarray(data[w_col], np.float64) if w_col else None
        return X, y, w, data

    def _split_valid(self, X, y, w, data):
        vcol = self.get("validation_indicator_col")
        if not vcol:
            return X, y, w, None
        mask = np.asarray(data[vcol], bool)
        valid = (X[mask], y[mask])
        keep = ~mask
        return X[keep], y[keep], (w[keep] if w is not None else None), valid

    def _train_booster(self, X, y, w, valid, num_class=1, group_ptr=None):
        params = self._gbdt_params(num_class)
        init_booster = None
        ms = self.get("model_string")
        if ms:
            init_booster = GBDTBooster.from_string(ms)
        num_batches = self.get("num_batches") or 0
        ckpt_kw = dict(checkpoint_dir=self.get("checkpoint_dir"),
                       checkpoint_every=self.get("checkpoint_every"),
                       monitor_port=self.get("monitor_port"),
                       monitor_stall_timeout_s=self.get(
                           "monitor_stall_timeout_s"))
        if num_batches > 1:
            # sequential batch training with warm start between batches
            # (reference LightGBMBase.scala:46-61).  Checkpoints would
            # collide across batches sharing one dir, so the batch index
            # namespaces them.
            bounds = np.linspace(0, len(y), num_batches + 1).astype(int)
            batch_params = dataclasses.replace(
                params, num_iterations=max(1, params.num_iterations // num_batches))
            result = None
            base_dir = ckpt_kw["checkpoint_dir"]
            for i in range(num_batches):
                sl = slice(bounds[i], bounds[i + 1])
                if base_dir:
                    ckpt_kw["checkpoint_dir"] = f"{base_dir}/batch_{i:04d}"
                result = gbdt_core.train(
                    X[sl], y[sl], batch_params,
                    sample_weight=None if w is None else w[sl],
                    valid=valid, init_booster=init_booster,
                    shard_rows=self.get("shard_rows"), **ckpt_kw)
                init_booster = result.booster
            return result
        return gbdt_core.train(X, y, params, sample_weight=w, valid=valid,
                               group_ptr=group_ptr, init_booster=init_booster,
                               shard_rows=self.get("shard_rows"), **ckpt_kw)


class _LightGBMModelBase(Model, HasFeaturesCol, HasPredictionCol):
    """Shared predict helpers (reference ``LightGBMModelMethods``)."""

    booster_param = ComplexParam("booster", "fitted GBDTBooster")

    @property
    def booster(self) -> GBDTBooster:
        return self.get_or_fail("booster")

    def get_model_string(self) -> str:
        return self.booster.to_string()

    def save_native_model(self, path: str) -> None:
        """Reference ``saveNativeModel`` (LightGBMBooster.scala:454)."""
        with open(path, "w") as f:
            f.write(self.booster.to_string())

    def get_feature_importances(self, importance_type: str = "split"):
        return self.booster.feature_importance(importance_type)

    def predict_leaf(self, df: DataFrame) -> DataFrame:
        fc = self.get("features_col")
        def per_part(p):
            X = stack_vector_column(p[fc])
            leaves = self.booster.predict_leaf(X)
            col = np.empty(len(leaves), dtype=object)
            for i in range(len(leaves)):
                col[i] = leaves[i].astype(np.float64)
            return {**p, "leaf_prediction": col}
        return df.map_partitions(per_part)

    def predict_contrib(self, df: DataFrame) -> DataFrame:
        fc = self.get("features_col")
        def per_part(p):
            X = stack_vector_column(p[fc])
            contrib = self.booster.predict_contrib(X)
            col = np.empty(len(contrib), dtype=object)
            for i in range(len(contrib)):
                col[i] = contrib[i]
            return {**p, "features_shap": col}
        return df.map_partitions(per_part)


# ---------------------------------------------------------------------------
# Classifier
# ---------------------------------------------------------------------------

@_shared_params
class LightGBMClassifier(_LightGBMBase, HasPredictionCol, HasProbabilityCol,
                         HasRawPredictionCol):
    """Binary/multiclass GBDT classifier (ref ``LightGBMClassifier.scala``)."""

    objective = Param("objective", "binary|multiclass (auto from labels if unset)",
                      "string", None)
    is_unbalance = Param("is_unbalance", "reweight classes by inverse frequency",
                         "bool", False)

    def _fit(self, df: DataFrame) -> "LightGBMClassificationModel":
        X, y, w, data = self._collect_xyw(df)
        classes = np.unique(y[~np.isnan(y)])
        num_class = len(classes)
        obj = self.get("objective") or ("binary" if num_class <= 2 else "multiclass")
        self._objective = obj
        y_idx = np.searchsorted(classes, y).astype(np.float64)
        if self.get("is_unbalance"):
            counts = np.bincount(y_idx.astype(int), minlength=num_class).astype(np.float64)
            cw = counts.sum() / np.maximum(counts, 1) / num_class
            w = (w if w is not None else np.ones_like(y_idx)) * cw[y_idx.astype(int)]
        Xt, yt, wt, valid = self._split_valid(X, y_idx, w, data)
        result = self._train_booster(Xt, yt, wt, valid,
                                     num_class=num_class if obj == "multiclass" else 1)
        model = LightGBMClassificationModel()
        model.set("booster", result.booster)
        model.set("classes", classes.tolist())
        for pcol in ("features_col", "prediction_col", "probability_col",
                     "raw_prediction_col"):
            model.set(pcol, self.get(pcol))
        return model


class LightGBMClassificationModel(_LightGBMModelBase, HasProbabilityCol,
                                  HasRawPredictionCol):
    classes = Param("classes", "label values in index order", "list")

    def _transform(self, df: DataFrame) -> DataFrame:
        fc = self.get("features_col")
        classes = np.asarray(self.get("classes"))
        booster = self.booster

        def per_part(p):
            X = stack_vector_column(p[fc])
            raw = booster.raw_scores(X)
            if booster.objective == "binary":
                p1 = 1.0 / (1.0 + np.exp(-booster.sigmoid * raw[:, 0]))
                prob = np.stack([1 - p1, p1], axis=1)
            else:
                z = raw - raw.max(axis=1, keepdims=True)
                e = np.exp(z)
                prob = e / e.sum(axis=1, keepdims=True)
            pred_idx = prob.argmax(axis=1)
            pred = classes[pred_idx].astype(np.float64)
            prob_col = np.empty(len(X), dtype=object)
            raw_col = np.empty(len(X), dtype=object)
            for i in range(len(X)):
                prob_col[i] = prob[i]
                raw_col[i] = raw[i]
            return {**p, self.get("prediction_col"): pred,
                    self.get("probability_col"): prob_col,
                    self.get("raw_prediction_col"): raw_col}

        return df.map_partitions(per_part)

    def transform_schema(self, schema):
        schema.require(self.get("features_col"))
        s = schema.add(self.get("prediction_col"), ColumnType.DOUBLE)
        s = s.add(self.get("probability_col"), ColumnType.VECTOR)
        return s.add(self.get("raw_prediction_col"), ColumnType.VECTOR)


# ---------------------------------------------------------------------------
# Regressor
# ---------------------------------------------------------------------------

@_shared_params
class LightGBMRegressor(_LightGBMBase, HasPredictionCol):
    """GBDT regressor (ref ``LightGBMRegressor.scala``); objectives:
    regression (L2), regression_l1, huber, quantile, poisson, tweedie
    (log-link count/compound-Poisson targets, as native LightGBM)."""

    objective = Param("objective", "regression|regression_l1|huber|quantile"
                      "|poisson|tweedie|gamma", "string", "regression")
    alpha = Param("alpha", "huber delta / quantile level", "float", 0.9)
    tweedie_variance_power = Param("tweedie_variance_power",
                                   "tweedie variance power in (1, 2)",
                                   "float", 1.5)

    def _fit(self, df: DataFrame) -> "LightGBMRegressionModel":
        self._objective = self.get("objective")
        X, y, w, data = self._collect_xyw(df)
        Xt, yt, wt, valid = self._split_valid(X, y, w, data)
        params = self._gbdt_params(1)
        params = dataclasses.replace(
            params, alpha=self.get("alpha"),
            tweedie_variance_power=self.get("tweedie_variance_power"))
        ms = self.get("model_string")
        init_booster = GBDTBooster.from_string(ms) if ms else None
        result = gbdt_core.train(Xt, yt, params, sample_weight=wt, valid=valid,
                                 init_booster=init_booster,
                                 shard_rows=self.get("shard_rows"),
                                 checkpoint_dir=self.get("checkpoint_dir"),
                                 checkpoint_every=self.get("checkpoint_every"),
                                 monitor_port=self.get("monitor_port"),
                                 monitor_stall_timeout_s=self.get(
                                     "monitor_stall_timeout_s"))
        model = LightGBMRegressionModel()
        model.set("booster", result.booster)
        model.set("features_col", self.get("features_col"))
        model.set("prediction_col", self.get("prediction_col"))
        return model


class LightGBMRegressionModel(_LightGBMModelBase):
    def _transform(self, df: DataFrame) -> DataFrame:
        fc = self.get("features_col")
        booster = self.booster

        def per_part(p):
            X = stack_vector_column(p[fc])
            return {**p, self.get("prediction_col"): booster.predict(X)}

        return df.map_partitions(per_part)

    def transform_schema(self, schema):
        schema.require(self.get("features_col"))
        return schema.add(self.get("prediction_col"), ColumnType.DOUBLE)


# ---------------------------------------------------------------------------
# Ranker
# ---------------------------------------------------------------------------

@_shared_params
class LightGBMRanker(_LightGBMBase, HasPredictionCol):
    """LambdaRank ranker (ref ``LightGBMRanker.scala``); requires group_col."""

    group_col = Param("group_col", "query-group id column", "string", "group")
    max_position = Param("max_position", "NDCG truncation", "int", 30)

    def _fit(self, df: DataFrame) -> "LightGBMRankerModel":
        self._objective = "lambdarank"
        fc, lc, gc = self.get("features_col"), self.get("label_col"), self.get("group_col")
        data = df.collect()
        groups = np.asarray(data[gc])
        order = np.argsort(groups, kind="stable")
        X = stack_vector_column(data[fc])[order]
        y = np.asarray(data[lc], np.float64)[order]
        w_col = self.get("weight_col")
        w = np.asarray(data[w_col], np.float64)[order] if w_col else None
        sorted_groups = groups[order]
        change = np.nonzero(np.concatenate([[True], sorted_groups[1:] != sorted_groups[:-1]]))[0]
        group_ptr = np.concatenate([change, [len(sorted_groups)]])
        result = self._train_booster(X, y, w, None, group_ptr=group_ptr)
        model = LightGBMRankerModel()
        model.set("booster", result.booster)
        model.set("features_col", fc)
        model.set("prediction_col", self.get("prediction_col"))
        return model


class LightGBMRankerModel(_LightGBMModelBase):
    def _transform(self, df: DataFrame) -> DataFrame:
        fc = self.get("features_col")
        booster = self.booster

        def per_part(p):
            X = stack_vector_column(p[fc])
            return {**p, self.get("prediction_col"): booster.raw_scores(X)[:, 0]}

        return df.map_partitions(per_part)

    def transform_schema(self, schema):
        schema.require(self.get("features_col"))
        return schema.add(self.get("prediction_col"), ColumnType.DOUBLE)
