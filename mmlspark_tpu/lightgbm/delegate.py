"""Training delegate — user hooks per batch/iteration.

Reference: ``LightGBMDelegate.scala`` — callbacks before/after training and
per iteration (used e.g. for dynamic learning-rate schedules).
"""
from __future__ import annotations

from typing import Dict, Optional


class LightGBMDelegate:
    """Subclass and pass via ``LightGBMClassifier.set('delegate', ...)`` or
    ``core.train(callbacks=[delegate.as_callback()])``."""

    def before_training_iteration(self, iteration: int) -> None:
        pass

    def after_training_iteration(self, iteration: int,
                                 eval_result: Optional[Dict] = None) -> None:
        pass

    def get_learning_rate(self, iteration: int, current_lr: float) -> float:
        """Return the LR for this iteration (dynamic schedules)."""
        return current_lr

    def as_callback(self):
        def cb(iteration, eval_result):
            self.after_training_iteration(iteration, eval_result)
        return cb
