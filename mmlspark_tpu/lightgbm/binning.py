"""Quantile feature binning — LightGBM's BinMapper equivalent.

Reference: LightGBM C++ bins features into <=255 histogram bins before
training (consumed via ``LGBM_DatasetCreateFromMat/CSR``,
``DatasetAggregator.scala:335,:442``).  Here binning is split: edge *finding*
on host (numpy quantiles over a row sample — one pass, driver side), bin
*application* on device (``ops.histogram.bin_matrix`` — a vectorized
searchsorted that XLA fuses with the ingest transfer).

NaN handling: NaN sorts to bin 0 (routes left), matching the booster's
missing-goes-left convention.
"""
from __future__ import annotations

from typing import Iterable, Optional

import numpy as np


class StreamingQuantileSketch:
    """Bounded-memory quantile sketch for out-of-core edge finding: a
    vectorized row reservoir (Algorithm R) fed tile by tile.

    ``BinMapper.fit`` already computes edges from a <=``sample_cnt`` row
    sample; this sketch produces the SAME kind of sample without ever
    holding the full matrix — ``fit_streaming`` over host tiles is the
    out-of-core twin of ``fit``.  When the total row count fits the
    reservoir the sample is the exact dataset (every row retained in
    order), so streamed edges are IDENTICAL to the in-memory fit's; above
    the cap each row survives with probability ``cap / n`` (within-chunk
    replacement collisions resolve last-write-wins — a sketch, not a
    permutation-exact reservoir, which edge quantiles do not need).
    """

    def __init__(self, num_features: int, sample_cnt: int = 200_000,
                 seed: int = 3):
        self.cap = int(sample_cnt)
        self.seen = 0
        self._buf = np.empty((self.cap, num_features), np.float32)
        self._rng = np.random.default_rng(seed)

    def add(self, chunk: np.ndarray) -> "StreamingQuantileSketch":
        chunk = np.asarray(chunk, np.float32)
        m = chunk.shape[0]
        fill = max(0, min(self.cap - self.seen, m))
        if fill:
            self._buf[self.seen:self.seen + fill] = chunk[:fill]
        rest = chunk[fill:]
        if rest.shape[0]:
            s = self.seen + fill + np.arange(rest.shape[0])
            accept = self._rng.random(rest.shape[0]) < self.cap / (s + 1.0)
            idx = np.flatnonzero(accept)
            if idx.size:
                slots = self._rng.integers(0, self.cap, size=idx.size)
                self._buf[slots] = rest[idx]
        self.seen += m
        return self

    def sample(self) -> np.ndarray:
        """The retained row sample (the whole stream when it fit)."""
        return self._buf[: min(self.seen, self.cap)]


class BinMapper:
    """Per-feature quantile bin edges.  edges[f] has length (max_bin - 1),
    padded with +inf for features with fewer distinct values."""

    def __init__(self, max_bin: int = 255, categorical_features=None):
        if not 2 <= max_bin <= 256:
            raise ValueError("max_bin must be in [2, 256]")
        self.max_bin = max_bin
        self.edges: Optional[np.ndarray] = None  # (F, max_bin - 1) float32
        # categorical features bin by CATEGORY CODE (bin = clip(round(x),
        # 0, max_bin-1)); no quantile edges exist for them (reference
        # categorical handling, LightGBMBase.getCategoricalIndexes:168)
        self.categorical_features = sorted(int(i) for i in
                                           (categorical_features or []))

    @property
    def num_bins(self) -> int:
        return self.max_bin

    def fit(self, X: np.ndarray, sample_cnt: int = 200_000, seed: int = 3) -> "BinMapper":
        X = np.asarray(X, np.float32)
        n, F = X.shape
        if n > sample_cnt:
            idx = np.random.default_rng(seed).choice(n, sample_cnt, replace=False)
            X = X[idx]
        B = self.max_bin
        # threaded C++ edge finding when the data plane is available AND
        # there are cores to thread over — the reference keeps this loop
        # native too (LightGBM BinMapper); single-core, vectorized numpy
        # quantiles win over the scalar C++ sort loop
        import multiprocessing
        if X.shape[0] * F >= 1 << 16 and multiprocessing.cpu_count() >= 4:
            from ..utils.native_loader import bin_edges_native
            native = bin_edges_native(X, B)
            if native is not None:
                if self.categorical_features:  # code-binned: no edges
                    native[self.categorical_features] = np.inf
                self.edges = native
                return self
        edges = np.full((F, B - 1), np.inf, np.float32)
        qs = np.linspace(0, 1, B + 1)[1:-1]  # B-1 interior quantiles
        cats = set(self.categorical_features)
        for f in range(F):
            if f in cats:
                continue  # code-binned: no numerical edges
            col = X[:, f]
            col = col[~np.isnan(col)]
            if col.size == 0:
                continue
            uniq = np.unique(col)
            if uniq.size <= 1:
                continue
            if uniq.size <= B:
                # few distinct values: midpoints between consecutive uniques
                mids = (uniq[:-1] + uniq[1:]) / 2.0
                edges[f, :mids.size] = mids
            else:
                e = np.quantile(col, qs)
                e = np.unique(e.astype(np.float32))
                edges[f, :e.size] = e
        self.edges = edges
        return self

    def fit_streaming(self, chunks: Iterable[np.ndarray],
                      sample_cnt: int = 200_000, seed: int = 3) -> "BinMapper":
        """Out-of-core ``fit``: edges from a :class:`StreamingQuantileSketch`
        fed one host tile at a time — no full-matrix materialization.  When
        the stream's total rows fit ``sample_cnt`` the resulting edges are
        bit-identical to ``fit`` on the concatenated matrix (the reservoir
        holds every row; ``fit`` would have used them all too)."""
        sketch: Optional[StreamingQuantileSketch] = None
        for chunk in chunks:
            chunk = np.asarray(chunk, np.float32)
            if sketch is None:
                sketch = StreamingQuantileSketch(chunk.shape[1], sample_cnt,
                                                 seed)
            sketch.add(chunk)
        if sketch is None:
            raise ValueError("fit_streaming received an empty chunk stream")
        # the sample already fits fit()'s budget: no re-subsampling happens
        return self.fit(sketch.sample(), sample_cnt=sample_cnt, seed=seed)

    def transform(self, X: np.ndarray, device: bool = False) -> np.ndarray:
        """(n, F) raw -> (n, F) uint8 bins.  bin = #edges < x; NaN -> 0.

        Default is HOST binning: the uint8 result is 4x smaller than the
        float32 input, so binning before the host->device transfer quarters
        the interconnect traffic (decisive through a device relay/DCN).
        Threaded C++ when the data plane + cores exist, vectorized numpy
        per-column searchsorted otherwise; ``device=True`` digitizes on the
        accelerator for data already device-resident.
        """
        if self.edges is None:
            raise RuntimeError("BinMapper not fitted")
        X = np.asarray(X, np.float32)
        if device:
            import jax.numpy as jnp
            from ..ops.histogram import bin_matrix  # module-level jit cache
            out = np.asarray(bin_matrix(jnp.asarray(X),
                                        jnp.asarray(self.edges),
                                        self.max_bin))
            return self._overwrite_cat_bins(X, out)
        import multiprocessing
        if X.size >= 1 << 16 and multiprocessing.cpu_count() >= 4:
            from ..utils.native_loader import bin_apply_native
            native = bin_apply_native(X, self.edges, self.max_bin)
            if native is not None:
                return self._overwrite_cat_bins(X, native)
        out = np.empty(X.shape, np.uint8)
        cats = set(self.categorical_features)
        for f in range(X.shape[1]):
            if f in cats:
                continue  # filled by _overwrite_cat_bins (single code path)
            finite_edges = self.edges[f][np.isfinite(self.edges[f])]
            out[:, f] = np.searchsorted(finite_edges, np.nan_to_num(X[:, f], nan=-np.inf),
                                        side="left")
        return self._overwrite_cat_bins(X, out)

    def _overwrite_cat_bins(self, X: np.ndarray, out: np.ndarray) -> np.ndarray:
        """The ONE categorical code-binning path (all transform variants end
        here): NaN -> reserved last bin; codes must be non-negative ints
        (clip+round would otherwise silently alias negatives onto code 0
        while predict-time walks compare the raw value)."""
        for f in self.categorical_features:
            col = X[:, f]
            finite = col[~np.isnan(col)]
            if finite.size and finite.min() < 0:
                raise ValueError(
                    f"categorical feature {f} holds negative codes "
                    f"(min {finite.min()}); encode categories as "
                    f"non-negative integers (e.g. via ValueIndexer)")
            codes = np.nan_to_num(col, nan=float(self.max_bin - 1))
            out[:, f] = np.clip(np.round(codes), 0, self.max_bin - 1) \
                .astype(np.uint8)
        return out

    def bin_upper_value(self) -> np.ndarray:
        """(F, max_bin-1) raw threshold value for 'bin <= t' splits (+inf pad
        means the split cannot occur there)."""
        return self.edges
