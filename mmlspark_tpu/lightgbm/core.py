"""GBDT training core — leaf-wise and level-wise tree growth as jitted XLA.

Reference hot path: ``TrainUtils.trainCore`` (``TrainUtils.scala:92-159``)
calls ``LGBM_BoosterUpdateOneIter`` per iteration — native histogram build +
socket allreduce + split finding.  TPU-native, one boosting iteration is a
single jitted function built from:

  histograms  = one fused segment-sum scatter   (ops.histogram)       [VPU]
  split find  = cumsum + argmax over (node, feature, bin)             [VPU]
  routing     = gather of each row's split decision                   [VPU]

Two growth strategies share those kernels:

- **leaf-wise** (LightGBM's defining best-first growth, the default when
  ``num_leaves`` is set): a ``lax.scan`` over ``num_leaves - 1`` split
  steps; each step splits the leaf with the global best gain, builds the
  left child's histogram with one masked pass and derives the right
  sibling by subtraction.  Trees are arrays-of-nodes with explicit child
  pointers (non-perfect shapes, ``num_leaves`` honoured exactly).
- **level-wise** (``max_depth``-driven, XGBoost-style depth growth): the
  python loop over static depth unrolls into XLA, one histogram pass per
  level for all frontier nodes at once — fewer data passes per tree, the
  fastest mode for the throughput bench.

Across data shards the histogram tensors are psum'd over the mesh's ``data``
axis — this replaces LightGBM's ``data_parallel`` TCP-ring allreduce.
``voting_parallel`` (reference ``parallelism`` + ``topK``,
``TrainParams.scala:11-12``) is implemented for real in both growth modes:
shards vote their local top-k features per node and only the global top-2k
features' histograms cross the mesh, cutting per-node ICI traffic from
O(F*B) to O(k*B).

Supports the reference's boosting modes (``boosting_type`` gbdt/rf/dart/goss,
``params/TrainParams.scala``), objectives, bagging, feature_fraction, L1/L2,
min_data_in_leaf, early stopping, and warm start from an existing booster.
"""
from __future__ import annotations

import dataclasses
import math
import time
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..models.gbdt import GBDTBooster
from ..observability.compute import instrumented_jit
from ..ops.histogram import build_histograms
from .binning import BinMapper


@dataclasses.dataclass
class GBDTParams:
    num_iterations: int = 100
    learning_rate: float = 0.1
    max_depth: int = 0               # leaf-wise: depth cap (0 = uncapped);
    #                                  level-wise: tree depth (0 -> 5)
    num_leaves: Optional[int] = None  # leaf-wise leaf budget (LightGBM
    #                                  numLeaves, default 31 when leaf growth)
    growth: str = "auto"             # leaf | level | auto (leaf iff
    #                                  num_leaves given, else level)
    max_bin: int = 255
    objective: str = "binary"
    num_class: int = 1
    boosting_type: str = "gbdt"      # gbdt | rf | dart | goss
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    min_gain_to_split: float = 0.0
    bagging_fraction: float = 1.0
    bagging_freq: int = 0
    feature_fraction: float = 1.0
    # goss
    top_rate: float = 0.2
    other_rate: float = 0.1
    # dart
    drop_rate: float = 0.1
    max_drop: int = 50
    skip_drop: float = 0.5
    # misc
    max_delta_step: float = 0.0
    sigmoid: float = 1.0
    alpha: float = 0.9               # huber / quantile
    tweedie_variance_power: float = 1.5  # tweedie: 1 (poisson) .. 2 (gamma)
    early_stopping_round: int = 0
    metric: str = ""
    seed: int = 0
    verbosity: int = -1
    # categorical splits (reference getCategoricalIndexes,
    # LightGBMBase.scala:168): these feature indices bin by CATEGORY CODE.
    # Low-cardinality features (<= max_cat_to_onehot observed codes) split
    # one-vs-rest (code == c); higher-cardinality features use LightGBM's
    # sorted-subset (many-vs-many) search: codes sorted by grad/hess ratio,
    # prefix subsets scanned from the same histogram tensor
    categorical_features: Optional[Tuple[int, ...]] = None
    max_cat_to_onehot: int = 4
    cat_smooth: float = 10.0         # ratio denominator smoothing
    cat_l2: float = 10.0             # extra L2 when scoring subset splits
    max_cat_threshold: int = 32      # cap on the smaller side's category count
    # resolved in train() from observed cardinalities (data-dependent, part
    # of the jit cache key); settable explicitly for tests
    cat_subset: Optional[Tuple[int, ...]] = None
    # voting-parallel (reference parallelism=voting_parallel + topK,
    # TrainParams.scala:11-12): each shard votes its local top-k features
    # per node; only the global top-2k features' histograms are allreduced,
    # cutting ICI traffic from O(F*B) to O(k*B) per node on wide data.
    # 0 = full histogram psum (data_parallel).
    voting_k: int = 0
    # quantized training (LightGBM 4.x "Quantized Training of GBDT", same
    # param names): per-row grad/hess stochastically rounded to
    # num_grad_quant_bins integer levels once per iteration, histograms
    # accumulated as packed integers (ops.histogram quantized builders) and
    # rescaled only at split-gain time; sibling subtraction is exact in
    # integer space.  None = auto: ON for accelerator backends, OFF on CPU
    # (train() resolves it; MMLSPARK_TPU_HIST_QUANT=0/1 is the escape hatch)
    use_quantized_grad: Optional[bool] = None
    num_grad_quant_bins: int = 16

    def resolve(self) -> "GBDTParams":
        """Normalize growth mode.  Leaf-wise (LightGBM semantics: numLeaves
        default 31, ``LightGBMParams.scala:331-332``) grows by global best
        gain with ``num_leaves`` as the stop and ``max_depth`` as an optional
        cap; level-wise grows a perfect depth-``max_depth`` tree."""
        p = dataclasses.replace(self)
        if p.growth == "auto":
            p.growth = "leaf" if p.num_leaves else "level"
        if p.growth == "level":
            if p.max_depth <= 0:
                p.max_depth = max(1, int(math.ceil(math.log2(max(2, p.num_leaves))))) \
                    if p.num_leaves else 5
            p.num_leaves = 2 ** p.max_depth
        elif p.growth == "leaf":
            p.num_leaves = p.num_leaves or 31
            if p.num_leaves < 2:
                raise ValueError("num_leaves must be >= 2")
        else:
            raise ValueError(f"growth must be leaf|level|auto, got {p.growth!r}")
        if p.boosting_type == "rf" and p.bagging_freq == 0:
            p.bagging_freq, p.bagging_fraction = 1, min(p.bagging_fraction, 0.632)
        if not 4 <= p.num_grad_quant_bins <= 128:
            raise ValueError("num_grad_quant_bins must be in [4, 128] "
                             f"(int8 operand lanes), got {p.num_grad_quant_bins}")
        return p

    @property
    def depth_bound(self) -> int:
        """Static walk-iteration bound for trees grown under these params
        (call on a resolved instance)."""
        if self.growth == "level":
            return max(1, self.max_depth)
        cap = self.max_depth if self.max_depth > 0 else (self.num_leaves or 31) - 1
        return max(1, min(cap, (self.num_leaves or 31) - 1))


# ---------------------------------------------------------------------------
# objectives: (scores, y, w) -> grad, hess     [all jitted, (n,K) scores]
# ---------------------------------------------------------------------------

def make_objective(params: GBDTParams) -> Callable:
    import jax.numpy as jnp
    obj, K = params.objective, params.num_class
    sig, alpha = params.sigmoid, params.alpha

    def binary(scores, y, w):
        p = 1.0 / (1.0 + jnp.exp(-sig * scores[:, 0]))
        g = sig * (p - y)
        h = jnp.maximum(sig * sig * p * (1.0 - p), 1e-16)
        return (g * w)[:, None], (h * w)[:, None]

    def multiclass(scores, y, w):
        z = scores - scores.max(axis=1, keepdims=True)
        e = jnp.exp(z)
        p = e / e.sum(axis=1, keepdims=True)
        onehot = (y[:, None] == jnp.arange(K)[None, :]).astype(p.dtype)
        g = p - onehot
        h = jnp.maximum(2.0 * p * (1.0 - p), 1e-16)
        return g * w[:, None], h * w[:, None]

    def l2(scores, y, w):
        g = scores[:, 0] - y
        return (g * w)[:, None], (w * jnp.ones_like(g))[:, None]

    def l1(scores, y, w):
        g = jnp.sign(scores[:, 0] - y)
        return (g * w)[:, None], (w * jnp.ones_like(g))[:, None]

    def huber(scores, y, w):
        d = scores[:, 0] - y
        g = jnp.clip(d, -alpha, alpha)
        return (g * w)[:, None], (w * jnp.ones_like(g))[:, None]

    def quantile(scores, y, w):
        d = scores[:, 0] - y
        g = jnp.where(d >= 0, 1.0 - alpha, -alpha)
        return (g * w)[:, None], (w * jnp.ones_like(g))[:, None]

    def poisson(scores, y, w):
        # log link: raw score s models log(mean); nll grad = exp(s) - y
        mu = jnp.exp(jnp.clip(scores[:, 0], -30.0, 30.0))
        g = mu - y
        h = jnp.maximum(mu, 1e-16)
        return (g * w)[:, None], (h * w)[:, None]

    rho = params.tweedie_variance_power

    def tweedie(scores, y, w):
        # compound-Poisson deviance with log link, variance power rho in
        # (1, 2): grad = -y*e^{(1-rho)s} + e^{(2-rho)s}
        sarr = jnp.clip(scores[:, 0], -30.0, 30.0)
        a = jnp.exp((1.0 - rho) * sarr)
        b = jnp.exp((2.0 - rho) * sarr)
        g = -y * a + b
        h = jnp.maximum(-(1.0 - rho) * y * a + (2.0 - rho) * b, 1e-16)
        return (g * w)[:, None], (h * w)[:, None]

    def gamma(scores, y, w):
        # gamma nll with log link: grad = 1 - y*e^{-s}, hess = y*e^{-s}
        e = jnp.exp(-jnp.clip(scores[:, 0], -30.0, 30.0))
        g = 1.0 - y * e
        h = jnp.maximum(y * e, 1e-16)
        return (g * w)[:, None], (h * w)[:, None]

    table = {"binary": binary, "multiclass": multiclass, "regression": l2,
             "regression_l1": l1, "huber": huber, "quantile": quantile,
             "poisson": poisson, "tweedie": tweedie, "gamma": gamma}
    if obj not in table and obj != "lambdarank":
        raise ValueError(f"unknown objective {obj!r}")
    return table.get(obj)


def make_lambdarank_grad_fn(y: np.ndarray, group_ptr: np.ndarray,
                            sigmoid: float = 1.0):
    """Device-resident LambdaRank gradients with |ΔNDCG| weighting.

    Padded-group tensorization: groups packed to (Q, Gmax) so the pairwise
    (Q, Gmax, Gmax) lambda computation is one jitted einsum-like pass —
    the XLA-friendly reshape of the reference's per-query C++ loops.

    The pack/unpack is INDEX GATHERS built once on host: the returned
    ``fn(scores_dev) -> (g, h)`` stays entirely on device, so the boosting
    loop pays zero host round trips per iteration (round-1 weak item 5:
    the old path re-packed numpy groups every iteration).
    """
    import jax
    import jax.numpy as jnp

    n = len(y)
    q = len(group_ptr) - 1
    gmax = int(max(group_ptr[i + 1] - group_ptr[i] for i in range(q)))
    pack_idx = np.zeros((q, gmax), np.int32)   # slot -> row (0 on padding)
    M_np = np.zeros((q, gmax), np.float32)
    row_q = np.zeros(n, np.int32)              # row -> (group, slot)
    row_slot = np.zeros(n, np.int32)
    covered_np = np.zeros(n, bool)             # rows outside group_ptr get 0
    for i in range(q):
        a, b = group_ptr[i], group_ptr[i + 1]
        pack_idx[i, : b - a] = np.arange(a, b)
        M_np[i, : b - a] = 1.0
        row_q[a:b] = i
        row_slot[a:b] = np.arange(b - a)
        covered_np[a:b] = True
    Y = jnp.asarray(np.asarray(y, np.float32)[pack_idx] * M_np)
    M = jnp.asarray(M_np)
    pack = jnp.asarray(pack_idx)
    rq, rs = jnp.asarray(row_q), jnp.asarray(row_slot)
    covered = jnp.asarray(covered_np)

    def fn(scores):
        S = scores[:, 0][pack] * M
        gain = (2.0 ** Y - 1.0) * M
        order = jnp.argsort(-jnp.where(M > 0, S, -jnp.inf), axis=1)
        ranks = jnp.argsort(order, axis=1).astype(jnp.float32)  # 0-based rank
        disc = 1.0 / jnp.log2(ranks + 2.0)
        ideal_gain = -jnp.sort(-gain, axis=1)
        ideal_disc = 1.0 / jnp.log2(jnp.arange(gmax, dtype=jnp.float32) + 2.0)
        idcg = jnp.sum(ideal_gain * ideal_disc, axis=1, keepdims=True)
        idcg = jnp.maximum(idcg, 1e-9)
        sdiff = S[:, :, None] - S[:, None, :]
        rho = 1.0 / (1.0 + jnp.exp(sigmoid * sdiff))      # P(j beats i)
        better = (Y[:, :, None] > Y[:, None, :]) & (M[:, :, None] > 0) & (M[:, None, :] > 0)
        delta_ndcg = jnp.abs(
            (gain[:, :, None] - gain[:, None, :]) *
            (disc[:, :, None] - disc[:, None, :])) / idcg[:, :, None]
        lam_ij = jnp.where(better, -sigmoid * rho * delta_ndcg, 0.0)
        hess_ij = jnp.where(better, sigmoid * sigmoid * rho * (1 - rho) * delta_ndcg, 0.0)
        G = jnp.sum(lam_ij, axis=2) - jnp.sum(lam_ij, axis=1)
        H = jnp.maximum(jnp.sum(hess_ij, axis=2) + jnp.sum(hess_ij, axis=1), 1e-16)
        # unpack by gather: row -> its (group, slot) cell; rows not covered
        # by group_ptr stay inert (g=0, h~0), matching the scatter unpack
        g_row = jnp.where(covered, G[rq, rs], 0.0)
        h_row = jnp.where(covered, H[rq, rs], 1e-16)
        return g_row[:, None], h_row[:, None]

    return instrumented_jit(fn, name="lightgbm.lambdarank_grads")


def lambdarank_grads(scores: np.ndarray, y: np.ndarray, group_ptr: np.ndarray,
                     sigmoid: float = 1.0, trunc: int = 30) -> Tuple[np.ndarray, np.ndarray]:
    """One-shot host-facing wrapper over ``make_lambdarank_grad_fn``."""
    import jax.numpy as jnp
    fn = make_lambdarank_grad_fn(y, group_ptr, sigmoid)
    g, h = fn(jnp.asarray(np.asarray(scores, np.float32).reshape(len(y), -1)))
    return np.asarray(g), np.asarray(h)


# ---------------------------------------------------------------------------
# jit caches: reusing compiled programs across train() calls saves the ~60-90s
# XLA compile on every fit (closures would otherwise defeat jit's cache)
# ---------------------------------------------------------------------------

_JIT_CACHE: Dict[tuple, object] = {}


def _params_sig(p: "GBDTParams") -> tuple:
    return (p.growth, p.num_leaves, p.max_depth, p.max_bin, p.objective,
            p.num_class, p.boosting_type,
            p.learning_rate, p.lambda_l1, p.lambda_l2, p.min_data_in_leaf,
            p.min_sum_hessian_in_leaf, p.min_gain_to_split, p.max_delta_step,
            p.sigmoid, p.alpha, p.tweedie_variance_power,
            p.top_rate, p.other_rate, p.feature_fraction,
            p.bagging_fraction, p.bagging_freq,
            tuple(p.categorical_features or ()), tuple(p.cat_subset or ()),
            p.max_cat_to_onehot, p.cat_smooth, p.cat_l2, p.max_cat_threshold,
            p.voting_k, p.use_quantized_grad, p.num_grad_quant_bins,
            # the quantizer's stochastic-rounding seed is baked into every
            # traced grower closure — without it in the key a second train()
            # with a different seed would silently reuse the old noise
            p.seed)


def _cached(key, builder):
    fn = _JIT_CACHE.get(key)
    if fn is None:
        fn = builder()
        _JIT_CACHE[key] = fn
    return fn


# ---------------------------------------------------------------------------
# tree grower
# ---------------------------------------------------------------------------

def _check_quant_psum_bound(use_quant: bool, quant_bins: int,
                            axis_name, psum_row_bound) -> None:
    """Sharded overflow guard: the quantized builders check int32 overflow
    against their LOCAL shard's rows, but the psum accumulates GLOBAL sums
    — a root-level cell can hold up to the total row count.  The grower
    knows the static global bound, so the check belongs here (review
    finding: 8 shards x 20M rows each passes every local guard yet wraps
    the hessian lane after the allreduce)."""
    if not use_quant or axis_name is None or psum_row_bound is None:
        return
    qh_cap = max(1, quant_bins - 1)
    if int(psum_row_bound) * qh_cap >= (1 << 31):
        raise ValueError(
            "quantized histograms overflow int32 after the cross-shard "
            f"psum above {(1 << 31) // qh_cap} total rows at "
            f"{quant_bins} quantization bins — lower num_grad_quant_bins "
            "or disable use_quantized_grad")

def _use_fused_frontier(use_quant: bool, axis_name, has_cat: bool,
                        backend: str, num_bins: int,
                        quant_bins: int) -> bool:
    """ONE eligibility predicate for the fused Pallas frontier (ISSUE 8),
    shared by both growers so they can never silently disagree on when the
    kernel engages.  Single-shard quantized numerical-split path only —
    sharded gains must run on the POST-psum global histogram, voting needs
    the per-feature local gain table, and categorical candidates need the
    sorted-subset scan; those paths keep the XLA split_gains (the pallas
    BUILDER still serves them through ``build_quantized``'s dispatcher).
    Resolved at trace time; ``train()`` keys its jit caches on every
    histogram env knob."""
    from ..ops import histogram as hist_ops
    from ..ops import pallas_histogram as pl_hist
    return (use_quant and axis_name is None and not has_cat
            and hist_ops.resolve_quantized_backend(backend) == "pallas"
            and pl_hist.pallas_supported(num_bins, quant_bins))


class _CatTools:
    """Categorical split machinery shared by both growers: static masks, the
    cat_l2-regularised score, ratio-sorted prefix stats (the many-vs-many
    candidate scan) and winner membership reconstruction.

    Reference: LightGBM's native sorted-subset categorical search, wired
    from ``LightGBMBase.scala:163-200`` (categoricalSlotIndexes ->
    ``categorical_feature`` engine param)."""

    def __init__(self, params: "GBDTParams", F: int, B: int):
        import jax.numpy as jnp
        self.jnp = jnp
        self.B = B
        self.cat_np = np.zeros((F,), bool)
        if params.categorical_features:
            self.cat_np[list(params.categorical_features)] = True
        self.sub_np = np.zeros((F,), bool)
        if params.cat_subset:
            self.sub_np[list(params.cat_subset)] = True
        self.has_cat = bool(self.cat_np.any())
        self.has_subset = bool(self.sub_np.any())
        self.cat_smooth = params.cat_smooth
        self.cat_l2 = params.cat_l2
        self.maxcat = float(params.max_cat_threshold)
        self.l1, self.l2 = params.lambda_l1, params.lambda_l2
        self.seenable_np = np.arange(B) != B - 1  # B-1 = NaN/overflow bin

    def leaf_score_cat(self, G, H):
        # subset splits score under extra regularisation (LightGBM cat_l2):
        # high-cardinality categoricals overfit the gain otherwise
        jnp = self.jnp
        t = jnp.sign(G) * jnp.maximum(jnp.abs(G) - self.l1, 0.0)
        return t ** 2 / (H + self.l2 + self.cat_l2)

    def sorted_prefix(self, hist_d):
        """Sorted-subset candidate stats for (..., B, 3) histograms: sort
        bins ascending by grad/hess ratio (cat_smooth in the denominator,
        LightGBM's categorical ordering); unseen bins and the NaN catch-all
        sort last (+inf), so the cumsum at position k is the stats of the
        BEST k+1 seen categories — the many-vs-many candidate set.  Returns
        (prefix_cumsum, sort_order, valid_prefix_mask)."""
        jnp, B = self.jnp, self.B
        seen = (hist_d[..., 2] > 0) & jnp.asarray(self.seenable_np)
        ratio = jnp.where(seen,
                          hist_d[..., 0] / (hist_d[..., 1] + self.cat_smooth),
                          jnp.inf)
        order = jnp.argsort(ratio, axis=-1)
        subcum = jnp.cumsum(
            jnp.take_along_axis(hist_d, order[..., None], axis=-2), axis=-2)
        nseen = seen.sum(axis=-1, keepdims=True).astype(jnp.float32)
        k1 = (jnp.arange(B) + 1).astype(jnp.float32)
        # a prefix must leave >=1 seen category right, and the smaller side
        # stays under max_cat_threshold (LightGBM's subset-size cap)
        sub_ok = (k1 < nseen) & ((k1 <= self.maxcat)
                                 | (nseen - k1 <= self.maxcat))
        return subcum, order, sub_ok

    def winner_member(self, win_hist, bf, bb):
        """(nodes, B) category membership of each node's winning split:
        subset winners take the first bb+1 bins of the ratio sort; one-vs-rest
        winners take the single code bb.  Only read where the winning feature
        is categorical."""
        jnp, B = self.jnp, self.B
        onehot_m = jnp.arange(B)[None, :] == bb[:, None]
        if not self.has_subset:
            return onehot_m
        _, ordw, _ = self.sorted_prefix(win_hist)
        msorted = jnp.arange(B)[None, :] <= bb[:, None]
        nodes = win_hist.shape[0]
        member_sub = jnp.zeros((nodes, B), bool).at[
            jnp.arange(nodes)[:, None], ordw].set(msorted)
        return jnp.where(jnp.asarray(self.sub_np)[bf][:, None], member_sub,
                         onehot_m)


def make_tree_grower(max_depth: int, num_features: int, num_bins: int,
                     params: GBDTParams, axis_name: str = None,
                     backend: str = "auto", psum_row_bound: int = None):
    """Level-wise grower.  Returns grow(binned, grad, hess, hist_mask,
    feat_mask, edges) -> (left_child, right_child, split_feature, threshold,
    threshold_bin, split_gain, internal_value, internal_count, leaf_value,
    leaf_count, leaf_of_row).  With `axis_name`, the function is
    meant to run inside shard_map over row shards: local histograms are
    psum'd over that mesh axis (the LGBM_NetworkInit ring replacement) and
    all split decisions replicate deterministically across shards.
    ``psum_row_bound`` (sharded only) is the static GLOBAL row count, which
    lets the quantized path pack grad/hess lanes into one int32 channel for
    the allreduce when the bound allows (``collectives.histogram_psum``)."""
    import jax
    import jax.numpy as jnp
    from ..models.gbdt import perfect_tree_children
    from ..ops import histogram as hist_ops
    from ..ops import pallas_histogram as pl_hist
    from ..parallel.collectives import histogram_psum

    use_quant = bool(params.use_quantized_grad)
    quant_bins = params.num_grad_quant_bins
    _check_quant_psum_bound(use_quant, quant_bins, axis_name, psum_row_bound)

    D, F, B = max_depth, num_features, num_bins
    I = 2 ** D - 1     # internal nodes
    L = 2 ** D         # leaves
    ct = _CatTools(params, F, B)
    # fused Pallas frontier (ISSUE 8): build + sibling subtraction +
    # split-gain scan in one VMEM-resident kernel (eligibility:
    # _use_fused_frontier)
    use_fused = _use_fused_frontier(use_quant, axis_name, ct.has_cat,
                                    backend, B, quant_bins)
    cat_np, sub_np = ct.cat_np, ct.sub_np
    has_cat, has_subset = ct.has_cat, ct.has_subset
    sorted_prefix, winner_member = ct.sorted_prefix, ct.winner_member
    leaf_score_cat = ct.leaf_score_cat
    l1, l2 = params.lambda_l1, params.lambda_l2
    min_data = float(params.min_data_in_leaf)
    min_hess = params.min_sum_hessian_in_leaf
    min_gain = params.min_gain_to_split
    max_delta = params.max_delta_step

    def thresh(G):
        return jnp.sign(G) * jnp.maximum(jnp.abs(G) - l1, 0.0)

    def leaf_score(G, H):
        return thresh(G) ** 2 / (H + l2)

    def leaf_output(G, H):
        v = -thresh(G) / (H + l2)
        if max_delta > 0:
            v = jnp.clip(v, -max_delta, max_delta)
        return v

    def grow(binned, grad, hess, hist_mask, feat_mask, edges):
        n = binned.shape[0]
        if use_quant:
            # quantize ONCE per tree: every level's histogram is then an
            # exact integer function of the same per-row ints, so sibling
            # subtraction below never leaves integer space.  Sharded, the
            # rounding noise is keyed per GLOBAL row (elastic resume,
            # ISSUE 14): a row quantizes identically at any shard count,
            # which is what makes resume onto a re-sized mesh bit-exact.
            row_ids = hist_ops.global_row_ids(axis_name, n)
            qg, qh, g_scale, h_scale = hist_ops.quantize_gradients(
                grad, hess, quant_bins, seed=params.seed, axis_name=axis_name,
                row_ids=row_ids)

        def build_local(node_a, num_nodes, max_rows=None):
            if use_quant:
                return hist_ops.build_quantized(
                    binned, qg, qh, node_a, num_nodes, num_bins,
                    quant_bins=quant_bins, backend=backend,
                    max_rows=max_rows, node_rows_bound=max_rows)
            return hist_ops.build(binned, grad, hess, node_a, num_nodes,
                                  num_bins, backend=backend,
                                  max_rows=max_rows)

        def hist(node_a, num_nodes, max_rows=None):
            out = build_local(node_a, num_nodes, max_rows=max_rows)
            if axis_name is not None:
                out = histogram_psum(out, axis_name,
                                     row_bound=psum_row_bound,
                                     quant_bins=quant_bins) \
                    if use_quant else jax.lax.psum(out, axis_name)
            return out

        def dehist(h_):
            # rescale integer sums to (grad, hess, count) floats — applied
            # only where gains/leaf stats are computed, never to the
            # subtraction chain
            if not use_quant:
                return h_
            return hist_ops.dequantize_histogram(h_, g_scale, h_scale)

        node = jnp.zeros((n,), jnp.int32)          # level-local node, all rows
        split_feature = jnp.full((I,), -1, jnp.int32)
        threshold_bin = jnp.zeros((I,), jnp.int32)
        threshold = jnp.zeros((I,), jnp.float32)
        split_gain = jnp.zeros((I,), jnp.float32)
        internal_value = jnp.zeros((I,), jnp.float32)
        internal_count = jnp.zeros((I,), jnp.float32)
        # per-internal-node category membership of the LEFT set (read only
        # where the split feature is categorical); 1-wide dummy otherwise
        cat_member = jnp.zeros((I, B if has_cat else 1), bool)

        cat_b = jnp.asarray(cat_np)
        sub_b = jnp.asarray(sub_np)
        edge_ok2 = jnp.concatenate(
            [jnp.isfinite(edges), jnp.zeros((F, 1), bool)], axis=1)
        edge_finite = edge_ok2[None, :, :]
        if has_cat:
            # every bin of a categorical feature is a candidate code EXCEPT
            # the last: BinMapper reserves bin max_bin-1 for NaN/overflow,
            # and a split on it would route missing rows left at train but
            # right at predict (x != code with NaN -> right)
            cat_cand = cat_b[None, :, None] & \
                (jnp.arange(B) != B - 1)[None, None, :]
            edge_finite = edge_finite | cat_cand
        def split_gains(hist_d, fmask2, edge3, catm2, subm2):
            """(nodes, Fs, B, 3) histograms -> (gain, left-stat pick, node
            totals).  LEFT-child stats: numerical split at t takes bins <= t
            (the cumsum); categorical one-vs-rest at code c takes bin c alone
            (the histogram itself); sorted-subset candidate k takes the best
            k+1 ratio-sorted categories (the prefix cumsum).  ``fmask2`` /
            ``catm2`` / ``subm2`` broadcast over (nodes, Fs); ``edge3`` over
            (nodes, Fs, B)."""
            cum = jnp.cumsum(hist_d, axis=2)
            tot = cum[:, :1, -1, :]                 # (nodes,1,3) totals
            left3 = jnp.where(catm2[:, :, None, None], hist_d, cum) \
                if has_cat else cum
            if has_subset:
                subcum, _, sub_ok = sorted_prefix(hist_d)
                left3 = jnp.where(subm2[:, :, None, None], subcum, left3)
                edge3 = jnp.where(subm2[:, :, None], sub_ok, edge3)
            GL, HL, CL = left3[..., 0], left3[..., 1], left3[..., 2]
            Gp, Hp, Cp = tot[..., 0], tot[..., 1], tot[..., 2]
            GR, HR, CR = (Gp[:, :, None] - GL, Hp[:, :, None] - HL,
                          Cp[:, :, None] - CL)
            gain = (leaf_score(GL, HL) + leaf_score(GR, HR)
                    - leaf_score(Gp, Hp)[:, :, None])
            if has_subset:
                gain_cat = (leaf_score_cat(GL, HL) + leaf_score_cat(GR, HR)
                            - leaf_score_cat(Gp, Hp)[:, :, None])
                gain = jnp.where(subm2[:, :, None], gain_cat, gain)
            # split at bin t => left: bins<=t, right: bins>t; needs a finite
            # edge (last bin and inf-padded pseudo-bins can't split)
            valid = ((CL >= min_data) & (CR >= min_data)
                     & (HL >= min_hess) & (HR >= min_hess)
                     & fmask2[:, :, None] & edge3)
            gain = jnp.where(valid, gain, -jnp.inf)
            pick = jnp.stack([GL, HL, CL], axis=-1)  # (nodes,Fs,B,3)
            return gain, pick, (Gp[:, 0], Hp[:, 0], Cp[:, 0])

        voting_k = params.voting_k
        # voting engages whenever it's requested and meaningful (F > k);
        # when 2k >= F the vote selects every feature — zero comm saving but
        # identical results, which the equality test exploits
        use_voting = axis_name is not None and 0 < voting_k < F
        prev_hist = None
        best_stats = None
        small_left = None      # set per level; read from the NEXT level on
        for d in range(D):
            nodes_d = 2 ** d
            off = nodes_d - 1                       # BFS offset of this level
            if d > 0 and not use_voting:
                # LightGBM's SMALLER-child rule (by the previous level's
                # split counts): rebuild only each parent's smaller child,
                # sibling = parent - small.  One definition serving both
                # the fused-kernel and XLA frontier paths below.
                is_left = node % 2 == 0
                in_small = is_left == small_left[node // 2]
                small_node = jnp.where(hist_mask & in_small, node // 2, -1)
            fused_d = False        # set by the fused branch when it engages
            if use_voting:
                # voting-parallel (reference voting_parallel + topK): each
                # shard ranks features by LOCAL gain, shards vote, and only
                # the global top-2k features' histograms cross the mesh —
                # O(k*B) comm instead of O(F*B).  Sibling subtraction stays
                # valid on the PRE-psum local histograms (local_right =
                # local_parent - local_left).
                if d == 0:
                    local = build_local(jnp.where(hist_mask, node, -1), 1)
                else:
                    left_node = jnp.where(hist_mask & (node % 2 == 0),
                                          node // 2, -1)
                    left_local = build_local(left_node, nodes_d // 2)
                    local = jnp.stack([left_local, prev_hist - left_local],
                                      axis=1).reshape(nodes_d, F, B, 3)
                prev_hist = local
                gain_l, _, _ = split_gains(dehist(local), feat_mask[None, :],
                                           edge_finite, cat_b[None, :],
                                           sub_b[None, :])
                per_feat = gain_l.max(axis=2)        # (nodes, F) local best
                top_gain, top_local = jax.lax.top_k(per_feat, voting_k)
                # a shard with fewer than k locally-valid candidates must not
                # cast spurious ballots for the tie-broken low-index features
                ballot = (top_gain > -jnp.inf).astype(jnp.float32)
                votes = jnp.zeros((nodes_d, F)).at[
                    jnp.arange(nodes_d)[:, None], top_local].add(ballot)
                votes = jax.lax.psum(votes, axis_name)
                k2 = min(2 * voting_k, F)
                _, sel = jax.lax.top_k(votes, k2)    # (nodes, k2) global pick
                sel_hist = jnp.take_along_axis(
                    local, sel[:, :, None, None], axis=1)
                sel_hist = histogram_psum(sel_hist, axis_name,
                                          row_bound=psum_row_bound,
                                          quant_bins=quant_bins) \
                    if use_quant else jax.lax.psum(sel_hist, axis_name)
                sel_hist = dehist(sel_hist)
                edge3 = jnp.take_along_axis(
                    jnp.broadcast_to(edge_finite, (nodes_d, F, B)),
                    sel[:, :, None], axis=1)
                gain, pick, (Gp0, Hp0, Cp0) = split_gains(
                    sel_hist, feat_mask[sel], edge3, cat_b[sel], sub_b[sel])
                hist_for_win = sel_hist
                Fs = k2
            elif use_fused and max(1, nodes_d // 2) <= \
                    pl_hist.FUSED_MAX_NODES:
                # fused Pallas frontier: the smaller-child build, the exact
                # integer sibling subtraction AND the split-gain scan run
                # in one VMEM-resident kernel; only the assembled child
                # histograms (the next level's parent) and the per-node
                # best-split record reach HBM.  Static per-level gate:
                # past FUSED_MAX_NODES frontier parents the kernel's
                # VMEM-resident blocks outgrow the tile-sizing budget, so
                # deeper levels take the XLA branch below (bit-exact
                # histograms; gains differ only by f32 cumsum rounding)
                fused_d = True
                if d == 0:
                    hist_d, fused_best = pl_hist.fused_frontier(
                        binned, qg, qh, jnp.where(hist_mask, node, -1), 1,
                        B, g_scale, h_scale, feat_mask, edge_ok2,
                        quant_bins=quant_bins, l1=l1, l2=l2,
                        min_data=min_data, min_hess=min_hess)
                else:
                    hist_d, fused_best = pl_hist.fused_frontier(
                        binned, qg, qh, small_node, nodes_d // 2, B,
                        g_scale, h_scale, feat_mask, edge_ok2,
                        quant_bins=quant_bins, l1=l1, l2=l2,
                        min_data=min_data, min_hess=min_hess,
                        parent_hist=prev_hist, small_left=small_left,
                        node_rows_bound=n // 2 + nodes_d)
                prev_hist = hist_d
                best_gain, bf, bb, bsel, tot3f = fused_best
                Gp0, Hp0, Cp0 = tot3f[:, 0], tot3f[:, 1], tot3f[:, 2]
            else:
                if d == 0:
                    hist_d = hist(jnp.where(hist_mask, node, -1), 1)
                else:
                    # smaller-child scatter (small_node above): at most
                    # floor(n/2) rows are ever scattered, which — single-
                    # shard — is a STATIC bound that truncates the matmul
                    # backend's block scan to half the blocks (sharded: a
                    # shard's rows may concentrate in globally smaller
                    # children, so no bound is claimed there).
                    cap = None if axis_name is not None else n // 2 + nodes_d
                    hist_small = hist(small_node, nodes_d // 2, max_rows=cap)
                    hist_sib = prev_hist - hist_small
                    sl4 = small_left[:, None, None, None]
                    hist_d = jnp.stack(
                        [jnp.where(sl4, hist_small, hist_sib),
                         jnp.where(sl4, hist_sib, hist_small)], axis=1) \
                        .reshape(nodes_d, F, B, 3)
                prev_hist = hist_d
                gain, pick, (Gp0, Hp0, Cp0) = split_gains(
                    dehist(hist_d), feat_mask[None, :], edge_finite,
                    cat_b[None, :], sub_b[None, :])
                hist_for_win = dehist(hist_d)
                sel = None
                Fs = F

            if not fused_d:
                flat = gain.reshape(nodes_d, Fs * B)
                best = jnp.argmax(flat, axis=1)
                best_gain = jnp.take_along_axis(flat, best[:, None],
                                                axis=1)[:, 0]
                bf_local = (best // B).astype(jnp.int32)
                bb = (best % B).astype(jnp.int32)
                bf = sel[jnp.arange(nodes_d), bf_local] \
                    if sel is not None else bf_local
                bsel = pick[jnp.arange(nodes_d), bf_local, bb, :]  # left
            do_split = best_gain > min_gain

            idx = off + jnp.arange(nodes_d)
            if has_cat:
                member = winner_member(
                    hist_for_win[jnp.arange(nodes_d), bf_local], bf, bb)
                cat_member = cat_member.at[idx].set(
                    member & do_split[:, None] & cat_b[bf][:, None])
            split_feature = split_feature.at[idx].set(jnp.where(do_split, bf, -1))
            threshold_bin = threshold_bin.at[idx].set(bb)
            thr_raw = edges[bf, jnp.clip(bb, 0, B - 2)]
            if has_cat:  # categorical: the raw threshold IS the category code
                thr_raw = jnp.where(cat_b[bf], bb.astype(jnp.float32), thr_raw)
            threshold = threshold.at[idx].set(thr_raw)
            split_gain = split_gain.at[idx].set(jnp.where(do_split, best_gain, 0.0))
            internal_value = internal_value.at[idx].set(leaf_output(Gp0, Hp0))
            internal_count = internal_count.at[idx].set(Cp0)

            # left/right child stats at the chosen split -> leaf values at the
            # last level come straight from here (no extra leaf pass)
            tot3 = jnp.stack([Gp0, Hp0, Cp0], axis=-1)
            left_stats = jnp.where(do_split[:, None], bsel, tot3)
            right_stats = tot3 - left_stats
            best_stats = (left_stats, right_stats, do_split, tot3)
            # the next level scatters only each parent's smaller child
            # (unsplit parents: right is empty -> small, contributing 0 rows)
            small_left = left_stats[:, 2] <= right_stats[:, 2]

            # route all rows (bagged-out rows too: they need leaf ids for scores)
            f_of_row = bf[node]
            t_of_row = bb[node]
            s_of_row = do_split[node]
            row_bin = binned[jnp.arange(n), jnp.maximum(f_of_row, 0)].astype(jnp.int32)
            if has_cat:
                memb_row = member[node, row_bin]
                right_dec = jnp.where(cat_b[jnp.maximum(f_of_row, 0)],
                                      ~memb_row, row_bin > t_of_row)
            else:
                right_dec = row_bin > t_of_row
            go_right = s_of_row & right_dec
            node = 2 * node + go_right.astype(jnp.int32)

        # leaves: children of the last level's nodes
        left_stats, right_stats, do_split, tot3 = best_stats
        lv = jnp.stack([leaf_output(left_stats[:, 0], left_stats[:, 1]),
                        leaf_output(right_stats[:, 0], right_stats[:, 1])],
                       axis=1).reshape(L)
        lc = jnp.stack([left_stats[:, 2], right_stats[:, 2]], axis=1).reshape(L)
        leaf_value = jnp.where(lc > 0, lv, 0.0)
        return (lc_const, rc_const, split_feature, threshold, threshold_bin,
                split_gain, internal_value, internal_count, leaf_value, lc,
                cat_member, node)

    lc_np, rc_np = perfect_tree_children(D)
    lc_const = jnp.asarray(lc_np)
    rc_const = jnp.asarray(rc_np)
    return grow

def leafwise_store_dtype(n_bound, use_quant: bool, quant_bins: int,
                         enabled: bool = True):
    """Storage dtype for the leaf-wise grower's per-leaf histogram carry
    (the ``(L, F, B, 3)`` buffer sibling subtraction reads from).

    Quantized sums are bounded by the STATIC row bound: every cell holds at
    most ``n_bound * (quant_bins - 1)`` (hess lane — the widest; ``|qg|``
    sums and counts are smaller), so when that fits int16 the stored buffer
    halves with zero information loss — the arithmetic (build, psum,
    subtraction) stays int32 and only the carry narrows.  This is exactly
    the regime out-of-core tiling creates: small per-tile row bounds make
    the stored histograms the dominant resident tensor, and 2-bit gradients
    (``num_grad_quant_bins=4``) stretch the int16 window to ~10.9k rows.
    ``n_bound=None`` (sharded without a declared global bound) and float
    mode keep the wide dtypes.  ``MMLSPARK_TPU_HIST_STORE16=0`` is the
    operational escape hatch (read at trace time, keyed into the jit
    caches via ``_resolve_hist_backend``).
    """
    import jax.numpy as jnp
    if not use_quant:
        return jnp.float32
    qh_cap = max(1, quant_bins - 1)
    if enabled and n_bound is not None and int(n_bound) * qh_cap < (1 << 15):
        return jnp.int16
    return jnp.int32


def _store16_enabled() -> bool:
    import os
    raw = os.environ.get("MMLSPARK_TPU_HIST_STORE16", "").strip().lower()
    return raw not in ("0", "false", "off", "no")


def make_leafwise_grower(num_leaves: int, depth_cap: int, num_features: int,
                         num_bins: int, params: GBDTParams,
                         axis_name: str = None, backend: str = "auto",
                         psum_row_bound: int = None):
    """Leaf-wise (best-first) grower — LightGBM's defining growth algorithm
    (reference exposes ``numLeaves`` default 31, ``LightGBMParams.scala:331``;
    the native engine grows by global best gain).

    One tree = ``lax.scan`` over ``num_leaves - 1`` split steps.  Per step:
    pick the live leaf with the global best stored gain, split it (left
    child keeps the leaf slot, right child takes slot ``step + 1`` —
    LightGBM's own leaf numbering), rebuild only the left child's histogram
    with one masked pass and derive the sibling by subtraction, then score
    both children's best candidate splits for later steps.  All state is
    fixed-shape; a step whose best gain fails ``min_gain_to_split`` becomes
    a no-op (every later step no-ops too, since the best gain is global).

    ``depth_cap`` > 0 forbids splits at that depth (LightGBM ``maxDepth``
    with leaf-wise growth).  With ``axis_name`` the histogram passes psum
    over the mesh axis; ``voting_k`` engages per-step feature voting
    (reference voting_parallel: only top-2k features' histograms cross the
    mesh).

    Returns grow(binned, grad, hess, hist_mask, feat_mask, edges) with the
    same output signature as the level-wise grower."""
    import jax
    import jax.numpy as jnp
    from ..ops import histogram as hist_ops
    from ..ops import pallas_histogram as pl_hist
    from ..parallel.collectives import histogram_psum

    use_quant = bool(params.use_quantized_grad)
    quant_bins = params.num_grad_quant_bins
    _check_quant_psum_bound(use_quant, quant_bins, axis_name, psum_row_bound)
    store16_ok = _store16_enabled()   # read OUTSIDE traced code; train()
    #                                   keys its jit caches on the env knob
    L, M, F, B = num_leaves, num_leaves - 1, num_features, num_bins
    ct = _CatTools(params, F, B)
    # fused Pallas frontier (ISSUE 8): per split step the left-child
    # rebuild, the exact integer sibling subtraction against the stored
    # carry and BOTH children's split-gain scans run in one VMEM-resident
    # kernel (shared eligibility: _use_fused_frontier)
    use_fused = _use_fused_frontier(use_quant, axis_name, ct.has_cat,
                                    backend, B, quant_bins)
    cat_np, sub_np = ct.cat_np, ct.sub_np
    has_cat, has_subset = ct.has_cat, ct.has_subset
    l1, l2 = params.lambda_l1, params.lambda_l2
    min_data = float(params.min_data_in_leaf)
    min_hess = params.min_sum_hessian_in_leaf
    min_gain = params.min_gain_to_split
    max_delta = params.max_delta_step
    voting_k = params.voting_k
    use_voting = axis_name is not None and 0 < voting_k < F

    def thresh(G):
        return jnp.sign(G) * jnp.maximum(jnp.abs(G) - l1, 0.0)

    def leaf_score(G, H):
        return thresh(G) ** 2 / (H + l2)

    def leaf_output(G, H):
        v = -thresh(G) / (H + l2)
        if max_delta > 0:
            v = jnp.clip(v, -max_delta, max_delta)
        return v

    def grow(binned, grad, hess, hist_mask, feat_mask, edges):
        n = binned.shape[0]
        cat_b = jnp.asarray(cat_np)
        sub_b = jnp.asarray(sub_np)
        edge_ok = jnp.concatenate(
            [jnp.isfinite(edges), jnp.zeros((F, 1), bool)], axis=1)
        if has_cat:
            # bin max_bin-1 is the NaN/overflow catch-all; splitting on it
            # would route missing left at train but right at predict
            edge_ok = jnp.where(cat_b[:, None],
                                (jnp.arange(B) != B - 1)[None, :], edge_ok)

        if use_quant:
            # one quantization per tree — every per-leaf rebuild and every
            # sibling subtraction below runs on the same per-row integers.
            # Sharded: noise keyed per GLOBAL row (elastic resume, ISSUE
            # 14) so re-sized meshes quantize each row identically.
            row_ids = hist_ops.global_row_ids(axis_name, n)
            qg, qh, g_scale, h_scale = hist_ops.quantize_gradients(
                grad, hess, quant_bins, seed=params.seed, axis_name=axis_name,
                row_ids=row_ids)

        def local_hist(mask):
            if use_quant:
                return hist_ops.build_quantized(
                    binned, qg, qh, jnp.where(mask, 0, -1), 1, B,
                    quant_bins=quant_bins, backend=backend)[0]  # (F, B, 3)
            return hist_ops.build(binned, grad, hess,
                                  jnp.where(mask, 0, -1), 1, B,
                                  backend=backend)[0]          # (F, B, 3)

        def psum_hist(h_):
            return histogram_psum(h_, axis_name, row_bound=psum_row_bound,
                                  quant_bins=quant_bins) \
                if use_quant else jax.lax.psum(h_, axis_name)

        def dehist(h_):
            # integer sums -> (grad, hess, count) floats at gain time only
            if not use_quant:
                return h_
            return hist_ops.dequantize_histogram(h_, g_scale, h_scale)

        def candidate_tables(hist_f3, fmask, depth_ok):
            """(F, B) gains + left-child pick stats from one leaf's (psum'd)
            histogram.  Same split semantics as the level-wise grower:
            numerical split at bin t takes bins <= t left (the cumsum);
            categorical one-vs-rest at code c takes bin c alone;
            sorted-subset candidate k takes the best k+1 ratio-sorted
            categories (the prefix cumsum)."""
            cum = jnp.cumsum(hist_f3, axis=1)
            tot = cum[0, -1, :]                               # (3,)
            left3 = jnp.where(cat_b[:, None, None], hist_f3, cum) \
                if has_cat else cum
            sub_edge = None
            if has_subset:
                subcum, _, sub_ok = ct.sorted_prefix(hist_f3)
                left3 = jnp.where(sub_b[:, None, None], subcum, left3)
                sub_edge = sub_ok
            GL, HL, CL = left3[..., 0], left3[..., 1], left3[..., 2]
            GR, HR, CR = tot[0] - GL, tot[1] - HL, tot[2] - CL
            gain = (leaf_score(GL, HL) + leaf_score(GR, HR)
                    - leaf_score(tot[0], tot[1]))
            if has_subset:
                gain_cat = (ct.leaf_score_cat(GL, HL)
                            + ct.leaf_score_cat(GR, HR)
                            - ct.leaf_score_cat(tot[0], tot[1]))
                gain = jnp.where(sub_b[:, None], gain_cat, gain)
            valid = ((CL >= min_data) & (CR >= min_data)
                     & (HL >= min_hess) & (HR >= min_hess)
                     & fmask[:, None] & depth_ok)
            if has_subset:  # subset prefixes have their own validity mask
                valid = valid & jnp.where(sub_b[:, None], sub_edge, True)
            return jnp.where(valid, gain, -jnp.inf), left3, tot

        def leaf_member(win_hist_b3, bf, bb):
            """(B,) membership of one leaf's winning categorical split."""
            return ct.winner_member(win_hist_b3[None], bf[None],
                                    bb[None])[0]

        def leaf_best(hist_f3, fmask, depth_ok):
            """Best candidate split of one leaf: (gain, feat, bin,
            left-child (G,H,C), totals, member bitset).  Accepts raw (int
            in quantized mode) histograms and rescales here — gain math
            always runs on float sums."""
            hist_f3 = dehist(hist_f3)
            gain, left3, tot = candidate_tables(hist_f3, fmask, depth_ok)
            # edge_ok is sound for subset features too: their position-(B-1)
            # candidate (a prefix of all bins) is invalid regardless
            gain = jnp.where(edge_ok, gain, -jnp.inf)
            flat = gain.reshape(-1)
            best = jnp.argmax(flat)
            bf = (best // B).astype(jnp.int32)
            bb = (best % B).astype(jnp.int32)
            member = leaf_member(hist_f3[bf], bf, bb) if has_cat else None
            return flat[best], bf, bb, left3[bf, bb], tot, member

        def leaf_best_voting(hist_local_f3, fmask, depth_ok):
            """Voting-parallel per-leaf split finding: rank features by
            LOCAL gain, psum ballots, then psum only the global top-2k
            features' histogram slices (O(k*B) ICI traffic per step)."""
            gain_l, _, _ = candidate_tables(dehist(hist_local_f3), fmask,
                                            depth_ok)
            gain_l = jnp.where(edge_ok, gain_l, -jnp.inf)
            per_feat = gain_l.max(axis=1)                     # (F,)
            top_gain, top_idx = jax.lax.top_k(per_feat, voting_k)
            ballot = (top_gain > -jnp.inf).astype(jnp.float32)
            votes = jnp.zeros((F,)).at[top_idx].add(ballot)
            votes = jax.lax.psum(votes, axis_name)
            k2 = min(2 * voting_k, F)
            _, sel = jax.lax.top_k(votes, k2)                 # (k2,) features
            sel_hist = dehist(psum_hist(hist_local_f3[sel]))
            cum = jnp.cumsum(sel_hist, axis=1)
            tot = dehist(psum_hist(
                jnp.cumsum(hist_local_f3[:1], axis=1)[0, -1, :]))
            left3 = jnp.where(cat_b[sel][:, None, None], sel_hist, cum) \
                if has_cat else cum
            sub_edge = True
            if has_subset:
                subcum, _, sub_ok = ct.sorted_prefix(sel_hist)
                left3 = jnp.where(sub_b[sel][:, None, None], subcum, left3)
                sub_edge = jnp.where(sub_b[sel][:, None], sub_ok, True)
            GL, HL, CL = left3[..., 0], left3[..., 1], left3[..., 2]
            GR, HR, CR = tot[0] - GL, tot[1] - HL, tot[2] - CL
            gain = (leaf_score(GL, HL) + leaf_score(GR, HR)
                    - leaf_score(tot[0], tot[1]))
            if has_subset:
                gain_cat = (ct.leaf_score_cat(GL, HL)
                            + ct.leaf_score_cat(GR, HR)
                            - ct.leaf_score_cat(tot[0], tot[1]))
                gain = jnp.where(sub_b[sel][:, None], gain_cat, gain)
            valid = ((CL >= min_data) & (CR >= min_data)
                     & (HL >= min_hess) & (HR >= min_hess)
                     & fmask[sel][:, None] & depth_ok & edge_ok[sel]
                     & sub_edge)
            gain = jnp.where(valid, gain, -jnp.inf)
            flat = gain.reshape(-1)
            best = jnp.argmax(flat)
            bf = sel[(best // B)].astype(jnp.int32)
            bb = (best % B).astype(jnp.int32)
            # membership from the winner's GLOBAL (psum'd) histogram slice:
            # every shard reconstructs the identical bitset
            member = leaf_member(sel_hist[best // B], bf, bb) \
                if has_cat else None
            return flat[best], bf, bb, left3[best // B, bb], tot, member

        best_of = leaf_best_voting if use_voting else leaf_best

        def psum_maybe(x):
            # with voting, per-leaf stored histograms stay LOCAL (sibling
            # subtraction then remains exact on local stats); without it the
            # stored histograms are global
            if axis_name is not None and not use_voting:
                return psum_hist(x)
            return x

        def depth_ok_of(d):
            if depth_cap <= 0:
                return jnp.bool_(True)
            return d < depth_cap

        # ---- root
        leaf_of_row = jnp.zeros((n,), jnp.int32)
        if use_fused:
            h_root1, fb_root = pl_hist.fused_frontier(
                binned, qg, qh, jnp.where(hist_mask, 0, -1), 1, B,
                g_scale, h_scale, feat_mask, edge_ok,
                quant_bins=quant_bins, l1=l1, l2=l2, min_data=min_data,
                min_hess=min_hess, depth_ok=depth_ok_of(0))
            h_root = h_root1[0]
            g0, f0, b0 = fb_root[0][0], fb_root[1][0], fb_root[2][0]
            lp0, tot0, m0 = fb_root[3][0], fb_root[4][0], None
        else:
            h_root = psum_maybe(local_hist(hist_mask))
            g0, f0, b0, lp0, tot0, m0 = best_of(h_root, feat_mask,
                                                depth_ok_of(0))

        # stored-histogram carry dtype: int16 when the STATIC row bound
        # keeps every quantized cell under 15 bits (sums stay exact; the
        # arithmetic below is int32 and only the carry narrows).  The bound
        # is this shard's n when stored histograms are local (single-shard
        # or voting), the declared global psum bound when they are global.
        stored_bound = n if (axis_name is None or use_voting) \
            else psum_row_bound
        st_dtype = leafwise_store_dtype(stored_bound, use_quant, quant_bins,
                                        store16_ok) if use_quant \
            else jnp.float32

        carry0 = dict(
            leaf_of_row=leaf_of_row,
            lc_arr=jnp.full((M,), -1, jnp.int32),
            rc_arr=jnp.full((M,), -1, jnp.int32),
            sf=jnp.full((M,), -1, jnp.int32),
            th=jnp.zeros((M,), jnp.float32),
            tb=jnp.zeros((M,), jnp.int32),
            sg=jnp.zeros((M,), jnp.float32),
            iv=jnp.zeros((M,), jnp.float32),
            ic=jnp.zeros((M,), jnp.float32),
            hists=jnp.zeros((L, F, B, 3), st_dtype)
            .at[0].set(h_root.astype(st_dtype)),
            best_gain=jnp.full((L,), -jnp.inf).at[0].set(g0),
            best_feat=jnp.zeros((L,), jnp.int32).at[0].set(f0),
            best_bin=jnp.zeros((L,), jnp.int32).at[0].set(b0),
            best_left=jnp.zeros((L, 3)).at[0].set(lp0),
            leaf_tot=jnp.zeros((L, 3)).at[0].set(tot0),
            leaf_depth=jnp.zeros((L,), jnp.int32),
            created=jnp.zeros((L,), bool).at[0].set(True),
            # per-internal-node LEFT category set + each live leaf's best
            # candidate's membership (1-wide dummies without categoricals)
            cbs=jnp.zeros((M, B if has_cat else 1), bool),
            best_member=(jnp.zeros((L, B), bool).at[0].set(m0) if has_cat
                         else jnp.zeros((L, 1), bool)),
        )

        def step(c, s):
            j = jnp.argmax(c["best_gain"]).astype(jnp.int32)
            gmax = c["best_gain"][j]
            do = gmax > min_gain
            new_leaf = (s + 1).astype(jnp.int32)
            f, b = c["best_feat"][j], c["best_bin"][j]

            def set_if(arr, idx, val, cond, oob):
                # conditional in-place update: a failed condition redirects
                # the index out of bounds, which mode="drop" discards
                return arr.at[jnp.where(cond, idx, oob)].set(val, mode="drop")

            tot = c["leaf_tot"][j]
            thr_raw = edges[f, jnp.clip(b, 0, B - 2)]
            if has_cat:
                thr_raw = jnp.where(cat_b[f], b.astype(jnp.float32), thr_raw)

            c = dict(c)
            if has_cat:
                member_j = c["best_member"][j]               # (B,)
                c["cbs"] = set_if(c["cbs"], s, member_j & cat_b[f], do, M)
            c["sf"] = set_if(c["sf"], s, f, do, M)
            c["tb"] = set_if(c["tb"], s, b, do, M)
            c["th"] = set_if(c["th"], s, thr_raw, do, M)
            c["sg"] = set_if(c["sg"], s, gmax, do, M)
            c["iv"] = set_if(c["iv"], s, leaf_output(tot[0], tot[1]), do, M)
            c["ic"] = set_if(c["ic"], s, tot[2], do, M)

            # re-point the parent edge that led to leaf j at internal node s
            pn = c["leaf_parent"][j]
            side = c["leaf_side"][j]
            c["lc_arr"] = set_if(c["lc_arr"], pn, s,
                                 do & (pn >= 0) & (side == 0), M)
            c["rc_arr"] = set_if(c["rc_arr"], pn, s,
                                 do & (pn >= 0) & (side == 1), M)
            # node s's own children: left keeps slot j, right takes new_leaf
            c["lc_arr"] = set_if(c["lc_arr"], s, -(j + 1), do, M)
            c["rc_arr"] = set_if(c["rc_arr"], s, -(new_leaf + 1), do, M)
            c["leaf_parent"] = set_if(c["leaf_parent"], j, s, do, L)
            c["leaf_side"] = set_if(c["leaf_side"], j, 0, do, L)
            c["leaf_parent"] = set_if(c["leaf_parent"], new_leaf, s, do, L)
            c["leaf_side"] = set_if(c["leaf_side"], new_leaf, 1, do, L)
            c["created"] = set_if(c["created"], new_leaf, True, do, L)

            # route rows of leaf j
            in_j = c["leaf_of_row"] == j
            row_bin = binned[jnp.arange(n), jnp.maximum(f, 0)].astype(jnp.int32)
            if has_cat:
                right_dec = jnp.where(cat_b[jnp.maximum(f, 0)],
                                      ~member_j[row_bin], row_bin > b)
            else:
                right_dec = row_bin > b
            c["leaf_of_row"] = jnp.where(do & in_j & right_dec, new_leaf,
                                         c["leaf_of_row"])

            # child stats + histograms (left rebuilt, right by subtraction)
            left_stats = c["best_left"][j]
            right_stats = tot - left_stats
            c["leaf_tot"] = set_if(c["leaf_tot"], j, left_stats, do, L)
            c["leaf_tot"] = set_if(c["leaf_tot"], new_leaf, right_stats, do, L)
            d_new = c["leaf_depth"][j] + 1
            c["leaf_depth"] = set_if(c["leaf_depth"], j, d_new, do, L)
            c["leaf_depth"] = set_if(c["leaf_depth"], new_leaf, d_new, do, L)

            dok = depth_ok_of(d_new)
            if use_fused:
                # one fused kernel: left-child rebuild, exact integer
                # sibling subtraction against the stored carry (widened
                # from the int16 storage dtype — arithmetic stays int32),
                # and both children's split-gain scans
                pair, fb2 = pl_hist.fused_frontier(
                    binned, qg, qh,
                    jnp.where(hist_mask & (c["leaf_of_row"] == j), 0, -1),
                    1, B, g_scale, h_scale, feat_mask, edge_ok,
                    quant_bins=quant_bins, l1=l1, l2=l2,
                    min_data=min_data, min_hess=min_hess,
                    parent_hist=c["hists"][j].astype(jnp.int32)[None],
                    small_left=jnp.ones((1,), bool), depth_ok=dok)
                hl, hr = pair[0], pair[1]
                gl, fl, bl, lpl = fb2[0][0], fb2[1][0], fb2[2][0], fb2[3][0]
                gr, fr, br, lpr = fb2[0][1], fb2[1][1], fb2[2][1], fb2[3][1]
                ml = mr = None
            else:
                hl = local_hist(hist_mask & (c["leaf_of_row"] == j))
                if axis_name is not None and not use_voting:
                    hl = psum_hist(hl)
                # subtraction widens back to the build dtype: the int16
                # carry is storage-only, the arithmetic stays exact int32
                hr = c["hists"][j].astype(hl.dtype) - hl
                gl, fl, bl, lpl, _, ml = best_of(hl, feat_mask, dok)
                gr, fr, br, lpr, _, mr = best_of(hr, feat_mask, dok)
            c["hists"] = set_if(c["hists"], j, hl.astype(st_dtype), do, L)
            c["hists"] = set_if(c["hists"], new_leaf, hr.astype(st_dtype),
                                do, L)
            if has_cat:
                c["best_member"] = set_if(c["best_member"], j, ml, do, L)
                c["best_member"] = set_if(c["best_member"], new_leaf, mr,
                                          do, L)
            c["best_gain"] = set_if(c["best_gain"], j, gl, do, L)
            c["best_gain"] = set_if(c["best_gain"], new_leaf, gr, do, L)
            c["best_feat"] = set_if(c["best_feat"], j, fl, do, L)
            c["best_feat"] = set_if(c["best_feat"], new_leaf, fr, do, L)
            c["best_bin"] = set_if(c["best_bin"], j, bl, do, L)
            c["best_bin"] = set_if(c["best_bin"], new_leaf, br, do, L)
            c["best_left"] = set_if(c["best_left"], j, lpl, do, L)
            c["best_left"] = set_if(c["best_left"], new_leaf, lpr, do, L)
            return c, None

        carry0["leaf_parent"] = jnp.full((L,), -1, jnp.int32)
        carry0["leaf_side"] = jnp.zeros((L,), jnp.int32)
        c, _ = jax.lax.scan(step, carry0, jnp.arange(M, dtype=jnp.int32))

        leaf_value = jnp.where(c["created"],
                               leaf_output(c["leaf_tot"][:, 0],
                                           c["leaf_tot"][:, 1]), 0.0)
        leaf_count = jnp.where(c["created"], c["leaf_tot"][:, 2], 0.0)
        return (c["lc_arr"], c["rc_arr"], c["sf"], c["th"], c["tb"], c["sg"],
                c["iv"], c["ic"], leaf_value, leaf_count, c["cbs"],
                c["leaf_of_row"])

    return grow


# ---------------------------------------------------------------------------
# binned tree walk (for incremental valid scoring / DART drop replay)
# ---------------------------------------------------------------------------

def make_binned_walker(depth_bound: int,
                       categorical_features: Optional[Tuple[int, ...]] = None):
    """Binned-space pointer-chase over array-of-nodes trees (leaf slots
    encoded ``~leaf_id``; leaves self-loop so a static ``depth_bound``
    iteration count resolves every tree shape).  ``bitset`` (M, B) carries
    sorted-subset categorical membership (bin in set -> left); without it,
    categorical nodes fall back to one-vs-rest code equality."""
    import jax
    import jax.numpy as jnp
    D = max(1, depth_bound)
    cats = frozenset(categorical_features or ())

    def walk(binned, split_feature, threshold_bin, left_child, right_child,
             bitset=None):
        n = binned.shape[0]
        node = jnp.zeros((n,), jnp.int32)
        F = binned.shape[1]
        cat_b = jnp.asarray(np.isin(np.arange(F), list(cats))) if cats else None
        for _ in range(D):
            j = jnp.maximum(node, 0)
            f = split_feature[j]
            t = threshold_bin[j]
            row_bin = binned[jnp.arange(n), jnp.maximum(f, 0)].astype(jnp.int32)
            if cat_b is not None:
                left_dec = bitset[j, row_bin] if bitset is not None \
                    else row_bin == t
                dec = jnp.where(cat_b[jnp.maximum(f, 0)], ~left_dec,
                                row_bin > t)
            else:
                dec = row_bin > t
            go_right = (f >= 0) & dec
            child = jnp.where(go_right, right_child[j], left_child[j])
            node = jnp.where(node >= 0, child, node)
        return ~node

    return instrumented_jit(walk, name="lightgbm.tree_walk")


# ---------------------------------------------------------------------------
# metrics (reference: core/metrics/MetricConstants.scala registry)
# ---------------------------------------------------------------------------

def _metric_binary_logloss(y, raw, w=None):
    p = 1.0 / (1.0 + np.exp(-raw[:, 0]))
    p = np.clip(p, 1e-15, 1 - 1e-15)
    ll = -(y * np.log(p) + (1 - y) * np.log(1 - p))
    return float(np.average(ll, weights=w))


def _metric_auc(y, raw, w=None):
    s = raw[:, 0]
    order = np.argsort(s)
    y_s = y[order]
    w_s = np.ones_like(y_s, dtype=np.float64) if w is None else np.asarray(w)[order]
    pos = (y_s > 0).astype(np.float64) * w_s
    neg = (1.0 - (y_s > 0)) * w_s
    cum_neg = np.cumsum(neg)
    auc = float(np.sum(pos * (cum_neg - 0.5 * neg)) /
                max(1e-12, np.sum(pos) * np.sum(neg)))
    return auc


def _metric_multi_logloss(y, raw, w=None):
    z = raw - raw.max(axis=1, keepdims=True)
    p = np.exp(z)
    p /= p.sum(axis=1, keepdims=True)
    p = np.clip(p[np.arange(len(y)), y.astype(int)], 1e-15, None)
    return float(np.average(-np.log(p), weights=w))


def _metric_l2(y, raw, w=None):
    return float(np.average((raw[:, 0] - y) ** 2, weights=w))


def _metric_rmse(y, raw, w=None):
    return math.sqrt(_metric_l2(y, raw, w))


def _metric_l1(y, raw, w=None):
    return float(np.average(np.abs(raw[:, 0] - y), weights=w))


def _metric_poisson_nll(y, raw, w=None):
    mu = np.exp(np.clip(raw[:, 0], -30, 30))
    return float(np.average(mu - y * np.log(np.maximum(mu, 1e-12)), weights=w))


def _metric_gamma_nll(y, raw, w=None):
    s_ = np.clip(raw[:, 0], -30, 30)
    return float(np.average(s_ + y * np.exp(-s_), weights=w))


def _metric_pinball(y, raw, alpha, w=None):
    e = y - raw[:, 0]
    return float(np.average(np.maximum(alpha * e, (alpha - 1.0) * e),
                            weights=w))


def _metric_tweedie_nll(y, raw, rho, w=None):
    """Tweedie deviance NLL with log link (raw = log mean), 1 < rho < 2."""
    s_ = np.clip(raw[:, 0], -30, 30)
    nll = (-y * np.exp((1.0 - rho) * s_) / (1.0 - rho)
           + np.exp((2.0 - rho) * s_) / (2.0 - rho))
    return float(np.average(nll, weights=w))


METRICS = {"binary_logloss": (_metric_binary_logloss, False),
           "poisson_nll": (_metric_poisson_nll, False),
           "gamma_nll": (_metric_gamma_nll, False),
           "auc": (_metric_auc, True),
           "multi_logloss": (_metric_multi_logloss, False),
           "l2": (_metric_l2, False), "mse": (_metric_l2, False),
           "rmse": (_metric_rmse, False), "l1": (_metric_l1, False),
           "mae": (_metric_l1, False)}


def resolve_metric(metric_name: str, p: "GBDTParams"):
    """(metric_fn, larger_better) for a requested or default metric name.
    tweedie_nll is parameterized by the variance power, so it resolves to a
    closure here instead of living in METRICS; unknown names fall back to
    the objective's default (and that fallback handles tweedie too)."""
    def closures(name):
        if name == "tweedie_nll":
            rho_m = p.tweedie_variance_power
            return (lambda y_, raw_, w_=None:
                    _metric_tweedie_nll(y_, raw_, rho_m, w_), False)
        if name == "pinball":
            a_m = p.alpha
            return (lambda y_, raw_, w_=None:
                    _metric_pinball(y_, raw_, a_m, w_), False)
        return None

    got = closures(metric_name)
    if got is not None:
        return got
    if metric_name in METRICS:
        return METRICS[metric_name]
    fallback = default_metric(p.objective)
    got = closures(fallback)
    if got is not None:
        return got
    return METRICS.get(fallback, METRICS["l2"])


def default_metric(objective: str) -> str:
    return {"binary": "binary_logloss", "multiclass": "multi_logloss",
            "regression": "l2", "regression_l1": "l1", "huber": "l2",
            "quantile": "pinball", "lambdarank": "l2",
            "poisson": "poisson_nll", "tweedie": "tweedie_nll",
            "gamma": "gamma_nll"}.get(objective, "l2")


# ---------------------------------------------------------------------------
# training driver
# ---------------------------------------------------------------------------

def _resolve_hist_backend() -> tuple:
    """(backend, block_rows, lo_width, residuals) env knobs the growers will
    trace with.  Resolved ONCE per train() call and made part of every jit
    cache key: the env overrides are read at trace time, so without keying
    on EVERY knob a cached program would silently keep serving a
    previously-selected configuration.  Add any new histogram env knob to
    this tuple."""
    import os
    return (os.environ.get("MMLSPARK_TPU_HIST_BACKEND", "auto"),
            os.environ.get("MMLSPARK_TPU_HIST_BLOCK_ROWS", ""),
            os.environ.get("MMLSPARK_TPU_HIST_LO", ""),
            os.environ.get("MMLSPARK_TPU_HIST_RESID", ""),
            os.environ.get("MMLSPARK_TPU_HIST_LAYOUT", ""),
            os.environ.get("MMLSPARK_TPU_HIST_QUANT", ""),
            os.environ.get("MMLSPARK_TPU_HIST_STORE16", ""),
            os.environ.get("MMLSPARK_TPU_HIST_PALLAS", ""))


def _make_grower(p: GBDTParams, F: int, B: int, axis_name: str = None,
                 backend: str = "auto", psum_row_bound: int = None):
    """Growth-mode dispatch (call with resolved params)."""
    if p.growth == "leaf":
        return make_leafwise_grower(p.num_leaves, p.max_depth, F, B, p,
                                    axis_name=axis_name, backend=backend,
                                    psum_row_bound=psum_row_bound)
    return make_tree_grower(p.max_depth, F, B, p, axis_name=axis_name,
                            backend=backend, psum_row_bound=psum_row_bound)


@dataclasses.dataclass
class TrainResult:
    booster: GBDTBooster
    evals: List[Dict[str, float]]
    bin_mapper: BinMapper
    # out-of-core runs attach streaming diagnostics (tile geometry +
    # prefetch-overlap accounting); in-memory train() leaves it None
    extras: Optional[Dict[str, float]] = None


def _content_fingerprint(arr: np.ndarray) -> int:
    """Cheap strided content hash for cache keys: crc32 over ~4k strided
    elements.  Catches in-place mutation of a cached array that id()/shape
    keys alone cannot, at O(4k) cost regardless of array size.  Mutations
    confined to the skipped strides are (by design) not detected — it is a
    guard rail, not a cryptographic digest."""
    import zlib
    if arr.size == 0:
        return 0
    step = max(1, arr.size // 4096)
    # arr.flat[::step] materializes ONLY the ~4k sampled elements; ravel()
    # would copy the whole array whenever it is not C-contiguous
    sample = arr.flat[::step]
    return zlib.crc32(np.ascontiguousarray(sample).tobytes())


def train(X: np.ndarray, y: np.ndarray, params: GBDTParams,
          sample_weight: Optional[np.ndarray] = None,
          valid: Optional[Tuple[np.ndarray, np.ndarray]] = None,
          group_ptr: Optional[np.ndarray] = None,
          init_booster: Optional[GBDTBooster] = None,
          feature_names: Optional[List[str]] = None,
          callbacks: Optional[List[Callable]] = None,
          shard_rows: bool = False,
          bin_cache: Optional[Dict] = None,
          checkpoint_dir: Optional[str] = None,
          checkpoint_every: int = 0,
          checkpoint_keep_last: int = 3,
          resume: str = "auto",
          monitor_port: Optional[int] = None,
          monitor_stall_timeout_s: Optional[float] = None) -> TrainResult:
    """Boosting loop.  Host python drives iterations; each tree is one jitted
    XLA program (reference: driver drives ``updateOneIteration`` per iter,
    ``TrainUtils.scala:67``).  ``shard_rows`` puts the binned matrix/gradients
    row-sharded over the active mesh's data axis (GSPMD psums histograms over
    ICI — the allreduce-ring replacement).

    ``bin_cache`` contract: the memo is keyed on ``(id(X), shape, strided
    content fingerprint, binning params)``.  Rebinding a NEW array reuses
    nothing; mutating X IN PLACE between calls is detected by the ~4k-element
    strided fingerprint and rebins — but a mutation that only touches
    elements the stride skips can slip through, so callers that rewrite X
    wholesale should pass a fresh cache dict rather than rely on detection.

    Fault tolerance (ISSUE 10): with ``checkpoint_dir`` set, the run
    snapshots its booster-so-far + iteration + host PRNG/bagging state
    atomically every ``checkpoint_every`` iterations (plus once at the end)
    — the snapshot arrays are handed to a background writer thread as
    device-array references, so the device-to-host fetch AND the disk
    write both happen off the boosting loop.  ``resume="auto"`` restores
    the newest valid snapshot and continues through the warm-start
    machinery (a torn newest snapshot falls back to the previous one);
    SIGTERM/SIGINT requests one final checkpoint at the next iteration
    boundary and returns the partial booster cleanly with
    ``extras["preempted"]`` set.  ``resume="must"`` raises when no usable
    snapshot exists (restart scripts must not silently retrain from
    zero).

    Elastic resume (ISSUE 14): the snapshot records a topology stanza —
    device count, mesh shape, shard count — that is allowed to differ on
    restore.  A ``shard_rows`` run resumed on a re-sized mesh re-pads the
    row stream and bagging mask and re-keys the ``histogram_psum`` lane
    bound on the new width; with quantized histograms the per-row
    rounding noise is keyed by GLOBAL row id, so the resumed booster is
    bit-identical to an uninterrupted run at either width (tested shrink
    and grow).  The change books ``mmlspark_reshard_total`` and sets
    ``extras["resharded"]``.

    Live monitoring (ISSUE 19): ``monitor_port`` (0 = ephemeral) serves
    ``GET /progress`` / ``/metrics`` / ``/debug/{dump,profile}`` for the
    duration of the loop, and either monitor arg arms a stall watchdog
    (no iteration within max(4x EWMA iteration time,
    ``monitor_stall_timeout_s``) books ``mmlspark_training_stalls_total``
    and writes a ``trigger="train_stall"`` flight dump); see
    docs/OBSERVABILITY.md "Training plane"."""
    import jax
    import jax.numpy as jnp
    from ..observability import get_registry
    from ..observability.tracing import (Span, ambient_phase, current_span,
                                         export_span)

    # training-phase telemetry: per-iteration observations into the global
    # registry + ONE lightgbm.train span (child of the ambient fit span)
    # carrying phase totals.  Timings are host-side dispatch+wait — no
    # block_until_ready() syncs are inserted, the hot loop stays async.
    _phase_h = get_registry().histogram(
        "mmlspark_lightgbm_phase_seconds",
        "per-iteration training phase timings (host-side)",
        labels=("phase", "backend", "quantized"))
    _phase_totals: Dict[str, float] = {}

    def _observe_phase(phase: str, seconds: float, times: int = 1) -> None:
        # exemplar: every phase bucket keeps the training trace id, so a
        # slow-iteration outlier on /metrics resolves to this fit's trace;
        # backend/quantized labels make A/B runs attributable on /metrics
        for _ in range(times):
            _phase_h.observe(seconds, _train_span.trace_id, phase=phase,
                             backend=_eff_backend,
                             quantized="1" if p.use_quantized_grad else "0")
        _phase_totals[phase] = _phase_totals.get(phase, 0.0) + seconds * times

    _parent_span = current_span()
    _train_span = Span(
        "lightgbm.train",
        trace_id=_parent_span.trace_id if _parent_span else None,
        parent_id=_parent_span.span_id if _parent_span else None)

    p = params.resolve()
    # histogram backend + quantization resolution, up front so every phase
    # observation below carries the effective (backend, quantized) labels.
    # All env knobs are read at trace time and key the jit caches.
    hist_cfg = _resolve_hist_backend()
    hist_backend = hist_cfg[0]
    _uq = p.use_quantized_grad
    if hist_cfg[5].strip():              # MMLSPARK_TPU_HIST_QUANT=0/1
        # case-insensitive: an operator's QUANT=OFF during an incident must
        # never fail open into force-ENABLING the feature
        _uq = hist_cfg[5].strip().lower() not in ("0", "false", "off", "no")
    if _uq is None:                      # auto: packed ints on accelerators
        _uq = jax.default_backend() != "cpu"
    p = dataclasses.replace(p, use_quantized_grad=bool(_uq))
    if hist_backend != "auto" and (p.use_quantized_grad
                                   or hist_backend != "pallas"):
        _eff_backend = hist_backend
    elif p.use_quantized_grad:
        # quantized auto may resolve to the fused Pallas kernel (TPU, or
        # MMLSPARK_TPU_HIST_PALLAS=1 anywhere) — label what actually runs
        from ..ops.histogram import resolve_quantized_backend
        _eff_backend = resolve_quantized_backend("auto")
    else:
        # float path — an explicit 'pallas' request falls back here too
        # (the fused kernel is integer-only; build() maps it to the float
        # builders), so the phase label must name what actually ran
        _eff_backend = "scatter" if jax.default_backend() == "cpu" \
            else "matmul"
    rng = np.random.default_rng(p.seed)
    X = np.asarray(X, np.float32)
    y = np.asarray(y, np.float32)
    n, F = X.shape
    K = p.num_class if p.objective == "multiclass" else 1
    w = np.ones(n, np.float32) if sample_weight is None else np.asarray(sample_weight, np.float32)

    if p.categorical_features:
        bad = [i for i in p.categorical_features if not 0 <= int(i) < F]
        if bad:
            raise ValueError(f"categorical_features indices {bad} out of "
                             f"range [0, {F}) — negative indices are not "
                             f"interpreted pythonically")
    if p.objective in ("poisson", "tweedie") and (y < 0).any():
        raise ValueError(f"objective {p.objective!r} requires non-negative "
                         f"labels (min label {float(y.min())})")
    if p.objective == "gamma" and (y <= 0).any():
        raise ValueError("objective 'gamma' requires strictly positive "
                         f"labels (min label {float(y.min())})")
    if p.objective == "tweedie" and not 1.0 < p.tweedie_variance_power < 2.0:
        raise ValueError(
            f"tweedie_variance_power must be in (1, 2), got "
            f"{p.tweedie_variance_power}; use objective='poisson' for the "
            f"rho=1 limit")
    # opt-in binning memo (bench/tuner: many train() calls over the SAME X
    # with fresh labels — quantile fit + digitize depend on X only).  The
    # dict pins X itself so the id() key can never be recycled by a
    # freed-and-reallocated array, and a signature miss drops EVERY derived
    # entry (incl. the device buffer) before repopulating.
    _bin_sig = (id(X), X.shape, _content_fingerprint(X), p.max_bin,
                tuple(p.categorical_features or ()))
    if bin_cache is not None and bin_cache.get("sig") == _bin_sig:
        mapper = bin_cache["mapper"]
        binned_np = bin_cache["binned"]
    else:
        _t_bin = time.perf_counter()
        with ambient_phase("lightgbm.binning"):
            mapper = BinMapper(
                p.max_bin,
                categorical_features=p.categorical_features).fit(X)
            binned_np = mapper.transform(X)
        _observe_phase("binning", time.perf_counter() - _t_bin)
        if bin_cache is not None:
            bin_cache.clear()
            bin_cache.update(sig=_bin_sig, X=X, mapper=mapper,
                             binned=binned_np)
    edges = jnp.asarray(mapper.edges)
    B = mapper.num_bins

    if p.categorical_features and p.cat_subset is None:
        # observed-cardinality mode split (LightGBM max_cat_to_onehot):
        # low-cardinality features stay one-vs-rest; the rest get the
        # sorted-subset many-vs-many search.  Data-dependent, hence part of
        # the resolved params (and the jit cache key).
        sub = []
        for f_i in p.categorical_features:
            codes = np.unique(binned_np[:, f_i])
            if int((codes != B - 1).sum()) > p.max_cat_to_onehot:
                sub.append(int(f_i))
        p = dataclasses.replace(p, cat_subset=tuple(sub))

    sig = _params_sig(p) + (hist_cfg,)

    # ---- fault tolerance (ISSUE 10/14): periodic atomic checkpoints +
    # resume through the warm-start machinery below.  The fingerprint is
    # the DATA/PARAMS identity only (must match); topology — device
    # count, mesh shape, shard count — rides a separate recorded stanza
    # that is allowed to differ, because the fleet a preempted run
    # restarts on is rarely the fleet it lost (elastic resume).
    import contextlib
    from ..io.checkpoint import (CheckpointManager, book_reshard,
                                 check_resume_arg, resume_required_error,
                                 topology_stanza)
    from ..utils.resilience import PreemptionToken, preemption_scope
    _ckpt_fingerprint = repr((sig, n, F, B, K, shard_rows,
                              _content_fingerprint(X)))
    _topo_mesh = None
    if shard_rows:
        from ..parallel import get_active_mesh as _gam
        from ..parallel.mesh import AXIS_DATA as _AXIS_DATA
        _topo_mesh = _gam()
        _cur_topology = topology_stanza(
            mesh=_topo_mesh,
            shard_count=int(_topo_mesh.shape[_AXIS_DATA]))
    else:
        _cur_topology = topology_stanza(shard_count=1, device_count=1)
    check_resume_arg(resume, checkpoint_dir=checkpoint_dir)
    _mgr = None
    if checkpoint_dir:
        _mgr = CheckpointManager(checkpoint_dir, site="lightgbm.train",
                                 keep_last=checkpoint_keep_last)
    _resume_meta = None
    _resume_bag: Optional[np.ndarray] = None
    _resharded = False
    _n_user_init_trees = init_booster.num_trees if init_booster is not None \
        else 0
    if _mgr is not None and resume in ("auto", "must"):
        _got = _mgr.load_latest(current_topology=_cur_topology)
        if _got is None and resume == "must":
            raise resume_required_error(checkpoint_dir)
        if _got is not None:
            _, _arrs, _meta = _got
            if _meta.get("fingerprint") != _ckpt_fingerprint:
                raise ValueError(_CKPT_FINGERPRINT_MISMATCH)
            _delta = _meta.get("topology_delta")
            if _delta is not None and _delta["changed"]:
                # re-sharding: the row stream re-partitions onto the new
                # mesh width below (padding, bag mask, psum lane bound all
                # re-key on it) — book the delta so the resume is visible
                book_reshard("lightgbm.train", _delta)
                _resharded = True
            from ..models.gbdt import children_depth_bound
            # the snapshot booster replaces any user init_booster: it
            # already CONTAINS those trees (they were replayed into the
            # run the snapshot came from)
            init_booster = GBDTBooster(
                np.asarray(_arrs["split_feature"]),
                np.asarray(_arrs["threshold"]),
                np.asarray(_arrs["threshold_bin"]),
                np.asarray(_arrs["split_gain"]),
                np.asarray(_arrs["internal_value"]),
                np.asarray(_arrs["internal_count"]),
                np.asarray(_arrs["leaf_value"]),
                np.asarray(_arrs["leaf_count"]),
                np.asarray(_arrs["tree_weight"], np.float32),
                left_child=np.asarray(_arrs["left_child"]),
                right_child=np.asarray(_arrs["right_child"]),
                max_depth=children_depth_bound(_arrs["left_child"],
                                               _arrs["right_child"]),
                num_features=F, objective=p.objective, num_class=K,
                init_score=float(_meta["init_score"]),
                average_output=(p.boosting_type == "rf"),
                sigmoid=p.sigmoid,
                categorical_features=list(p.categorical_features or []),
                cat_bitset=(np.asarray(_arrs["cat_bitset"], bool)
                            if "cat_bitset" in _arrs else None))
            _n_user_init_trees = int(_meta.get("n_init_trees", 0))
            if "bag_mask" in _arrs:
                # unpacked at the restore site below: shard_rows pads n
                # between here and there
                _resume_bag = np.asarray(_arrs["bag_mask"])
            _resume_meta = _meta

    n_data = n           # pre-pad row count: host stats and the bagging
    y_data, w_data = y, w  # draw must be independent of the mesh width
    if shard_rows:
        from jax.sharding import PartitionSpec as P
        from ..parallel import batch_sharded
        from ..parallel.mesh import AXIS_DATA
        from ..parallel.sharding import pad_to_multiple
        mesh = _topo_mesh
        nd = mesh.shape[AXIS_DATA]
        binned_np, n_valid_rows = pad_to_multiple(binned_np, nd)
        y_pad, _ = pad_to_multiple(y, nd)
        w_pad, _ = pad_to_multiple(w, nd)
        w_pad[n_valid_rows:] = 0.0  # padded rows carry zero weight everywhere
        y, w = y_pad, w_pad
        n = binned_np.shape[0]
        sharding = batch_sharded(mesh)
        from ..observability.compute import device_put as _obs_device_put
        binned = _obs_device_put(binned_np, sharding,
                                 site="lightgbm.binned_shards")

        # explicit SPMD: each shard builds local histograms, psum over ICI
        def _build_sharded():
            # psum_row_bound = GLOBAL padded rows: the quantized path sizes
            # its packed allreduce lanes from it, so it is baked into the
            # closure — hence n in the cache key below
            grow_raw = _make_grower(p, F, B, axis_name=AXIS_DATA,
                                    backend=hist_backend, psum_row_bound=n)
            return instrumented_jit(jax.shard_map(
                grow_raw, mesh=mesh,
                in_specs=(P(AXIS_DATA), P(AXIS_DATA), P(AXIS_DATA), P(AXIS_DATA),
                          P(), P()),
                out_specs=(P(),) * 11 + (P(AXIS_DATA),), check_vma=False),
                name="lightgbm.sharded_grower")
        grower = _cached(("sharded_grower", sig, F, id(mesh), n),
                         _build_sharded)
    else:
        # the 200MB-at-bench-shape uint8 device put rides the memo too: the
        # device buffer is immutable to the trainer, so reuse is safe
        if bin_cache is not None and "binned_dev" in bin_cache \
                and bin_cache.get("sig") == _bin_sig:
            binned = bin_cache["binned_dev"]
        else:
            binned = jnp.asarray(binned_np)
            if bin_cache is not None:
                bin_cache["binned_dev"] = binned
        grower = _cached(("grower", sig, F),
                         lambda: instrumented_jit(
                             _make_grower(p, F, B, backend=hist_backend),
                             name="lightgbm.grower"))
    objective = make_objective(p)
    D = p.depth_bound                 # static walk bound during training
    L = p.num_leaves                  # leaf slots (level-wise: 2^max_depth)

    # init score (BoostFromAverage analogue) — computed on the UNPADDED
    # arrays: the padded tail is zero-weighted either way, but a pairwise
    # host sum over a width-dependent padded length would make the base
    # score (and so every f32 score after it) drift across mesh widths,
    # breaking elastic resume's bit-identity (ISSUE 14)
    init_score = 0.0
    if p.objective == "binary":
        pbar = float(np.clip(np.average(y_data, weights=w_data),
                             1e-6, 1 - 1e-6))
        init_score = math.log(pbar / (1 - pbar)) / p.sigmoid
    elif p.objective in ("regression", "huber"):
        init_score = float(np.average(y_data, weights=w_data))
    elif p.objective in ("poisson", "tweedie", "gamma"):  # log link
        init_score = float(np.log(max(np.average(y_data, weights=w_data),
                                      1e-9)))
    elif p.objective == "regression_l1":
        init_score = float(np.median(y_data))

    scores = jnp.full((n, K), init_score, jnp.float32)
    y_dev = jnp.asarray(y)
    w_dev = jnp.asarray(w)

    # warm start: replay existing booster on binned data
    _TREE_KEYS = ("left_child", "right_child", "split_feature", "threshold",
                  "threshold_bin", "split_gain", "internal_value",
                  "internal_count", "leaf_value", "leaf_count")
    has_cat = bool(p.categorical_features)
    # subset splits need the per-node category bitset persisted; a warm-start
    # booster that carries bitsets keeps them through continuation too
    store_bitset = has_cat and (
        bool(p.cat_subset)
        or (init_booster is not None
            and getattr(init_booster, "cat_bitset", None) is not None))
    tree_keys = _TREE_KEYS + (("cat_bitset",) if store_bitset else ())
    trees: Dict[str, List[np.ndarray]] = {k: [] for k in tree_keys}
    tree_weights: List[float] = []
    # the replay walker must also resolve warm-start trees, which may be
    # DEEPER than this run's depth bound (e.g. uncapped leaf-wise booster
    # continued with a capped run): truncating their walk would gather from
    # a negative pseudo-leaf and silently corrupt every later gradient
    walk_bound = max(D, init_booster.max_depth if init_booster is not None else 0)
    walker = _cached(("walker", walk_bound, tuple(p.categorical_features or ())),
                     lambda: make_binned_walker(walk_bound,
                                                p.categorical_features))
    if init_booster is not None:
        assert init_booster.num_leaves == L and init_booster.num_features == F
        # one-vs-rest warm-start trees get onehot bitsets synthesized so the
        # continued booster's trees are uniform
        init_cbs = init_booster.resolve_cat_bitset(B) if store_bitset else None
        for t in range(init_booster.num_trees):
            for k in _TREE_KEYS:
                trees[k].append(getattr(init_booster, k)[t])
            if store_bitset:
                trees["cat_bitset"].append(init_cbs[t])
            tree_weights.append(float(init_booster.tree_weight[t]))
            leaf = walker(binned, jnp.asarray(init_booster.split_feature[t]),
                          jnp.asarray(init_booster.threshold_bin[t]),
                          jnp.asarray(init_booster.left_child[t]),
                          jnp.asarray(init_booster.right_child[t]),
                          bitset=(jnp.asarray(init_cbs[t])
                                  if store_bitset else None))
            contrib = jnp.asarray(init_booster.leaf_value[t])[leaf] * init_booster.tree_weight[t]
            scores = scores.at[:, t % K].add(contrib)
        # shift base score to the incoming booster's BEFORE reassigning, so
        # continued training optimizes against the recorded init_score
        scores = scores + (init_booster.init_score - init_score)
        init_score = init_booster.init_score

    metric_name = p.metric or default_metric(p.objective)
    metric_fn, larger_better = resolve_metric(metric_name, p)
    evals: List[Dict[str, float]] = []
    has_valid = valid is not None
    if has_valid:
        Xv = np.asarray(valid[0], np.float32)
        yv = np.asarray(valid[1], np.float32)
        binned_v = jnp.asarray(mapper.transform(Xv))
        scores_v = jnp.full((Xv.shape[0], K), init_score, jnp.float32)
        if _resume_meta is not None and init_booster is not None:
            # resumed run: valid scores must carry the contributions of
            # the trees grown BEFORE the crash (user warm-start trees stay
            # out, matching the uninterrupted run's scores_v history)
            init_cbs_v = init_booster.resolve_cat_bitset(B) \
                if store_bitset else None
            for t in range(_n_user_init_trees, init_booster.num_trees):
                leaf_v = walker(binned_v,
                                jnp.asarray(init_booster.split_feature[t]),
                                jnp.asarray(init_booster.threshold_bin[t]),
                                jnp.asarray(init_booster.left_child[t]),
                                jnp.asarray(init_booster.right_child[t]),
                                bitset=(jnp.asarray(init_cbs_v[t])
                                        if store_bitset else None))
                scores_v = scores_v.at[:, t % K].add(
                    jnp.asarray(init_booster.leaf_value[t])[leaf_v]
                    * init_booster.tree_weight[t])
    best_metric = -np.inf if larger_better else np.inf
    best_iter = -1
    rounds_no_improve = 0
    if _resume_meta is not None:
        # restore the host-side loop state the snapshot carried: the PRNG
        # (feature/bagging/dart draws), early-stopping scalars, and evals
        rng.bit_generator.state = _resume_meta["rng_state"]
        best_metric = float(_resume_meta["best_metric"])
        best_iter = int(_resume_meta["best_iter"])
        rounds_no_improve = int(_resume_meta["rounds_no_improve"])
        evals[:] = [dict(e) for e in _resume_meta.get("evals", [])]

    feat_mask_full = jnp.ones((F,), bool)
    hist_mask_full = jnp.ones((n,), bool) if not shard_rows else jnp.asarray(w > 0)

    # Fused per-iteration step (single-program path): objective + GOSS + K
    # tree grows + score updates in ONE jitted XLA program — eager per-op
    # dispatch through the device relay costs ~10-100 ms per op, which
    # dominated the loop before fusion.
    grow_fn = None if shard_rows else _make_grower(p, F, B,
                                                   backend=hist_backend)
    shrink_const = 1.0 if p.boosting_type == "rf" else p.learning_rate
    is_goss = p.boosting_type == "goss"
    a_n = int(p.top_rate * n) if is_goss else 0
    b_n = int(p.other_rate * n) if is_goss else 0

    def _iter_body(scores, y_d, w_d, binned_d, base_mask, feat_mask_d, edges_d,
                   grad_scale, new_w, key, g_pre, h_pre, use_pre):
        if use_pre:
            g, h = g_pre, h_pre
        else:
            g, h = objective(scores / grad_scale, y_d, w_d)
        hist_mask = base_mask
        if is_goss and not use_pre:
            absg = jnp.abs(g).sum(axis=1)
            order = jnp.argsort(-absg)
            top_idx = order[:a_n]
            rest = order[a_n:]
            perm = jax.random.permutation(key, rest.shape[0])
            small_idx = rest[perm[:b_n]]
            mask = jnp.zeros((n,), bool).at[top_idx].set(True).at[small_idx].set(True)
            amp = (1.0 - p.top_rate) / max(p.other_rate, 1e-12)
            wamp = jnp.ones((n,)).at[small_idx].set(amp)
            hist_mask = hist_mask & mask
            g, h = g * wamp[:, None], h * wamp[:, None]
        tree_out = []
        for c in range(K):
            lch, rch, sf, th, tb, sg, iv, ic, lv, lc, cbs, leaf = grow_fn(
                binned_d, g[:, c], h[:, c], hist_mask, feat_mask_d, edges_d)
            lv_s = lv * shrink_const
            scores = scores.at[:, c].add(lv_s[leaf] * new_w)
            tree_out.append((lch, rch, sf, th, tb, sg, iv, ic, lv_s, lc, cbs))
        return scores, tree_out

    # scores is donated: each iteration consumes the previous score buffer
    # in place instead of allocating a fresh (n, K) f32 per dispatch.  The
    # use_pre=False variant binds g_pre/h_pre statically to None so the
    # donated scores buffer is never also passed as another (aliased) arg.
    _iter_jit = {} if shard_rows else {
        False: _cached(("iter", sig, F, K, n, False),
                       lambda: instrumented_jit(
                           partial(_iter_body, g_pre=None,
                                   h_pre=None, use_pre=False),
                           donate_argnums=(0,), name="lightgbm.iter")),
        True: _cached(("iter", sig, F, K, n, True),
                      lambda: instrumented_jit(
                          partial(_iter_body, use_pre=True),
                          donate_argnums=(0,), name="lightgbm.iter_pre"))}

    import jax.random as jrandom
    jit_objective = instrumented_jit(objective, name="lightgbm.objective") \
        if objective is not None else None
    start_iter = len(tree_weights) // K

    # ---- scan-chunked multi-iteration path: CH boosting iterations per
    # device dispatch, amortizing the relay's per-dispatch latency.  Default
    # ON for accelerators.  The round-3/4 readings once quoted here
    # (1.4-3.2M rows/s) were partially relay-cache-polluted (VERDICT r4 weak
    # #3); the authoritative CH sweep is round 5's cache-busted median-of-3
    # log, bench_attempts/tune_r5.log (tools/tune_r5.py: fresh labels per
    # train() call, raw t_a/t_b recorded, physically-impossible rates
    # rejected).  CPU keeps CH=1: scan compile cost
    # dominates there.  MMLSPARK_TPU_GBDT_CHUNK overrides either way.
    _ch_env = __import__("os").environ.get("MMLSPARK_TPU_GBDT_CHUNK")
    if _ch_env is not None:
        CH = max(1, int(_ch_env))
    else:
        CH = 4 if jax.default_backend() != "cpu" else 1
    chunk_ok = (CH > 1 and not shard_rows and p.objective != "lambdarank"
                and not p.categorical_features  # valid-walk is numerical-only
                and p.boosting_type != "dart" and p.bagging_freq <= 1
                and p.num_iterations >= 2 * CH
                and n >= 50_000)  # small data: scan compile cost dominates

    def _build_multi():
        keep = max(1, int(round(p.feature_fraction * F)))
        bag_on = p.bagging_freq > 0 and p.bagging_fraction < 1.0
        ff_on = p.feature_fraction < 1.0
        rf_mode = p.boosting_type == "rf"

        def body(carry, key):
            scores_c, t = carry
            kf, kb, kg = jrandom.split(key, 3)
            feat_mask = jnp.ones((F,), bool)
            if ff_on:
                sel = jrandom.choice(kf, F, (keep,), replace=False)
                feat_mask = jnp.zeros((F,), bool).at[sel].set(True)
            base_mask = jnp.ones((n,), bool)
            if bag_on:
                base_mask = jrandom.uniform(kb, (n,)) < p.bagging_fraction
            grad_scale = jnp.maximum(1.0, jnp.floor(t / K)) if rf_mode else 1.0
            g, h = objective(scores_c / grad_scale, y_dev, w_dev)
            hist_mask = base_mask
            if is_goss:
                absg = jnp.abs(g).sum(axis=1)
                order = jnp.argsort(-absg)
                top_idx = order[:a_n]
                rest = order[a_n:]
                perm = jrandom.permutation(kg, rest.shape[0])
                small_idx = rest[perm[:b_n]]
                mask = jnp.zeros((n,), bool).at[top_idx].set(True)                     .at[small_idx].set(True)
                amp = (1.0 - p.top_rate) / max(p.other_rate, 1e-12)
                wamp = jnp.ones((n,)).at[small_idx].set(amp)
                hist_mask = hist_mask & mask
                g, h = g * wamp[:, None], h * wamp[:, None]
            outs = []
            for c in range(K):
                # chunked path excludes categoricals, so the bitset is a dummy
                lch, rch, sf, th, tb, sg, iv, ic, lv, lc, _cbs, leaf = grow_fn(
                    binned, g[:, c], h[:, c], hist_mask, feat_mask, edges)
                lv_s = lv * shrink_const
                scores_c = scores_c.at[:, c].add(lv_s[leaf])
                outs.append((lch, rch, sf, th, tb, sg, iv, ic, lv_s, lc))
            stacked = tuple(jnp.stack([o[j] for o in outs]) for j in range(10))
            return (scores_c, t + K), stacked

        def multi(scores_c, t0, keys):
            (scores_c, t), stacked = jax.lax.scan(body, (scores_c, t0), keys)
            return scores_c, stacked

        return instrumented_jit(multi, donate_argnums=(0,),
                                name="lightgbm.multi_iter")

    multi_iter = _cached(("multi", sig, F, K, n, CH), _build_multi) if chunk_ok else None

    def _build_valid_update():
        def upd(scores_v_c, binned_v_c, sf_all, tb_all, lv_all, lch_all,
                rch_all):
            CK = sf_all.shape[0] * sf_all.shape[1]
            sf_f = sf_all.reshape(CK, -1)
            tb_f = tb_all.reshape(CK, -1)
            lv_f = lv_all.reshape(CK, -1)
            lch_f = lch_all.reshape(CK, -1)
            rch_f = rch_all.reshape(CK, -1)
            nv = binned_v_c.shape[0]

            def walk_one(sf_t, tb_t, lc_t, rc_t):
                node = jnp.zeros((nv,), jnp.int32)
                for _ in range(D):
                    j = jnp.maximum(node, 0)
                    f = sf_t[j]
                    tt = tb_t[j]
                    row_bin = binned_v_c[jnp.arange(nv),
                                         jnp.maximum(f, 0)].astype(jnp.int32)
                    go_right = (f >= 0) & (row_bin > tt)
                    child = jnp.where(go_right, rc_t[j], lc_t[j])
                    node = jnp.where(node >= 0, child, node)
                return ~node

            leaves = jax.vmap(walk_one)(sf_f, tb_f, lch_f, rch_f)   # (CK, nv)
            vals = jnp.take_along_axis(lv_f, leaves, axis=1)        # (CK, nv)
            for c in range(K):
                scores_v_c = scores_v_c.at[:, c].add(vals[c::K].sum(axis=0))
            return scores_v_c

        return instrumented_jit(upd, name="lightgbm.valid_update")

    valid_chunk_update = _cached(("validupd", D, K), _build_valid_update)

    it = start_iter
    bag_mask = None  # sampled lazily on the first bagging-eligible iteration
    if _resume_bag is not None:
        # stored packed bits cover the SNAPSHOT's padded width; re-pad to
        # this run's (the real rows [0, n_data) are identical, and padded
        # rows never enter a histogram regardless of their bag bit)
        _bits = np.unpackbits(_resume_bag)
        if _bits.size < n:
            _bits = np.pad(_bits, (0, n - _bits.size))
        bag_mask = jnp.asarray(_bits[:n].astype(bool))
    lambda_fn = None  # built on first lambdarank iteration, reused after
    _run_iter0 = start_iter
    _done_before = 0
    if _resume_meta is not None:
        _done_before = int(_resume_meta["iteration"])
        if _resume_meta.get("finished") and p.num_iterations <= int(
                _resume_meta.get("num_iterations", _done_before)):
            # the snapshot IS the finished run: skip the loop and return
            # its booster; a LARGER num_iterations target keeps training
            _done_before = p.num_iterations
    end_iter = start_iter + max(0, p.num_iterations - _done_before)
    _preempted = False
    _last_ckpt_iter = start_iter
    _trees_at_loop_start = len(tree_weights)

    def _save_ckpt_train(finished: bool, block: bool = False) -> None:
        # snapshot = list copies of DEVICE array refs (immutable; the tree
        # outputs are never donated) — np.asarray/stack/serialize/publish
        # all run on the manager's writer thread, so the boosting loop
        # never waits on the device fetch or the disk.  Completed-
        # iteration accounting derives from the TREE COUNT (one shared
        # convention with train_streamed): loop counters disagree with
        # completed work at early-stop breaks and mid-chunk boundaries.
        done = len(tree_weights) // K - _n_user_init_trees // K
        meta = _booster_ckpt_meta(done, _n_user_init_trees, rng,
                                  best_metric, best_iter, rounds_no_improve,
                                  evals, init_score, _ckpt_fingerprint,
                                  finished, p.num_iterations, "booster_v1",
                                  topology=_cur_topology)
        _mgr.save(done, _booster_ckpt_arrays(trees, tree_weights, bag_mask),
                  meta, block=block)

    # ---- live training monitor (ISSUE 19): opt-in heartbeat + stall
    # watchdog + HTTP sidecar; ticks ride the callbacks seam the loop
    # already invokes, so monitoring adds no new iteration hook
    _watch = _wsrv = None
    if monitor_port is not None or monitor_stall_timeout_s is not None:
        from ..observability.trainwatch import start_training_monitor
        _watch, _wsrv = start_training_monitor(
            "lightgbm.train", total_steps=p.num_iterations,
            rows_per_step=n, monitor_port=monitor_port,
            stall_timeout_s=monitor_stall_timeout_s,
            driver="lightgbm.train")
        _watch.set_phase("boosting")

        def _watch_cb(i, ev, _w=_watch):
            # the eval entry (when present) carries {metric_name: value,
            # "iteration": it} — feed the metric value to the loss tail
            val = None
            if isinstance(ev, dict):
                for k, v in ev.items():
                    if k != "iteration" and isinstance(v, (int, float)):
                        val = float(v)
                        break
            _w.tick(step=i + 1, loss=val)

        callbacks = list(callbacks or []) + [_watch_cb]

    _scope = preemption_scope() if _mgr is not None \
        else contextlib.nullcontext(PreemptionToken())
    with contextlib.ExitStack() as _stack:
      if _wsrv is not None:
          _stack.callback(_wsrv.stop)
      if _watch is not None:
          _stack.callback(_watch.close)
      _token = _stack.enter_context(_scope)
      if _watch is not None:
          _watch.set_preemption_token(_token)
      while it < end_iter:
        if _token.requested:
            # preempted: final checkpoint at this iteration boundary, then
            # a clean partial return the caller can resume from
            _save_ckpt_train(finished=False, block=True)
            _preempted = True
            break
        if _mgr is not None and checkpoint_every > 0 \
                and it - _last_ckpt_iter >= checkpoint_every:
            _save_ckpt_train(finished=False)
            _last_ckpt_iter = it
        if multi_iter is not None and end_iter - it >= CH:
            keys = jnp.stack([jrandom.PRNGKey(p.seed * 1000003 + it + j)
                              for j in range(CH)])
            _t_grow = time.perf_counter()
            with ambient_phase("lightgbm.histogram"):
                scores, stacked = multi_iter(scores,
                                             jnp.float32(len(tree_weights)),
                                             keys)
            # CH fused iterations per dispatch: book the per-iteration share
            # CH times so histogram counts stay 1:1 with boosting iterations
            _observe_phase("histogram_split_update",
                           (time.perf_counter() - _t_grow) / CH, times=CH)
            for ci in range(CH):
                for c in range(K):
                    for k_name, arr in zip(_TREE_KEYS, stacked):
                        trees[k_name].append(arr[ci, c])
                    tree_weights.append(1.0)
            if has_valid:
                _t_eval = time.perf_counter()
                with ambient_phase("lightgbm.eval"):
                    scores_v = valid_chunk_update(scores_v, binned_v,
                                                  stacked[2], stacked[4],
                                                  stacked[8], stacked[0],
                                                  stacked[1])
                    raw_v = np.asarray(scores_v, np.float64)
                    m = metric_fn(yv, raw_v)
                _observe_phase("eval", time.perf_counter() - _t_eval)
                evals.append({metric_name: m, "iteration": it + CH - 1})
                improved = m > best_metric if larger_better else m < best_metric
                if improved:
                    best_metric, best_iter, rounds_no_improve = m, it + CH - 1, 0
                else:
                    rounds_no_improve += CH
                if p.early_stopping_round > 0 and \
                        rounds_no_improve >= p.early_stopping_round:
                    break
            if callbacks:
                for cb in callbacks:
                    cb(it + CH - 1, evals[-1] if evals else None)
            it += CH
            continue

        # ---- host-side per-iteration randomness
        feat_mask = feat_mask_full
        if p.feature_fraction < 1.0:
            keep = max(1, int(round(p.feature_fraction * F)))
            sel = rng.choice(F, size=keep, replace=False)
            feat_mask = jnp.zeros((F,), bool).at[jnp.asarray(sel)].set(True)
        base_mask = hist_mask_full
        if p.boosting_type != "goss" and p.bagging_freq > 0 and p.bagging_fraction < 1.0:
            # resample on schedule-aligned iterations AND on the first
            # iteration of this call (a warm start may begin off-schedule,
            # in which case bag_mask would otherwise be unbound)
            if it % p.bagging_freq == 0 or bag_mask is None:
                # draw over the UNPADDED rows (padded tail stays out of
                # the bag): the PRNG stream — and so every later draw —
                # is then independent of the mesh width, which elastic
                # resume's cross-width bit-identity rides on (ISSUE 14)
                _draw = rng.random(n_data) < p.bagging_fraction
                bag_mask = jnp.asarray(np.pad(_draw, (0, n - n_data)))
            base_mask = hist_mask_full & bag_mask

        # ---- gradients precomputed for lambdarank / dart
        _t_grad = time.perf_counter()
        g_pre = h_pre = None
        dropped: List[int] = []
        if p.objective == "lambdarank":
            if group_ptr is None:
                raise ValueError("lambdarank requires group_ptr")
            if lambda_fn is None:  # packing gathers built once, then the
                lambda_fn = make_lambdarank_grad_fn(y, group_ptr, p.sigmoid)
            g_pre, h_pre = lambda_fn(scores)  # stays on device every iter
        elif p.boosting_type == "dart" and tree_weights and rng.random() >= p.skip_drop:
            k_drop = min(p.max_drop, max(1, int(round(p.drop_rate * len(tree_weights)))))
            dropped = sorted(rng.choice(len(tree_weights), size=min(k_drop, len(tree_weights)),
                                        replace=False).tolist())
            drop_delta = jnp.zeros_like(scores)
            for t in dropped:
                leaf = walker(binned, trees["split_feature"][t],
                              trees["threshold_bin"][t],
                              trees["left_child"][t], trees["right_child"][t],
                              bitset=(trees["cat_bitset"][t]
                                      if store_bitset else None))
                drop_delta = drop_delta.at[:, t % K].add(
                    trees["leaf_value"][t][leaf] * tree_weights[t])
            g_pre, h_pre = jit_objective(scores - drop_delta, y_dev, w_dev)

        new_w = 1.0 / (1.0 + len(dropped)) if dropped else 1.0
        grad_scale = float(max(1, len(tree_weights) // K)) \
            if p.boosting_type == "rf" and tree_weights else 1.0
        key = jrandom.PRNGKey(p.seed * 1000003 + it)
        if g_pre is not None:  # lambdarank/dart gradients were built above
            _observe_phase("gradients", time.perf_counter() - _t_grad)

        _t_grow = time.perf_counter()
        if not shard_rows:
            use_pre = g_pre is not None
            with ambient_phase("lightgbm.histogram"):
                if use_pre:
                    scores, tree_out = _iter_jit[True](
                        scores, y_dev, w_dev, binned, base_mask, feat_mask,
                        edges, grad_scale, new_w, key, g_pre, h_pre)
                else:
                    scores, tree_out = _iter_jit[False](
                        scores, y_dev, w_dev, binned, base_mask, feat_mask,
                        edges, grad_scale, new_w, key)
            # one fused program: histogram build + split find + score update
            _observe_phase("histogram_split_update",
                           time.perf_counter() - _t_grow)
        else:
            # multi-chip path: explicit shard_map grower per class — the
            # only path where gradients / grow / update dispatch separately
            if g_pre is not None:
                g_eff, h_eff = g_pre, h_pre
            else:
                g_eff, h_eff = jit_objective(scores / grad_scale, y_dev, w_dev)
                _observe_phase("gradients", time.perf_counter() - _t_grow)
            shrink = 1.0 if p.boosting_type == "rf" else p.learning_rate
            tree_out = []
            for c in range(K):
                _t_c = time.perf_counter()
                with ambient_phase("lightgbm.histogram"):
                    (lch, rch, sf, th, tb, sg, iv, ic, lv, lc, cbs,
                     leaf_of_row) = grower(
                        binned, g_eff[:, c], h_eff[:, c], base_mask,
                        feat_mask, edges)
                _observe_phase("histogram_split", time.perf_counter() - _t_c)
                _t_u = time.perf_counter()
                lv_s = lv * shrink
                scores = scores.at[:, c].add(lv_s[leaf_of_row] * new_w)
                tree_out.append((lch, rch, sf, th, tb, sg, iv, ic, lv_s, lc,
                                 cbs))
                _observe_phase("update", time.perf_counter() - _t_u)

        for c, (lch, rch, sf, th, tb, sg, iv, ic, lv_s, lc, cbs) \
                in enumerate(tree_out):
            # keep tree arrays on device: every host fetch is a relay
            # round-trip; one device_get happens after the loop
            vals = (lch, rch, sf, th, tb, sg, iv, ic, lv_s, lc) \
                + ((cbs,) if store_bitset else ())
            for k_name, v in zip(tree_keys, vals):
                trees[k_name].append(v)
            tree_weights.append(new_w)
            if has_valid:
                leaf_v = walker(binned_v, sf, tb, lch, rch,
                                bitset=cbs if store_bitset else None)
                scores_v = scores_v.at[:, c].add(lv_s[leaf_v] * new_w)

        # ---- dart renormalize dropped trees
        if p.boosting_type == "dart" and dropped:
            factor = len(dropped) / (1.0 + len(dropped))
            for t in dropped:
                # subtract the shrunken part from train/valid scores
                bs_t = trees["cat_bitset"][t] if store_bitset else None
                leaf = walker(binned, trees["split_feature"][t],
                              trees["threshold_bin"][t],
                              trees["left_child"][t], trees["right_child"][t],
                              bitset=bs_t)
                delta = trees["leaf_value"][t][leaf] * tree_weights[t] * (factor - 1.0)
                scores = scores.at[:, t % K].add(delta)
                if has_valid:
                    leaf_v = walker(binned_v, trees["split_feature"][t],
                                    trees["threshold_bin"][t],
                                    trees["left_child"][t],
                                    trees["right_child"][t], bitset=bs_t)
                    delta_v = trees["leaf_value"][t][leaf_v] * tree_weights[t] * (factor - 1.0)
                    scores_v = scores_v.at[:, t % K].add(delta_v)
                tree_weights[t] *= factor

        # ---- eval / early stopping
        if has_valid:
            _t_eval = time.perf_counter()
            with ambient_phase("lightgbm.eval"):
                raw_v = np.asarray(scores_v, np.float64)
                m = metric_fn(yv, raw_v)
            _observe_phase("eval", time.perf_counter() - _t_eval)
            evals.append({metric_name: m, "iteration": it})
            improved = m > best_metric if larger_better else m < best_metric
            if improved:
                best_metric, best_iter, rounds_no_improve = m, it, 0
            else:
                rounds_no_improve += 1
            if p.early_stopping_round > 0 and rounds_no_improve >= p.early_stopping_round:
                break
        if callbacks:
            for cb in callbacks:
                cb(it, evals[-1] if evals else None)
        it += 1

    if _mgr is not None:
        if not _preempted and (len(tree_weights) > _trees_at_loop_start
                              or _resume_meta is None):
            # terminal snapshot (covers early stopping too): resume of a
            # finished run restores the final booster without retraining;
            # a finished-run restore that grew nothing skips the re-save
            _save_ckpt_train(finished=True, block=True)
        _mgr.close()

    trees_np = jax.device_get({k: v for k, v in trees.items()})  # one transfer
    lch_np = np.stack(trees_np["left_child"])
    rch_np = np.stack(trees_np["right_child"])
    if p.growth == "leaf":
        # tight walk bound: leaf-wise trees are usually far shallower than
        # the worst-case num_leaves - 1 chain (this also covers deeper
        # warm-start trees, which are in lch_np/rch_np too)
        from ..models.gbdt import children_depth_bound
        D = children_depth_bound(lch_np, rch_np)
    elif init_booster is not None:
        # level-wise continuation must keep a bound that resolves the
        # warm-start trees, which may be deeper than this run's depth
        D = max(D, init_booster.max_depth)
    cat_bitset = None
    if store_bitset:
        cat_bitset = np.stack([np.asarray(a, bool)
                               for a in trees_np["cat_bitset"]])
    booster = GBDTBooster(
        np.stack(trees_np["split_feature"]), np.stack(trees_np["threshold"]),
        np.stack(trees_np["threshold_bin"]), np.stack(trees_np["split_gain"]),
        np.stack(trees_np["internal_value"]), np.stack(trees_np["internal_count"]),
        np.stack(trees_np["leaf_value"]), np.stack(trees_np["leaf_count"]),
        np.asarray(tree_weights, np.float32),
        left_child=lch_np, right_child=rch_np,
        max_depth=D, num_features=F, objective=p.objective, num_class=K,
        init_score=init_score, average_output=(p.boosting_type == "rf"),
        feature_names=feature_names, best_iteration=best_iter, sigmoid=p.sigmoid,
        categorical_features=list(p.categorical_features or []),
        cat_bitset=cat_bitset)
    for k, v in sorted(_phase_totals.items()):
        _train_span.set_attribute(f"phase.{k}_s", round(v, 6))
    _train_span.set_attribute("rows", n)
    _train_span.set_attribute("features", F)
    _train_span.set_attribute("iterations", len(tree_weights) // K)
    _train_span.set_attribute("growth", p.growth)
    _extras = None
    if _mgr is not None:
        _extras = {"preempted": float(_preempted),
                   "resumed_from_iteration":
                       float(_resume_meta["iteration"])
                       if _resume_meta is not None else -1.0,
                   "checkpoint_saves": float(_mgr.saves_ok),
                   "resharded": float(_resharded)}
        for k, v in _extras.items():
            _train_span.set_attribute(f"ckpt.{k}", v)
    export_span(_train_span)
    return TrainResult(booster=booster, evals=evals, bin_mapper=mapper,
                       extras=_extras)


# ---------------------------------------------------------------------------
# out-of-core streamed training (ISSUE 7): host-RAM tiles -> device HBM
# ---------------------------------------------------------------------------

def _check_quant_tile_bound(use_quant: bool, quant_bins: int,
                            total_rows: int) -> None:
    """Tile-accumulation twin of ``_check_quant_psum_bound``: each per-tile
    build guards int32 overflow against its OWN tile's rows, but the driver
    accumulates decoded partials across every tile — a root-level cell can
    hold the full dataset's sums, so the guard must see the total."""
    if not use_quant:
        return
    qh_cap = max(1, quant_bins - 1)
    if int(total_rows) * qh_cap >= (1 << 31):
        raise ValueError(
            "quantized histograms overflow int32 when accumulated across "
            f"tiles above {(1 << 31) // qh_cap} total rows at {quant_bins} "
            "quantization bins — lower num_grad_quant_bins or disable "
            "use_quantized_grad")


#: the streamed paths' array-of-nodes tree surface (booster column order)
_STREAM_TREE_KEYS = ("left_child", "right_child", "split_feature",
                     "threshold", "threshold_bin", "split_gain",
                     "internal_value", "internal_count", "leaf_value",
                     "leaf_count")


def _quant_mix(g_host: np.ndarray, h_host: np.ndarray) -> np.int32:
    """Per-iteration quantization key mix for the streamed driver: an
    exact INTEGER fold of the bitcast |grad|/hess magnitudes over the
    whole host row space.  Integer adds are associative and the host
    arrays are tile-independent, so the mix — and every row's stochastic
    rounding — survives a resume onto a different tile width bit-for-bit
    (the tile-level twin of the sharded grower's psum'd mix)."""
    gi = int(np.abs(g_host).view(np.int32).astype(np.int64).sum())
    hi = int(h_host.view(np.int32).astype(np.int64).sum())
    total = (gi + 3 * hi) & 0xFFFFFFFF
    if total >= 1 << 31:
        total -= 1 << 32
    return np.int32(total)


def _booster_ckpt_arrays(trees: Dict[str, list], tree_weights: list,
                         bag_mask) -> Callable[[], Dict[str, np.ndarray]]:
    """Snapshot-arrays callable shared by ``train`` and ``train_streamed``
    (one copy so the two drivers' checkpoint formats cannot drift).  The
    training thread pays only list copies; ``np.asarray``/``np.stack``/
    ``np.packbits`` — including any device-to-host fetches for device-
    resident trees or bagging masks — run on the manager's writer thread.
    Tree arrays and the bag mask are immutable once captured (the loop
    REBINDS them rather than mutating), so the deferred reads are safe."""
    tl = {k: list(v) for k, v in trees.items()}
    tw = list(tree_weights)

    def _arrays(tl=tl, tw=tw, bm=bag_mask):
        out = {k: np.stack([np.asarray(a) for a in v])
               for k, v in tl.items()}
        out["tree_weight"] = np.asarray(tw, np.float32)
        if bm is not None:
            out["bag_mask"] = np.packbits(np.asarray(bm, bool))
        return out

    return _arrays


def _booster_ckpt_meta(completed_iter: int, n_init_trees: int, rng,
                       best_metric, best_iter: int, rounds_no_improve: int,
                       evals: list, init_score: float, fingerprint: str,
                       finished: bool, num_iterations: int,
                       fmt: str, topology: Optional[Dict] = None) -> Dict:
    """Snapshot meta shared by both drivers.  ``completed_iter`` is the
    ONE convention both must use: boosting iterations completed beyond the
    user's warm-start trees, derived from the tree count (robust to early
    stopping and the fused multi-iteration chunk path, where loop counters
    and completed work can disagree at the break).  ``topology`` is the
    recorded-but-not-identity stanza (ISSUE 14): a resume onto a changed
    mesh width / tile geometry diffs it instead of rejecting it."""
    meta = {"iteration": int(completed_iter),
            "n_init_trees": int(n_init_trees),
            "rng_state": rng.bit_generator.state,
            "best_metric": best_metric, "best_iter": int(best_iter),
            "rounds_no_improve": int(rounds_no_improve),
            "evals": [dict(e) for e in evals],
            "init_score": float(init_score),
            "fingerprint": fingerprint, "finished": bool(finished),
            "num_iterations": int(num_iterations), "format": fmt}
    if topology is not None:
        meta["topology"] = topology
    return meta


_CKPT_FINGERPRINT_MISMATCH = (
    "checkpoint_dir holds a snapshot for different data or params "
    "(fingerprint mismatch) — point checkpoint_dir at a fresh directory, "
    "or pass resume='never' (docs/RESILIENCE.md: training fault tolerance)")


def _np_walk_tree(binned: np.ndarray, sf: np.ndarray, tb: np.ndarray,
                  lch: np.ndarray, rch: np.ndarray,
                  depth_bound: int) -> np.ndarray:
    """Host twin of ``make_binned_walker`` for numerical splits: per-row
    leaf index of ONE tree over host-resident binned data.  Integer
    compares and gathers only, so the leaf assignment is exactly the one
    the device walker (and the streamed router) produces — which is what
    lets resume replay reconstruct training scores bit-for-bit without
    ever putting the full binned matrix on device."""
    n = binned.shape[0]
    node = np.zeros((n,), np.int64)
    rows = np.arange(n)
    sf = np.asarray(sf, np.int64)
    tb = np.asarray(tb, np.int64)
    lch = np.asarray(lch, np.int64)
    rch = np.asarray(rch, np.int64)
    for _ in range(max(1, int(depth_bound))):
        j = np.maximum(node, 0)
        f = sf[j]
        go_right = (f >= 0) & (binned[rows, np.maximum(f, 0)].astype(np.int64)
                               > tb[j])
        child = np.where(go_right, rch[j], lch[j])
        node = np.where(node >= 0, child, node)
    return ~node


def _np_leaf_output(G, H, l1: float, l2: float, max_delta: float):
    """Host-side twin of the growers' leaf_output (f32 in, f32 out).
    Empty nodes (G=H=0, l2=0) yield NaN exactly like the device version —
    callers mask them behind a count check, so the numpy warning is
    suppressed rather than papered over with a fake value."""
    with np.errstate(invalid="ignore", divide="ignore"):
        t = np.sign(G) * np.maximum(np.abs(G) - l1, 0.0)
        v = (-t / (H + l2)).astype(np.float32)
    if max_delta > 0:
        v = np.clip(v, -max_delta, max_delta)
    return v


def train_streamed(X, y: Optional[np.ndarray] = None, params: GBDTParams = None,
                   sample_weight: Optional[np.ndarray] = None,
                   valid: Optional[Tuple[np.ndarray, np.ndarray]] = None,
                   tile_rows: Optional[int] = None,
                   memory_budget_bytes: Optional[int] = None,
                   feature_names: Optional[List[str]] = None,
                   init_booster: Optional[GBDTBooster] = None,
                   callbacks: Optional[List[Callable]] = None,
                   checkpoint_dir: Optional[str] = None,
                   checkpoint_every: int = 0,
                   checkpoint_keep_last: int = 3,
                   resume: str = "auto",
                   monitor_port: Optional[int] = None,
                   monitor_stall_timeout_s: Optional[float] = None
                   ) -> TrainResult:
    """Out-of-core boosting: the dataset lives in host RAM and streams
    through the device in fixed-shape tiles with double-buffered prefetch
    (Snap ML's host->HBM hierarchy, ``io.chunked``).  Nothing row-sized is
    ever resident on the device except the two live tiles, so the trainable
    dataset is bounded by host RAM, not HBM.

    Numerics contract (tested): bin edges come from a streaming quantile
    sketch (identical to the in-memory fit whenever the stream fits the
    sample budget); quantization scales come from a global max first pass
    over every tile, so each tile quantizes in IDENTICAL units and the
    per-tile int32 histogram partials accumulate bit-exactly to the
    monolithic build; split decisions therefore see the same integer sums
    either way.  The only divergence from ``train`` is the stochastic
    rounding noise (keyed per tile instead of per dataset), which is
    unbiased — end-to-end parity holds within the committed accuracy-gate
    precisions.

    Both grower families stream: ``growth="level"`` runs one accumulate ->
    decide -> route cycle per level (D passes over the tiles per tree);
    ``growth="leaf"`` rebuilds the split leaf's left child per step and
    derives the sibling by exact integer subtraction from a host-resident
    stored-histogram table (``num_leaves - 1`` passes per tree).

    ``X`` may be a raw ``(n, F)`` array or a prebuilt
    :class:`~mmlspark_tpu.io.chunked.ChunkedDataset` (then ``y``/``w`` ride
    its columns).  Tile size resolves from ``tile_rows`` /
    ``memory_budget_bytes`` / ``MMLSPARK_TPU_TILE_ROWS`` (see
    ``io.chunked.resolve_tile_rows``); prefetch overlap books into
    ``mmlspark_prefetch_wait_seconds`` / ``mmlspark_tile_compute_seconds``
    and is returned in ``TrainResult.extras``.

    Warm start: ``init_booster`` continues training from an existing
    single-output gbdt booster, matching ``train()`` — its trees replay on
    the host (exact integer walks + the same float32 score adds training
    performs), so continuation optimizes against the recorded scores.
    Binning must agree with the booster's (same dataset or same edge
    semantics, the ``train()`` contract).

    Fault tolerance (ISSUE 10): with ``checkpoint_dir`` set, the run
    snapshots its booster-so-far + iteration + host PRNG/bagging state
    atomically every ``checkpoint_every`` iterations (plus once at the
    end), serialization riding a background writer thread so device work
    never waits on disk; ``resume="auto"`` restores the newest VALID
    snapshot (a torn newest falls back to the previous one) and continues
    through the same replay machinery — the resumed run's booster is
    bit-identical to an uninterrupted one (the integer histogram path
    makes that exact; tested by the chaos harness).  SIGTERM/SIGINT
    during the loop requests one final checkpoint at the next iteration
    boundary and returns cleanly with ``extras["preempted"]`` set.
    ``resume="must"`` raises when no usable snapshot exists.

    Elastic resume (ISSUE 14): the snapshot's topology stanza records the
    tile geometry but is NOT identity — a resume may re-partition the row
    stream onto a different ``tile_rows``/``num_tiles`` (the change books
    ``mmlspark_reshard_total`` and sets ``extras["resharded"]``).  With
    quantized histograms the rounding noise is keyed per GLOBAL row, so
    the per-tile int32 partials accumulate to the same integers under any
    tiling and the resumed booster stays bit-identical to an
    uninterrupted run at either width (tested shrink and grow).

    Live monitoring (ISSUE 19): ``monitor_port`` (0 = ephemeral) serves
    ``GET /progress`` — step/ETA, rows/sec EWMA, loss tail, live tile
    overlap %, checkpoint age — plus ``/metrics`` and
    ``/debug/{dump,profile}`` for the duration of the loop; either monitor
    arg arms a stall watchdog whose ``train_stall`` flight dump captures
    the prefetch state a hung tile load leaves behind (see
    docs/OBSERVABILITY.md "Training plane").

    Not (yet) streamed: multiclass, lambdarank, dart/goss/rf, categorical
    features, and ``shard_rows`` (the multi-host composition — per-tile
    accumulation under ``collectives.histogram_psum(num_tiles=)`` — is
    exercised at the collective level; see docs/out_of_core.md).
    """
    import jax
    import jax.numpy as jnp
    from ..io.chunked import ChunkedDataset, TilePrefetcher, pad_tile
    from ..observability.compute import device_put as _obs_device_put
    from ..observability.tracing import (Span, ambient_phase, current_span,
                                         export_span)
    from ..ops import histogram as hist_ops

    if params is None:
        raise ValueError("params is required")
    p = params.resolve()
    if p.objective in ("lambdarank", "multiclass"):
        raise ValueError(f"streamed training does not support objective="
                         f"{p.objective!r} yet (see docs/out_of_core.md)")
    if p.boosting_type != "gbdt":
        raise ValueError("streamed training supports boosting_type='gbdt' "
                         f"only (got {p.boosting_type!r})")
    if p.categorical_features:
        raise ValueError("streamed training does not support categorical "
                         "features yet (see docs/out_of_core.md)")

    # ---- dataset geometry
    if isinstance(X, ChunkedDataset):
        cd = X
        if tile_rows is not None or memory_budget_bytes is not None:
            raise ValueError("tile sizing belongs to the ChunkedDataset "
                             "when one is passed directly")
        y = cd.columns.get("y") if y is None else np.asarray(y, np.float32)
        w = cd.columns.get("w")
        if w is not None and sample_weight is not None:
            raise ValueError("sample weights belong to the ChunkedDataset "
                             "('w' column) when one is passed directly")
    else:
        cd = ChunkedDataset(np.asarray(X, np.float32), tile_rows=tile_rows,
                            memory_budget_bytes=memory_budget_bytes)
        w = None
    if y is None:
        raise ValueError("labels are required (y= or a 'y' dataset column)")
    y = np.asarray(y, np.float32)
    n, F = cd.n_rows, cd.num_features
    T = cd.tile_rows
    if w is None:
        w = np.ones(n, np.float32) if sample_weight is None \
            else np.asarray(sample_weight, np.float32)
    if len(y) != n or len(w) != n:
        raise ValueError("X, y and sample_weight row counts disagree")
    if p.objective in ("poisson", "tweedie") and (y < 0).any():
        raise ValueError(f"objective {p.objective!r} requires non-negative "
                         "labels")
    if p.objective == "gamma" and (y <= 0).any():
        raise ValueError("objective 'gamma' requires strictly positive "
                         "labels")
    if init_booster is not None:
        # continuation guards, same raise-with-pointer shape as the other
        # streamed rejects: the streamed path is single-output numerical
        # gbdt, so only boosters of that shape can continue here
        if init_booster.num_class != 1 or init_booster.objective == "multiclass":
            raise ValueError(
                "streamed continuation supports single-output boosters only "
                f"(init_booster.num_class={init_booster.num_class}); use "
                "train() for multiclass continuation (docs/out_of_core.md)")
        if bool(getattr(init_booster, "average_output", False)):
            raise ValueError(
                "streamed training does not support rf-averaged boosters "
                "(boosting_type='rf' is not streamed; docs/out_of_core.md)")
        if getattr(init_booster, "categorical_features", None) \
                or getattr(init_booster, "cat_bitset", None) is not None:
            raise ValueError(
                "streamed training does not support categorical features "
                "yet, so a categorical booster cannot continue here "
                "(docs/out_of_core.md)")
        if int(init_booster.num_features) != F:
            raise ValueError(
                f"init_booster was trained on {init_booster.num_features} "
                f"features, dataset has {F}")

    # ---- backend / quantization resolution (same contract as train())
    hist_cfg = _resolve_hist_backend()
    hist_backend = hist_cfg[0]
    _uq = p.use_quantized_grad
    if hist_cfg[5].strip():
        _uq = hist_cfg[5].strip().lower() not in ("0", "false", "off", "no")
    if _uq is None:
        _uq = jax.default_backend() != "cpu"
    p = dataclasses.replace(p, use_quantized_grad=bool(_uq))
    use_quant = p.use_quantized_grad
    qb = p.num_grad_quant_bins
    qg_cap = max(1, qb // 2)
    qh_cap = max(1, qb - 1)
    _check_quant_tile_bound(use_quant, qb, n)
    sig = _params_sig(p) + (hist_cfg,)

    _parent = current_span()
    _span = Span("lightgbm.train_streamed",
                 trace_id=_parent.trace_id if _parent else None,
                 parent_id=_parent.span_id if _parent else None)

    # ---- streamed binning: sketch pass (host), then host uint8 tiles
    def _tile_chunks():
        for i in range(cd.num_tiles):
            lo, hi = cd.tile_slice(i)
            yield cd.X[lo:hi]

    with ambient_phase("ooc.binning"):
        mapper = BinMapper(p.max_bin).fit_streaming(_tile_chunks())
        B = mapper.num_bins
        binned_h = np.empty((n, F), np.uint8)
        for i in range(cd.num_tiles):
            lo, hi = cd.tile_slice(i)
            binned_h[lo:hi] = mapper.transform(cd.X[lo:hi])
    edges_np = mapper.edges
    edge_ok = np.concatenate(
        [np.isfinite(edges_np), np.zeros((F, 1), bool)], axis=1)
    edge_ok_dev = jnp.asarray(edge_ok)

    l1, l2 = p.lambda_l1, p.lambda_l2
    min_data = float(p.min_data_in_leaf)
    min_hess = p.min_sum_hessian_in_leaf
    min_gain = p.min_gain_to_split
    max_delta = p.max_delta_step
    lr = p.learning_rate
    objective = make_objective(p)
    D = p.depth_bound
    rng = np.random.default_rng(p.seed)

    def thresh(G):
        return jnp.sign(G) * jnp.maximum(jnp.abs(G) - l1, 0.0)

    def leaf_score(G, H):
        return thresh(G) ** 2 / (H + l2)

    def dehist(h_, gsc, hsc):
        if not use_quant:
            return h_
        return hist_ops.dequantize_histogram(h_, gsc, hsc)

    # ---- jitted per-tile kernels (ONE signature across all tiles: the
    # static tile shape is the point of ChunkedDataset)
    def _build_grad():
        def grad_tile(scores_t, y_t, w_t):
            g, h = objective(scores_t[:, None], y_t, w_t)
            return (g[:, 0], h[:, 0],
                    jnp.max(jnp.abs(g)), jnp.max(h))
        return instrumented_jit(grad_tile, name="lightgbm.ooc_grad")

    grad_fn = _cached(("ooc_grad", sig, T), _build_grad)

    def _build_accum():
        def accum(acc, b_t, g_t, h_t, node_t, ids_t, mixv, gsc, hsc):
            nodes_d = acc.shape[0]          # static at trace time
            if use_quant:
                # noise keyed per GLOBAL row id + one per-iteration mix
                # (elastic resume, ISSUE 14): a row quantizes identically
                # under ANY tile width, so per-tile int32 partials
                # accumulate to the same integers after a re-tiled resume
                qg, qh, _, _ = hist_ops.quantize_gradients(
                    g_t, h_t, qb, seed=p.seed, g_scale=gsc, h_scale=hsc,
                    row_ids=ids_t, mix=mixv)
                part = hist_ops.build_quantized(
                    b_t, qg, qh, node_t, nodes_d, B, quant_bins=qb,
                    backend=hist_backend, node_rows_bound=T)
            else:
                part = hist_ops.build(b_t, g_t, h_t, node_t, nodes_d, B,
                                      backend=hist_backend)
            return acc + part
        # level growth legitimately compiles one signature per level (the
        # acc node axis doubles: nodes_d = 1..2^(D-1)), so the storm
        # threshold scales with depth — the default 8 would book a false
        # recompile-storm on any healthy max_depth>=8 run
        return instrumented_jit(accum, donate_argnums=(0,),
                                name="lightgbm.ooc_tile_hist",
                                storm_signatures=D + 8)

    accum_fn = _cached(("ooc_accum", sig, F, B, T), _build_accum)

    def _build_decide():
        def decide(acc, gsc, hsc, fmask, eok):
            hist = dehist(acc, gsc, hsc)              # (nodes, F, B, 3)
            nodes_d = hist.shape[0]
            cum = jnp.cumsum(hist, axis=2)
            tot = cum[:, :1, -1, :]                   # (nodes, 1, 3)
            GL, HL, CL = cum[..., 0], cum[..., 1], cum[..., 2]
            Gp, Hp, Cp = tot[..., 0], tot[..., 1], tot[..., 2]
            GR, HR, CR = (Gp[:, :, None] - GL, Hp[:, :, None] - HL,
                          Cp[:, :, None] - CL)
            gain = (leaf_score(GL, HL) + leaf_score(GR, HR)
                    - leaf_score(Gp, Hp)[:, :, None])
            valid = ((CL >= min_data) & (CR >= min_data)
                     & (HL >= min_hess) & (HR >= min_hess)
                     & fmask[None, :, None] & eok[None])
            gain = jnp.where(valid, gain, -jnp.inf)
            flat = gain.reshape(nodes_d, F * B)
            best = jnp.argmax(flat, axis=1)
            best_gain = jnp.take_along_axis(flat, best[:, None],
                                            axis=1)[:, 0]
            bf = (best // B).astype(jnp.int32)
            bb = (best % B).astype(jnp.int32)
            do = best_gain > min_gain
            pick = jnp.stack([GL, HL, CL], axis=-1)
            left = pick[jnp.arange(nodes_d), bf, bb, :]
            tot3 = jnp.stack([Gp[:, 0], Hp[:, 0], Cp[:, 0]], axis=-1)
            left_stats = jnp.where(do[:, None], left, tot3)
            return bf, bb, do, best_gain, left_stats, tot3 - left_stats, tot3
        # one signature per level, like the accumulator above
        return instrumented_jit(decide, name="lightgbm.ooc_level_decide",
                                storm_signatures=D + 8)

    decide_fn = _cached(("ooc_decide", sig, F, B), _build_decide)

    def _build_leaf_best():
        def leaf_best(hist_f3, gsc, hsc, fmask, depth_ok, eok):
            hist = dehist(hist_f3, gsc, hsc)          # (F, B, 3)
            cum = jnp.cumsum(hist, axis=1)
            tot = cum[0, -1, :]
            GL, HL, CL = cum[..., 0], cum[..., 1], cum[..., 2]
            GR, HR, CR = tot[0] - GL, tot[1] - HL, tot[2] - CL
            gain = (leaf_score(GL, HL) + leaf_score(GR, HR)
                    - leaf_score(tot[0], tot[1]))
            valid = ((CL >= min_data) & (CR >= min_data)
                     & (HL >= min_hess) & (HR >= min_hess)
                     & fmask[:, None] & depth_ok & eok)
            gain = jnp.where(valid, gain, -jnp.inf)
            flat = gain.reshape(-1)
            best = jnp.argmax(flat)
            bf = (best // B).astype(jnp.int32)
            bb = (best % B).astype(jnp.int32)
            left = jnp.stack([GL, HL, CL], axis=-1)[bf, bb]
            return flat[best], bf, bb, left, tot
        return instrumented_jit(leaf_best, name="lightgbm.ooc_leaf_best")

    leaf_best_fn = _cached(("ooc_leaf_best", sig, F, B), _build_leaf_best)

    # ---- prefetch plumbing: payloads built AND placed on the worker
    # thread (routing for the next tile rides there too, overlapped with
    # the consumer's histogram dispatch on the current tile)
    OOC_SITE = "lightgbm.ooc_tile"
    stream_totals = {"wait_s": 0.0, "compute_s": 0.0, "tiles": 0.0}
    # the live prefetcher (one active pass at a time): /progress and the
    # train_stall flight dump read its snapshot() — a hung tile load shows
    # up as waiting=True with tiles_served frozen
    _live_pf: Dict[str, Optional[TilePrefetcher]] = {"pf": None}

    def _stream(make_tile):
        def load(i):
            # prefetch worker thread: attribute its samples to tile load,
            # distinct from the consumer's accumulate dispatch
            with ambient_phase("ooc.tile_load"):
                lo, hi = cd.tile_slice(i)
                host = make_tile(i, lo, hi)
                return (i, lo, hi, _obs_device_put(host, site=OOC_SITE))
        pf = TilePrefetcher(range(cd.num_tiles), load, site=OOC_SITE)
        _live_pf["pf"] = pf
        return pf

    def _finish_stream(pf):
        st = pf.overlap_stats()
        stream_totals["wait_s"] += st["wait_s"]
        stream_totals["compute_s"] += st["compute_s"]
        stream_totals["tiles"] += st["tiles"]

    def _prefetch_state() -> Dict[str, Any]:
        """Monitor-side view: cumulative overlap totals + the live pass."""
        busy = stream_totals["wait_s"] + stream_totals["compute_s"]
        d: Dict[str, Any] = {
            "wait_s": round(stream_totals["wait_s"], 6),
            "compute_s": round(stream_totals["compute_s"], 6),
            "tiles": stream_totals["tiles"],
            "overlap_pct": round(
                100.0 * stream_totals["compute_s"] / busy, 2)
            if busy > 0 else 100.0,
        }
        pf = _live_pf["pf"]
        if pf is not None:
            d["live"] = pf.snapshot()
        return d

    # ---- init score (same as train())
    init_score = 0.0
    if p.objective == "binary":
        pbar = float(np.clip(np.average(y, weights=w), 1e-6, 1 - 1e-6))
        init_score = math.log(pbar / (1 - pbar)) / p.sigmoid
    elif p.objective in ("regression", "huber"):
        init_score = float(np.average(y, weights=w))
    elif p.objective in ("poisson", "tweedie", "gamma"):
        init_score = float(np.log(max(np.average(y, weights=w), 1e-9)))
    elif p.objective == "regression_l1":
        init_score = float(np.median(y))
    scores_h = np.full((n,), init_score, np.float32)
    g_host = np.empty((n,), np.float32)
    h_host = np.empty((n,), np.float32)

    # ---- valid set (in-memory: the heldout set is driver-sized)
    metric_name = p.metric or default_metric(p.objective)
    metric_fn, larger_better = resolve_metric(metric_name, p)
    evals: List[Dict[str, float]] = []
    has_valid = valid is not None
    if has_valid:
        Xv = np.asarray(valid[0], np.float32)
        yv = np.asarray(valid[1], np.float32)
        binned_v_h = mapper.transform(Xv)   # host copy: resume replay walks
        binned_v = jnp.asarray(binned_v_h)
        scores_v = np.full((Xv.shape[0], 1), init_score, np.float32)
        walker = _cached(("walker", D, ()), lambda: make_binned_walker(D))
    best_metric = -np.inf if larger_better else np.inf
    best_iter = -1
    rounds_no_improve = 0

    level_growth = p.growth == "level"
    L = p.num_leaves                      # leaf slots
    I = L - 1                             # internal nodes
    if level_growth:
        from ..models.gbdt import perfect_tree_children
        lc_const, rc_const = perfect_tree_children(D)

    trees: Dict[str, List[np.ndarray]] = {k: [] for k in _STREAM_TREE_KEYS}
    tree_weights: List[float] = []
    bag_on = p.bagging_freq > 0 and p.bagging_fraction < 1.0
    ff_on = p.feature_fraction < 1.0
    mask_h = np.ones((n,), bool)
    bag_mask = None

    # ---- fault tolerance (ISSUE 10): periodic atomic checkpoints,
    # resume-through-replay, preemption-aware shutdown
    import contextlib
    from ..io.checkpoint import (CheckpointManager, book_reshard,
                                 check_resume_arg, resume_required_error,
                                 topology_stanza)
    from ..utils.resilience import PreemptionToken, preemption_scope
    # identity (must match) carries data/params only; the tile geometry is
    # the streamed driver's topology stanza — recorded, allowed to differ
    # on resume (elastic resume, ISSUE 14: the host that restarts a
    # preempted stream rarely has the old host-RAM budget)
    fingerprint = repr((sig, n, F, B, _content_fingerprint(cd.X)))
    _cur_topology = topology_stanza(shard_count=1,
                                    num_tiles=int(cd.num_tiles),
                                    tile_rows=int(T))
    check_resume_arg(resume, checkpoint_dir=checkpoint_dir)
    manager = None
    if checkpoint_dir:
        manager = CheckpointManager(checkpoint_dir,
                                    site="lightgbm.train_streamed",
                                    keep_last=checkpoint_keep_last)
    n_init_trees = 0
    start_iter = 0
    resumed_from = -1
    resharded = False
    preempted = False

    def _replay_range(t0: int, t1: int, valid_too: bool) -> None:
        """Replay stored trees [t0, t1) into the running scores with the
        EXACT float32 adds the live loop performs (host walks are pure
        integer ops), so a resumed run's state is bit-identical to the
        uninterrupted one's at the same iteration."""
        if t1 <= t0:
            return
        from ..models.gbdt import children_depth_bound
        depth_b = children_depth_bound(
            np.stack(trees["left_child"][t0:t1]),
            np.stack(trees["right_child"][t0:t1]))
        for t in range(t0, t1):
            sf_t, tb_t = trees["split_feature"][t], trees["threshold_bin"][t]
            lch_t, rch_t = trees["left_child"][t], trees["right_child"][t]
            lv_t = np.asarray(trees["leaf_value"][t], np.float32)
            w_t = float(tree_weights[t])
            leaf = _np_walk_tree(binned_h, sf_t, tb_t, lch_t, rch_t, depth_b)
            contrib = lv_t[leaf]
            if w_t != 1.0:
                contrib = (contrib * np.float32(w_t)).astype(np.float32)
            # in-place add (same ufunc the live loop's += runs) without
            # rebinding the closed-over array
            np.add(scores_h, contrib, out=scores_h)
            if valid_too and has_valid:
                leaf_v = _np_walk_tree(binned_v_h, sf_t, tb_t, lch_t, rch_t,
                                       depth_b)
                contrib_v = lv_t[leaf_v]
                if w_t != 1.0:
                    contrib_v = (contrib_v * np.float32(w_t)) \
                        .astype(np.float32)
                scores_v[:, 0] += contrib_v

    def _save_ckpt(finished: bool, block: bool = False) -> None:
        # snapshot on the training thread is just list copies + the PRNG
        # state dict; stacking + device-independent serialization + the
        # atomic publish all ride the manager's writer thread.  The one
        # completed-iteration convention (shared with train()): trees
        # grown beyond the warm-start prefix.
        done = len(tree_weights) - n_init_trees
        meta = _booster_ckpt_meta(done, n_init_trees, rng, best_metric,
                                  best_iter, rounds_no_improve, evals,
                                  init_score, fingerprint, finished,
                                  p.num_iterations, "streamed_booster_v1",
                                  topology=_cur_topology)
        manager.save(done, _booster_ckpt_arrays(trees, tree_weights,
                                                bag_mask), meta,
                     block=block)

    resumed = False
    if manager is not None and resume in ("auto", "must"):
        got = manager.load_latest(current_topology=_cur_topology)
        if got is None and resume == "must":
            raise resume_required_error(checkpoint_dir)
        if got is not None:
            _, _arrs, _meta = got
            if _meta.get("fingerprint") != fingerprint:
                raise ValueError(_CKPT_FINGERPRINT_MISMATCH)
            _delta = _meta.get("topology_delta")
            if _delta is not None and _delta["changed"]:
                # re-tiled resume: the row stream re-partitions onto this
                # run's tile geometry; with quantized histograms the
                # global-row-keyed rounding keeps the continued booster
                # bit-identical to an uninterrupted run at either width
                book_reshard("lightgbm.train_streamed", _delta)
                resharded = True
            T_done = int(_arrs["split_feature"].shape[0])
            for k in _STREAM_TREE_KEYS:
                trees[k] = [np.asarray(_arrs[k][t]) for t in range(T_done)]
            tree_weights[:] = [float(x) for x in _arrs["tree_weight"]]
            n_init_trees = int(_meta.get("n_init_trees", 0))
            rng.bit_generator.state = _meta["rng_state"]
            if "bag_mask" in _arrs:
                bag_mask = np.unpackbits(_arrs["bag_mask"])[:n].astype(bool)
            best_metric = float(_meta["best_metric"])
            best_iter = int(_meta["best_iter"])
            rounds_no_improve = int(_meta["rounds_no_improve"])
            evals[:] = [dict(e) for e in _meta.get("evals", [])]
            _replay_range(0, n_init_trees, valid_too=False)
            if float(_meta["init_score"]) != float(init_score):
                scores_h += np.float32(float(_meta["init_score"])
                                       - init_score)
                init_score = float(_meta["init_score"])
                if has_valid:
                    scores_v[:] = init_score
            _replay_range(n_init_trees, T_done, valid_too=True)
            resumed_from = int(_meta["iteration"])
            start_iter = resumed_from
            if _meta.get("finished") and \
                    p.num_iterations <= int(_meta.get("num_iterations",
                                                      resumed_from)):
                # the snapshot IS the finished run (early stop included):
                # skip the loop and return its booster; a LARGER
                # num_iterations target keeps training instead
                start_iter = p.num_iterations
            resumed = True
    if not resumed and init_booster is not None:
        # warm start (the substrate resume rides): replay the incoming
        # booster's trees on the host, matching train()'s machinery
        for t in range(init_booster.num_trees):
            for k in _STREAM_TREE_KEYS:
                trees[k].append(np.asarray(getattr(init_booster, k)[t]))
            tree_weights.append(float(init_booster.tree_weight[t]))
        n_init_trees = init_booster.num_trees
        _replay_range(0, n_init_trees, valid_too=False)
        if float(init_booster.init_score) != float(init_score):
            # shift base score AFTER replay (train() order), so continued
            # training optimizes against the recorded init_score
            scores_h += np.float32(init_booster.init_score - init_score)
            init_score = float(init_booster.init_score)
            if has_valid:
                scores_v[:] = init_score

    def _grad_pass():
        """First pass: gradients per tile (device), stored host-side, plus
        the GLOBAL grad/hess maxima every tile's quantization shares — the
        tile-level twin of the sharded pmax."""
        pf = _stream(lambda i, lo, hi: (pad_tile(scores_h, lo, hi, T),
                                        pad_tile(y, lo, hi, T),
                                        pad_tile(w, lo, hi, T)))
        gmax = hmax = 0.0
        with ambient_phase("ooc.gradients"):
            for i, lo, hi, (sc_t, y_t, w_t) in pf:
                g_t, h_t, gm, hm = grad_fn(sc_t, y_t, w_t)
                g_host[lo:hi] = np.asarray(g_t)[: hi - lo]
                h_host[lo:hi] = np.asarray(h_t)[: hi - lo]
                gmax = max(gmax, float(gm))
                hmax = max(hmax, float(hm))
        _finish_stream(pf)
        g_scale = max(gmax, 1e-12) / qg_cap
        h_scale = max(hmax, 1e-12) / qh_cap
        return float(g_scale), float(h_scale)

    def _route(lo, hi, bf, bb, do):
        """Host-side row routing (numerical splits): node -> 2*node + right,
        matching the level-wise grower's gather bit for bit."""
        node = node_h[lo:hi]
        f = np.maximum(bf[node], 0)
        rb = binned_h[lo:hi][np.arange(hi - lo), f].astype(np.int32)
        go_right = do[node] & (rb > bb[node])
        node_h[lo:hi] = 2 * node + go_right

    # per-iteration quantization mix (elastic resume): an exact-integer
    # fold of the HOST gradient arrays, so the value — and with it every
    # row's rounding noise — is identical under any tile width.  Written
    # once per iteration before the histogram passes read it.
    row_ids_h = np.arange(n, dtype=np.int32)
    _iter_mix = {"mix": np.int32(0)}

    def _hist_pass(nodes_d, gsc, hsc, decisions, node_of):
        """One accumulate pass over every tile: routing for this level
        (when ``decisions`` carries the previous level's splits) happens on
        the PREFETCH worker, then the consumer folds the tile's quantized
        partial into the int32 accumulator."""
        mixv = _iter_mix["mix"]

        def make_tile(i, lo, hi):
            if decisions is not None:
                _route(lo, hi, *decisions)
            node_t = np.where(mask_h[lo:hi], node_of(lo, hi),
                              -1).astype(np.int32)
            return (pad_tile(binned_h, lo, hi, T),
                    pad_tile(g_host, lo, hi, T),
                    pad_tile(h_host, lo, hi, T),
                    # node_t is already the slice: pad from its own origin
                    pad_tile(node_t, 0, hi - lo, T, fill=-1),
                    pad_tile(row_ids_h, lo, hi, T))
        acc = jnp.zeros((nodes_d, F, B, 3),
                        jnp.int32 if use_quant else jnp.float32)
        pf = _stream(make_tile)
        with ambient_phase("ooc.histogram"):
            for i, lo, hi, (b_t, g_t, h_t, n_t, i_t) in pf:
                acc = accum_fn(acc, b_t, g_t, h_t, n_t, i_t, mixv, gsc,
                               hsc)
        _finish_stream(pf)
        return acc

    # live monitor (ISSUE 19): one tick per boosting iteration.  The stall
    # watchdog covers the streamed passes too — a hung tile load freezes
    # the tick stream and trips as ``train_stall`` with the live
    # prefetcher snapshot showing ``waiting=True``.
    _watch = _wsrv = None
    if monitor_port is not None or monitor_stall_timeout_s is not None:
        from ..observability.trainwatch import start_training_monitor
        _watch, _wsrv = start_training_monitor(
            "lightgbm.train_streamed", total_steps=p.num_iterations,
            rows_per_step=n, monitor_port=monitor_port,
            stall_timeout_s=monitor_stall_timeout_s,
            driver="lightgbm.train_streamed")
        _watch.set_phase("boosting")
        _watch.set_prefetch_fn(_prefetch_state)

        def _watch_cb(i, ev, _w=_watch):
            val = None
            if ev:
                for k, v in ev.items():
                    if k != "iteration" and isinstance(v, (int, float)):
                        val = float(v)
                        break
            _w.tick(step=i + 1, loss=val)
        callbacks = list(callbacks or []) + [_watch_cb]

    # preemption scope only when checkpointing is on: without a durable
    # snapshot to write, a SIGTERM should keep its default behaviour
    _scope = preemption_scope() if manager is not None \
        else contextlib.nullcontext(PreemptionToken())
    _last_ckpt_iter = start_iter
    _trees_at_loop_start = len(tree_weights)
    with contextlib.ExitStack() as _stack:
      if _wsrv is not None:
          _stack.callback(_wsrv.stop)
      if _watch is not None:
          _stack.callback(_watch.close)
      _token = _stack.enter_context(_scope)
      if _watch is not None:
          _watch.set_preemption_token(_token)
      for it in range(start_iter, p.num_iterations):
        if _token.requested:
            # preempted: one final checkpoint at this iteration boundary,
            # then a clean partial return the caller can resume from
            _save_ckpt(finished=False, block=True)
            preempted = True
            break
        # ---- per-iteration host randomness (same semantics as train())
        feat_mask = np.ones((F,), bool)
        if ff_on:
            keep = max(1, int(round(p.feature_fraction * F)))
            feat_mask[:] = False
            feat_mask[rng.choice(F, size=keep, replace=False)] = True
        if bag_on and (it % p.bagging_freq == 0 or bag_mask is None):
            bag_mask = rng.random(n) < p.bagging_fraction
        mask_h = bag_mask if bag_on else np.ones((n,), bool)
        fm_dev = jnp.asarray(feat_mask)

        gsc, hsc = _grad_pass()
        if use_quant:
            _iter_mix["mix"] = _quant_mix(g_host, h_host)
        node_h = np.zeros((n,), np.int32)

        sf = np.full((I,), -1, np.int32)
        tb = np.zeros((I,), np.int32)
        th = np.zeros((I,), np.float32)
        sg = np.zeros((I,), np.float32)
        iv = np.zeros((I,), np.float32)
        ic = np.zeros((I,), np.float32)

        if level_growth:
            decisions = None
            for d in range(D):
                nodes_d = 2 ** d
                off = nodes_d - 1
                acc = _hist_pass(nodes_d, gsc, hsc, decisions,
                                 lambda lo, hi: node_h[lo:hi])
                bf_d, bb_d, do_d, gain_d, left_d, right_d, tot_d = [
                    np.asarray(a) for a in decide_fn(acc, gsc, hsc, fm_dev,
                                                     edge_ok_dev)]
                idx = off + np.arange(nodes_d)
                sf[idx] = np.where(do_d, bf_d, -1)
                tb[idx] = bb_d
                th[idx] = edges_np[bf_d, np.clip(bb_d, 0, B - 2)]
                sg[idx] = np.where(do_d, gain_d, 0.0)
                iv[idx] = _np_leaf_output(tot_d[:, 0], tot_d[:, 1], l1, l2,
                                          max_delta)
                ic[idx] = tot_d[:, 2]
                decisions = (bf_d, bb_d, do_d)
            # final routing (level D decisions) over the whole host array
            _route(0, n, *decisions)
            lv2 = np.stack([_np_leaf_output(left_d[:, 0], left_d[:, 1], l1,
                                            l2, max_delta),
                            _np_leaf_output(right_d[:, 0], right_d[:, 1],
                                            l1, l2, max_delta)],
                           axis=1).reshape(L)
            lc2 = np.stack([left_d[:, 2], right_d[:, 2]], axis=1).reshape(L)
            leaf_value = np.where(lc2 > 0, lv2, 0.0).astype(np.float32)
            leaf_count = lc2.astype(np.float32)
            leaf_of_row = node_h
            lch, rch = lc_const, rc_const
        else:
            (sf, tb, th, sg, iv, ic, leaf_value, leaf_count, lch, rch,
             leaf_of_row) = _grow_leafwise_streamed(
                p, n, F, B, T, D, gsc, hsc, fm_dev, edge_ok_dev, node_h,
                mask_h, binned_h, edges_np, _hist_pass, leaf_best_fn, l1,
                l2, max_delta)

        lv_s = (leaf_value * lr).astype(np.float32)
        scores_h += lv_s[leaf_of_row]
        for k_name, arr in zip(
                _STREAM_TREE_KEYS,
                (lch, rch, sf, th, tb, sg, iv, ic, lv_s, leaf_count)):
            trees[k_name].append(np.asarray(arr))
        tree_weights.append(1.0)

        if has_valid:
            with ambient_phase("ooc.eval"):
                leaf_v = np.asarray(walker(
                    binned_v, jnp.asarray(sf), jnp.asarray(tb),
                    jnp.asarray(np.asarray(lch, np.int32)),
                    jnp.asarray(np.asarray(rch, np.int32))))
                scores_v[:, 0] += lv_s[leaf_v]
                m = metric_fn(yv, scores_v.astype(np.float64))
            evals.append({metric_name: m, "iteration": it})
            improved = m > best_metric if larger_better else m < best_metric
            if improved:
                best_metric, best_iter, rounds_no_improve = m, it, 0
            else:
                rounds_no_improve += 1
            if p.early_stopping_round > 0 and \
                    rounds_no_improve >= p.early_stopping_round:
                break
        if callbacks:
            for cb in callbacks:
                cb(it, evals[-1] if evals else None)
        if manager is not None and checkpoint_every > 0 \
                and it + 1 - _last_ckpt_iter >= checkpoint_every:
            _save_ckpt(finished=False)
            _last_ckpt_iter = it + 1

    if manager is not None:
        if not preempted and (len(tree_weights) > _trees_at_loop_start
                              or not resumed):
            # terminal snapshot (covers early stopping too): resume of a
            # finished run restores the final booster instead of
            # re-training the tail.  A finished-run restore that grew
            # nothing skips the redundant re-save.
            _save_ckpt(finished=True, block=True)
        manager.close()

    if p.growth == "leaf":
        from ..models.gbdt import children_depth_bound
        D = children_depth_bound(np.stack(trees["left_child"]),
                                 np.stack(trees["right_child"]))
    booster = GBDTBooster(
        np.stack(trees["split_feature"]), np.stack(trees["threshold"]),
        np.stack(trees["threshold_bin"]), np.stack(trees["split_gain"]),
        np.stack(trees["internal_value"]),
        np.stack(trees["internal_count"]),
        np.stack(trees["leaf_value"]), np.stack(trees["leaf_count"]),
        np.asarray(tree_weights, np.float32),
        left_child=np.stack(trees["left_child"]),
        right_child=np.stack(trees["right_child"]),
        max_depth=D, num_features=F, objective=p.objective, num_class=1,
        init_score=init_score, feature_names=feature_names,
        best_iteration=best_iter, sigmoid=p.sigmoid)

    busy = stream_totals["wait_s"] + stream_totals["compute_s"]
    extras = {
        "num_tiles": float(cd.num_tiles), "tile_rows": float(T),
        "prefetch_wait_s": round(stream_totals["wait_s"], 6),
        "tile_compute_s": round(stream_totals["compute_s"], 6),
        "tiles_streamed": stream_totals["tiles"],
        "prefetch_overlap_pct": round(
            100.0 * stream_totals["compute_s"] / busy, 2) if busy > 0
        else 100.0,
        "quantized": float(use_quant),
    }
    if manager is not None:
        extras["preempted"] = float(preempted)
        extras["resumed_from_iteration"] = float(resumed_from)
        extras["checkpoint_saves"] = float(manager.saves_ok)
        extras["resharded"] = float(resharded)
    for k, v in extras.items():
        _span.set_attribute(f"ooc.{k}", v)
    _span.set_attribute("rows", n)
    _span.set_attribute("features", F)
    _span.set_attribute("iterations", len(tree_weights))
    export_span(_span)
    return TrainResult(booster=booster, evals=evals, bin_mapper=mapper,
                       extras=extras)


def _grow_leafwise_streamed(p, n, F, B, T, depth_bound, gsc, hsc, fm_dev,
                            edge_ok_dev, node_h, mask_h, binned_h, edges_np,
                            hist_pass, leaf_best_fn, l1, l2, max_delta):
    """One leaf-wise tree over the tile stream: LightGBM's best-first
    growth with the histogram passes streamed.  Per split step the LEFT
    child's histogram is rebuilt with one accumulate pass over every tile
    (``hist_pass`` with a single node) and the sibling comes from exact
    integer subtraction against a host-resident stored-histogram table —
    the same histogram-halving the in-memory grower runs, with the storage
    moved off-device (out-of-core all the way down).  Bookkeeping mirrors
    ``make_leafwise_grower.step`` in host numpy; a step whose best gain
    fails ``min_gain_to_split`` ends the tree (later steps could only see
    smaller global-best gains)."""
    import jax.numpy as jnp

    L, M = p.num_leaves, p.num_leaves - 1
    depth_cap = p.max_depth
    min_gain = p.min_gain_to_split
    stored = np.zeros((L, F, B, 3),
                      np.int32 if p.use_quantized_grad else np.float32)

    lc_arr = np.full((M,), -1, np.int32)
    rc_arr = np.full((M,), -1, np.int32)
    sf = np.full((M,), -1, np.int32)
    tb = np.zeros((M,), np.int32)
    th = np.zeros((M,), np.float32)
    sg = np.zeros((M,), np.float32)
    iv = np.zeros((M,), np.float32)
    ic = np.zeros((M,), np.float32)
    leaf_tot = np.zeros((L, 3), np.float32)
    leaf_depth = np.zeros((L,), np.int32)
    created = np.zeros((L,), bool)
    created[0] = True
    leaf_parent = np.full((L,), -1, np.int32)
    leaf_side = np.zeros((L,), np.int32)
    best_gain = np.full((L,), -np.inf, np.float32)
    best_feat = np.zeros((L,), np.int32)
    best_bin = np.zeros((L,), np.int32)
    best_left = np.zeros((L, 3), np.float32)

    def depth_ok_of(d):
        return True if depth_cap <= 0 else bool(d < depth_cap)

    def candidates(hist_np, slot, dok):
        g, f, b, left, tot = leaf_best_fn(jnp.asarray(hist_np), gsc, hsc,
                                          fm_dev, dok, edge_ok_dev)
        best_gain[slot] = float(g)
        best_feat[slot] = int(f)
        best_bin[slot] = int(b)
        best_left[slot] = np.asarray(left)
        return np.asarray(tot)

    # root: one streamed pass with a single node id
    h_root = np.asarray(hist_pass(1, gsc, hsc, None,
                                  lambda lo, hi: np.zeros((hi - lo,),
                                                          np.int32)))[0]
    stored[0] = h_root
    leaf_tot[0] = candidates(h_root, 0, depth_ok_of(0))

    for s in range(M):
        j = int(np.argmax(best_gain))
        if not best_gain[j] > min_gain:
            break
        new_leaf = s + 1
        f, b = int(best_feat[j]), int(best_bin[j])
        tot = leaf_tot[j].copy()

        sf[s] = f
        tb[s] = b
        th[s] = edges_np[f, min(max(b, 0), B - 2)]
        sg[s] = best_gain[j]
        iv[s] = _np_leaf_output(tot[0:1], tot[1:2], l1, l2, max_delta)[0]
        ic[s] = tot[2]

        pn, side = leaf_parent[j], leaf_side[j]
        if pn >= 0:
            (lc_arr if side == 0 else rc_arr)[pn] = s
        lc_arr[s] = -(j + 1)
        rc_arr[s] = -(new_leaf + 1)
        leaf_parent[j], leaf_side[j] = s, 0
        leaf_parent[new_leaf], leaf_side[new_leaf] = s, 1
        created[new_leaf] = True

        # route leaf j's rows (whole host array: one vectorized pass)
        in_j = node_h == j
        go_right = in_j & (binned_h[:, f].astype(np.int32) > b)
        node_h[go_right] = new_leaf

        left_stats = best_left[j].copy()
        leaf_tot[j] = left_stats
        leaf_tot[new_leaf] = tot - left_stats
        d_new = leaf_depth[j] + 1
        leaf_depth[j] = leaf_depth[new_leaf] = d_new

        # left child rebuilt over the stream; sibling by exact subtraction
        hl = np.asarray(hist_pass(
            1, gsc, hsc, None,
            lambda lo, hi: np.where(node_h[lo:hi] == j, 0, -1)
            .astype(np.int32)))[0]
        hr = stored[j] - hl
        stored[j], stored[new_leaf] = hl, hr

        dok = depth_ok_of(d_new)
        candidates(hl, j, dok)
        candidates(hr, new_leaf, dok)

    lv = _np_leaf_output(leaf_tot[:, 0], leaf_tot[:, 1], l1, l2, max_delta)
    leaf_value = np.where(created, lv, 0.0).astype(np.float32)
    leaf_count = np.where(created, leaf_tot[:, 2], 0.0).astype(np.float32)
    return (sf, tb, th, sg, iv, ic, leaf_value, leaf_count, lc_arr, rc_arr,
            node_h.copy())
