"""GBDT training core — level-wise tree growth as a jitted XLA program.

Reference hot path: ``TrainUtils.trainCore`` (``TrainUtils.scala:92-159``)
calls ``LGBM_BoosterUpdateOneIter`` per iteration — native histogram build +
socket allreduce + split finding.  TPU-native, one boosting iteration is a
single jitted function:

  histograms  = one fused segment-sum scatter   (ops.histogram)       [VPU]
  split find  = cumsum + argmax over (node, feature, bin)             [VPU]
  routing     = gather of each row's split decision                   [VPU]
  ... repeated depth-wise (python loop over static depth => unrolled XLA)

Across data shards the histogram tensors are psum'd over the mesh's ``data``
axis (GSPMD inserts the collective from sharding annotations) — this replaces
LightGBM's ``data_parallel`` TCP-ring allreduce.  ``voting_parallel``'s top-K
trick is unnecessary on ICI (histogram psum is bandwidth-cheap relative to
HBM traffic) but the param is accepted for API parity.

Supports the reference's boosting modes (``boosting_type`` gbdt/rf/dart/goss,
``params/TrainParams.scala``), objectives, bagging, feature_fraction, L1/L2,
min_data_in_leaf, early stopping, and warm start from an existing booster.
"""
from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..models.gbdt import GBDTBooster
from ..ops.histogram import build_histograms
from .binning import BinMapper


@dataclasses.dataclass
class GBDTParams:
    num_iterations: int = 100
    learning_rate: float = 0.1
    max_depth: int = 5               # 2^5 = 32 leaves ~ LightGBM num_leaves=31
    num_leaves: Optional[int] = None  # accepted for parity; sets max_depth
    max_bin: int = 255
    objective: str = "binary"
    num_class: int = 1
    boosting_type: str = "gbdt"      # gbdt | rf | dart | goss
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    min_gain_to_split: float = 0.0
    bagging_fraction: float = 1.0
    bagging_freq: int = 0
    feature_fraction: float = 1.0
    # goss
    top_rate: float = 0.2
    other_rate: float = 0.1
    # dart
    drop_rate: float = 0.1
    max_drop: int = 50
    skip_drop: float = 0.5
    # misc
    max_delta_step: float = 0.0
    sigmoid: float = 1.0
    alpha: float = 0.9               # huber / quantile
    tweedie_variance_power: float = 1.5  # tweedie: 1 (poisson) .. 2 (gamma)
    early_stopping_round: int = 0
    metric: str = ""
    seed: int = 0
    verbosity: int = -1
    # one-vs-rest categorical splits (reference getCategoricalIndexes,
    # LightGBMBase.scala:168): these feature indices bin by CATEGORY CODE
    # and split as code == c vs rest (LightGBM's max_cat_to_onehot mode)
    categorical_features: Optional[Tuple[int, ...]] = None
    # voting-parallel (reference parallelism=voting_parallel + topK,
    # TrainParams.scala:11-12): each shard votes its local top-k features
    # per node; only the global top-2k features' histograms are allreduced,
    # cutting ICI traffic from O(F*B) to O(k*B) per node on wide data.
    # 0 = full histogram psum (data_parallel).
    voting_k: int = 0

    def resolve(self) -> "GBDTParams":
        p = dataclasses.replace(self)
        if p.num_leaves:
            p.max_depth = max(1, int(math.ceil(math.log2(max(2, p.num_leaves)))))
        if p.boosting_type == "rf" and p.bagging_freq == 0:
            p.bagging_freq, p.bagging_fraction = 1, min(p.bagging_fraction, 0.632)
        return p


# ---------------------------------------------------------------------------
# objectives: (scores, y, w) -> grad, hess     [all jitted, (n,K) scores]
# ---------------------------------------------------------------------------

def make_objective(params: GBDTParams) -> Callable:
    import jax.numpy as jnp
    obj, K = params.objective, params.num_class
    sig, alpha = params.sigmoid, params.alpha

    def binary(scores, y, w):
        p = 1.0 / (1.0 + jnp.exp(-sig * scores[:, 0]))
        g = sig * (p - y)
        h = jnp.maximum(sig * sig * p * (1.0 - p), 1e-16)
        return (g * w)[:, None], (h * w)[:, None]

    def multiclass(scores, y, w):
        z = scores - scores.max(axis=1, keepdims=True)
        e = jnp.exp(z)
        p = e / e.sum(axis=1, keepdims=True)
        onehot = (y[:, None] == jnp.arange(K)[None, :]).astype(p.dtype)
        g = p - onehot
        h = jnp.maximum(2.0 * p * (1.0 - p), 1e-16)
        return g * w[:, None], h * w[:, None]

    def l2(scores, y, w):
        g = scores[:, 0] - y
        return (g * w)[:, None], (w * jnp.ones_like(g))[:, None]

    def l1(scores, y, w):
        g = jnp.sign(scores[:, 0] - y)
        return (g * w)[:, None], (w * jnp.ones_like(g))[:, None]

    def huber(scores, y, w):
        d = scores[:, 0] - y
        g = jnp.clip(d, -alpha, alpha)
        return (g * w)[:, None], (w * jnp.ones_like(g))[:, None]

    def quantile(scores, y, w):
        d = scores[:, 0] - y
        g = jnp.where(d >= 0, 1.0 - alpha, -alpha)
        return (g * w)[:, None], (w * jnp.ones_like(g))[:, None]

    def poisson(scores, y, w):
        # log link: raw score s models log(mean); nll grad = exp(s) - y
        mu = jnp.exp(jnp.clip(scores[:, 0], -30.0, 30.0))
        g = mu - y
        h = jnp.maximum(mu, 1e-16)
        return (g * w)[:, None], (h * w)[:, None]

    rho = params.tweedie_variance_power

    def tweedie(scores, y, w):
        # compound-Poisson deviance with log link, variance power rho in
        # (1, 2): grad = -y*e^{(1-rho)s} + e^{(2-rho)s}
        sarr = jnp.clip(scores[:, 0], -30.0, 30.0)
        a = jnp.exp((1.0 - rho) * sarr)
        b = jnp.exp((2.0 - rho) * sarr)
        g = -y * a + b
        h = jnp.maximum(-(1.0 - rho) * y * a + (2.0 - rho) * b, 1e-16)
        return (g * w)[:, None], (h * w)[:, None]

    def gamma(scores, y, w):
        # gamma nll with log link: grad = 1 - y*e^{-s}, hess = y*e^{-s}
        e = jnp.exp(-jnp.clip(scores[:, 0], -30.0, 30.0))
        g = 1.0 - y * e
        h = jnp.maximum(y * e, 1e-16)
        return (g * w)[:, None], (h * w)[:, None]

    table = {"binary": binary, "multiclass": multiclass, "regression": l2,
             "regression_l1": l1, "huber": huber, "quantile": quantile,
             "poisson": poisson, "tweedie": tweedie, "gamma": gamma}
    if obj not in table and obj != "lambdarank":
        raise ValueError(f"unknown objective {obj!r}")
    return table.get(obj)


def make_lambdarank_grad_fn(y: np.ndarray, group_ptr: np.ndarray,
                            sigmoid: float = 1.0):
    """Device-resident LambdaRank gradients with |ΔNDCG| weighting.

    Padded-group tensorization: groups packed to (Q, Gmax) so the pairwise
    (Q, Gmax, Gmax) lambda computation is one jitted einsum-like pass —
    the XLA-friendly reshape of the reference's per-query C++ loops.

    The pack/unpack is INDEX GATHERS built once on host: the returned
    ``fn(scores_dev) -> (g, h)`` stays entirely on device, so the boosting
    loop pays zero host round trips per iteration (round-1 weak item 5:
    the old path re-packed numpy groups every iteration).
    """
    import jax
    import jax.numpy as jnp

    n = len(y)
    q = len(group_ptr) - 1
    gmax = int(max(group_ptr[i + 1] - group_ptr[i] for i in range(q)))
    pack_idx = np.zeros((q, gmax), np.int32)   # slot -> row (0 on padding)
    M_np = np.zeros((q, gmax), np.float32)
    row_q = np.zeros(n, np.int32)              # row -> (group, slot)
    row_slot = np.zeros(n, np.int32)
    covered_np = np.zeros(n, bool)             # rows outside group_ptr get 0
    for i in range(q):
        a, b = group_ptr[i], group_ptr[i + 1]
        pack_idx[i, : b - a] = np.arange(a, b)
        M_np[i, : b - a] = 1.0
        row_q[a:b] = i
        row_slot[a:b] = np.arange(b - a)
        covered_np[a:b] = True
    Y = jnp.asarray(np.asarray(y, np.float32)[pack_idx] * M_np)
    M = jnp.asarray(M_np)
    pack = jnp.asarray(pack_idx)
    rq, rs = jnp.asarray(row_q), jnp.asarray(row_slot)
    covered = jnp.asarray(covered_np)

    @jax.jit
    def fn(scores):
        S = scores[:, 0][pack] * M
        gain = (2.0 ** Y - 1.0) * M
        order = jnp.argsort(-jnp.where(M > 0, S, -jnp.inf), axis=1)
        ranks = jnp.argsort(order, axis=1).astype(jnp.float32)  # 0-based rank
        disc = 1.0 / jnp.log2(ranks + 2.0)
        ideal_gain = -jnp.sort(-gain, axis=1)
        ideal_disc = 1.0 / jnp.log2(jnp.arange(gmax, dtype=jnp.float32) + 2.0)
        idcg = jnp.sum(ideal_gain * ideal_disc, axis=1, keepdims=True)
        idcg = jnp.maximum(idcg, 1e-9)
        sdiff = S[:, :, None] - S[:, None, :]
        rho = 1.0 / (1.0 + jnp.exp(sigmoid * sdiff))      # P(j beats i)
        better = (Y[:, :, None] > Y[:, None, :]) & (M[:, :, None] > 0) & (M[:, None, :] > 0)
        delta_ndcg = jnp.abs(
            (gain[:, :, None] - gain[:, None, :]) *
            (disc[:, :, None] - disc[:, None, :])) / idcg[:, :, None]
        lam_ij = jnp.where(better, -sigmoid * rho * delta_ndcg, 0.0)
        hess_ij = jnp.where(better, sigmoid * sigmoid * rho * (1 - rho) * delta_ndcg, 0.0)
        G = jnp.sum(lam_ij, axis=2) - jnp.sum(lam_ij, axis=1)
        H = jnp.maximum(jnp.sum(hess_ij, axis=2) + jnp.sum(hess_ij, axis=1), 1e-16)
        # unpack by gather: row -> its (group, slot) cell; rows not covered
        # by group_ptr stay inert (g=0, h~0), matching the scatter unpack
        g_row = jnp.where(covered, G[rq, rs], 0.0)
        h_row = jnp.where(covered, H[rq, rs], 1e-16)
        return g_row[:, None], h_row[:, None]

    return fn


def lambdarank_grads(scores: np.ndarray, y: np.ndarray, group_ptr: np.ndarray,
                     sigmoid: float = 1.0, trunc: int = 30) -> Tuple[np.ndarray, np.ndarray]:
    """One-shot host-facing wrapper over ``make_lambdarank_grad_fn``."""
    import jax.numpy as jnp
    fn = make_lambdarank_grad_fn(y, group_ptr, sigmoid)
    g, h = fn(jnp.asarray(np.asarray(scores, np.float32).reshape(len(y), -1)))
    return np.asarray(g), np.asarray(h)


# ---------------------------------------------------------------------------
# jit caches: reusing compiled programs across train() calls saves the ~60-90s
# XLA compile on every fit (closures would otherwise defeat jit's cache)
# ---------------------------------------------------------------------------

_JIT_CACHE: Dict[tuple, object] = {}


def _params_sig(p: "GBDTParams") -> tuple:
    return (p.max_depth, p.max_bin, p.objective, p.num_class, p.boosting_type,
            p.learning_rate, p.lambda_l1, p.lambda_l2, p.min_data_in_leaf,
            p.min_sum_hessian_in_leaf, p.min_gain_to_split, p.max_delta_step,
            p.sigmoid, p.alpha, p.tweedie_variance_power,
            p.top_rate, p.other_rate, p.feature_fraction,
            p.bagging_fraction, p.bagging_freq,
            tuple(p.categorical_features or ()), p.voting_k)


def _cached(key, builder):
    fn = _JIT_CACHE.get(key)
    if fn is None:
        fn = builder()
        _JIT_CACHE[key] = fn
    return fn


# ---------------------------------------------------------------------------
# tree grower
# ---------------------------------------------------------------------------

def make_tree_grower(max_depth: int, num_features: int, num_bins: int,
                     params: GBDTParams, axis_name: str = None,
                     backend: str = "auto"):
    """Returns grow(binned, grad, hess, hist_mask, feat_mask, edges)
    -> (tree arrays..., leaf_of_row).  With `axis_name`, the function is
    meant to run inside shard_map over row shards: local histograms are
    psum'd over that mesh axis (the LGBM_NetworkInit ring replacement) and
    all split decisions replicate deterministically across shards."""
    import jax
    import jax.numpy as jnp
    from ..ops import histogram as hist_ops

    def hist(binned, g, h, node, num_nodes):
        out = hist_ops.build(binned, g, h, node, num_nodes, num_bins,
                             backend=backend)
        if axis_name is not None:
            out = jax.lax.psum(out, axis_name)
        return out

    D, F, B = max_depth, num_features, num_bins
    I = 2 ** D - 1     # internal nodes
    L = 2 ** D         # leaves
    cat_np = np.zeros((F,), bool)
    if params.categorical_features:
        cat_np[list(params.categorical_features)] = True
    has_cat = bool(cat_np.any())
    l1, l2 = params.lambda_l1, params.lambda_l2
    min_data = float(params.min_data_in_leaf)
    min_hess = params.min_sum_hessian_in_leaf
    min_gain = params.min_gain_to_split
    max_delta = params.max_delta_step

    def thresh(G):
        return jnp.sign(G) * jnp.maximum(jnp.abs(G) - l1, 0.0)

    def leaf_score(G, H):
        return thresh(G) ** 2 / (H + l2)

    def leaf_output(G, H):
        v = -thresh(G) / (H + l2)
        if max_delta > 0:
            v = jnp.clip(v, -max_delta, max_delta)
        return v

    def grow(binned, grad, hess, hist_mask, feat_mask, edges):
        n = binned.shape[0]
        node = jnp.zeros((n,), jnp.int32)          # level-local node, all rows
        split_feature = jnp.full((I,), -1, jnp.int32)
        threshold_bin = jnp.zeros((I,), jnp.int32)
        threshold = jnp.zeros((I,), jnp.float32)
        split_gain = jnp.zeros((I,), jnp.float32)
        internal_value = jnp.zeros((I,), jnp.float32)
        internal_count = jnp.zeros((I,), jnp.float32)

        cat_b = jnp.asarray(cat_np)
        edge_finite = jnp.concatenate(
            [jnp.isfinite(edges), jnp.zeros((F, 1), bool)], axis=1)[None, :, :]
        if has_cat:
            # every bin of a categorical feature is a candidate code EXCEPT
            # the last: BinMapper reserves bin max_bin-1 for NaN/overflow,
            # and a split on it would route missing rows left at train but
            # right at predict (x != code with NaN -> right)
            cat_cand = cat_b[None, :, None] & \
                (jnp.arange(B) != B - 1)[None, None, :]
            edge_finite = edge_finite | cat_cand
        def split_gains(hist_d, fmask2, edge3, catm2):
            """(nodes, Fs, B, 3) histograms -> (gain, left-stat pick, node
            totals).  LEFT-child stats: numerical split at t takes bins <= t
            (the cumsum); categorical one-vs-rest at code c takes bin c alone
            (the histogram itself).  ``fmask2``/``catm2`` broadcast over
            (nodes, Fs); ``edge3`` over (nodes, Fs, B)."""
            cum = jnp.cumsum(hist_d, axis=2)
            tot = cum[:, :1, -1, :]                 # (nodes,1,3) totals
            left3 = jnp.where(catm2[:, :, None, None], hist_d, cum) \
                if has_cat else cum
            GL, HL, CL = left3[..., 0], left3[..., 1], left3[..., 2]
            Gp, Hp, Cp = tot[..., 0], tot[..., 1], tot[..., 2]
            GR, HR, CR = (Gp[:, :, None] - GL, Hp[:, :, None] - HL,
                          Cp[:, :, None] - CL)
            gain = (leaf_score(GL, HL) + leaf_score(GR, HR)
                    - leaf_score(Gp, Hp)[:, :, None])
            # split at bin t => left: bins<=t, right: bins>t; needs a finite
            # edge (last bin and inf-padded pseudo-bins can't split)
            valid = ((CL >= min_data) & (CR >= min_data)
                     & (HL >= min_hess) & (HR >= min_hess)
                     & fmask2[:, :, None] & edge3)
            gain = jnp.where(valid, gain, -jnp.inf)
            pick = jnp.stack([GL, HL, CL], axis=-1)  # (nodes,Fs,B,3)
            return gain, pick, (Gp[:, 0], Hp[:, 0], Cp[:, 0])

        voting_k = params.voting_k
        # voting engages whenever it's requested and meaningful (F > k);
        # when 2k >= F the vote selects every feature — zero comm saving but
        # identical results, which the equality test exploits
        use_voting = axis_name is not None and 0 < voting_k < F
        prev_hist = None
        best_stats = None
        for d in range(D):
            nodes_d = 2 ** d
            off = nodes_d - 1                       # BFS offset of this level
            if use_voting:
                # voting-parallel (reference voting_parallel + topK): each
                # shard ranks features by LOCAL gain, shards vote, and only
                # the global top-2k features' histograms cross the mesh —
                # O(k*B) comm instead of O(F*B).  Sibling subtraction stays
                # valid on the PRE-psum local histograms (local_right =
                # local_parent - local_left).
                if d == 0:
                    local = hist_ops.build(binned, grad, hess,
                                           jnp.where(hist_mask, node, -1), 1,
                                           num_bins, backend=backend)
                else:
                    left_node = jnp.where(hist_mask & (node % 2 == 0),
                                          node // 2, -1)
                    left_local = hist_ops.build(binned, grad, hess, left_node,
                                                nodes_d // 2, num_bins,
                                                backend=backend)
                    local = jnp.stack([left_local, prev_hist - left_local],
                                      axis=1).reshape(nodes_d, F, B, 3)
                prev_hist = local
                gain_l, _, _ = split_gains(local, feat_mask[None, :],
                                           edge_finite, cat_b[None, :])
                per_feat = gain_l.max(axis=2)        # (nodes, F) local best
                top_gain, top_local = jax.lax.top_k(per_feat, voting_k)
                # a shard with fewer than k locally-valid candidates must not
                # cast spurious ballots for the tie-broken low-index features
                ballot = (top_gain > -jnp.inf).astype(jnp.float32)
                votes = jnp.zeros((nodes_d, F)).at[
                    jnp.arange(nodes_d)[:, None], top_local].add(ballot)
                votes = jax.lax.psum(votes, axis_name)
                k2 = min(2 * voting_k, F)
                _, sel = jax.lax.top_k(votes, k2)    # (nodes, k2) global pick
                sel_hist = jnp.take_along_axis(
                    local, sel[:, :, None, None], axis=1)
                sel_hist = jax.lax.psum(sel_hist, axis_name)
                edge3 = jnp.take_along_axis(
                    jnp.broadcast_to(edge_finite, (nodes_d, F, B)),
                    sel[:, :, None], axis=1)
                gain, pick, (Gp0, Hp0, Cp0) = split_gains(
                    sel_hist, feat_mask[sel], edge3, cat_b[sel])
                Fs = k2
            else:
                if d == 0:
                    hist_d = hist(binned, grad, hess,
                                  jnp.where(hist_mask, node, -1), 1)
                else:
                    # sibling-subtraction (LightGBM's histogram halving):
                    # scatter only rows in LEFT children, right = parent - left
                    left_node = jnp.where(hist_mask & (node % 2 == 0),
                                          node // 2, -1)
                    hist_left = hist(binned, grad, hess, left_node, nodes_d // 2)
                    hist_right = prev_hist - hist_left
                    hist_d = jnp.stack([hist_left, hist_right], axis=1) \
                        .reshape(nodes_d, F, B, 3)
                prev_hist = hist_d
                gain, pick, (Gp0, Hp0, Cp0) = split_gains(
                    hist_d, feat_mask[None, :], edge_finite, cat_b[None, :])
                sel = None
                Fs = F

            flat = gain.reshape(nodes_d, Fs * B)
            best = jnp.argmax(flat, axis=1)
            best_gain = jnp.take_along_axis(flat, best[:, None], axis=1)[:, 0]
            bf_local = (best // B).astype(jnp.int32)
            bb = (best % B).astype(jnp.int32)
            bf = sel[jnp.arange(nodes_d), bf_local] if sel is not None else bf_local
            do_split = best_gain > min_gain

            idx = off + jnp.arange(nodes_d)
            split_feature = split_feature.at[idx].set(jnp.where(do_split, bf, -1))
            threshold_bin = threshold_bin.at[idx].set(bb)
            thr_raw = edges[bf, jnp.clip(bb, 0, B - 2)]
            if has_cat:  # categorical: the raw threshold IS the category code
                thr_raw = jnp.where(cat_b[bf], bb.astype(jnp.float32), thr_raw)
            threshold = threshold.at[idx].set(thr_raw)
            split_gain = split_gain.at[idx].set(jnp.where(do_split, best_gain, 0.0))
            internal_value = internal_value.at[idx].set(leaf_output(Gp0, Hp0))
            internal_count = internal_count.at[idx].set(Cp0)

            # left/right child stats at the chosen split -> leaf values at the
            # last level come straight from here (no extra leaf pass)
            bsel = pick[jnp.arange(nodes_d), bf_local, bb, :]  # (nodes,3) left
            tot3 = jnp.stack([Gp0, Hp0, Cp0], axis=-1)
            left_stats = jnp.where(do_split[:, None], bsel, tot3)
            right_stats = tot3 - left_stats
            best_stats = (left_stats, right_stats, do_split, tot3)

            # route all rows (bagged-out rows too: they need leaf ids for scores)
            f_of_row = bf[node]
            t_of_row = bb[node]
            s_of_row = do_split[node]
            row_bin = binned[jnp.arange(n), jnp.maximum(f_of_row, 0)].astype(jnp.int32)
            if has_cat:
                right_dec = jnp.where(cat_b[jnp.maximum(f_of_row, 0)],
                                      row_bin != t_of_row, row_bin > t_of_row)
            else:
                right_dec = row_bin > t_of_row
            go_right = s_of_row & right_dec
            node = 2 * node + go_right.astype(jnp.int32)

        # leaves: children of the last level's nodes
        left_stats, right_stats, do_split, tot3 = best_stats
        lv = jnp.stack([leaf_output(left_stats[:, 0], left_stats[:, 1]),
                        leaf_output(right_stats[:, 0], right_stats[:, 1])],
                       axis=1).reshape(L)
        lc = jnp.stack([left_stats[:, 2], right_stats[:, 2]], axis=1).reshape(L)
        leaf_value = jnp.where(lc > 0, lv, 0.0)
        return (split_feature, threshold, threshold_bin, split_gain,
                internal_value, internal_count, leaf_value, lc, node)

    return grow

# ---------------------------------------------------------------------------
# binned tree walk (for incremental valid scoring / DART drop replay)
# ---------------------------------------------------------------------------

def make_binned_walker(max_depth: int,
                       categorical_features: Optional[Tuple[int, ...]] = None):
    import jax
    import jax.numpy as jnp
    D = max_depth
    cats = frozenset(categorical_features or ())

    @jax.jit
    def walk(binned, split_feature, threshold_bin):
        n = binned.shape[0]
        node = jnp.zeros((n,), jnp.int32)
        F = binned.shape[1]
        cat_b = jnp.asarray(np.isin(np.arange(F), list(cats))) if cats else None
        for _ in range(D):
            f = split_feature[node]
            t = threshold_bin[node]
            row_bin = binned[jnp.arange(n), jnp.maximum(f, 0)].astype(jnp.int32)
            if cat_b is not None:
                dec = jnp.where(cat_b[jnp.maximum(f, 0)], row_bin != t,
                                row_bin > t)
            else:
                dec = row_bin > t
            go_right = (f >= 0) & dec
            node = 2 * node + 1 + go_right.astype(jnp.int32)
        return node - (2 ** D - 1)

    return walk


# walk() above uses BFS-global node ids; the grower uses level-local ids.
# Convert level-local internal arrays (length I in BFS order already) -> OK:
# the grower writes BFS order, so walker and booster share indexing.


# ---------------------------------------------------------------------------
# metrics (reference: core/metrics/MetricConstants.scala registry)
# ---------------------------------------------------------------------------

def _metric_binary_logloss(y, raw, w=None):
    p = 1.0 / (1.0 + np.exp(-raw[:, 0]))
    p = np.clip(p, 1e-15, 1 - 1e-15)
    ll = -(y * np.log(p) + (1 - y) * np.log(1 - p))
    return float(np.average(ll, weights=w))


def _metric_auc(y, raw, w=None):
    s = raw[:, 0]
    order = np.argsort(s)
    y_s = y[order]
    w_s = np.ones_like(y_s, dtype=np.float64) if w is None else np.asarray(w)[order]
    pos = (y_s > 0).astype(np.float64) * w_s
    neg = (1.0 - (y_s > 0)) * w_s
    cum_neg = np.cumsum(neg)
    auc = float(np.sum(pos * (cum_neg - 0.5 * neg)) /
                max(1e-12, np.sum(pos) * np.sum(neg)))
    return auc


def _metric_multi_logloss(y, raw, w=None):
    z = raw - raw.max(axis=1, keepdims=True)
    p = np.exp(z)
    p /= p.sum(axis=1, keepdims=True)
    p = np.clip(p[np.arange(len(y)), y.astype(int)], 1e-15, None)
    return float(np.average(-np.log(p), weights=w))


def _metric_l2(y, raw, w=None):
    return float(np.average((raw[:, 0] - y) ** 2, weights=w))


def _metric_rmse(y, raw, w=None):
    return math.sqrt(_metric_l2(y, raw, w))


def _metric_l1(y, raw, w=None):
    return float(np.average(np.abs(raw[:, 0] - y), weights=w))


def _metric_poisson_nll(y, raw, w=None):
    mu = np.exp(np.clip(raw[:, 0], -30, 30))
    return float(np.average(mu - y * np.log(np.maximum(mu, 1e-12)), weights=w))


def _metric_gamma_nll(y, raw, w=None):
    s_ = np.clip(raw[:, 0], -30, 30)
    return float(np.average(s_ + y * np.exp(-s_), weights=w))


def _metric_pinball(y, raw, alpha, w=None):
    e = y - raw[:, 0]
    return float(np.average(np.maximum(alpha * e, (alpha - 1.0) * e),
                            weights=w))


def _metric_tweedie_nll(y, raw, rho, w=None):
    """Tweedie deviance NLL with log link (raw = log mean), 1 < rho < 2."""
    s_ = np.clip(raw[:, 0], -30, 30)
    nll = (-y * np.exp((1.0 - rho) * s_) / (1.0 - rho)
           + np.exp((2.0 - rho) * s_) / (2.0 - rho))
    return float(np.average(nll, weights=w))


METRICS = {"binary_logloss": (_metric_binary_logloss, False),
           "poisson_nll": (_metric_poisson_nll, False),
           "gamma_nll": (_metric_gamma_nll, False),
           "auc": (_metric_auc, True),
           "multi_logloss": (_metric_multi_logloss, False),
           "l2": (_metric_l2, False), "mse": (_metric_l2, False),
           "rmse": (_metric_rmse, False), "l1": (_metric_l1, False),
           "mae": (_metric_l1, False)}


def resolve_metric(metric_name: str, p: "GBDTParams"):
    """(metric_fn, larger_better) for a requested or default metric name.
    tweedie_nll is parameterized by the variance power, so it resolves to a
    closure here instead of living in METRICS; unknown names fall back to
    the objective's default (and that fallback handles tweedie too)."""
    def closures(name):
        if name == "tweedie_nll":
            rho_m = p.tweedie_variance_power
            return (lambda y_, raw_, w_=None:
                    _metric_tweedie_nll(y_, raw_, rho_m, w_), False)
        if name == "pinball":
            a_m = p.alpha
            return (lambda y_, raw_, w_=None:
                    _metric_pinball(y_, raw_, a_m, w_), False)
        return None

    got = closures(metric_name)
    if got is not None:
        return got
    if metric_name in METRICS:
        return METRICS[metric_name]
    fallback = default_metric(p.objective)
    got = closures(fallback)
    if got is not None:
        return got
    return METRICS.get(fallback, METRICS["l2"])


def default_metric(objective: str) -> str:
    return {"binary": "binary_logloss", "multiclass": "multi_logloss",
            "regression": "l2", "regression_l1": "l1", "huber": "l2",
            "quantile": "pinball", "lambdarank": "l2",
            "poisson": "poisson_nll", "tweedie": "tweedie_nll",
            "gamma": "gamma_nll"}.get(objective, "l2")


# ---------------------------------------------------------------------------
# training driver
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class TrainResult:
    booster: GBDTBooster
    evals: List[Dict[str, float]]
    bin_mapper: BinMapper


def train(X: np.ndarray, y: np.ndarray, params: GBDTParams,
          sample_weight: Optional[np.ndarray] = None,
          valid: Optional[Tuple[np.ndarray, np.ndarray]] = None,
          group_ptr: Optional[np.ndarray] = None,
          init_booster: Optional[GBDTBooster] = None,
          feature_names: Optional[List[str]] = None,
          callbacks: Optional[List[Callable]] = None,
          shard_rows: bool = False) -> TrainResult:
    """Boosting loop.  Host python drives iterations; each tree is one jitted
    XLA program (reference: driver drives ``updateOneIteration`` per iter,
    ``TrainUtils.scala:67``).  ``shard_rows`` puts the binned matrix/gradients
    row-sharded over the active mesh's data axis (GSPMD psums histograms over
    ICI — the allreduce-ring replacement)."""
    import jax
    import jax.numpy as jnp

    p = params.resolve()
    rng = np.random.default_rng(p.seed)
    X = np.asarray(X, np.float32)
    y = np.asarray(y, np.float32)
    n, F = X.shape
    K = p.num_class if p.objective == "multiclass" else 1
    w = np.ones(n, np.float32) if sample_weight is None else np.asarray(sample_weight, np.float32)

    if p.categorical_features:
        bad = [i for i in p.categorical_features if not 0 <= int(i) < F]
        if bad:
            raise ValueError(f"categorical_features indices {bad} out of "
                             f"range [0, {F}) — negative indices are not "
                             f"interpreted pythonically")
    if p.objective in ("poisson", "tweedie") and (y < 0).any():
        raise ValueError(f"objective {p.objective!r} requires non-negative "
                         f"labels (min label {float(y.min())})")
    if p.objective == "gamma" and (y <= 0).any():
        raise ValueError("objective 'gamma' requires strictly positive "
                         f"labels (min label {float(y.min())})")
    if p.objective == "tweedie" and not 1.0 < p.tweedie_variance_power < 2.0:
        raise ValueError(
            f"tweedie_variance_power must be in (1, 2), got "
            f"{p.tweedie_variance_power}; use objective='poisson' for the "
            f"rho=1 limit")
    mapper = BinMapper(p.max_bin,
                       categorical_features=p.categorical_features).fit(X)
    binned_np = mapper.transform(X)
    edges = jnp.asarray(mapper.edges)
    B = mapper.num_bins

    sig = _params_sig(p)
    if shard_rows:
        from jax.sharding import PartitionSpec as P
        from ..parallel import get_active_mesh, batch_sharded
        from ..parallel.mesh import AXIS_DATA
        from ..parallel.sharding import pad_to_multiple
        mesh = get_active_mesh()
        nd = mesh.shape[AXIS_DATA]
        binned_np, n_valid_rows = pad_to_multiple(binned_np, nd)
        y_pad, _ = pad_to_multiple(y, nd)
        w_pad, _ = pad_to_multiple(w, nd)
        w_pad[n_valid_rows:] = 0.0  # padded rows carry zero weight everywhere
        y, w = y_pad, w_pad
        n = binned_np.shape[0]
        sharding = batch_sharded(mesh)
        binned = jax.device_put(binned_np, sharding)

        # explicit SPMD: each shard builds local histograms, psum over ICI
        def _build_sharded():
            grow_raw = make_tree_grower(p.max_depth, F, B, p, axis_name=AXIS_DATA)
            return jax.jit(jax.shard_map(
                grow_raw, mesh=mesh,
                in_specs=(P(AXIS_DATA), P(AXIS_DATA), P(AXIS_DATA), P(AXIS_DATA),
                          P(), P()),
                out_specs=(P(),) * 8 + (P(AXIS_DATA),), check_vma=False))
        grower = _cached(("sharded_grower", sig, F, id(mesh)), _build_sharded)
    else:
        binned = jnp.asarray(binned_np)
        grower = _cached(("grower", sig, F),
                         lambda: jax.jit(make_tree_grower(p.max_depth, F, B, p)))
    objective = make_objective(p)
    D = p.max_depth
    I, L = 2 ** D - 1, 2 ** D

    # init score (BoostFromAverage analogue)
    init_score = 0.0
    if p.objective == "binary":
        pbar = float(np.clip(np.average(y, weights=w), 1e-6, 1 - 1e-6))
        init_score = math.log(pbar / (1 - pbar)) / p.sigmoid
    elif p.objective in ("regression", "huber"):
        init_score = float(np.average(y, weights=w))
    elif p.objective in ("poisson", "tweedie", "gamma"):  # log link
        init_score = float(np.log(max(np.average(y, weights=w), 1e-9)))
    elif p.objective == "regression_l1":
        init_score = float(np.median(y))

    scores = jnp.full((n, K), init_score, jnp.float32)
    y_dev = jnp.asarray(y)
    w_dev = jnp.asarray(w)

    # warm start: replay existing booster on binned data
    trees: Dict[str, List[np.ndarray]] = {k: [] for k in
                                          ("split_feature", "threshold", "threshold_bin",
                                           "split_gain", "internal_value", "internal_count",
                                           "leaf_value", "leaf_count")}
    tree_weights: List[float] = []
    walker = _cached(("walker", D, tuple(p.categorical_features or ())),
                     lambda: make_binned_walker(D, p.categorical_features))
    if init_booster is not None:
        assert init_booster.max_depth == D and init_booster.num_features == F
        for t in range(init_booster.num_trees):
            for k in trees:
                trees[k].append(getattr(init_booster, {"leaf_value": "leaf_value",
                                                       "leaf_count": "leaf_count"}.get(k, k))[t])
            tree_weights.append(float(init_booster.tree_weight[t]))
            leaf = walker(binned, jnp.asarray(init_booster.split_feature[t]),
                          jnp.asarray(init_booster.threshold_bin[t]))
            contrib = jnp.asarray(init_booster.leaf_value[t])[leaf] * init_booster.tree_weight[t]
            scores = scores.at[:, t % K].add(contrib)
        # shift base score to the incoming booster's BEFORE reassigning, so
        # continued training optimizes against the recorded init_score
        scores = scores + (init_booster.init_score - init_score)
        init_score = init_booster.init_score

    metric_name = p.metric or default_metric(p.objective)
    metric_fn, larger_better = resolve_metric(metric_name, p)
    evals: List[Dict[str, float]] = []
    has_valid = valid is not None
    if has_valid:
        Xv = np.asarray(valid[0], np.float32)
        yv = np.asarray(valid[1], np.float32)
        binned_v = jnp.asarray(mapper.transform(Xv))
        scores_v = jnp.full((Xv.shape[0], K), init_score, jnp.float32)
    best_metric = -np.inf if larger_better else np.inf
    best_iter = -1
    rounds_no_improve = 0

    feat_mask_full = jnp.ones((F,), bool)
    hist_mask_full = jnp.ones((n,), bool) if not shard_rows else jnp.asarray(w > 0)

    # Fused per-iteration step (single-program path): objective + GOSS + K
    # tree grows + score updates in ONE jitted XLA program — eager per-op
    # dispatch through the device relay costs ~10-100 ms per op, which
    # dominated the loop before fusion.
    grow_fn = None if shard_rows else make_tree_grower(p.max_depth, F, B, p)
    shrink_const = 1.0 if p.boosting_type == "rf" else p.learning_rate
    is_goss = p.boosting_type == "goss"
    a_n = int(p.top_rate * n) if is_goss else 0
    b_n = int(p.other_rate * n) if is_goss else 0

    def _iter_body(scores, y_d, w_d, binned_d, base_mask, feat_mask_d, edges_d,
                   grad_scale, new_w, key, g_pre, h_pre, use_pre):
        if use_pre:
            g, h = g_pre, h_pre
        else:
            g, h = objective(scores / grad_scale, y_d, w_d)
        hist_mask = base_mask
        if is_goss and not use_pre:
            absg = jnp.abs(g).sum(axis=1)
            order = jnp.argsort(-absg)
            top_idx = order[:a_n]
            rest = order[a_n:]
            perm = jax.random.permutation(key, rest.shape[0])
            small_idx = rest[perm[:b_n]]
            mask = jnp.zeros((n,), bool).at[top_idx].set(True).at[small_idx].set(True)
            amp = (1.0 - p.top_rate) / max(p.other_rate, 1e-12)
            wamp = jnp.ones((n,)).at[small_idx].set(amp)
            hist_mask = hist_mask & mask
            g, h = g * wamp[:, None], h * wamp[:, None]
        tree_out = []
        for c in range(K):
            sf, th, tb, sg, iv, ic, lv, lc, leaf = grow_fn(
                binned_d, g[:, c], h[:, c], hist_mask, feat_mask_d, edges_d)
            lv_s = lv * shrink_const
            scores = scores.at[:, c].add(lv_s[leaf] * new_w)
            tree_out.append((sf, th, tb, sg, iv, ic, lv_s, lc))
        return scores, tree_out

    _iter_jit = {} if shard_rows else {
        False: _cached(("iter", sig, F, K, n, False),
                       lambda: jax.jit(partial(_iter_body, use_pre=False))),
        True: _cached(("iter", sig, F, K, n, True),
                      lambda: jax.jit(partial(_iter_body, use_pre=True)))}

    import jax.random as jrandom
    jit_objective = jax.jit(objective) if objective is not None else None
    start_iter = len(tree_weights) // K

    # ---- scan-chunked multi-iteration path: CH boosting iterations per
    # device dispatch.  Opt-in (MMLSPARK_TPU_GBDT_CHUNK=8): on a single chip
    # the async dispatch queue already pipelines iterations (measured wash),
    # but on multi-host meshes chunking amortizes collective launch latency.
    CH = max(1, int(__import__("os").environ.get("MMLSPARK_TPU_GBDT_CHUNK", "1")))
    chunk_ok = (CH > 1 and not shard_rows and p.objective != "lambdarank"
                and p.boosting_type != "dart" and p.bagging_freq <= 1
                and p.num_iterations >= 2 * CH
                and n >= 50_000)  # small data: scan compile cost dominates

    def _build_multi():
        keep = max(1, int(round(p.feature_fraction * F)))
        bag_on = p.bagging_freq > 0 and p.bagging_fraction < 1.0
        ff_on = p.feature_fraction < 1.0
        rf_mode = p.boosting_type == "rf"

        def body(carry, key):
            scores_c, t = carry
            kf, kb, kg = jrandom.split(key, 3)
            feat_mask = jnp.ones((F,), bool)
            if ff_on:
                sel = jrandom.choice(kf, F, (keep,), replace=False)
                feat_mask = jnp.zeros((F,), bool).at[sel].set(True)
            base_mask = jnp.ones((n,), bool)
            if bag_on:
                base_mask = jrandom.uniform(kb, (n,)) < p.bagging_fraction
            grad_scale = jnp.maximum(1.0, jnp.floor(t / K)) if rf_mode else 1.0
            g, h = objective(scores_c / grad_scale, y_dev, w_dev)
            hist_mask = base_mask
            if is_goss:
                absg = jnp.abs(g).sum(axis=1)
                order = jnp.argsort(-absg)
                top_idx = order[:a_n]
                rest = order[a_n:]
                perm = jrandom.permutation(kg, rest.shape[0])
                small_idx = rest[perm[:b_n]]
                mask = jnp.zeros((n,), bool).at[top_idx].set(True)                     .at[small_idx].set(True)
                amp = (1.0 - p.top_rate) / max(p.other_rate, 1e-12)
                wamp = jnp.ones((n,)).at[small_idx].set(amp)
                hist_mask = hist_mask & mask
                g, h = g * wamp[:, None], h * wamp[:, None]
            outs = []
            for c in range(K):
                sf, th, tb, sg, iv, ic, lv, lc, leaf = grow_fn(
                    binned, g[:, c], h[:, c], hist_mask, feat_mask, edges)
                lv_s = lv * shrink_const
                scores_c = scores_c.at[:, c].add(lv_s[leaf])
                outs.append((sf, th, tb, sg, iv, ic, lv_s, lc))
            stacked = tuple(jnp.stack([o[j] for o in outs]) for j in range(8))
            return (scores_c, t + K), stacked

        def multi(scores_c, t0, keys):
            (scores_c, t), stacked = jax.lax.scan(body, (scores_c, t0), keys)
            return scores_c, stacked

        return jax.jit(multi)

    multi_iter = _cached(("multi", sig, F, K, n, CH), _build_multi) if chunk_ok else None

    def _build_valid_update():
        def upd(scores_v_c, binned_v_c, sf_all, tb_all, lv_all):
            CK = sf_all.shape[0] * sf_all.shape[1]
            sf_f = sf_all.reshape(CK, -1)
            tb_f = tb_all.reshape(CK, -1)
            lv_f = lv_all.reshape(CK, -1)
            nv = binned_v_c.shape[0]

            def walk_one(sf_t, tb_t):
                node = jnp.zeros((nv,), jnp.int32)
                for _ in range(D):
                    f = sf_t[node]
                    tt = tb_t[node]
                    row_bin = binned_v_c[jnp.arange(nv),
                                         jnp.maximum(f, 0)].astype(jnp.int32)
                    go_right = (f >= 0) & (row_bin > tt)
                    node = 2 * node + 1 + go_right.astype(jnp.int32)
                return node - (2 ** D - 1)

            leaves = jax.vmap(walk_one)(sf_f, tb_f)                 # (CK, nv)
            vals = jnp.take_along_axis(lv_f, leaves, axis=1)        # (CK, nv)
            for c in range(K):
                scores_v_c = scores_v_c.at[:, c].add(vals[c::K].sum(axis=0))
            return scores_v_c

        return jax.jit(upd)

    valid_chunk_update = _cached(("validupd", D, K), _build_valid_update)

    it = start_iter
    bag_mask = None  # sampled lazily on the first bagging-eligible iteration
    lambda_fn = None  # built on first lambdarank iteration, reused after
    end_iter = start_iter + p.num_iterations
    while it < end_iter:
        if multi_iter is not None and end_iter - it >= CH:
            keys = jnp.stack([jrandom.PRNGKey(p.seed * 1000003 + it + j)
                              for j in range(CH)])
            scores, stacked = multi_iter(scores, jnp.float32(len(tree_weights)),
                                         keys)
            names = ("split_feature", "threshold", "threshold_bin", "split_gain",
                     "internal_value", "internal_count", "leaf_value", "leaf_count")
            for ci in range(CH):
                for c in range(K):
                    for k_name, arr in zip(names, stacked):
                        trees[k_name].append(arr[ci, c])
                    tree_weights.append(1.0)
            if has_valid:
                scores_v = valid_chunk_update(scores_v, binned_v, stacked[0],
                                              stacked[2], stacked[6])
                raw_v = np.asarray(scores_v, np.float64)
                m = metric_fn(yv, raw_v)
                evals.append({metric_name: m, "iteration": it + CH - 1})
                improved = m > best_metric if larger_better else m < best_metric
                if improved:
                    best_metric, best_iter, rounds_no_improve = m, it + CH - 1, 0
                else:
                    rounds_no_improve += CH
                if p.early_stopping_round > 0 and \
                        rounds_no_improve >= p.early_stopping_round:
                    break
            if callbacks:
                for cb in callbacks:
                    cb(it + CH - 1, evals[-1] if evals else None)
            it += CH
            continue

        # ---- host-side per-iteration randomness
        feat_mask = feat_mask_full
        if p.feature_fraction < 1.0:
            keep = max(1, int(round(p.feature_fraction * F)))
            sel = rng.choice(F, size=keep, replace=False)
            feat_mask = jnp.zeros((F,), bool).at[jnp.asarray(sel)].set(True)
        base_mask = hist_mask_full
        if p.boosting_type != "goss" and p.bagging_freq > 0 and p.bagging_fraction < 1.0:
            # resample on schedule-aligned iterations AND on the first
            # iteration of this call (a warm start may begin off-schedule,
            # in which case bag_mask would otherwise be unbound)
            if it % p.bagging_freq == 0 or bag_mask is None:
                bag_mask = jnp.asarray(rng.random(n) < p.bagging_fraction)
            base_mask = hist_mask_full & bag_mask

        # ---- gradients precomputed for lambdarank / dart
        g_pre = h_pre = None
        dropped: List[int] = []
        if p.objective == "lambdarank":
            if group_ptr is None:
                raise ValueError("lambdarank requires group_ptr")
            if lambda_fn is None:  # packing gathers built once, then the
                lambda_fn = make_lambdarank_grad_fn(y, group_ptr, p.sigmoid)
            g_pre, h_pre = lambda_fn(scores)  # stays on device every iter
        elif p.boosting_type == "dart" and tree_weights and rng.random() >= p.skip_drop:
            k_drop = min(p.max_drop, max(1, int(round(p.drop_rate * len(tree_weights)))))
            dropped = sorted(rng.choice(len(tree_weights), size=min(k_drop, len(tree_weights)),
                                        replace=False).tolist())
            drop_delta = jnp.zeros_like(scores)
            for t in dropped:
                leaf = walker(binned, trees["split_feature"][t],
                              trees["threshold_bin"][t])
                drop_delta = drop_delta.at[:, t % K].add(
                    trees["leaf_value"][t][leaf] * tree_weights[t])
            g_pre, h_pre = jit_objective(scores - drop_delta, y_dev, w_dev)

        new_w = 1.0 / (1.0 + len(dropped)) if dropped else 1.0
        grad_scale = float(max(1, len(tree_weights) // K)) \
            if p.boosting_type == "rf" and tree_weights else 1.0
        key = jrandom.PRNGKey(p.seed * 1000003 + it)

        if not shard_rows:
            use_pre = g_pre is not None
            gp = g_pre if use_pre else scores
            hp = h_pre if use_pre else scores
            scores, tree_out = _iter_jit[use_pre](
                scores, y_dev, w_dev, binned, base_mask, feat_mask, edges,
                grad_scale, new_w, key, gp, hp)
        else:
            # multi-chip path: explicit shard_map grower per class
            if g_pre is not None:
                g_eff, h_eff = g_pre, h_pre
            else:
                g_eff, h_eff = jit_objective(scores / grad_scale, y_dev, w_dev)
            shrink = 1.0 if p.boosting_type == "rf" else p.learning_rate
            tree_out = []
            for c in range(K):
                (sf, th, tb, sg, iv, ic, lv, lc, leaf_of_row) = grower(
                    binned, g_eff[:, c], h_eff[:, c], base_mask, feat_mask, edges)
                lv_s = lv * shrink
                scores = scores.at[:, c].add(lv_s[leaf_of_row] * new_w)
                tree_out.append((sf, th, tb, sg, iv, ic, lv_s, lc))

        for c, (sf, th, tb, sg, iv, ic, lv_s, lc) in enumerate(tree_out):
            # keep tree arrays on device: every host fetch is a relay
            # round-trip; one device_get happens after the loop
            for k_name, v in zip(("split_feature", "threshold", "threshold_bin",
                                  "split_gain", "internal_value", "internal_count",
                                  "leaf_value", "leaf_count"),
                                 (sf, th, tb, sg, iv, ic, lv_s, lc)):
                trees[k_name].append(v)
            tree_weights.append(new_w)
            if has_valid:
                leaf_v = walker(binned_v, sf, tb)
                scores_v = scores_v.at[:, c].add(lv_s[leaf_v] * new_w)

        # ---- dart renormalize dropped trees
        if p.boosting_type == "dart" and dropped:
            factor = len(dropped) / (1.0 + len(dropped))
            for t in dropped:
                # subtract the shrunken part from train/valid scores
                leaf = walker(binned, trees["split_feature"][t],
                              trees["threshold_bin"][t])
                delta = trees["leaf_value"][t][leaf] * tree_weights[t] * (factor - 1.0)
                scores = scores.at[:, t % K].add(delta)
                if has_valid:
                    leaf_v = walker(binned_v, trees["split_feature"][t],
                                    trees["threshold_bin"][t])
                    delta_v = trees["leaf_value"][t][leaf_v] * tree_weights[t] * (factor - 1.0)
                    scores_v = scores_v.at[:, t % K].add(delta_v)
                tree_weights[t] *= factor

        # ---- eval / early stopping
        if has_valid:
            raw_v = np.asarray(scores_v, np.float64)
            m = metric_fn(yv, raw_v)
            evals.append({metric_name: m, "iteration": it})
            improved = m > best_metric if larger_better else m < best_metric
            if improved:
                best_metric, best_iter, rounds_no_improve = m, it, 0
            else:
                rounds_no_improve += 1
            if p.early_stopping_round > 0 and rounds_no_improve >= p.early_stopping_round:
                break
        if callbacks:
            for cb in callbacks:
                cb(it, evals[-1] if evals else None)
        it += 1

    trees_np = jax.device_get({k: v for k, v in trees.items()})  # one transfer
    booster = GBDTBooster(
        np.stack(trees_np["split_feature"]), np.stack(trees_np["threshold"]),
        np.stack(trees_np["threshold_bin"]), np.stack(trees_np["split_gain"]),
        np.stack(trees_np["internal_value"]), np.stack(trees_np["internal_count"]),
        np.stack(trees_np["leaf_value"]), np.stack(trees_np["leaf_count"]),
        np.asarray(tree_weights, np.float32),
        max_depth=D, num_features=F, objective=p.objective, num_class=K,
        init_score=init_score, average_output=(p.boosting_type == "rf"),
        feature_names=feature_names, best_iteration=best_iter, sigmoid=p.sigmoid,
        categorical_features=list(p.categorical_features or []))
    return TrainResult(booster=booster, evals=evals, bin_mapper=mapper)
