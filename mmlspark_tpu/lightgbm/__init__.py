from .binning import BinMapper
from .core import GBDTParams, train, TrainResult
from .estimators import (LightGBMClassifier, LightGBMClassificationModel,
                         LightGBMRegressor, LightGBMRegressionModel,
                         LightGBMRanker, LightGBMRankerModel)

__all__ = ["BinMapper", "GBDTParams", "train", "TrainResult",
           "LightGBMClassifier", "LightGBMClassificationModel",
           "LightGBMRegressor", "LightGBMRegressionModel",
           "LightGBMRanker", "LightGBMRankerModel"]
