from .binning import BinMapper, StreamingQuantileSketch
from .core import GBDTParams, train, train_streamed, TrainResult
from .estimators import (LightGBMClassifier, LightGBMClassificationModel,
                         LightGBMRegressor, LightGBMRegressionModel,
                         LightGBMRanker, LightGBMRankerModel)

__all__ = ["BinMapper", "StreamingQuantileSketch", "GBDTParams", "train",
           "train_streamed", "TrainResult",
           "LightGBMClassifier", "LightGBMClassificationModel",
           "LightGBMRegressor", "LightGBMRegressionModel",
           "LightGBMRanker", "LightGBMRankerModel"]
