"""Goodput & cost attribution — the useful-vs-wasted decode ledger (ISSUE 17).

The serving stack can say it is slow (PR 15), burning (PR 11) or
recompiling (PR 6), but not **what each request cost or how much device
work was useful**.  This module is the accounting plane the next decode
optimisations (speculative decoding, prefix caching — both bets on
converting wasted device work into goodput) will be judged on:

- :class:`RequestCost` — the per-request host-side ledger.  Maintained by
  ``ContinuousDecoder`` / one-shot ``decode()`` entirely OFF the compiled
  path: queue wait, prefill vs decode tokens, device-step seconds
  amortized over the step's *live* slots (riding the PR 15
  ``device_time_every`` dispatch/device split), and page-seconds
  integrated at the page alloc/extend/free edges.  Zero new compile keys
  by construction — nothing here touches an executable signature.
- **token outcome ledger** — every decode-step cell lands in exactly one
  ``mmlspark_decode_tokens_outcome_total{outcome}`` bucket
  (:data:`OUTCOMES`), so ``useful + wasted == steps x slots`` is a
  conservation law, not a dashboard approximation.  ``hedge_loser`` is
  booked client-side by ``RoutingClient`` when a hedge leg loses the race
  (the whole reply was device work the caller discarded).
- :class:`RequestRecordRing` — the bounded per-server ring of canonical
  wide-event records (trace id, class, cost stanza, verdict) behind
  ``GET /debug/requests?k=&class=&verdict=`` and the flight recorder's
  ``source.requests`` section.
- :class:`CapacityModel` — the fleet half: folds the federated ledgers
  into fleet goodput%, per-class ``device_seconds_per_1k_tokens`` and a
  per-class headroom report (arrival rate x measured cost vs the fleet's
  device-seconds budget) behind ``GET /fleet/capacity``.
"""
from __future__ import annotations

import collections
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from .metrics import MetricsRegistry, get_registry
from .slo import coalesce_append

__all__ = ["OUTCOMES", "attribution_instruments", "RequestCost",
           "RequestRecordRing", "CapacityModel"]

#: every bucket a decode-step cell (or a discarded hedge reply's token) can
#: land in — the ledger's whole vocabulary, closed so conservation is
#: checkable:
#:
#: - ``useful``                     — tokens a caller received in a 2xx reply
#: - ``pad_row``                    — batch cells dispatched with no live
#:                                    request behind them (padding / finished
#:                                    rows still riding the fused step)
#: - ``denied_row``                 — tokens of rows frozen by page-pool
#:                                    exhaustion mid-flight
#: - ``deadline_expired_midflight`` — tokens of requests whose deadline
#:                                    expired after decode work started
#: - ``shed_after_work``            — tokens of requests cancelled/errored
#:                                    after decode work started (engine
#:                                    abort, drain teardown, caller gone)
#: - ``hedge_loser``                — tokens of a completed reply the
#:                                    routing client discarded because the
#:                                    other hedge leg won
OUTCOMES = ("useful", "pad_row", "denied_row", "deadline_expired_midflight",
            "shed_after_work", "hedge_loser")

#: ContinuousDecoder/decode() terminal outcome -> ledger bucket
ENGINE_OUTCOME_MAP = {
    "ok": "useful",
    "expired": "deadline_expired_midflight",
    "denied": "denied_row",
    "cancelled": "shed_after_work",
    "error": "shed_after_work",
}


def attribution_instruments(registry: Optional[MetricsRegistry] = None
                            ) -> Dict[str, Any]:
    """Register (idempotently) and return the attribution families.
    ``ModelRunner`` construction calls this so the ledger exists before the
    first decode; ``PipelineServer`` calls it for the class-labelled cost
    rollups it books at record emission; ``RoutingClient`` for the
    hedge-loser bucket (coverage-gated, like every family)."""
    reg = registry if registry is not None else get_registry()
    return {
        "tokens": reg.counter(
            "mmlspark_decode_tokens_outcome_total",
            "decode-step cells by terminal outcome — useful vs each wasted-"
            "work cause; sums to decode steps x batch width",
            labels=("outcome",)),
        "device": reg.counter(
            "mmlspark_decode_device_seconds_total",
            "estimated device-seconds attributed to decode requests (the "
            "per-step amount amortized over live slots)"),
        "class_tokens": reg.counter(
            "mmlspark_request_class_decode_tokens_total",
            "decode tokens delivered, by request class (booked at request-"
            "record emission from the cost ledger)", labels=("class",)),
        "class_device": reg.counter(
            "mmlspark_request_class_device_seconds_total",
            "estimated device-seconds consumed, by request class (booked "
            "at request-record emission from the cost ledger)",
            labels=("class",)),
    }


class RequestCost:
    """Host-side per-request cost ledger (one per ``StreamHandle`` /
    decode row).  Mutated only by the engine that owns the request — no
    locking: every writer runs on the decode loop's thread (or the
    submitting thread before the handle is visible to it)."""

    __slots__ = ("queue_s", "prefill_tokens", "prefill_cached",
                 "decode_tokens", "device_s", "page_seconds", "pages_held",
                 "pages_peak", "_page_t")

    def __init__(self, queue_s: float = 0.0, prefill_tokens: int = 0):
        self.queue_s = float(queue_s)
        self.prefill_tokens = int(prefill_tokens)
        # prefix-cache lane (ISSUE 20): of prefill_tokens, how many were
        # served from resident shared pages — device work SKIPPED, not
        # spent, so goodput accounting books them as saved rather than
        # silently dropping them from the conservation story
        self.prefill_cached = 0
        self.decode_tokens = 0
        self.device_s = 0.0
        self.page_seconds = 0.0
        self.pages_held = 0
        self.pages_peak = 0
        self._page_t: Optional[float] = None

    def page_edge(self, now: float, delta_pages: int) -> None:
        """Integrate page-seconds up to ``now`` and apply a page-count
        edge (+n at alloc/extend, -held at free).  Called at exactly the
        pool-op edges, so the integral is exact for piecewise-constant
        holdings — no sampling error."""
        if self._page_t is not None and self.pages_held > 0:
            self.page_seconds += self.pages_held * max(0.0, now - self._page_t)
        self._page_t = now
        self.pages_held = max(0, self.pages_held + int(delta_pages))
        self.pages_peak = max(self.pages_peak, self.pages_held)

    def close_pages(self, now: float) -> None:
        """Final page edge: integrate and drop every held page."""
        self.page_edge(now, -self.pages_held)

    def as_dict(self) -> Dict[str, float]:
        """The record's cost stanza — JSON-safe, bounded, rounded to keep
        the ring and the dump compact."""
        return {
            "queue_s": round(self.queue_s, 6),
            "prefill_tokens": int(self.prefill_tokens),
            "prefill_cached": int(self.prefill_cached),
            "decode_tokens": int(self.decode_tokens),
            "device_s": round(self.device_s, 6),
            "page_seconds": round(self.page_seconds, 6),
            "pages_peak": int(self.pages_peak),
        }


class RequestRecordRing:
    """Bounded, thread-safe ring of canonical request records — one dict
    per terminal request (trace id, class, verdict, status, cost stanza).
    Newest-first queries serve ``GET /debug/requests``; :meth:`tail`
    feeds the flight recorder's ``source.requests`` section so a
    stall/crash dump shows what the engine was serving when it died."""

    def __init__(self, maxlen: int = 256):
        self._ring: "collections.deque" = collections.deque(
            maxlen=max(1, int(maxlen)))
        self._lock = threading.Lock()
        self.appended = 0

    def append(self, record: Dict[str, Any]) -> None:
        with self._lock:
            self._ring.append(record)
            self.appended += 1

    def query(self, k: int = 50, klass: Optional[str] = None,
              verdict: Optional[str] = None) -> List[Dict[str, Any]]:
        """Newest-first records matching the optional class/verdict
        filters, capped at ``k``."""
        with self._lock:
            records = list(self._ring)
        out: List[Dict[str, Any]] = []
        for rec in reversed(records):
            if klass is not None and rec.get("class") != klass:
                continue
            if verdict is not None and rec.get("verdict") != verdict:
                continue
            out.append(rec)
            if len(out) >= max(0, int(k)):
                break
        return out

    def tail(self, k: int = 32) -> List[Dict[str, Any]]:
        """The newest ``k`` records, oldest-first (dump-section order)."""
        with self._lock:
            records = list(self._ring)
        return records[-max(0, int(k)):]


def _window_delta(samples, now: float, window_s: float):
    """Difference the newest cumulative sample against the newest sample
    at/older than the window edge (``window_fraction``'s base-pick rule,
    generalized to n-field tuples).  Returns ``(elapsed_s, deltas)`` or
    ``None`` with fewer than two samples / no elapsed time.  Negative
    deltas clamp to 0 — callers clear history on detected counter resets,
    this is only the residual-race guard."""
    if len(samples) < 2:
        return None
    newest = samples[-1]
    cutoff = now - window_s
    base = samples[0]
    for sample in reversed(samples[:-1]):
        if sample[0] <= cutoff:
            base = sample
            break
    if base is newest:
        return None
    dt = newest[0] - base[0]
    if dt <= 0:
        return None
    return dt, tuple(max(0.0, n - b) for n, b in zip(newest[1:], base[1:]))


class CapacityModel:
    """Fleet capacity from the federated cost ledgers (``GET
    /fleet/capacity``).

    Per request class it keeps a bounded cumulative history of
    ``(t, device_seconds, decode_tokens, received_requests)`` — fed from
    each :class:`FleetView` the federation poll produces — and reports
    windowed rates: measured ``device_seconds_per_1k_tokens``, arrival
    rate, device utilization against the class's device-seconds budget
    (one device-second per wall-second per live replica), and the
    remaining headroom.  The SLO/autoscale window discipline applies
    verbatim: bounded per-class rings maintained with
    :func:`slo.coalesce_append`, cleared on counter reset or scrape-
    coverage change, and a class with too little history reports ``null``
    rates instead of confidently-wrong ones."""

    TOKENS_FAMILY = "mmlspark_decode_tokens_outcome_total"
    CLASS_TOKENS_FAMILY = "mmlspark_request_class_decode_tokens_total"
    CLASS_DEVICE_FAMILY = "mmlspark_request_class_device_seconds_total"
    REQUESTS_FAMILY = "mmlspark_serving_requests_total"
    PREFIX_TOKENS_FAMILY = "mmlspark_prefix_hit_tokens_total"

    def __init__(self, clock: Callable[[], float] = time.monotonic,
                 window_s: float = 300.0):
        self.clock = clock
        self.window_s = float(window_s)
        self._min_spacing_s = 2.0 * self.window_s / 4096
        self._lock = threading.Lock()
        self._state: Dict[str, Dict] = {}

    # ------------------------------------------------------------- signals
    def _class_sample(self, view, klass: str, workers: List[Dict]):
        """One cumulative (device_s, tokens, received) triple for a class
        from the fleet view.  Device/token rollups carry the ``class``
        label directly; arrivals come from the class workers' serving
        counters (the autoscale addr-matching rule)."""
        addrs = {f"{w['host']}:{w['port']}" for w in workers}
        dev = view.counter_sum(self.CLASS_DEVICE_FAMILY, {"class": klass})
        tok = view.counter_sum(self.CLASS_TOKENS_FAMILY, {"class": klass})
        recv = sum(
            v for labels, v in view.counters.get(
                self.REQUESTS_FAMILY, {}).items()
            if dict(labels).get("status") == "received"
            and dict(labels).get("server") in addrs)
        return dev, tok, recv

    def report(self, view, workers_by_class: Dict[str, List[Dict]],
               now: Optional[float] = None) -> Dict[str, Any]:
        """Fold one fleet view into the per-class histories and return the
        ``GET /fleet/capacity`` payload."""
        now = self.clock() if now is None else float(now)
        classes: Dict[str, Dict] = {}
        with self._lock:
            for klass in sorted(workers_by_class):
                workers = workers_by_class[klass]
                n = len(workers)
                st = self._state.setdefault(klass, {
                    "hist": collections.deque(maxlen=4096),
                    "coverage": None})
                coverage = frozenset(
                    sid for w in workers
                    if (sid := w.get("server_id")) is not None
                    and view.workers.get(sid, {}).get("ok", False))
                hist = st["hist"]
                if coverage != st["coverage"]:
                    # scrape coverage changed: cumulative counts are not
                    # comparable across the change (the autoscale /
                    # SLO re-baselining rule)
                    hist.clear()
                    st["coverage"] = coverage
                dev, tok, recv = self._class_sample(view, klass, workers)
                if hist and (dev < hist[-1][1] or tok < hist[-1][2]
                             or recv < hist[-1][3]):
                    hist.clear()  # counter reset: a replica restarted
                coalesce_append(hist, (now, dev, tok, recv),
                                self._min_spacing_s)
                delta = _window_delta(list(hist), now, self.window_s)
                row: Dict[str, Any] = {
                    "replicas": n,
                    "device_seconds_per_1k_tokens": None,
                    "decode_tokens_per_s": None, "arrival_rps": None,
                    "device_utilization": None, "headroom_pct": None,
                    "samples": len(hist),
                }
                if delta is not None:
                    dt, (d_dev, d_tok, d_recv) = delta
                    row["decode_tokens_per_s"] = round(d_tok / dt, 4)
                    row["arrival_rps"] = round(d_recv / dt, 4)
                    if d_tok > 0:
                        row["device_seconds_per_1k_tokens"] = round(
                            1000.0 * d_dev / d_tok, 6)
                    # budget: one device-second per wall-second per replica
                    util = (d_dev / dt) / max(1, n)
                    row["device_utilization"] = round(util, 4)
                    row["headroom_pct"] = round(100.0 * (1.0 - util), 2)
                classes[klass] = row
            dead = [k for k in self._state if k not in workers_by_class]
            for k in dead:
                self._state.pop(k)
        by_outcome = {
            o: view.counter_sum(self.TOKENS_FAMILY, {"outcome": o})
            for o in OUTCOMES}
        total = sum(by_outcome.values())
        goodput = 100.0 * by_outcome["useful"] / total if total > 0 else None
        return {
            "goodput_pct": round(goodput, 4) if goodput is not None else None,
            "tokens_by_outcome": by_outcome,
            "token_samples": total,
            # prefix-cache savings (ISSUE 20): prefill tokens served from
            # resident pages fleet-wide — device work the cache SKIPPED,
            # reported beside goodput so capacity math sees the win
            "prefill_cached_tokens": view.counter_sum(
                self.PREFIX_TOKENS_FAMILY, {}),
            "classes": classes,
            "window_s": self.window_s,
            "evaluated_at": view.scraped_at,
        }
