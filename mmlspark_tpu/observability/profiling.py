"""Host-stack sampling profiler with span attribution (ISSUE 15).

The telemetry stack can say *that* a path is slow (spans + exemplars,
compile plane, fleet SLO burn) but not *where the host time goes* — the
continuous-batching bench notes call the CPU proxy "dispatch-bound" with
no tool to prove which frames eat the step loop.  This module closes that
gap with a production-shaped sampling profiler:

- a daemon thread samples ``sys._current_frames()`` at a configurable hz
  (no tracing hooks, no per-call overhead on the profiled code — the cost
  is one stack walk per thread per sample, paid by the sampler thread);
- every sample is attributed to the sampled thread's **ambient span/phase
  name** (``tracing.thread_phases()`` — maintained by ``trace_span`` and
  the hot-loop ``ambient_phase``), so "dispatch-bound" decomposes into
  named serving/decode/train phases;
- **idle threads are excluded by default** (py-spy's ``--idle`` default
  brought to pure Python): a thread whose top frame sits in a stdlib wait
  wrapper (``threading.py``, ``queue.py``, ``socket.py``, ...) is blocked
  in a C-level wait with the GIL released — counting it would dilute the
  by-span rollup with parked handler/worker threads until no busy phase
  could ever dominate.  Idle thread-samples are still counted
  (``idle_samples`` in the report — never a silent drop), and
  ``include_idle=True`` / ``?idle=1`` restores wall-clock attribution;
- aggregation is **bounded**: stacks fold into ``span;frame;frame;...``
  keys capped at ``max_stacks`` distinct entries (overflow counted, never
  grown), so a long window cannot OOM the process it profiles;
- ``profile_window()`` is the blocking convenience behind
  ``GET /debug/profile?seconds=&hz=`` on ``PipelineServer``; one window at
  a time per process (a second concurrent request gets ``busy`` — two
  samplers would double the overhead both are trying to measure);
- an optional ``jax.profiler.trace`` capture rides the same window behind
  the ``MMLSPARK_TPU_JAX_TRACE_DIR`` env knob, with a clean fallback when
  jax (or its profiler) is unavailable — the host sampler always works.

Output is folded-stack JSON (flamegraph-ready: each entry is one
root-first ``;``-joined stack with a count), plus a ``by_span`` rollup —
the number the decode acceptance gate reads.

Metric families (registered by :func:`profiler_instruments`; the
telemetry-coverage sweep gates on the booking sites):
``mmlspark_profiler_runs_total{result}`` (started/completed/error/busy),
``mmlspark_profiler_samples_total{span}``,
``mmlspark_profiler_stacks_dropped_total``.
"""
from __future__ import annotations

import os
import sys
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

from .metrics import MetricsRegistry, get_registry
from ..utils.concurrency import make_lock
from .tracing import thread_phases

__all__ = ["SamplingProfiler", "ProfilerBusy", "profile_window",
           "profiler_instruments", "DEFAULT_HZ", "MAX_SECONDS", "MAX_HZ",
           "JAX_TRACE_DIR_ENV", "UNATTRIBUTED"]

#: default sampling rate — high enough to resolve ms-scale phases over a
#: few-second window (a 2 s window still lands ~60 samples), low enough
#: that the echo-serving overhead A/B stays within its 3% gate (bench
#: ``SERVING_PROFILER`` arm: on a contended 1-core host each sampler wake
#: also preempts the serving thread, so the felt per-request cost is GIL
#: hand-offs, not just stack-walk CPU); prime, so the sampler never
#: phase-locks to common 100/50/25 Hz timers
DEFAULT_HZ = 29

#: clamps for the HTTP endpoint: a typo'd ?seconds= must not pin a handler
#: thread for an hour, a huge ?hz= must not melt the host
MAX_SECONDS = 60.0
MAX_HZ = 1000

#: env knob: when set to a directory, profile windows ALSO capture a
#: ``jax.profiler.trace`` into it (device-side timeline for TensorBoard);
#: absent/empty = host sampler only.  Failures fall back cleanly — the
#: report records the error and the host samples still serve.
JAX_TRACE_DIR_ENV = "MMLSPARK_TPU_JAX_TRACE_DIR"

#: span label for threads sampled outside any trace_span/ambient_phase
UNATTRIBUTED = "unattributed"

#: top-frame module basenames that mark a thread as BLOCKED: the C-level
#: waits these wrappers issue (lock/condition waits, selector polls,
#: socket reads, queue gets) release the GIL and leave the wrapper as the
#: newest Python frame — the only evidence of idleness visible from pure
#: Python.  A thread genuinely executing Python inside one of these
#: modules misclassifies; acceptable for a sampling profiler's default.
_IDLE_FILES = frozenset({"threading.py", "selectors.py", "socket.py",
                         "socketserver.py", "queue.py", "ssl.py"})


def _is_idle(frame) -> bool:
    code = frame.f_code
    if code.co_filename.rsplit(os.sep, 1)[-1] in _IDLE_FILES:
        return True
    # the profile window's own blocking sleep (time.sleep is C, so the
    # newest Python frame is profile_window itself) parks a handler thread
    # for the whole window — the one guaranteed-idle frame we control
    return code.co_name == "profile_window"


class ProfilerBusy(RuntimeError):
    """A profile window is already running in this process."""


def profiler_instruments(registry: Optional[MetricsRegistry] = None
                         ) -> Dict[str, Any]:
    """Register (idempotently) and return the profiler metric families —
    called at PipelineServer construction so the families exist before the
    first ``/debug/profile`` request (coverage-gated)."""
    reg = registry if registry is not None else get_registry()
    return {
        "runs": reg.counter(
            "mmlspark_profiler_runs_total",
            "profile windows by result (started/completed/error/busy)",
            labels=("result",)),
        "samples": reg.counter(
            "mmlspark_profiler_samples_total",
            "profiler samples attributed per ambient span name",
            labels=("span",)),
        "dropped": reg.counter(
            "mmlspark_profiler_stacks_dropped_total",
            "samples whose distinct folded stack exceeded the aggregation "
            "bound (counted into by_span, dropped from stacks)"),
    }


#: per-code-object frame label memo: the label is FUNCTION-granular
#: (``co_firstlineno``, not ``f_lineno``) so every hit of the same function
#: is one dict lookup instead of an f-string + path split — the fold is on
#: the sampler's per-wake path and its cost is serving-thread preemption
#: time on a busy host.  Bounded: cleared if it ever grows past 8192
#: distinct code objects (churning test processes; a server's steady state
#: is a few hundred).
_LABELS: Dict[Any, str] = {}


def _frame_label(code) -> str:
    label = _LABELS.get(code)
    if label is None:
        if len(_LABELS) > 8192:
            _LABELS.clear()
        fname = code.co_filename.rsplit(os.sep, 1)[-1]
        label = _LABELS[code] = \
            f"{code.co_name} ({fname}:{code.co_firstlineno})"
    return label


def _fold_frame(frame, max_depth: int = 64) -> str:
    """Root-first ``;``-joined fold of one thread's stack:
    ``func (module.py:42);func2 (...)`` — the flamegraph convention, at
    function granularity."""
    parts = []
    f = frame
    while f is not None and len(parts) < max_depth:
        parts.append(_frame_label(f.f_code))
        f = f.f_back
    parts.reverse()
    return ";".join(parts)


class SamplingProfiler:
    """Bounded host-thread sampling profiler.

    ``start()`` launches the daemon sampler; ``stop()`` joins it and books
    the per-span sample counters; ``report()`` returns the folded-stack
    JSON.  ``sample_once(frames=)`` is the deterministic unit-test entry
    point (inject frames, skip the thread machinery entirely).
    """

    def __init__(self, hz: float = DEFAULT_HZ,
                 registry: Optional[MetricsRegistry] = None,
                 max_stacks: int = 2048, max_depth: int = 64,
                 include_idle: bool = False,
                 clock: Callable[[], float] = time.monotonic):
        if hz <= 0:
            raise ValueError("hz must be > 0")
        self.hz = min(float(hz), float(MAX_HZ))
        self.registry = registry if registry is not None else get_registry()
        self.max_stacks = max(1, int(max_stacks))
        self.max_depth = max(1, int(max_depth))
        self.include_idle = bool(include_idle)
        self.clock = clock
        self._m = profiler_instruments(self.registry)
        self._lock = make_lock("SamplingProfiler._lock")
        #: (span, folded_stack) -> count, bounded at max_stacks entries
        self._stacks: Dict[Tuple[str, str], int] = {}
        self._by_span: Dict[str, int] = {}
        self._samples = 0
        self._idle = 0
        self._dropped = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._t_start: Optional[float] = None
        self._t_stop: Optional[float] = None

    # ------------------------------------------------------------- sampling
    def sample_once(self, frames: Optional[Dict[int, Any]] = None,
                    phases: Optional[Dict[int, str]] = None) -> int:
        """Take one sample of every live thread (or the injected
        ``frames``/``phases`` in tests), excluding the sampler's own
        thread.  Returns the number of threads sampled."""
        own = threading.get_ident()
        if frames is None:
            frames = sys._current_frames()
        if phases is None:
            phases = thread_phases()
        # fold OUTSIDE the lock: the stack walk is the expensive part
        folded = []
        idle = 0
        for tid, frame in frames.items():
            if tid == own:
                continue
            if not self.include_idle and _is_idle(frame):
                idle += 1
                continue
            folded.append((phases.get(tid, UNATTRIBUTED),
                           _fold_frame(frame, self.max_depth)))
        del frames  # frames pin every sampled thread's locals — drop early
        dropped = 0
        with self._lock:
            self._idle += idle
            for span, stack in folded:
                self._samples += 1
                self._by_span[span] = self._by_span.get(span, 0) + 1
                key = (span, stack)
                n = self._stacks.get(key)
                if n is not None:
                    self._stacks[key] = n + 1
                elif len(self._stacks) < self.max_stacks:
                    self._stacks[key] = 1
                else:
                    # bounded aggregation: the sample still counts toward
                    # its span, only the distinct-stack detail is dropped
                    self._dropped += 1
                    dropped += 1
        if dropped:
            self._m["dropped"].inc(dropped)
        return len(folded)

    def _run(self) -> None:
        interval = 1.0 / self.hz
        while not self._stop.wait(interval):
            try:
                self.sample_once()
            except Exception:  # noqa: BLE001 — the sampler must never kill
                pass           # the process it observes

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "SamplingProfiler":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._t_start = self.clock()
        self._t_stop = None
        self._stop.clear()
        self._m["runs"].inc(result="started")
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="mmlspark-profiler")
        self._thread.start()
        return self

    def stop(self) -> "SamplingProfiler":
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=5.0)
        self._t_stop = self.clock()
        with self._lock:
            by_span = dict(self._by_span)
        for span, n in by_span.items():
            self._m["samples"].inc(n, span=span)
        self._m["runs"].inc(result="completed")
        return self

    # --------------------------------------------------------------- report
    def report(self, top: int = 200) -> Dict[str, Any]:
        """Folded-stack JSON: ``stacks`` (top-``top`` by count, flamegraph
        fold format), ``by_span`` rollup, sample/drop accounting."""
        with self._lock:
            stacks = sorted(self._stacks.items(), key=lambda kv: -kv[1])
            by_span = dict(self._by_span)
            samples, dropped = self._samples, self._dropped
            idle = self._idle
        end = self._t_stop if self._t_stop is not None else self.clock()
        duration = max(0.0, end - (self._t_start or end))
        return {
            "hz": self.hz,
            "duration_s": round(duration, 6),
            "samples": samples,
            "idle_samples": idle,
            "include_idle": self.include_idle,
            "by_span": dict(sorted(by_span.items(), key=lambda kv: -kv[1])),
            "stacks": [{"span": span, "stack": stack, "count": count}
                       for (span, stack), count in stacks[:max(0, int(top))]],
            "distinct_stacks": len(stacks),
            "stacks_dropped": dropped,
        }


# one window at a time per process: two concurrent samplers would double
# the very overhead each is trying to measure (and race the jax trace dir)
_WINDOW_LOCK = make_lock("profiling._WINDOW_LOCK")


class _JaxTraceHatch:
    """The optional device-capture hatch: wraps the window in
    ``jax.profiler.trace(dir)`` when ``MMLSPARK_TPU_JAX_TRACE_DIR`` is
    set.  EVERY failure (jax absent, profiler unsupported on this backend,
    unwritable dir, enter/exit raising) degrades to host-only sampling
    with the error recorded in the report — CPU-only containers keep a
    working ``/debug/profile`` no matter what the device plane does."""

    def __init__(self):
        self.verdict: Optional[Dict[str, Any]] = None
        self._scope = None
        self._dir = os.environ.get(JAX_TRACE_DIR_ENV, "")

    def _fail(self, e: BaseException) -> None:
        self.verdict = {"dir": self._dir, "ok": False,
                        "error": f"{type(e).__name__}: {e}"}
        self._scope = None

    def enter(self) -> None:
        if not self._dir:
            return
        try:
            import jax
            scope = jax.profiler.trace(self._dir)
            scope.__enter__()
            self._scope = scope
            self.verdict = {"dir": self._dir, "ok": True}
        except Exception as e:  # noqa: BLE001 — capture is best-effort
            self._fail(e)

    def exit(self) -> None:
        scope, self._scope = self._scope, None
        if scope is None:
            return
        try:
            scope.__exit__(None, None, None)
        except Exception as e:  # noqa: BLE001 — capture is best-effort
            self._fail(e)


def profile_window(seconds: float = 2.0, hz: float = DEFAULT_HZ,
                   registry: Optional[MetricsRegistry] = None,
                   include_idle: bool = False,
                   sleep: Callable[[float], None] = time.sleep
                   ) -> Dict[str, Any]:
    """Run one blocking profile window and return the report — the
    ``GET /debug/profile`` implementation.  Inputs are clamped
    (``seconds`` to (0, 60], ``hz`` to [1, 1000]); a concurrent window
    raises :class:`ProfilerBusy` (the endpoint replies 409)."""
    reg = registry if registry is not None else get_registry()
    seconds = min(max(0.01, float(seconds)), MAX_SECONDS)
    hz = min(max(1.0, float(hz)), float(MAX_HZ))
    if not _WINDOW_LOCK.acquire(blocking=False):
        profiler_instruments(reg)["runs"].inc(result="busy")
        raise ProfilerBusy("a profile window is already running; "
                           "retry when it finishes")
    try:
        profiler = SamplingProfiler(hz=hz, registry=reg,
                                    include_idle=include_idle)
        hatch = _JaxTraceHatch()
        try:
            hatch.enter()
            profiler.start()
            sleep(seconds)
            profiler.stop()
            hatch.exit()
        except Exception:
            profiler_instruments(reg)["runs"].inc(result="error")
            raise
        report = profiler.report()
        report["requested_seconds"] = seconds
        if hatch.verdict is not None:
            report["jax_trace"] = hatch.verdict
        return report
    finally:
        _WINDOW_LOCK.release()
