"""Compute-plane telemetry — the half of observability that lives below jit.

PRs 2 and 4 made the HOST side legible (queue-vs-score splits, spans,
exemplars), but once execution enters XLA the system was dark: a recompile
storm, an HBM high-water creep, or a host->device transfer stall all looked
identical ("score phase got slow").  This module instruments the compile/
device boundary itself:

- :func:`instrumented_jit` — drop-in for ``jax.jit`` (including
  ``jax.jit(jax.shard_map(...))`` composites).  Every call resolves the
  arguments' *abstract shape signature*; a signature hit is a dict lookup
  straight into the compiled executable, a miss books one compilation:
  ``mmlspark_jit_compile_total{fn}`` / ``mmlspark_jit_compile_seconds{fn}``,
  the compile's ``cost_analysis()`` (FLOPs / bytes-accessed gauges, so a
  bench rows/sec can be read as %% of achievable utilization), and — when a
  single function crosses ``storm_signatures`` distinct signatures — a
  *recompile-storm* warning event plus
  ``mmlspark_jit_recompile_storm_total{fn}``, the classic silent TPU
  production killer.  All booking happens on the HOST side of the cache
  miss, never inside traced code (tracer-safe by construction; graft-lint
  TRC treats ``instrumented_jit`` as a tracing entry point so the wrapped
  functions keep their tracer-safety coverage).
- device-memory gauges — ``mmlspark_device_bytes_in_use{device}`` /
  ``mmlspark_device_peak_bytes_in_use{device}`` sampled from
  ``device.memory_stats()`` at scrape time (callback gauges; platforms
  without memory introspection — CPU — simply don't register the series).
- :func:`device_put` — drop-in for ``jax.device_put`` booking
  ``mmlspark_device_transfer_bytes_total{site}``: the host->device feed the
  billion-row out-of-core item needs visible before it lands.
- :func:`compile_report` — the JSON behind ``GET /debug/compile`` on
  ``PipelineServer``: per-function compile counts, the signatures seen, and
  the last cost analysis.
- :func:`ensure_build_info` — ``mmlspark_build_info`` gauge (jax version /
  backend / device kind / device count labels) so scraped dashboards can
  pivot every series by environment.

``jax`` is imported lazily inside functions: graft-lint environments import
this package without jax (PR 3 contract).
"""
from __future__ import annotations

import functools
import inspect
import os
import threading
import time
import weakref
from typing import Any, Callable, Dict, List, Optional, Tuple

from .metrics import MetricsRegistry, get_registry

__all__ = ["InstrumentedJit", "instrumented_jit", "device_put",
           "transfer_nbytes", "compile_report",
           "ensure_device_memory_gauges", "ensure_build_info",
           "STORM_SIGNATURES_ENV", "DEFAULT_STORM_SIGNATURES"]

#: env override for the recompile-storm threshold (distinct signatures one
#: function may compile before each further signature books a storm trip)
STORM_SIGNATURES_ENV = "MMLSPARK_TPU_JIT_STORM_SIGS"
DEFAULT_STORM_SIGNATURES = 8

#: env hatch disabling the AOT executable cache (the wrapper then books
#: compiles by signature but dispatches through plain ``jax.jit``)
AOT_ENV = "MMLSPARK_TPU_JIT_AOT"


def _storm_threshold() -> int:
    raw = os.environ.get(STORM_SIGNATURES_ENV, "")
    try:
        return max(2, int(raw)) if raw.strip() else DEFAULT_STORM_SIGNATURES
    except ValueError:
        return DEFAULT_STORM_SIGNATURES


# ---------------------------------------------------------------------------
# abstract shape signatures
# ---------------------------------------------------------------------------

_DTYPE_SHORT = {"float32": "f32", "float64": "f64", "float16": "f16",
                "bfloat16": "bf16", "int32": "i32", "int64": "i64",
                "int16": "i16", "int8": "i8", "uint8": "u8",
                "uint16": "u16", "uint32": "u32", "bool": "b1"}


def _leaf_sig(leaf) -> Tuple:
    """One leaf's cache identity, mirroring jax.jit's semantics: arrays key
    on (shape, dtype, weak_type, sharding) — placement included because an
    AOT executable is specialized to its inputs' shardings exactly like
    jit's own cache; python scalars key on their TYPE only (jit traces them
    weak-typed, so a new float VALUE is not a recompile)."""
    if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
        # the dtype OBJECT keys the signature (hashable, equality-correct);
        # stringification happens only at render time — str(np.dtype) walks
        # numpy's uncached name machinery and costs ~10us per leaf per call
        return ("a", tuple(leaf.shape), leaf.dtype,
                bool(getattr(leaf, "weak_type", False)),
                getattr(leaf, "sharding", None))
    return ("py", type(leaf).__name__)


def _render_leaf(sig: Tuple) -> str:
    if sig[0] == "a":
        name = str(sig[2])
        dt = _DTYPE_SHORT.get(name, name)
        out = f"{dt}[{','.join(str(d) for d in sig[1])}]"
        spec = getattr(sig[4], "spec", None) if len(sig) > 4 else None
        if spec is not None:  # NamedSharding: show the partitioning
            out += f"@{spec}"
        return out
    if sig[0] == "py":
        return f"py:{sig[1]}"
    return f"static:{sig[1]!r}"


def _extract_cost(analysis) -> Optional[Dict[str, float]]:
    """Normalize ``Compiled.cost_analysis()`` (a dict on new jax, a
    one-element list of dicts on 0.4.x) to {flops, bytes_accessed}."""
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else None
    if not isinstance(analysis, dict):
        return None
    out = {}
    if "flops" in analysis:
        out["flops"] = float(analysis["flops"])
    if "bytes accessed" in analysis:
        out["bytes_accessed"] = float(analysis["bytes accessed"])
    return out or None


class _SigEntry:
    """One compiled signature: the executable (or None when the AOT path
    was not viable and dispatch stays on plain jit) plus its book-keeping."""

    __slots__ = ("compiled", "rendered", "compile_s", "cost")

    def __init__(self, rendered: str):
        self.compiled = None
        self.rendered = rendered
        self.compile_s = 0.0
        self.cost: Optional[Dict[str, float]] = None


class InstrumentedJit:
    """``jax.jit`` with compile-boundary telemetry.

    Dispatch: the arguments' abstract signature indexes a dict of compiled
    executables — the steady-state path is one signature build + dict hit,
    with zero metric writes.  A miss lowers + compiles once (AOT), books the
    compile counter/histogram, captures ``cost_analysis()``, and checks the
    recompile-storm threshold.  Any AOT failure (exotic argument placement,
    jax version quirk) falls back to the plain jitted callable for that
    signature — semantics are never worse than ``jax.jit``.
    """

    def __init__(self, fn: Callable, *, name: Optional[str] = None,
                 registry: Optional[MetricsRegistry] = None,
                 storm_signatures: Optional[int] = None,
                 static_argnums=(), static_argnames=(), **jit_kwargs):
        import jax
        self._fn = fn
        self.name = name or getattr(fn, "__name__", None) or "anonymous"
        self.registry = registry if registry is not None else get_registry()
        self.storm_signatures = storm_signatures or _storm_threshold()
        # an explicit empty static_argnums would DISABLE jax's inference of
        # positions from static_argnames — only forward what was given
        static_kw = {}
        if static_argnums not in ((), None):
            static_kw["static_argnums"] = static_argnums
        if static_argnames not in ((), None, ""):
            static_kw["static_argnames"] = static_argnames
        self._jitted = jax.jit(fn, **static_kw, **jit_kwargs)
        self._aot = os.environ.get(AOT_ENV, "1").strip().lower() \
            not in ("0", "false", "off", "no")
        # static-argument plan: the AOT executable takes only dynamic args,
        # so static positions must be resolvable — by number directly, and
        # by name through the function signature (static_argnames may be
        # passed positionally at call sites)
        self._static_nums = set(int(i) for i in (
            (static_argnums,) if isinstance(static_argnums, int)
            else static_argnums))
        self._static_names = set((static_argnames,) if isinstance(
            static_argnames, str) else static_argnames)
        if self._static_names:
            try:
                params = list(inspect.signature(fn).parameters)
                for nm in self._static_names:
                    if nm in params:
                        self._static_nums.add(params.index(nm))
            except (TypeError, ValueError):
                self._aot = False  # cannot split positionally-passed statics
        self._has_static = bool(self._static_nums or self._static_names)
        self._entries: Dict[Tuple, _SigEntry] = {}
        self._lock = threading.Lock()
        self._storm_tripped = False
        self.last_compile_s = 0.0
        # metric children bound once (the miss path is rare but the labels
        # must not be resolved per compile inside any lock)
        reg = self.registry
        self._c_compile = reg.counter(
            "mmlspark_jit_compile_total",
            "XLA compilations by instrumented function",
            labels=("fn",)).labels(fn=self.name)
        self._h_compile = reg.histogram(
            "mmlspark_jit_compile_seconds",
            "lower+compile wall time per new abstract signature",
            labels=("fn",)).labels(fn=self.name)
        self._c_storm = reg.counter(
            "mmlspark_jit_recompile_storm_total",
            "signatures compiled at/over the recompile-storm threshold",
            labels=("fn",)).labels(fn=self.name)
        self._g_flops = reg.gauge(
            "mmlspark_jit_flops",
            "cost_analysis FLOPs of the last compile",
            labels=("fn",))
        self._g_bytes = reg.gauge(
            "mmlspark_jit_bytes_accessed",
            "cost_analysis bytes accessed of the last compile",
            labels=("fn",))
        table = getattr(reg, "_jit_wrappers", None)
        if table is None:
            table = reg._jit_wrappers = {}
        table.setdefault(self.name, weakref.WeakSet()).add(self)

    # ------------------------------------------------------------- dispatch
    def _signature(self, args, kwargs) -> Tuple:
        import jax
        sig: List = []
        for i, a in enumerate(args):
            if i in self._static_nums:
                sig.append(("static", a))
                continue
            leaves, treedef = jax.tree_util.tree_flatten(a)
            sig.append((treedef, tuple(_leaf_sig(l) for l in leaves)))
        for k in sorted(kwargs):
            if k in self._static_names:
                sig.append((k, ("static", kwargs[k])))
                continue
            leaves, treedef = jax.tree_util.tree_flatten(kwargs[k])
            sig.append((k, treedef, tuple(_leaf_sig(l) for l in leaves)))
        return tuple(sig)

    def _render(self, args, kwargs) -> str:
        parts: List[str] = []
        import jax
        for i, a in enumerate(args):
            if i in self._static_nums:
                parts.append(f"static:{a!r}")
            else:
                leaves, _ = jax.tree_util.tree_flatten(a)
                parts.append("/".join(_render_leaf(_leaf_sig(l))
                                      for l in leaves) or "()")
        for k in sorted(kwargs):
            if k in self._static_names:
                parts.append(f"{k}=static:{kwargs[k]!r}")
            else:
                leaves, _ = jax.tree_util.tree_flatten(kwargs[k])
                parts.append(f"{k}=" + ("/".join(
                    _render_leaf(_leaf_sig(l)) for l in leaves) or "()"))
        return ", ".join(parts)

    def _call_compiled(self, compiled, args, kwargs):
        if not self._has_static:
            return compiled(*args, **kwargs)
        dyn_args = tuple(a for i, a in enumerate(args)
                         if i not in self._static_nums)
        dyn_kwargs = {k: v for k, v in kwargs.items()
                      if k not in self._static_names}
        return compiled(*dyn_args, **dyn_kwargs)

    def __call__(self, *args, **kwargs):
        sig = self._signature(args, kwargs)
        entry = self._entries.get(sig)  # GIL-atomic read; hot path
        if entry is not None:
            if entry.compiled is not None:
                return self._call_compiled(entry.compiled, args, kwargs)
            return self._jitted(*args, **kwargs)
        return self._compile_miss(sig, args, kwargs)

    def _compile_miss(self, sig, args, kwargs):
        """Cache miss: compile (AOT when possible), book, then execute.
        Serialized per wrapper so concurrent first calls book one compile."""
        # environment/device gauges ride the first compile, NOT wrapper
        # construction: module-level `@instrumented_jit` must never
        # initialize the jax backend at import time (both are idempotent)
        ensure_build_info(self.registry)
        ensure_device_memory_gauges(self.registry)
        with self._lock:
            entry = self._entries.get(sig)
            if entry is None:
                entry = self._do_compile(sig, args, kwargs)
        # execution happens OUTSIDE the wrapper lock
        if entry.compiled is not None:
            try:
                return self._call_compiled(entry.compiled, args, kwargs)
            except TypeError:
                # Compiled raises TypeError for call-shape mismatches
                # (pytree drift, tracer args) BEFORE executing — safe to
                # fall back to plain jit.  Anything else is a real runtime
                # failure and must propagate: re-executing would double-run
                # side effects and crash on donated (consumed) buffers.
                entry.compiled = None  # permanent fallback for this sig
        return self._jitted(*args, **kwargs)

    def _do_compile(self, sig, args, kwargs) -> _SigEntry:
        entry = _SigEntry(self._render(args, kwargs))
        t0 = time.perf_counter()
        try:
            lowered = self._jitted.lower(*args, **kwargs)
            compiled = lowered.compile()
        except Exception:  # noqa: BLE001 — fall back to plain jit dispatch
            compiled = None
        entry.compile_s = time.perf_counter() - t0
        if compiled is not None and self._aot:
            entry.compiled = compiled
        if compiled is not None:
            try:
                entry.cost = _extract_cost(compiled.cost_analysis())
            except Exception:  # noqa: BLE001 — cost analysis is best-effort
                entry.cost = None
        self._entries[sig] = entry
        self.last_compile_s = entry.compile_s
        self._book_compile(entry, len(self._entries))
        return entry

    def _book_compile(self, entry: _SigEntry, n_sigs: int) -> None:
        # all booking is host-side, after compile, before execution — a
        # compile that produces a failing program is still a compile
        self._c_compile.inc()
        self._h_compile.observe(entry.compile_s)
        if entry.cost:
            if "flops" in entry.cost:
                self._g_flops.set(entry.cost["flops"], fn=self.name)
            if "bytes_accessed" in entry.cost:
                self._g_bytes.set(entry.cost["bytes_accessed"], fn=self.name)
        if n_sigs >= self.storm_signatures:
            self._c_storm.inc()
            if not self._storm_tripped:
                self._storm_tripped = True
                from ..core.logging import log_event  # lazy: import cycle
                log_event({"event": "recompile_storm",
                           "className": "InstrumentedJit", "fn": self.name,
                           "distinct_signatures": n_sigs,
                           "threshold": self.storm_signatures,
                           "last_signature": entry.rendered})

    # --------------------------------------------------------------- report
    @property
    def compiles(self) -> int:
        return len(self._entries)

    def snapshot(self) -> Dict[str, Any]:
        """JSON-able state for ``/debug/compile``."""
        with self._lock:
            entries = list(self._entries.values())
            tripped = self._storm_tripped
        last_cost = None
        sigs = []
        for e in entries:
            sigs.append({"signature": e.rendered,
                         "compile_seconds": round(e.compile_s, 6),
                         "cost_analysis": e.cost,
                         "aot": e.compiled is not None})
            if e.cost is not None:
                last_cost = e.cost
        return {"fn": self.name, "compiles": len(entries),
                "storm_threshold": self.storm_signatures,
                "storm_tripped": tripped,
                "signatures": sigs, "last_cost_analysis": last_cost}

    # a drop-in must still expose the AOT entry point some callers use
    def lower(self, *args, **kwargs):
        return self._jitted.lower(*args, **kwargs)

    def __repr__(self):
        return (f"InstrumentedJit({self.name!r}, "
                f"signatures={len(self._entries)})")


def instrumented_jit(fn: Optional[Callable] = None, *,
                     name: Optional[str] = None,
                     registry: Optional[MetricsRegistry] = None,
                     storm_signatures: Optional[int] = None,
                     static_argnums=(), static_argnames=(), **jit_kwargs):
    """Drop-in ``jax.jit`` replacement with compile-plane telemetry; usable
    as ``instrumented_jit(fn, name=...)`` or ``@instrumented_jit(name=...)``.
    See :class:`InstrumentedJit`."""
    if fn is None:
        return functools.partial(
            instrumented_jit, name=name, registry=registry,
            storm_signatures=storm_signatures, static_argnums=static_argnums,
            static_argnames=static_argnames, **jit_kwargs)
    return InstrumentedJit(fn, name=name, registry=registry,
                           storm_signatures=storm_signatures,
                           static_argnums=static_argnums,
                           static_argnames=static_argnames, **jit_kwargs)


def compile_report(registry: Optional[MetricsRegistry] = None) -> Dict[str, Any]:
    """Aggregated per-function compile state — ``GET /debug/compile``.

    Wrappers sharing a ``name`` (e.g. one per jit-cache key) merge into one
    entry; functions whose wrappers were garbage-collected drop out."""
    reg = registry if registry is not None else get_registry()
    table: Dict[str, Any] = getattr(reg, "_jit_wrappers", {})
    functions: Dict[str, Any] = {}
    for name in sorted(table):
        wrappers = [w for w in table[name]]
        if not wrappers:
            continue
        snaps = [w.snapshot() for w in wrappers]
        functions[name] = {
            "compiles": sum(s["compiles"] for s in snaps),
            "storm_threshold": min(s["storm_threshold"] for s in snaps),
            "storm_tripped": any(s["storm_tripped"] for s in snaps),
            "signatures": [sig for s in snaps for sig in s["signatures"]],
            "last_cost_analysis": next(
                (s["last_cost_analysis"] for s in reversed(snaps)
                 if s["last_cost_analysis"] is not None), None),
        }
    return {"functions": functions,
            "storm_threshold_default": _storm_threshold()}


# ---------------------------------------------------------------------------
# device-memory gauges
# ---------------------------------------------------------------------------

def _mem_stat(device, key: str) -> float:
    stats = device.memory_stats()
    if not stats:
        return float("nan")
    return float(stats.get(key, float("nan")))


def ensure_device_memory_gauges(registry: Optional[MetricsRegistry] = None,
                                devices=None) -> bool:
    """Register per-local-device callback gauges sampled from
    ``device.memory_stats()`` at scrape time:

    - ``mmlspark_device_bytes_in_use{device}``
    - ``mmlspark_device_peak_bytes_in_use{device}``

    Idempotent per registry.  Platforms without memory introspection (CPU
    returns None) register nothing — a dashboard should see no series, not
    a wall of NaN.  Returns True when the gauges are live."""
    reg = registry if registry is not None else get_registry()
    state = getattr(reg, "_device_mem_gauges", None)
    if state:
        return True
    # a cached negative verdict short-circuits only the ambient path —
    # explicit devices= (tests, late-attached accelerators) re-evaluate
    if state is False and devices is None:
        return False
    if devices is None:
        try:
            import jax
            devices = jax.local_devices()
        except Exception:  # noqa: BLE001 — no jax / backend unreachable:
            return False   # transient — no verdict cached, retried next
                           # compile (misses are rare by construction)
    live = []
    for d in devices:
        try:
            if d.memory_stats():
                live.append(d)
        except Exception:  # noqa: BLE001 — introspection unsupported
            continue
    if not live:
        reg._device_mem_gauges = False
        return False
    g_use = reg.gauge("mmlspark_device_bytes_in_use",
                      "live allocated bytes per local device (sampled from "
                      "memory_stats at scrape time)", labels=("device",))
    g_peak = reg.gauge("mmlspark_device_peak_bytes_in_use",
                       "high-water allocated bytes per local device",
                       labels=("device",))
    for d in live:
        label = f"{d.platform}:{d.id}"
        g_use.set_function(
            functools.partial(_mem_stat, d, "bytes_in_use"), device=label)
        g_peak.set_function(
            functools.partial(_mem_stat, d, "peak_bytes_in_use"),
            device=label)
    reg._device_mem_gauges = True
    return True


# ---------------------------------------------------------------------------
# host->device transfer accounting
# ---------------------------------------------------------------------------

def transfer_nbytes(x) -> int:
    """Total buffer bytes in a pytree (what a device_put will move or, for
    already-resident arrays, re-place)."""
    import jax
    leaves, _ = jax.tree_util.tree_flatten(x)
    return sum(int(getattr(l, "nbytes", 0)) for l in leaves)


def _transfer_child(site: str, reg: MetricsRegistry):
    cache = getattr(reg, "_transfer_children", None)
    if cache is None:
        cache = reg._transfer_children = {}
    child = cache.get(site)
    if child is None:
        child = cache[site] = reg.counter(
            "mmlspark_device_transfer_bytes_total",
            "bytes offered to device_put by call site (host->device feed; "
            "already-resident arrays count as placement)",
            labels=("site",)).labels(site=site)
    return child


def device_put(x, device=None, *, site: str = "unlabeled",
               registry: Optional[MetricsRegistry] = None, **kw):
    """Drop-in ``jax.device_put`` that books
    ``mmlspark_device_transfer_bytes_total{site}`` before the transfer.
    The byte count is computed host-side from the input leaves, so the
    booking adds no device sync."""
    import jax
    reg = registry if registry is not None else get_registry()
    _transfer_child(site, reg).inc(transfer_nbytes(x))
    if device is None:
        return jax.device_put(x, **kw)
    return jax.device_put(x, device, **kw)


# ---------------------------------------------------------------------------
# build info
# ---------------------------------------------------------------------------

def ensure_build_info(registry: Optional[MetricsRegistry] = None) -> bool:
    """Register the ``mmlspark_build_info`` gauge (constant 1) labelled with
    the jax version, backend, device kind, and local device count — the
    pivot every scraped dashboard needs to split series by environment.
    Idempotent per registry; a jax-less environment registers nothing."""
    reg = registry if registry is not None else get_registry()
    state = getattr(reg, "_build_info_done", None)
    if state is not None:
        return state
    try:
        import jax
        devices = jax.local_devices()
        backend = jax.default_backend()
        kind = devices[0].device_kind if devices else "unknown"
        reg.gauge("mmlspark_build_info",
                  "constant 1; labels identify the compute environment",
                  labels=("jax", "backend", "device_kind", "device_count")
                  ).set(1.0, jax=jax.__version__, backend=backend,
                        device_kind=kind, device_count=str(len(devices)))
    except Exception:  # noqa: BLE001 — no jax / no backend: skip quietly
        reg._build_info_done = False
        return False
    reg._build_info_done = True
    return True
