"""SpanCollector — bounded in-process span buffer with OTLP-shaped export.

PR 2 left spans dying in the ``core/logging.py`` event ring: aggregate
percentiles on ``/metrics`` could not be traced back to the request that
caused them.  This module closes the loop:

- every finished span lands in a **bounded, drop-counting ring**
  (``record()`` is one deque append under a short lock — it NEVER blocks
  the caller, and overflow drops the oldest span and counts the drop);
- the ring answers ``trace(trace_id)`` / ``trace_tree(trace_id)`` /
  ``slowest(k)`` — the queries behind ``GET /trace/<id>`` and
  ``GET /debug/slow`` on ``PipelineServer``;
- a background **flusher** (off by default; enabled by the
  ``MMLSPARK_TPU_OTLP_ENDPOINT`` env knob or explicit construction)
  batches spans into OTLP/JSON-shaped payloads and writes them to a file
  sink (``file://<path>`` — one JSON payload per line) or POSTs them
  through the breaker/deadline-aware ``io/http.py`` client.  A dead
  collector endpoint costs one probe per breaker cooldown, never
  backpressure: failed batches are dropped and counted, the scoring path
  is untouched.

Export telemetry (registered by ``instruments.instrument_collector``):
ring drops, export spans/batches by result, flush latency, live queue
depth — the collector watches the pipeline, and the registry watches the
collector.

Timestamps: spans run on injectable (usually monotonic) clocks; OTLP wants
unix nanos.  ``epoch_offset_s`` (default: ``time.time() - time.monotonic()``
captured once at construction) shifts span times into the unix epoch —
best-effort for payload shape, exact only for spans on the monotonic clock.
"""
from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Any, Callable, Deque, Dict, List, Optional

from .metrics import MetricsRegistry, get_registry
from ..utils.concurrency import make_lock

__all__ = ["SpanCollector", "get_collector", "OTLP_ENDPOINT_ENV",
           "OTLP_SAMPLE_ENV", "OTLP_SLOW_S_ENV"]

#: env knob enabling span export (off when unset/empty).  ``http(s)://``
#: values POST OTLP/JSON; ``file://<path>`` appends one payload per line.
OTLP_ENDPOINT_ENV = "MMLSPARK_TPU_OTLP_ENDPOINT"

#: tail-sampling mode: ``slow_error`` keeps only slow (>= the threshold
#: below) or non-ok spans AT EXPORT TIME — the ring (and with it
#: ``/trace/<id>`` + ``/debug/slow``) always sees everything; only the
#: exporter's egress shrinks.  Unset/empty = export every span.
OTLP_SAMPLE_ENV = "MMLSPARK_TPU_OTLP_SAMPLE"

#: duration (seconds, float) at which a span counts as slow for
#: tail-sampling; default 0.25.
OTLP_SLOW_S_ENV = "MMLSPARK_TPU_OTLP_SLOW_S"


def _otlp_value(v: Any) -> Dict[str, Any]:
    """One OTLP AnyValue."""
    if isinstance(v, bool):
        return {"boolValue": v}
    if isinstance(v, int):
        return {"intValue": str(v)}
    if isinstance(v, float):
        return {"doubleValue": v}
    return {"stringValue": str(v)}


class SpanCollector:
    """Bounded span ring + optional OTLP-shaped exporter.

    ``record(span)`` is the only hot-path entry point: append to the ring
    (and, when exporting, the export queue) under one short lock; counters
    are booked after release.  Everything slow — serialization, file I/O,
    HTTP — happens on the flusher thread or in scrape-time queries.
    """

    def __init__(self, capacity: int = 2048, registry: Optional[MetricsRegistry] = None,
                 clock: Callable[[], float] = time.monotonic,
                 endpoint: Optional[str] = None,
                 batch_size: int = 128, flush_interval_s: float = 2.0,
                 breaker=None, http_timeout_s: float = 5.0,
                 transport=None, epoch_offset_s: Optional[float] = None,
                 service_name: str = "mmlspark_tpu",
                 sample_mode: Optional[str] = None,
                 slow_threshold_s: Optional[float] = None):
        self.registry = registry if registry is not None else get_registry()
        self.clock = clock
        self.capacity = max(1, int(capacity))
        self.batch_size = max(1, int(batch_size))
        self.flush_interval_s = float(flush_interval_s)
        self.service_name = service_name
        self.http_timeout_s = float(http_timeout_s)
        self._transport = transport
        self._client = None  # lazily built io/http client (HTTP sinks only)
        if endpoint is None:
            endpoint = os.environ.get(OTLP_ENDPOINT_ENV, "")
        self.endpoint = endpoint or ""
        self.exporting = bool(self.endpoint)
        if sample_mode is None:
            sample_mode = os.environ.get(OTLP_SAMPLE_ENV, "")
        if sample_mode not in ("", "slow_error"):
            raise ValueError(f"unknown {OTLP_SAMPLE_ENV} mode "
                             f"{sample_mode!r}; expected 'slow_error'")
        self.sample_mode = sample_mode
        if slow_threshold_s is None:
            slow_threshold_s = float(
                os.environ.get(OTLP_SLOW_S_ENV, "") or 0.25)
        self.slow_threshold_s = float(slow_threshold_s)
        self._file_sink = self.endpoint[len("file://"):] \
            if self.endpoint.startswith("file://") else None
        if epoch_offset_s is None:
            # one wall-clock anchor per collector (module-level-style
            # amortization): exact when spans ride time.monotonic, a
            # best-effort shape otherwise (FakeClock tests pass 0.0)
            epoch_offset_s = time.time() - time.monotonic() \
                if clock is time.monotonic else 0.0
        self.epoch_offset_s = float(epoch_offset_s)
        self._lock = make_lock("SpanCollector._lock")
        self._ring: Deque = collections.deque(maxlen=self.capacity)
        self._export_q: Deque = collections.deque(maxlen=self.capacity)
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._flusher: Optional[threading.Thread] = None
        if breaker is None and self.exporting and self._file_sink is None:
            from ..utils.resilience import CircuitBreaker
            breaker = CircuitBreaker(failure_threshold=3, window_s=60.0,
                                     cooldown_s=30.0, name="otlp-export")
        self.breaker = breaker
        from .instruments import instrument_collector
        self._m = instrument_collector(self, self.registry)
        # self-register as the registry's collector (last construction
        # wins): export_span() resolves `registry._span_collector`, so an
        # explicitly built exporter must take over from (or preempt) the
        # implicit ring-only collector — otherwise it would silently
        # receive nothing while a hidden second collector ate the spans
        self.registry._span_collector = self
        if self.exporting:
            self.start()

    # ------------------------------------------------------------ hot path
    def record(self, span) -> None:
        """Buffer one finished span.  Never blocks: bounded ring, oldest
        dropped on overflow (counted), export queue likewise."""
        ring_dropped = export_dropped = False
        wake = False
        with self._lock:
            if len(self._ring) >= self.capacity:
                ring_dropped = True      # deque maxlen evicts the oldest
            self._ring.append(span)
            if self.exporting:
                if len(self._export_q) >= self.capacity:
                    export_dropped = True
                self._export_q.append(span)
                wake = len(self._export_q) >= self.batch_size
        # telemetry books OUTSIDE the collector lock (LCK discipline)
        if ring_dropped:
            self._m["ring_dropped"].inc()
        if export_dropped:
            self._m["spans_dropped"].inc()
        if wake:
            self._wake.set()

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._export_q)

    # ------------------------------------------------------------- queries
    def trace(self, trace_id: str) -> List:
        """Finished spans of a trace still in the ring, oldest-finish first."""
        with self._lock:
            spans = list(self._ring)
        return [s for s in spans if s.trace_id == trace_id]

    def trace_tree(self, trace_id: str) -> Optional[Dict[str, Any]]:
        """Assembled span tree for ``GET /trace/<id>``: spans nested under
        their parents (orphans — parent already evicted or in another
        process — surface as roots).  None when the trace is unknown."""
        spans = self.trace(trace_id)
        if not spans:
            return None
        nodes = {s.span_id: self._node(s) for s in spans}
        roots: List[Dict[str, Any]] = []
        for s in spans:
            parent = nodes.get(s.parent_id) if s.parent_id else None
            if parent is not None:
                parent["children"].append(nodes[s.span_id])
            else:
                roots.append(nodes[s.span_id])
        return {"traceId": trace_id, "spanCount": len(spans), "roots": roots}

    @staticmethod
    def _node(s) -> Dict[str, Any]:
        return {"name": s.name, "spanId": s.span_id, "parentId": s.parent_id,
                "startS": s.start_s, "durationS": round(s.duration_s, 6),
                "status": s.status, "attributes": dict(s.attributes),
                "children": []}

    def slowest(self, k: int = 10, name: str = "serving.request",
                server: Optional[str] = None) -> List[Dict[str, Any]]:
        """Top-``k`` slowest ring spans named ``name`` (optionally filtered
        to one server's label), slowest first — the ``/debug/slow`` query."""
        with self._lock:
            spans = list(self._ring)
        picked = [s for s in spans if s.name == name and
                  (server is None or s.attributes.get("server") == server)]
        picked.sort(key=lambda s: s.duration_s, reverse=True)
        # attributes spread last (status/queue_s/score_s/verdict/server for
        # serving.request); the span's own status keeps a distinct key
        return [{"traceId": s.trace_id, "durationS": round(s.duration_s, 6),
                 "spanStatus": s.status, **{k_: v for k_, v in
                                            s.attributes.items()}}
                for s in picked[:max(0, int(k))]]

    # -------------------------------------------------------------- export
    def start(self) -> "SpanCollector":
        if self._flusher is None or not self._flusher.is_alive():
            self._stop.clear()
            self._flusher = threading.Thread(target=self._flush_loop,
                                             daemon=True,
                                             name="mmlspark-otlp-flusher")
            self._flusher.start()
        return self

    def stop(self, drain: bool = True) -> None:
        self._stop.set()
        self._wake.set()
        if self._flusher is not None:
            self._flusher.join(timeout=2.0)
            self._flusher = None
        if drain:
            while self.flush_now():
                pass

    def _flush_loop(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(self.flush_interval_s)
            self._wake.clear()
            while self.flush_now() and not self._stop.is_set():
                pass

    def _sample(self, span) -> bool:
        """Tail-sampling verdict at export time: keep non-ok spans and
        spans at/over the slow threshold; everything else is sampled out
        (counted, never sent).  Ring queries are unaffected."""
        if self.sample_mode != "slow_error":
            return True
        return span.status != "ok" or span.duration_s >= self.slow_threshold_s

    def flush_now(self) -> int:
        """Drain up to ``batch_size`` spans and export one payload.
        Returns the number of spans attempted (0 = queue empty).  A failed
        batch is dropped and counted — a dead sink must never make the
        queue (or anything upstream of it) grow without bound.  With
        tail-sampling on, fast-ok spans drain from the queue but are
        dropped (``mmlspark_otlp_sampled_out_total``) before
        serialization, so a healthy system exports ~nothing."""
        with self._lock:
            batch = [self._export_q.popleft()
                     for _ in range(min(self.batch_size, len(self._export_q)))]
        if not batch:
            return 0
        drained = len(batch)
        kept = [s for s in batch if self._sample(s)]
        if len(kept) < drained:
            self._m["sampled_out"].inc(drained - len(kept))
        if not kept:
            return drained          # queue drained; nothing crossed the wire
        batch = kept
        payload = self.to_otlp(batch)
        t0 = self.clock()
        try:
            ok = self._send(payload)
        except Exception:  # noqa: BLE001 — export must never propagate
            ok = False
        self._m["flush_seconds"].observe(max(0.0, self.clock() - t0))
        result = "ok" if ok else "fail"
        self._m[f"batches_{result}"].inc()
        self._m[f"spans_{result}"].inc(len(batch))
        return drained

    def _send(self, payload: Dict[str, Any]) -> bool:
        if self._file_sink is not None:
            line = json.dumps(payload, default=str)
            with open(self._file_sink, "a", encoding="utf-8") as fh:
                fh.write(line + "\n")
            return True
        # HTTP sink rides the resilient client: breaker short-circuits a
        # dead endpoint to a synthetic 503 (one probe per cooldown), the
        # timeout bounds a hung one.  Lazy import: io/http imports tracing.
        from ..io.http import HTTPClient
        client = self._client
        if client is None:
            client = self._client = HTTPClient(
                retries=0, timeout_s=self.http_timeout_s,
                breaker=self.breaker, transport=self._transport)
        resp = client.send_json(self.endpoint, payload)
        return resp is not None and 200 <= resp.status_code < 300

    def to_otlp(self, spans) -> Dict[str, Any]:
        """OTLP/JSON-shaped ExportTraceServiceRequest for a span batch."""
        off = self.epoch_offset_s
        out = []
        for s in spans:
            end_s = s.end_s if s.end_s is not None else s.start_s
            out.append({
                "traceId": s.trace_id,
                "spanId": s.span_id,
                "parentSpanId": s.parent_id or "",
                "name": s.name,
                "kind": 1,  # SPAN_KIND_INTERNAL
                "startTimeUnixNano": str(int((s.start_s + off) * 1e9)),
                "endTimeUnixNano": str(int((end_s + off) * 1e9)),
                "attributes": [{"key": k, "value": _otlp_value(v)}
                               for k, v in s.attributes.items()],
                "status": ({"code": 1} if s.status == "ok" else
                           {"code": 2, "message": s.status}),
            })
        return {"resourceSpans": [{
            "resource": {"attributes": [
                {"key": "service.name",
                 "value": {"stringValue": self.service_name}}]},
            "scopeSpans": [{
                "scope": {"name": "mmlspark_tpu.observability"},
                "spans": out}]}]}


_collector_lock = make_lock("collector._collector_lock")


def get_collector(registry: Optional[MetricsRegistry] = None) -> SpanCollector:
    """The per-registry collector, created on first use (ring always on;
    export only when ``MMLSPARK_TPU_OTLP_ENDPOINT`` is set at creation).
    An explicitly constructed ``SpanCollector(registry=...)`` registers
    itself and is returned here instead."""
    reg = registry if registry is not None else get_registry()
    coll = getattr(reg, "_span_collector", None)
    if coll is None:
        with _collector_lock:
            coll = getattr(reg, "_span_collector", None)
            if coll is None:
                coll = SpanCollector(registry=reg)  # __init__ registers it
    return coll
