"""Declarative SLOs + multi-window burn-rate engine (PR 11).

The Gemma-on-TPU serving study (PAPERS.md) gates its fleet comparisons on
per-class p99/SLO verdicts; this module computes them against the
federated fleet view.  An :class:`SLO` names an objective over one metric
family:

- **latency quantile** — ``p99(mmlspark_serving_request_latency_seconds
  {class=decode}) <= 0.15``: at most ``(100-q)%`` of observations may
  exceed the threshold (that fraction IS the error budget);
- **error-rate budget** — ``error_rate(mmlspark_serving_requests_total
  {status=shed} / mmlspark_serving_requests_total{status=received})
  <= 0.1%``: bad events over total events, both counter selections.

The :class:`SLOEngine` evaluates every SLO against successive
:class:`~.federation.FleetView` snapshots with Google-SRE-style
**multi-window burn rates**: each evaluation appends the cumulative
(bad, total) pair to a history ring, the fast (~5 m) and slow (~1 h)
windows difference that history at their edges, and the burn rate is the
windowed bad-fraction over the budget.  The objective is *burning* only
when BOTH windows burn past ``alert_burn_rate`` — the fast window gives
the page its speed, the slow window keeps a single spike from paging.
Everything runs on an injectable clock; verdicts land on
``GET /fleet/slo``, gauges on ``mmlspark_slo_{burn_rate,budget_remaining}``,
and burning transitions book ``slo_burn``/``slo_recovered`` ring events.
"""
from __future__ import annotations

import collections
import re
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

from .metrics import MetricsRegistry, get_registry

__all__ = ["SLO", "SLOEngine", "parse_slo"]


_FAMILY = r"[A-Za-z_:][\w:]*"
_LATENCY_RE = re.compile(
    rf"^\s*p(?P<q>\d+(?:\.\d+)?)\s*\(\s*(?P<family>{_FAMILY})\s*"
    r"(?P<labels>\{[^}]*\})?\s*\)\s*<=\s*(?P<bound>[0-9.eE+-]+)\s*"
    r"(?P<unit>ms|s)?\s*$")
_ERROR_RATE_RE = re.compile(
    rf"^\s*error_rate\s*\(\s*(?P<bad>{_FAMILY})\s*"
    rf"(?P<bad_labels>\{{[^}}]*\}})?\s*/\s*(?P<total>{_FAMILY})\s*"
    r"(?P<total_labels>\{[^}]*\})?\s*\)\s*<=\s*"
    r"(?P<bound>[0-9.eE+-]+)\s*(?P<pct>%)?\s*$")


def _parse_label_block(block: Optional[str]) -> Dict[str, str]:
    if not block:
        return {}
    inner = block.strip()[1:-1].strip()
    if not inner:
        return {}
    out: Dict[str, str] = {}
    for pair in inner.split(","):
        k, sep, v = pair.partition("=")
        if not sep or not k.strip():
            raise ValueError(f"bad label selector {pair!r} in {block!r}")
        out[k.strip()] = v.strip().strip('"')
    return out


@dataclass
class SLO:
    """One objective.  ``kind`` is ``"latency"`` (quantile ``q`` of
    histogram ``family`` must stay <= ``threshold`` seconds) or
    ``"error_rate"`` (counter selection ``family``+``labels`` over
    ``total_family``+``total_labels`` must stay <= ``threshold``).
    ``budget`` is the allowed bad fraction the burn rate divides by."""

    name: str
    kind: str                      # "latency" | "error_rate"
    family: str
    threshold: float               # seconds (latency) / fraction (error)
    q: float = 99.0
    labels: Dict[str, str] = field(default_factory=dict)
    total_family: str = ""
    total_labels: Dict[str, str] = field(default_factory=dict)
    spec: str = ""

    def __post_init__(self):
        if self.kind not in ("latency", "error_rate"):
            raise ValueError(f"unknown SLO kind {self.kind!r}")
        if self.kind == "latency" and not 0.0 < self.q < 100.0:
            raise ValueError(f"latency quantile must be in (0, 100): {self.q}")
        if self.threshold <= 0:
            raise ValueError(f"SLO threshold must be > 0: {self.threshold}")
        if self.kind == "error_rate" and not self.total_family:
            self.total_family = self.family

    @property
    def budget(self) -> float:
        """Allowed bad-event fraction: the tail the quantile leaves open,
        or the error-rate bound itself."""
        if self.kind == "latency":
            return (100.0 - self.q) / 100.0
        return self.threshold

    def describe(self) -> str:
        if self.spec:
            return self.spec
        if self.kind == "latency":
            return f"p{self.q:g}({self.family}) <= {self.threshold:g}"
        return (f"error_rate({self.family} / {self.total_family}) "
                f"<= {self.threshold:g}")


def parse_slo(spec: str, name: Optional[str] = None) -> SLO:
    """Parse the declarative grammar into an :class:`SLO`:

    - ``p<q>(family{k=v,...}) <= <seconds>[ms]``
    - ``error_rate(family{bad...} / family{total...}) <= <bound>[%]``

    Raises ``ValueError`` on anything else — a typo'd objective must fail
    construction, not silently never fire."""
    m = _LATENCY_RE.match(spec)
    if m is not None:
        bound = float(m.group("bound"))
        if m.group("unit") == "ms":
            bound /= 1000.0
        return SLO(name=name or spec.strip(), kind="latency",
                   family=m.group("family"), threshold=bound,
                   q=float(m.group("q")),
                   labels=_parse_label_block(m.group("labels")), spec=spec)
    m = _ERROR_RATE_RE.match(spec)
    if m is not None:
        bound = float(m.group("bound"))
        if m.group("pct"):
            bound /= 100.0
        return SLO(name=name or spec.strip(), kind="error_rate",
                   family=m.group("bad"), threshold=bound,
                   labels=_parse_label_block(m.group("bad_labels")),
                   total_family=m.group("total"),
                   total_labels=_parse_label_block(m.group("total_labels")),
                   spec=spec)
    raise ValueError(
        f"unparseable SLO spec {spec!r}; expected "
        "'p<q>(family{...}) <= <seconds>' or "
        "'error_rate(family{...} / family{...}) <= <fraction|%>'")


def window_fraction(samples: List[Tuple[float, float, float]], now: float,
                    window_s: float) -> float:
    """Windowed bad-fraction from cumulative (t, bad, total) samples:
    difference the newest sample against the newest sample at/older than
    the window edge (the whole history when shorter than the window).
    No traffic in the window — or a single sample — reads as 0.0: an
    idle fleet is in compliance, not in an undefined state.  Callers own
    monotonicity: difference only histories whose cumulative totals never
    regress (``SLOEngine``/``AutoscaleAdvisor`` clear history on a
    detected counter reset and hold verdicts on shrunken scrape coverage
    — see their docstrings)."""
    if len(samples) < 2:
        return 0.0
    newest = samples[-1]
    cutoff = now - window_s
    base = samples[0]
    for sample in reversed(samples[:-1]):
        if sample[0] <= cutoff:
            base = sample
            break
    if base is newest:
        return 0.0
    d_total = newest[2] - base[2]
    if d_total <= 0:
        return 0.0
    d_bad = max(0.0, newest[1] - base[1])
    return min(1.0, d_bad / d_total)


def coalesce_append(hist, sample: Tuple[float, float, float],
                    min_spacing_s: float) -> None:
    """Append a cumulative sample to a bounded history ring, coalescing
    into the newest slot while it sits within ``min_spacing_s`` of the
    last RETAINED sample (``hist[-2]``).  Retained samples therefore stay
    >= ``min_spacing_s`` apart, so the bounded ring always SPANS at least
    ``min_spacing_s * (maxlen - 2)`` of time regardless of caller cadence.
    Comparing against the newest slot itself would refresh its timestamp
    on every pass and collapse the ring to [oldest, latest] forever —
    silently turning every window lifetime-wide.  The newest slot is
    committed once it has matured ``min_spacing_s`` past its predecessor;
    until then fresh samples coalesce into it."""
    if len(hist) > 1 and hist[-1][0] - hist[-2][0] < min_spacing_s:
        hist[-1] = sample
    else:
        hist.append(sample)


class SLOEngine:
    """Evaluate a set of SLOs against successive fleet views.

    ``slos`` accepts :class:`SLO` objects or grammar strings.  Each
    :meth:`evaluate` appends one cumulative sample per SLO and recomputes
    both windows, so history accumulates at whatever cadence the
    federation poll (or the on-demand endpoints) run — the windows
    difference by *time*, not by sample count.

    Degraded-telemetry discipline: fleet-cumulative counts are only
    comparable across views with the same worker coverage.  When a worker
    that scraped ok last pass drops out (scrape failure or departure),
    this pass HOLDS the previous verdicts instead of differencing a
    shrunken total — a telemetry outage must never fire a false
    ``slo_recovered`` mid-incident.  Coverage GROWTH is the symmetric
    hazard: a worker rejoining after a multi-poll outage injects its
    process-lifetime counts, which did not happen inside any window — so
    any coverage change rebuilds every SLO's history from the new
    baseline (a brief blind window beats a false ``slo_burn`` page).  A
    cumulative total that regresses with stable coverage (worker restart
    resetting its counters) is treated as a counter reset the same way."""

    def __init__(self, slos: Sequence[Union[SLO, str]] = (),
                 registry: Optional[MetricsRegistry] = None,
                 clock: Callable[[], float] = time.monotonic,
                 fast_window_s: float = 300.0, slow_window_s: float = 3600.0,
                 alert_burn_rate: float = 1.0, history_cap: int = 4096):
        self.registry = registry if registry is not None else get_registry()
        self.clock = clock
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.alert_burn_rate = float(alert_burn_rate)
        self.slos: List[SLO] = [s if isinstance(s, SLO) else parse_slo(s)
                                for s in slos]
        names = [s.name for s in self.slos]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO names: {sorted(names)}")
        self._lock = threading.Lock()
        self._history: Dict[str, collections.deque] = {
            s.name: collections.deque(maxlen=max(2, int(history_cap)))
            for s in self.slos}
        # coalescing bound: evaluates arriving faster than this replace
        # the newest sample instead of appending, so a high-cadence
        # on-demand caller can never age the slow-window edge out of the
        # bounded ring (the ring must always SPAN >= slow_window_s)
        self._min_spacing_s = 2.0 * self.slow_window_s / max(2, int(history_cap))
        self._burning: Dict[str, bool] = {s.name: False for s in self.slos}
        self._last_ok_workers: frozenset = frozenset()
        self._last_result: Optional[Dict] = None
        self._pending_rebaseline = False
        from .instruments import instrument_slo_engine
        self._m = instrument_slo_engine(self, self.registry)

    def _cumulative(self, slo: SLO, view) -> Tuple[float, float]:
        """(bad, total) cumulative event counts for one SLO from a view."""
        if slo.kind == "latency":
            return view.fraction_over(slo.family, slo.threshold, slo.labels)
        bad = view.counter_sum(slo.family, slo.labels)
        total = view.counter_sum(slo.total_family, slo.total_labels)
        return bad, total

    def evaluate(self, view, now: Optional[float] = None) -> Dict:
        """One evaluation pass: sample every SLO from ``view``, recompute
        both burn windows, book gauges, and edge-trigger ring events on
        burning transitions.  Returns the ``GET /fleet/slo`` payload.

        A view whose scrape coverage SHRANK since the previous pass holds
        the previous verdicts (``telemetry: held_partial_view``) — see the
        class docstring; a cumulative total that regressed anyway clears
        that SLO's history (counter-reset semantics)."""
        now = self.clock() if now is None else float(now)
        ok_now = frozenset(sid for sid, info in view.workers.items()
                           if info.get("ok", False))
        with self._lock:
            prev_ok = self._last_ok_workers
            self._last_ok_workers = ok_now
            lost = prev_ok - ok_now
            gained = ok_now - prev_ok
            if lost and self._last_result is not None:
                held = dict(self._last_result)
                # whatever coverage the fleet settles on, the NEXT
                # differencing pass must rebuild from a fresh baseline
                self._pending_rebaseline = True
            else:
                held = None
                if gained or self._pending_rebaseline:
                    # coverage CHANGED (a worker rejoined after an outage,
                    # or we are resuming after a held pass): the new view's
                    # cumulative totals include counts that did not happen
                    # inside any window — symmetric twin of the hold rule;
                    # a rejoining worker's lifetime sheds must not fire a
                    # false slo_burn any more than a vanishing worker's
                    # missing counts may fire a false slo_recovered.  No
                    # prev-coverage guard: a pending rebaseline from a
                    # TOTAL outage must survive even though the previous
                    # ok-set was empty (clearing an already-empty history
                    # on the first-ever pass is a no-op anyway).
                    for hist in self._history.values():
                        hist.clear()
                self._pending_rebaseline = False
        if held is not None:
            held["telemetry"] = "held_partial_view"
            held["lost_workers"] = sorted(lost)
            return held
        verdicts: List[Dict] = []
        transitions: List[Dict] = []
        for slo in self.slos:
            bad, total = self._cumulative(slo, view)
            with self._lock:
                hist = self._history[slo.name]
                if hist and total < hist[-1][2]:
                    # cumulative total went backwards with stable coverage:
                    # a worker restarted (fresh counters) or left for good —
                    # counter-reset semantics, rebuild from the new baseline
                    hist.clear()
                coalesce_append(hist, (now, bad, total),
                                self._min_spacing_s)
                samples = list(hist)
            frac_fast = window_fraction(samples, now, self.fast_window_s)
            frac_slow = window_fraction(samples, now, self.slow_window_s)
            budget = slo.budget
            burn_fast = frac_fast / budget
            burn_slow = frac_slow / budget
            rebuilding = len(samples) < 2
            if rebuilding:
                # the windows were just rebaselined (coverage change /
                # counter reset): one sample proves nothing, so the
                # burning state HOLDS — computing "not burning" from an
                # empty window would fire the false slo_recovered the
                # held_partial_view rule exists to prevent; the next pass
                # with real differenced data settles it
                burning = self._burning[slo.name]
            else:
                burning = burn_fast > self.alert_burn_rate \
                    and burn_slow > self.alert_burn_rate
            remaining = max(0.0, 1.0 - burn_slow)
            if not rebuilding:
                # a rebuilding pass computes 0.0 from a <2-sample window —
                # writing that would clear a firing burn-rate alert mid-
                # incident while the verdict deliberately holds burning;
                # the gauges hold their previous values like the verdict
                self._m["burn_rate"].set(burn_fast, slo=slo.name,
                                         window="fast")
                self._m["burn_rate"].set(burn_slow, slo=slo.name,
                                         window="slow")
                self._m["budget_remaining"].set(remaining, slo=slo.name)
            with self._lock:
                flipped = burning != self._burning[slo.name]
                if flipped:
                    self._burning[slo.name] = burning
            if flipped:
                transitions.append(
                    {"event": "slo_burn" if burning else "slo_recovered",
                     "slo": slo.name, "spec": slo.describe(),
                     "burn_fast": round(burn_fast, 4),
                     "burn_slow": round(burn_slow, 4)})
            verdicts.append({
                "slo": slo.name, "spec": slo.describe(), "kind": slo.kind,
                "ok": not burning, "burning": burning,
                "window_rebuilding": rebuilding,
                "burn_rate": {"fast": burn_fast, "slow": burn_slow},
                "bad_fraction": {"fast": frac_fast, "slow": frac_slow},
                "budget": budget, "budget_remaining": remaining,
                "events_total": total,
                "windows_s": {"fast": self.fast_window_s,
                              "slow": self.slow_window_s}})
        # ring events book outside the lock (LCK discipline) — the burn is
        # the page, the ring is where chaos tests and operators read it
        for payload in transitions:
            from ..core.logging import log_event
            log_event(payload)
            if payload["event"] == "slo_burn":
                # flight-recorder dump on the burning EDGE (ISSUE 15):
                # edge-triggered like the ring event, so a sustained burn
                # costs one dump, not one per evaluate pass.  Only an
                # ALREADY-constructed recorder dumps — the engine must not
                # grow process-global crash hooks as a side effect of an
                # SLO evaluation
                rec = getattr(self.registry, "_flight_recorder", None)
                if rec is not None:
                    try:
                        rec.dump(trigger="slo_burn")
                    except Exception:  # noqa: BLE001 — the page still fires
                        pass
        result = {"evaluated_at": now,
                  "alert_burn_rate": self.alert_burn_rate,
                  "slos": verdicts}
        with self._lock:
            self._last_result = result
        return result

    def burning(self) -> Dict[str, bool]:
        with self._lock:
            return dict(self._burning)
