"""Instrumentation adapters — wire existing subsystems into the registry.

Kept separate from ``utils/resilience.py`` so the resilience primitives stay
dependency-free: a ``CircuitBreaker`` only exposes a generic listener hook,
and this module turns it into gauges/counters.
"""
from __future__ import annotations

from typing import Optional

from .metrics import MetricsRegistry, get_registry

__all__ = ["BREAKER_STATE_CODES", "instrument_breaker"]

#: numeric encoding for the breaker-state gauge (alerting rules compare
#: against these: anything > 0 means degraded)
BREAKER_STATE_CODES = {"closed": 0, "half_open": 1, "open": 2}


def instrument_breaker(breaker, registry: Optional[MetricsRegistry] = None,
                       name: Optional[str] = None):
    """Register a ``CircuitBreaker`` with a registry:

    - ``mmlspark_breaker_state{breaker}`` — callback gauge (0 closed /
      1 half-open / 2 open), sampled at scrape time;
    - ``mmlspark_breaker_failure_rate{breaker}`` — callback gauge over the
      breaker's rolling outcome window;
    - ``mmlspark_breaker_transitions_total{breaker,to}`` — counter fed by
      the breaker's transition listener;
    - the breaker lands in ``registry.breakers`` so ``/stats`` endpoints can
      dump ``as_dict()`` per breaker.

    Returns the breaker (chainable at construction sites).
    """
    reg = registry or get_registry()
    bname = name or breaker.name or f"breaker-{id(breaker):x}"
    reg.breakers[bname] = breaker
    reg.gauge("mmlspark_breaker_state",
              "circuit state: 0 closed, 1 half-open, 2 open",
              labels=("breaker",)).set_function(
        lambda b=breaker: BREAKER_STATE_CODES.get(b.state, -1), breaker=bname)
    reg.gauge("mmlspark_breaker_failure_rate",
              "failures / outcomes inside the rolling window",
              labels=("breaker",)).set_function(
        lambda b=breaker: b.failure_rate(), breaker=bname)
    transitions = reg.counter("mmlspark_breaker_transitions_total",
                              "breaker state transitions", labels=("breaker", "to"))

    def on_transition(_breaker, old: str, new: str) -> None:
        transitions.inc(breaker=bname, to=new)

    breaker.add_listener(on_transition)
    return breaker
