"""Instrumentation adapters — wire existing subsystems into the registry.

Kept separate from ``utils/resilience.py`` so the resilience primitives stay
dependency-free: a ``CircuitBreaker`` only exposes a generic listener hook,
and this module turns it into gauges/counters.
"""
from __future__ import annotations

from typing import Optional

from .metrics import MetricsRegistry, get_registry

__all__ = ["BREAKER_STATE_CODES", "instrument_breaker",
           "uninstrument_breaker", "instrument_collector",
           "instrument_federator", "instrument_slo_engine",
           "instrument_autoscaler"]

#: numeric encoding for the breaker-state gauge (alerting rules compare
#: against these: anything > 0 means degraded)
BREAKER_STATE_CODES = {"closed": 0, "half_open": 1, "open": 2}


def instrument_breaker(breaker, registry: Optional[MetricsRegistry] = None,
                       name: Optional[str] = None):
    """Register a ``CircuitBreaker`` with a registry:

    - ``mmlspark_breaker_state{breaker}`` — callback gauge (0 closed /
      1 half-open / 2 open), sampled at scrape time;
    - ``mmlspark_breaker_failure_rate{breaker}`` — callback gauge over the
      breaker's rolling outcome window;
    - ``mmlspark_breaker_transitions_total{breaker,to}`` — counter fed by
      the breaker's transition listener;
    - the breaker lands in ``registry.breakers`` so ``/stats`` endpoints can
      dump ``as_dict()`` per breaker.

    Returns the breaker (chainable at construction sites).
    """
    reg = registry or get_registry()
    bname = name or breaker.name or f"breaker-{id(breaker):x}"
    reg.breakers[bname] = breaker
    reg.gauge("mmlspark_breaker_state",
              "circuit state: 0 closed, 1 half-open, 2 open",
              labels=("breaker",)).set_function(
        lambda b=breaker: BREAKER_STATE_CODES.get(b.state, -1), breaker=bname)
    reg.gauge("mmlspark_breaker_failure_rate",
              "failures / outcomes inside the rolling window",
              labels=("breaker",)).set_function(
        lambda b=breaker: b.failure_rate(), breaker=bname)
    transitions = reg.counter("mmlspark_breaker_transitions_total",
                              "breaker state transitions", labels=("breaker", "to"))

    def on_transition(_breaker, old: str, new: str) -> None:
        transitions.inc(breaker=bname, to=new)

    breaker.add_listener(on_transition)
    # remembered so uninstrument_breaker can detach it — instrument after
    # uninstrument must not leave two listeners double-counting transitions
    _listeners(reg)[bname] = on_transition
    return breaker


def instrument_collector(collector, registry: Optional[MetricsRegistry] = None
                         ) -> dict:
    """Wire a ``SpanCollector``'s public surface into a registry — the
    collector watches the pipeline; these series watch the collector:

    - ``mmlspark_span_ring_dropped_total`` — ring overflow (oldest span
      evicted before any ``/trace`` query could see it);
    - ``mmlspark_otlp_export_spans_total{result}`` — exported (``ok``),
      in failed batches (``fail``), or dropped on export-queue overflow
      (``dropped``);
    - ``mmlspark_otlp_export_batches_total{result}`` — flush outcomes;
    - ``mmlspark_otlp_flush_seconds`` — per-flush latency (serialize +
      sink write, breaker short-circuits included);
    - ``mmlspark_otlp_export_queue_depth`` — callback gauge, sampled at
      scrape time;
    - ``mmlspark_otlp_sampled_out_total`` — spans dropped by tail-sampling
      at export time (``MMLSPARK_TPU_OTLP_SAMPLE=slow_error``): drained
      from the queue but never serialized or sent.

    Returns the bound children keyed by the names the collector's hot and
    flush paths use (children resolved once, never per call).  The
    collector's breaker (HTTP sinks) additionally goes through
    ``instrument_breaker`` so a dead endpoint shows up as an open circuit
    on ``/metrics`` and ``/stats``.
    """
    reg = registry or get_registry()
    spans = reg.counter("mmlspark_otlp_export_spans_total",
                        "spans by export outcome", labels=("result",))
    batches = reg.counter("mmlspark_otlp_export_batches_total",
                          "export flushes by outcome", labels=("result",))
    children = {
        "ring_dropped": reg.counter(
            "mmlspark_span_ring_dropped_total",
            "spans evicted from the collector ring on overflow").labels(),
        "spans_ok": spans.labels(result="ok"),
        "spans_fail": spans.labels(result="fail"),
        "spans_dropped": spans.labels(result="dropped"),
        "batches_ok": batches.labels(result="ok"),
        "batches_fail": batches.labels(result="fail"),
        "flush_seconds": reg.histogram(
            "mmlspark_otlp_flush_seconds",
            "span export flush latency").labels(),
        "sampled_out": reg.counter(
            "mmlspark_otlp_sampled_out_total",
            "spans dropped by slow_error tail-sampling at export "
            "time").labels(),
    }
    reg.gauge("mmlspark_otlp_export_queue_depth",
              "spans buffered for export").set_function(
        lambda c=collector: c.queue_depth())
    if getattr(collector, "breaker", None) is not None:
        instrument_breaker(collector.breaker, reg)
    return children


def instrument_federator(federator, registry: Optional[MetricsRegistry] = None
                         ) -> dict:
    """Wire a ``MetricsFederator`` into a registry — the fleet plane
    watches the workers; these series watch the fleet plane:

    - ``mmlspark_federation_scrape_total{worker,result}`` — per-worker
      scrape outcomes (``ok``/``error``/``parse_error``/
      ``deadline_exhausted``);
    - ``mmlspark_federation_scrape_seconds`` — full-sweep latency;
    - ``mmlspark_federation_stale_workers{federation}`` — callback gauge:
      live workers whose last successful scrape is older than the
      staleness bound (never-scraped counts); labelled by the federator's
      ``name`` so federators sharing a registry neither clobber each
      other's callback nor remove each other's series on close;
    - ``mmlspark_federation_bucket_mismatch_total{family}`` — histogram
      worker-children skipped on mismatched bucket bounds (the
      never-silently-merge rule made visible).

    Returns the bound children/families keyed as the federator's scrape
    path uses them."""
    reg = registry or get_registry()
    children = {
        "scrapes": reg.counter(
            "mmlspark_federation_scrape_total",
            "federation /metrics scrapes by worker and outcome",
            labels=("worker", "result")),
        "scrape_seconds": reg.histogram(
            "mmlspark_federation_scrape_seconds",
            "full federation sweep latency (fan-out + parse + merge)"
            ).labels(),
        "bucket_mismatch": reg.counter(
            "mmlspark_federation_bucket_mismatch_total",
            "histogram children skipped on mismatched bucket bounds "
            "(never silently merged)", labels=("family",)),
    }
    reg.gauge("mmlspark_federation_stale_workers",
              "live workers without a fresh successful scrape",
              labels=("federation",)).set_function(
        lambda f=federator: f.stale_workers(), federation=federator.name)
    return children


def instrument_slo_engine(engine, registry: Optional[MetricsRegistry] = None
                          ) -> dict:
    """Register the SLO engine's verdict gauges:

    - ``mmlspark_slo_burn_rate{slo,window}`` — windowed bad-fraction over
      the error budget (> 1 on both windows = burning);
    - ``mmlspark_slo_budget_remaining{slo}`` — slow-window budget left,
      clamped to [0, 1]."""
    reg = registry or get_registry()
    return {
        "burn_rate": reg.gauge(
            "mmlspark_slo_burn_rate",
            "error-budget burn rate per window (fast/slow)",
            labels=("slo", "window")),
        "budget_remaining": reg.gauge(
            "mmlspark_slo_budget_remaining",
            "fraction of the error budget left over the slow window",
            labels=("slo",)),
    }


def instrument_autoscaler(advisor, registry: Optional[MetricsRegistry] = None
                          ) -> dict:
    """Register the autoscale advisor's recommendation series:

    - ``mmlspark_autoscale_desired_replicas{class}`` — the signal itself;
    - ``mmlspark_autoscale_recommendations_total{class,direction}`` —
      recomputations by direction (``up``/``down``/``hold``) so flapping
      is visible as a rate."""
    reg = registry or get_registry()
    return {
        "desired": reg.gauge(
            "mmlspark_autoscale_desired_replicas",
            "desired replica count per request class", labels=("class",)),
        "recommendations": reg.counter(
            "mmlspark_autoscale_recommendations_total",
            "autoscale recomputations by class and direction",
            labels=("class", "direction")),
    }


def _listeners(reg: MetricsRegistry) -> dict:
    """Per-registry map of breaker name -> transition listener."""
    table = getattr(reg, "_breaker_listeners", None)
    if table is None:
        table = reg._breaker_listeners = {}
    return table


def breaker_registry_name(breaker) -> str:
    """The name a breaker was registered under by ``instrument_breaker``
    (when no explicit ``name=`` override was given)."""
    return breaker.name or f"breaker-{id(breaker):x}"


def uninstrument_breaker(breaker_or_name,
                         registry: Optional[MetricsRegistry] = None) -> None:
    """Reverse of ``instrument_breaker`` for a breaker that is gone for
    good (e.g. its worker was evicted from the topology): drops the
    ``/stats`` entry and the state/failure-rate gauge series, whose
    callback closures would otherwise pin the breaker and scrape frozen
    values forever.  The ``transitions_total`` counter series stays — it
    is history and holds no object references.  No-op if never registered.
    """
    reg = registry or get_registry()
    name = breaker_or_name if isinstance(breaker_or_name, str) \
        else breaker_registry_name(breaker_or_name)
    breaker = reg.breakers.pop(name, None)
    listener = _listeners(reg).pop(name, None)
    if breaker is not None and listener is not None:
        breaker.remove_listener(listener)
    for fam_name in ("mmlspark_breaker_state",
                     "mmlspark_breaker_failure_rate"):
        fam = reg.family(fam_name)  # never CREATE an empty family here
        if fam is not None:
            fam.remove(breaker=name)


def training_instruments(registry: Optional[MetricsRegistry] = None) -> dict:
    """Register (once per registry) the training-plane families that
    :class:`~mmlspark_tpu.observability.trainwatch.TrainingRun` books —
    the ISSUE 19 twin of ``flightrecorder_instruments``.  Counters and the
    step-time histogram are bound per ``job`` by each run; the
    progress/ETA/throughput gauges are callback series the run installs at
    construction and removes at close (the eviction hygiene the breaker
    gauges established)."""
    reg = registry or get_registry()
    got = getattr(reg, "_training_families", None)
    if got is not None:
        return got
    fams = {
        "steps": reg.counter(
            "mmlspark_training_steps_total",
            "training steps/iterations completed per job", labels=("job",)),
        "rows": reg.counter(
            "mmlspark_training_rows_total",
            "training rows processed per job (steps x dataset rows for the "
            "gbdt drivers, batch rows for the parallel trainer)",
            labels=("job",)),
        "stalls": reg.counter(
            "mmlspark_training_stalls_total",
            "training stall-watchdog trips (no tick within "
            "max(k x EWMA step time, floor)); each trip also writes a "
            "trigger=train_stall flight dump", labels=("job",)),
        "step_seconds": reg.histogram(
            "mmlspark_training_step_seconds",
            "tick-to-tick training step time (host wall clock)",
            labels=("job",)),
        "progress": reg.gauge(
            "mmlspark_training_progress_ratio",
            "completed fraction of the declared total steps (NaN when the "
            "driver declared no total)", labels=("job",)),
        "eta": reg.gauge(
            "mmlspark_training_eta_seconds",
            "EWMA-projected seconds to completion (+Inf until the EWMA "
            "and a total are known)", labels=("job",)),
        "rate": reg.gauge(
            "mmlspark_training_rows_per_second",
            "EWMA training throughput in rows/second", labels=("job",)),
    }
    reg._training_families = fams
    return fams
