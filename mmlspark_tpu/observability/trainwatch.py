"""Training observability plane (ISSUE 19): live job monitor + stall watchdog.

The serving half answers "where did the time go" from one registry and one
trace; a multi-hour ``train_streamed`` / ``Trainer.train_stream`` job was a
black box while it ran.  This module gives the training loops the same
surface the fleet already has, in three pieces:

- :class:`TrainingRun` — the heartbeat.  Drivers call ``tick(step, rows,
  loss)`` from their existing ``callbacks`` seam once per boosting
  iteration / train step.  The run maintains an EWMA step time (→ rows/sec
  and a finite ETA when ``total_steps`` is known), a bounded loss tail,
  and books ``mmlspark_training_{steps,rows}_total`` /
  ``mmlspark_training_step_seconds`` plus callback gauges for progress,
  ETA and throughput (families created once per registry by
  ``instruments.training_instruments``).

- the **stall watchdog** — a :class:`~mmlspark_tpu.utils.resilience.Watchdog`
  whose timeout tracks the run: each tick re-``arm``\\ s it (resetting the
  once-per-section trip latch, so recovery re-enables detection) and
  rescales ``stall_timeout_s`` to ``max(stall_factor × EWMA step time,
  floor)``.  A trip books ``mmlspark_training_stalls_total{job}``, fires a
  flight-recorder dump with ``trigger="train_stall"`` (the run
  ``add_source``\\ s its own progress snapshot, so the dump shows
  step/phase/prefetch state), and — opt-in — requests graceful preemption
  so a checkpointing job exits cleanly instead of hanging a pod.
  Deterministic suites construct the run on a FakeClock and call
  :meth:`TrainingRun.check` directly; drivers call :meth:`TrainingRun.start`
  for the real daemon poll thread.

- :class:`MonitorServer` — an opt-in (``monitor_port=`` on all three train
  drivers) HTTP sidecar serving ``GET /progress`` (the JSON snapshot),
  ``GET /metrics`` (Prometheus text, OpenMetrics-negotiated like
  ``PipelineServer``), ``GET /stats`` (the fleet-aggregation shape —
  carries ``checkpoint_last_success_age_seconds`` so "checkpoints stopped
  landing" pages fleet-wide for trainers too), ``GET /health``,
  ``GET /debug/dump`` and ``GET /debug/profile``.  It can register with a
  :class:`~mmlspark_tpu.serving.distributed.TopologyService` under
  ``role="trainer"`` — the federator scrapes it into ``/fleet/metrics``,
  while ``GET /routing`` excludes trainer rows so score traffic never
  lands here.

Every lock routes through ``utils.concurrency.make_lock`` (the ISSUE 18
lock-order sanitizer covers this plane).  The tick path is deliberately
cheap — a clock read, a few float folds, two counter incs and one
histogram observation; the measured overhead on the streamed driver is
recorded in docs/OBSERVABILITY.md ("Training plane").
"""
from __future__ import annotations

import json
import math
import time
import threading
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional

from .instruments import training_instruments
from .metrics import MetricsRegistry, get_registry
from ..utils.concurrency import make_lock
from ..utils.resilience import Watchdog, request_preemption

#: no-tick floor when the caller gives no ``monitor_stall_timeout_s`` —
#: generous on purpose: before the first two ticks there is no EWMA, and a
#: cold first iteration (trace + compile) legitimately dwarfs the steady
#: state.
DEFAULT_STALL_FLOOR_S = 30.0
#: stall threshold = max(factor × EWMA step time, floor) — the "k" of the
#: detection contract.  4× absorbs normal step-time jitter (checkpoint
#: boundaries, eval iterations) without masking a real hang.
DEFAULT_STALL_FACTOR = 4.0
#: EWMA fold weight for step time / throughput: ~2/(N+1) for an effective
#: window of a handful of steps — fast enough to follow a phase change,
#: smooth enough that one slow iteration does not whipsaw the ETA.
DEFAULT_EWMA_ALPHA = 0.3
DEFAULT_LOSS_WINDOW = 64


def _roster(registry: MetricsRegistry, attr: str) -> list:
    """Per-registry enrolment list (the flightrecorder pattern): tests and
    the E2E suite discover live runs/monitors through the registry they
    passed to the driver, without a process-global."""
    got = getattr(registry, attr, None)
    if got is None:
        got = []
        setattr(registry, attr, got)
    return got


def active_runs(registry: Optional[MetricsRegistry] = None) -> List["TrainingRun"]:
    """Live (un-closed) :class:`TrainingRun`\\ s enrolled on ``registry``."""
    reg = registry if registry is not None else get_registry()
    return list(_roster(reg, "_training_runs"))


def active_monitors(registry: Optional[MetricsRegistry] = None
                    ) -> List["MonitorServer"]:
    """Live (started, un-stopped) :class:`MonitorServer`\\ s on ``registry``."""
    reg = registry if registry is not None else get_registry()
    return list(_roster(reg, "_training_monitors"))


class TrainingRun:
    """One training job's heartbeat state + stall watchdog.

    Drivers construct it when monitoring is requested, call :meth:`tick`
    from their ``callbacks`` seam, and :meth:`close` in a ``finally``.
    ``loss`` is optional by contract: ``Trainer.train_stream`` fetches
    losses AFTER its loop (a per-step ``float()`` would serialize the
    pipeline the prefetcher exists to overlap), so its ticks carry
    ``loss=None`` and the tail stays empty for that driver.
    """

    def __init__(self, job: str, *, total_steps: Optional[int] = None,
                 rows_per_step: int = 0,
                 registry: Optional[MetricsRegistry] = None,
                 clock: Callable[[], float] = time.monotonic,
                 stall_timeout_s: Optional[float] = None,
                 stall_factor: float = DEFAULT_STALL_FACTOR,
                 ewma_alpha: float = DEFAULT_EWMA_ALPHA,
                 loss_window: int = DEFAULT_LOSS_WINDOW,
                 preempt_on_stall: bool = False,
                 flight_dump: bool = True,
                 driver: str = ""):
        self.job = str(job)
        self.driver = driver or self.job
        self.total_steps = int(total_steps) if total_steps else None
        self.rows_per_step = int(rows_per_step)
        self.registry = registry if registry is not None else get_registry()
        self.clock = clock
        self.stall_floor_s = float(stall_timeout_s) if stall_timeout_s \
            else DEFAULT_STALL_FLOOR_S
        self.stall_factor = float(stall_factor)
        self.ewma_alpha = float(ewma_alpha)
        self.preempt_on_stall = bool(preempt_on_stall)

        self._lock = make_lock("TrainingRun._lock")
        self._step = 0
        self._rows = 0
        self._losses: deque = deque(maxlen=max(1, int(loss_window)))
        self._ewma_step_s: Optional[float] = None
        self._ewma_rows_rate: Optional[float] = None
        self._last_tick_s: Optional[float] = None
        self._started_s = clock()
        self._stalls = 0
        self._phase = ""
        self._closed = False
        self._prefetch_fn: Optional[Callable[[], Dict]] = None
        self._token = None  # PreemptionToken, when the driver shares one

        fams = training_instruments(self.registry)
        self._c_steps = fams["steps"].labels(job=self.job)
        self._c_rows = fams["rows"].labels(job=self.job)
        self._c_stalls = fams["stalls"].labels(job=self.job)
        self._h_step = fams["step_seconds"].labels(job=self.job)
        # sampled at scrape, never pushed on the tick path
        fams["progress"].set_function(self._progress_ratio, job=self.job)
        fams["eta"].set_function(self._eta_value, job=self.job)
        fams["rate"].set_function(self._rate_value, job=self.job)
        self._fams = fams

        # armed from birth: the hang class this plane exists for includes
        # "the FIRST tile load never returned" — a watchdog armed only
        # after the first tick would sleep through it
        self._watchdog = Watchdog(self.stall_floor_s, clock=clock,
                                  on_stall=self._on_stall,
                                  name=f"trainwatch.{self.job}")
        self._watchdog.arm(self.job)

        self._recorder = None
        if flight_dump:
            from .flightrecorder import get_flight_recorder
            self._recorder = get_flight_recorder(self.registry)
            self._recorder.add_source(f"training.{self.job}", self.progress)

        _roster(self.registry, "_training_runs").append(self)

    # ------------------------------------------------------------ heartbeat
    def tick(self, step: Optional[int] = None, rows: Optional[int] = None,
             loss: Optional[float] = None) -> None:
        """One unit of progress.  ``step`` is the driver's absolute step
        counter (the chunked lightgbm path advances several iterations per
        callback — the delta books them all); ``rows`` overrides the
        ``rows_per_step × delta`` default; ``loss`` (or an eval-metric
        value) feeds the bounded tail when the driver has one host-side."""
        now = self.clock()
        with self._lock:
            if self._closed:
                return
            prev_step = self._step
            self._step = int(step) if step is not None else prev_step + 1
            d_step = max(self._step - prev_step, 1)
            d_rows = int(rows) if rows is not None \
                else self.rows_per_step * d_step
            self._rows += d_rows
            prev_tick, self._last_tick_s = self._last_tick_s, now
            dt = None
            if prev_tick is not None:
                dt = max(now - prev_tick, 1e-9)
                per_step = dt / d_step
                a = self.ewma_alpha
                self._ewma_step_s = per_step if self._ewma_step_s is None \
                    else a * per_step + (1.0 - a) * self._ewma_step_s
                if d_rows > 0:
                    rate = d_rows / dt
                    self._ewma_rows_rate = rate \
                        if self._ewma_rows_rate is None \
                        else a * rate + (1.0 - a) * self._ewma_rows_rate
            if loss is not None:
                self._losses.append(float(loss))
            ewma = self._ewma_step_s
        # booking outside the lock (registry children lock internally)
        self._c_steps.inc(d_step)
        if d_rows:
            self._c_rows.inc(d_rows)
        if dt is not None:
            self._h_step.observe(dt / d_step)
        if ewma is not None:
            self._watchdog.stall_timeout_s = max(
                self.stall_factor * ewma, self.stall_floor_s)
        # re-arm (not heartbeat): arm() bumps the generation and resets the
        # once-per-section trip latch, so a run that recovered from one
        # stall is watched for the next
        self._watchdog.arm(self.job)

    def set_phase(self, name: str) -> None:
        """Coarse driver phase for ``/progress`` (the profiler's
        ``ambient_phase`` is per-thread; this is the job-level headline)."""
        with self._lock:
            self._phase = str(name)

    def set_prefetch_fn(self, fn: Optional[Callable[[], Dict]]) -> None:
        """Install the driver's prefetch-state snapshot (overlap totals +
        the live :meth:`TilePrefetcher.snapshot`); read at ``/progress``
        and flight-dump time, never on the tick path."""
        with self._lock:
            self._prefetch_fn = fn

    def set_preemption_token(self, token) -> None:
        """Share the driver's :class:`PreemptionToken` so ``/progress``
        reports whether a graceful shutdown is already in flight."""
        with self._lock:
            self._token = token

    # --------------------------------------------------------- monitor side
    def check(self) -> bool:
        """One watchdog poll (FakeClock suites call this directly;
        :meth:`start` runs it on a daemon thread)."""
        return self._watchdog.check()

    def start(self, poll_interval_s: Optional[float] = None) -> "TrainingRun":
        """Start the real-clock watchdog poll thread (idempotent)."""
        self._watchdog.start(poll_interval_s)
        return self

    def _on_stall(self, label: str, elapsed: float) -> None:
        # runs on the monitor thread, outside the watchdog lock; a raise
        # is swallowed by the watchdog, so each step is individually safe
        with self._lock:
            self._stalls += 1
        self._c_stalls.inc()
        if self._recorder is not None:
            # the dump carries source.training.<job> (this run's progress
            # snapshot, prefetch state included) + the thread-phase table
            self._recorder.dump(trigger="train_stall")
        if self.preempt_on_stall:
            request_preemption(
                f"trainwatch: {self.job} made no progress for "
                f"{elapsed:.1f}s (timeout "
                f"{self._watchdog.stall_timeout_s:.1f}s)")

    # ------------------------------------------------------------- snapshot
    def _progress_ratio(self) -> float:
        with self._lock:
            if not self.total_steps:
                return float("nan")
            return min(1.0, self._step / float(self.total_steps))

    def _eta_value(self) -> float:
        with self._lock:
            ewma, step = self._ewma_step_s, self._step
        if not ewma or not self.total_steps:
            # armed-but-unknowable stays +Inf on /metrics (the checkpoint
            # age gauge convention); /progress serializes it as null
            return float("inf")
        return max(self.total_steps - step, 0) * ewma

    def _rate_value(self) -> float:
        with self._lock:
            return self._ewma_rows_rate if self._ewma_rows_rate is not None \
                else float("nan")

    def _checkpoint_age_s(self) -> Optional[float]:
        # max finite last-success age across this registry's checkpoint
        # sites — the PipelineServer /stats convention, so the fleet
        # aggregator pages on the same number for trainers
        fam = self.registry.family(
            "mmlspark_checkpoint_last_success_age_seconds")
        if fam is None:
            return None
        vals = [child.value for _k, child in fam._snapshot()]
        vals = [v for v in vals if math.isfinite(v)]
        return max(vals) if vals else None

    def progress(self) -> Dict[str, Any]:
        """The ``/progress`` JSON body (also the flight-dump source): every
        value JSON-safe, unknowns ``null`` rather than non-finite."""
        with self._lock:
            now = self.clock()
            ewma = self._ewma_step_s
            step = self._step
            snap: Dict[str, Any] = {
                "job": self.job,
                "driver": self.driver,
                "step": step,
                "total_steps": self.total_steps,
                "rows": self._rows,
                "rows_per_second": round(self._ewma_rows_rate, 3)
                if self._ewma_rows_rate is not None else None,
                "ewma_step_seconds": round(ewma, 6)
                if ewma is not None else None,
                "elapsed_seconds": round(max(0.0, now - self._started_s), 6),
                "loss_tail": list(self._losses),
                "phase": self._phase,
                "stalls": self._stalls,
            }
            token = self._token
            pf_fn = self._prefetch_fn
        eta = None
        if ewma and self.total_steps:
            eta = max(self.total_steps - step, 0) * ewma
        snap["eta_seconds"] = round(eta, 3) if eta is not None else None
        snap["preemption_requested"] = bool(getattr(token, "requested", False))
        wd = self._watchdog
        snap["watchdog"] = {
            "stalled_for_seconds": round(wd.stalled_for(), 3),
            "stall_timeout_seconds": round(wd.stall_timeout_s, 3),
            "trips": wd.trips,
        }
        if pf_fn is not None:
            try:
                snap["prefetch"] = pf_fn()
            except Exception as e:  # noqa: BLE001 — snapshot must not die
                snap["prefetch"] = {"error": f"{type(e).__name__}: {e}"}
        age = self._checkpoint_age_s()
        if age is not None:
            snap["checkpoint_age_seconds"] = round(age, 3)
        return snap

    # -------------------------------------------------------------- closing
    def close(self) -> None:
        """End of run: stop the watchdog, unhook the flight source, remove
        the per-job gauge series (counters stay — they are history)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._watchdog.disarm()
        self._watchdog.stop()
        if self._recorder is not None:
            self._recorder.remove_source(f"training.{self.job}")
        for key in ("progress", "eta", "rate"):
            try:
                self._fams[key].remove(job=self.job)
            except Exception:  # noqa: BLE001 — a shared-label twin may have
                pass           # removed the series first
        runs = _roster(self.registry, "_training_runs")
        if self in runs:
            runs.remove(self)


def _post_json(url: str, payload: Dict, timeout_s: float = 2.0) -> Dict:
    # lazy: observability must stay importable without the serving layer,
    # and the serving layer imports observability at module scope.  The
    # shared helper clips to the ambient deadline and rides the trace id.
    from ..serving.distributed import _http_json
    return _http_json(url, payload, timeout=timeout_s)


class MonitorServer:
    """Opt-in HTTP sidecar for one :class:`TrainingRun`.

    Deliberately tiny: read-only GETs off the run's snapshot and the shared
    registry, on a ``ThreadingHTTPServer`` daemon thread — no admission
    control, no queue, because the only clients are an operator's curl,
    a Prometheus scrape, and the fleet federator.
    """

    def __init__(self, run: TrainingRun, port: int = 0,
                 host: str = "127.0.0.1",
                 topology_address: Optional[str] = None,
                 server_id: Optional[str] = None):
        self.run = run
        self.registry = run.registry
        self.host, self.port = host, int(port)
        self.topology_address = topology_address
        self.server_id = server_id or f"train-{run.job}"
        self.registered = False
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # ---------------------------------------------------------------- http
    def _make_handler(self):
        mon = self

        class Handler(BaseHTTPRequestHandler):
            # HTTP/1.1 + explicit Content-Length: keep-alive-safe, same
            # contract as PipelineServer so scrapers share client code
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # quiet
                pass

            def _write_raw(self, status: int, body: bytes,
                           ctype: bytes = b"application/json") -> None:
                self.send_response(status)
                self.send_header("Content-Type", ctype.decode())
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                try:
                    self.wfile.write(body)
                except (BrokenPipeError, ConnectionResetError):
                    pass

            def _respond(self, status: int, obj) -> None:
                self._write_raw(status, json.dumps(obj, default=str).encode())

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                if path == "/health":
                    # the TopologyService probe target: a live monitor IS
                    # healthy (training liveness is the watchdog's job —
                    # a stalled run must keep serving its diagnosis)
                    self._write_raw(200, b"ok", b"text/plain")
                elif path == "/progress":
                    self._respond(200, mon.run.progress())
                elif path == "/stats":
                    self._respond(200, mon._stats())
                elif path == "/metrics":
                    # OpenMetrics negotiation, verbatim PipelineServer
                    # semantics: exemplars only under the content type
                    # whose parsers accept them
                    accept = self.headers.get("Accept", "")
                    if "application/openmetrics-text" in accept:
                        body = (mon.registry.to_prometheus(openmetrics=True)
                                + "# EOF\n").encode()
                        ctype = (b"application/openmetrics-text; "
                                 b"version=1.0.0; charset=utf-8")
                    else:
                        body = mon.registry.to_prometheus().encode()
                        ctype = b"text/plain; version=0.0.4; charset=utf-8"
                    self._write_raw(200, body, ctype)
                elif path == "/debug/dump":
                    from .flightrecorder import get_flight_recorder
                    rec = get_flight_recorder(mon.registry)
                    dump_path = rec.dump(trigger="http")
                    snap = dict(rec.last_snapshot or {})
                    snap["dump_path"] = dump_path
                    self._respond(200, snap)
                elif path == "/debug/profile":
                    from .profiling import ProfilerBusy, profile_window
                    seconds, hz, idle = 2.0, None, False
                    query = self.path.partition("?")[2]
                    try:
                        for part in query.split("&"):
                            if part.startswith("seconds="):
                                seconds = float(part[len("seconds="):])
                            elif part.startswith("hz="):
                                hz = float(part[len("hz="):])
                            elif part.startswith("idle="):
                                idle = bool(int(part[len("idle="):]))
                    except ValueError:
                        self._respond(400, {"error": "seconds/hz/idle must "
                                                     "be numeric"})
                        return
                    try:
                        kw = {} if hz is None else {"hz": hz}
                        report = profile_window(seconds=seconds,
                                                registry=mon.registry,
                                                include_idle=idle, **kw)
                    except ProfilerBusy as e:
                        self._respond(409, {"error": str(e)})
                        return
                    self._respond(200, report)
                else:
                    self._respond(404, {"error": "not found"})

        return Handler

    def _stats(self) -> Dict[str, Any]:
        """The shape ``TopologyService.aggregate_stats`` folds: trainers
        contribute no request counters, but their checkpoint age must page
        fleet-wide exactly like a serving worker's."""
        p = self.run.progress()
        d: Dict[str, Any] = {"role": "trainer", "job": self.run.job,
                             "step": p["step"], "stalls": p["stalls"],
                             "preemption_requested":
                                 p["preemption_requested"]}
        age = p.get("checkpoint_age_seconds")
        if age is not None:
            d["checkpoint_last_success_age_seconds"] = age
        return d

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "MonitorServer":
        if self._httpd is not None:
            return self
        httpd = ThreadingHTTPServer((self.host, self.port),
                                    self._make_handler())
        httpd.daemon_threads = True
        self._httpd = httpd
        self.port = httpd.server_address[1]
        thread = threading.Thread(
            target=httpd.serve_forever,
            name=f"mmlspark-trainwatch:{self.run.job}", daemon=True)
        self._thread = thread
        thread.start()
        _roster(self.registry, "_training_monitors").append(self)
        if self.topology_address:
            # best-effort enrolment: a down driver must not kill training.
            # role="trainer" keeps this box out of GET /routing (score
            # traffic) while the federator still scrapes its /metrics.
            try:
                _post_json(f"{self.topology_address}/register",
                           self._registration())
                self.registered = True
            except Exception:  # noqa: BLE001
                self.registered = False
        return self

    def _registration(self) -> Dict[str, Any]:
        return {"server_id": self.server_id, "host": self.host,
                "port": self.port, "api_path": "/progress",
                "request_class": "training", "role": "trainer",
                "generation": 0, "partition_ids": []}

    @property
    def address(self) -> str:
        return f"http://{self.host}:{self.port}"

    def stop(self) -> None:
        httpd, self._httpd = self._httpd, None
        if httpd is None:
            return
        if self.registered and self.topology_address:
            try:
                _post_json(f"{self.topology_address}/deregister",
                           {"server_id": self.server_id})
            except Exception:  # noqa: BLE001
                pass
            self.registered = False
        httpd.shutdown()
        httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        monitors = _roster(self.registry, "_training_monitors")
        if self in monitors:
            monitors.remove(self)


def start_training_monitor(job: str, *, total_steps: Optional[int] = None,
                           rows_per_step: int = 0,
                           registry: Optional[MetricsRegistry] = None,
                           monitor_port: Optional[int] = None,
                           stall_timeout_s: Optional[float] = None,
                           stall_factor: float = DEFAULT_STALL_FACTOR,
                           topology_address: Optional[str] = None,
                           preempt_on_stall: bool = False,
                           clock: Callable[[], float] = time.monotonic,
                           driver: str = ""):
    """Driver-side one-call wiring: build the :class:`TrainingRun`, start
    its watchdog thread, and (when ``monitor_port`` is given — 0 binds an
    ephemeral port) serve it.  Returns ``(run, server_or_None)``; the
    driver owns cleanup (``server.stop()`` then ``run.close()``)."""
    run = TrainingRun(job, total_steps=total_steps,
                      rows_per_step=rows_per_step, registry=registry,
                      clock=clock, stall_timeout_s=stall_timeout_s,
                      stall_factor=stall_factor,
                      preempt_on_stall=preempt_on_stall, driver=driver)
    run.start()
    server = None
    if monitor_port is not None:
        server = MonitorServer(run, port=int(monitor_port),
                               topology_address=topology_address)
        server.start()
    return run, server
