"""Flight recorder — atomic postmortem dumps on crash/preemption/SLO burn.

When a chaos drill SIGKILLs a worker, a fleet shrink preempts training, or
a scorer thread dies on an uncaught exception, the diagnostic state that
explains the incident — the event ring, the slow-span ring, the decode
slot tables, the page-pool occupancy, the compile report — dies with the
process.  The flight recorder is the black box: a bounded snapshot of all
of it, assembled on demand and **dumped atomically** (via
``io/checkpoint.atomic_write`` — a dump racing the crash publishes whole
or not at all, never torn) on:

- **crash** — ``sys.excepthook`` + ``threading.excepthook`` (chained to
  the previous hooks, never replacing them);
- **preemption** — ``utils.resilience`` preemption hooks: both a signal
  landing in a ``preemption_scope`` and a programmatic
  ``request_preemption`` (the membership-shrink path) fire a dump before
  the final checkpoint-and-exit;
- **slo_burn** — the ``SLOEngine`` burning edge (driver-side);
- **demand** — ``GET /debug/dump`` on ``PipelineServer`` (and the
  deadline-bounded ``GET /fleet/dump`` fan-out on ``TopologyService``).

Snapshot sources that cannot be pulled from the registry ride per-registry
``WeakSet`` rosters: ``ContinuousDecoder`` (slot table + pool occupancy),
``ModelRunner`` (last decode geometry) and ``TopologyService`` (membership
epoch) enrol themselves at construction, so the recorder needs no wiring
order and holds no strong references.  ``add_source(name, fn)`` registers
arbitrary extra state.

Metric families (the telemetry-coverage sweep gates on the booking
sites): ``mmlspark_flightrecorder_dumps_total{trigger,result}`` and the
``mmlspark_flightrecorder_last_dump_age_seconds`` callback gauge.

Disk layout: ``<dump_dir>/flightdump_<seq>_<trigger>.json``, keep-last-K
pruned.  With no ``dump_dir`` (parameter or ``MMLSPARK_TPU_FLIGHT_DUMP_DIR``
env), on-demand snapshots still serve over HTTP; trigger dumps book
``result="no_dir"`` and write nothing — a test process must opt in before
its crashes litter the working directory.
"""
from __future__ import annotations

import itertools
import json
import os
import sys
import threading
import time
import weakref
from typing import Any, Callable, Dict, List, Optional

from .metrics import MetricsRegistry, get_registry
from ..utils.concurrency import make_lock

__all__ = ["FlightRecorder", "get_flight_recorder",
           "flightrecorder_instruments", "DUMP_DIR_ENV"]

#: env knob: directory for postmortem dump files (empty/unset = no files;
#: on-demand ``/debug/dump`` snapshots are unaffected)
DUMP_DIR_ENV = "MMLSPARK_TPU_FLIGHT_DUMP_DIR"

_RECORDER_IDS = itertools.count()


def flightrecorder_instruments(registry: Optional[MetricsRegistry] = None
                               ) -> Dict[str, Any]:
    """Register (idempotently) and return the recorder metric families —
    PipelineServer/TopologyService construction calls this so the families
    exist before the first trigger (coverage-gated)."""
    reg = registry if registry is not None else get_registry()
    return {
        "dumps": reg.counter(
            "mmlspark_flightrecorder_dumps_total",
            "flight-recorder dumps by trigger and result",
            labels=("trigger", "result")),
        "age": reg.gauge(
            "mmlspark_flightrecorder_last_dump_age_seconds",
            "seconds since the last successful dump (+Inf before the "
            "first)", labels=("recorder",)),
    }


def _roster(registry, attr: str):
    """The per-registry WeakSet roster named ``attr`` (created on first
    use) — ContinuousDecoder/ModelRunner/TopologyService enrol, the
    recorder iterates live members."""
    ws = getattr(registry, attr, None)
    if ws is None:
        ws = weakref.WeakSet()
        setattr(registry, attr, ws)
    return ws


class FlightRecorder:
    """Bounded black-box snapshot + atomic dump-on-trigger.

    One per registry via :func:`get_flight_recorder` (which also installs
    the crash/preemption hooks); construct explicitly with
    ``install=False`` for hook-free tests.
    """

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 dump_dir: Optional[str] = None, ring_n: int = 128,
                 slow_k: int = 10, keep_last: int = 8,
                 max_metric_entries: int = 400,
                 clock: Callable[[], float] = time.monotonic,
                 install: bool = False):
        self.registry = registry if registry is not None else get_registry()
        if dump_dir is None:
            dump_dir = os.environ.get(DUMP_DIR_ENV, "") or None
        self.dump_dir = dump_dir
        self.ring_n = max(1, int(ring_n))
        self.slow_k = max(0, int(slow_k))
        self.keep_last = max(1, int(keep_last))
        self.max_metric_entries = max(1, int(max_metric_entries))
        self.clock = clock
        self._label = f"r{next(_RECORDER_IDS)}"
        self._m = flightrecorder_instruments(self.registry)
        self._lock = make_lock("FlightRecorder._lock")
        self._seq = itertools.count()
        self._last_dump_s: Optional[float] = None
        #: counter-family baseline from the previous snapshot: the dump
        #: reports DELTAS so "what moved since the last dump" is one read
        self._counter_baseline: Dict[str, float] = {}
        self._sources: Dict[str, Callable[[], Any]] = {}
        self._prev_sys_hook = None
        self._prev_threading_hook = None
        self._installed = False
        self.last_snapshot: Optional[Dict[str, Any]] = None
        self._m["age"].set_function(self._age_s, recorder=self._label)
        if install:
            self.install()

    # ------------------------------------------------------------- sources
    def add_source(self, name: str, fn: Callable[[], Any]) -> None:
        """Register an extra snapshot source; ``fn()`` must return a
        JSON-able value.  A raising source becomes an error row, never a
        failed dump."""
        with self._lock:
            self._sources[str(name)] = fn

    def remove_source(self, name: str) -> None:
        with self._lock:
            self._sources.pop(str(name), None)

    def _age_s(self) -> float:
        last = self._last_dump_s
        return float("inf") if last is None \
            else max(0.0, self.clock() - last)

    # ------------------------------------------------------------ snapshot
    def _metric_section(self) -> Dict[str, Any]:
        """Counter deltas since the previous snapshot + current gauge
        values, bounded to ``max_metric_entries`` rows each (largest
        absolute movers kept; the cut is counted, never silent)."""
        from .metrics import Counter, Gauge, _fmt_labels
        deltas: List = []
        gauges: List = []
        baseline: Dict[str, float] = {}
        for fam in self.registry.families():
            if isinstance(fam, Counter):
                for key, child in fam._snapshot():
                    series = fam.name + _fmt_labels(fam.label_names, key)
                    val = child.value
                    baseline[series] = val
                    prev = self._counter_baseline.get(series, 0.0)
                    if val != prev:
                        deltas.append((series, val - prev, val))
            elif isinstance(fam, Gauge):
                for key, child in fam._snapshot():
                    series = fam.name + _fmt_labels(fam.label_names, key)
                    v = child.value
                    gauges.append((series, v if v == v and abs(v) != float(
                        "inf") else repr(v)))
        self._counter_baseline = baseline
        deltas.sort(key=lambda row: -abs(row[1]))
        cut_d = max(0, len(deltas) - self.max_metric_entries)
        cut_g = max(0, len(gauges) - self.max_metric_entries)
        return {
            "counter_deltas": {s: {"delta": d, "total": t}
                               for s, d, t in
                               deltas[:self.max_metric_entries]},
            "gauges": dict(gauges[:self.max_metric_entries]),
            "truncated": {"counters": cut_d, "gauges": cut_g},
        }

    def _decode_section(self) -> List[Dict[str, Any]]:
        out = []
        for dec in list(_roster(self.registry, "_decode_streams")):
            try:
                out.append(dec.debug_state())
            except Exception as e:  # noqa: BLE001 — a torn decoder is a row
                out.append({"error": f"{type(e).__name__}: {e}"})
        return out

    def _runner_section(self) -> List[Dict[str, Any]]:
        out = []
        for runner in list(_roster(self.registry, "_model_runners")):
            try:
                out.append({"runner": runner.name,
                            "executables": len(runner._executables),
                            "last_decode_extras": runner.last_decode_extras})
            except Exception as e:  # noqa: BLE001
                out.append({"error": f"{type(e).__name__}: {e}"})
        return out

    def _membership_section(self) -> List[Dict[str, Any]]:
        out = []
        for svc in list(_roster(self.registry, "_topology_services")):
            try:
                m = svc.membership()
                out.append({"epoch": m.get("epoch"),
                            "instance": m.get("instance"),
                            "workers": sorted(m.get("workers", {}))})
            except Exception as e:  # noqa: BLE001
                out.append({"error": f"{type(e).__name__}: {e}"})
        return out

    def snapshot(self, trigger: str = "demand") -> Dict[str, Any]:
        """Assemble the bounded black-box snapshot.  Every section is
        individually guarded: one failing source costs its row, never the
        dump — a recorder that throws while the process is already dying
        would be worse than useless."""
        from ..core.logging import recent_events
        from .collector import get_collector
        from .compute import compile_report
        from .tracing import thread_phases

        snap: Dict[str, Any] = {
            "trigger": trigger,
            "pid": os.getpid(),
            "dumped_at_unix": time.time(),
            "recorder": self._label,
        }
        sections: List = [
            ("ring_events", lambda: recent_events()[-self.ring_n:]),
            ("slow_spans", lambda: get_collector(self.registry).slowest(
                k=self.slow_k)),
            ("compile", lambda: compile_report(self.registry)),
            ("metrics", self._metric_section),
            # thread ident -> innermost ambient phase at dump time: a
            # train_stall dump names WHICH phase every worker was stuck in
            # (tile_load vs histogram vs train_step), not just that the
            # loop went quiet (ISSUE 19)
            ("phases", lambda: {str(tid): name
                                for tid, name in thread_phases().items()}),
            ("decode_streams", self._decode_section),
            ("runners", self._runner_section),
            ("membership", self._membership_section),
        ]
        with self._lock:
            extra = list(self._sources.items())
        for name, fn in extra:
            sections.append((f"source.{name}", fn))
        for name, fn in sections:
            try:
                snap[name] = fn()
            except Exception as e:  # noqa: BLE001 — see docstring
                snap[name] = {"error": f"{type(e).__name__}: {e}"}
        return snap

    # ---------------------------------------------------------------- dump
    def dump(self, trigger: str = "demand") -> Optional[str]:
        """Assemble and (when a ``dump_dir`` is configured) atomically
        publish one dump file; returns its path, or None when no directory
        is configured (``result="no_dir"``) or the write failed
        (``result="error"`` — the snapshot still lands on
        ``last_snapshot``).  Books every outcome."""
        snap = self.snapshot(trigger)
        self.last_snapshot = snap
        if self.dump_dir is None:
            self._m["dumps"].inc(trigger=trigger, result="no_dir")
            return None
        seq = next(self._seq)
        path = os.path.join(self.dump_dir,
                            f"flightdump_{seq:06d}_{trigger}.json")
        try:
            from ..io.checkpoint import atomic_write
            with atomic_write(path, "w") as fh:
                json.dump(snap, fh, default=str)
            self._last_dump_s = self.clock()
            self._m["dumps"].inc(trigger=trigger, result="ok")
            self._prune()
            return path
        except Exception:  # noqa: BLE001 — a failed dump must never
            self._m["dumps"].inc(trigger=trigger, result="error")
            return None   # cascade into the crash path that asked for it

    def _prune(self) -> None:
        """Keep the newest ``keep_last`` dump files (by sequence in the
        name; best-effort — a prune failure never fails the dump)."""
        try:
            names = sorted(n for n in os.listdir(self.dump_dir)
                           if n.startswith("flightdump_")
                           and n.endswith(".json"))
            for stale in names[:-self.keep_last]:
                try:
                    os.unlink(os.path.join(self.dump_dir, stale))
                except OSError:
                    pass
        except OSError:
            pass

    # ----------------------------------------------------------- triggers
    def _on_preemption(self, reason) -> None:
        try:
            self.dump(trigger="preemption")
        except Exception:  # noqa: BLE001 — never block the shutdown path
            pass

    def _sys_hook(self, exc_type, exc, tb) -> None:
        try:
            self.dump(trigger="crash")
        except Exception:  # noqa: BLE001 — the original traceback wins
            pass
        prev = self._prev_sys_hook or sys.__excepthook__
        prev(exc_type, exc, tb)

    def _threading_hook(self, args) -> None:
        try:
            self.dump(trigger="crash")
        except Exception:  # noqa: BLE001
            pass
        prev = self._prev_threading_hook or threading.__excepthook__
        prev(args)

    def install(self) -> "FlightRecorder":
        """Chain the crash hooks and register the preemption hook.
        Idempotent; :meth:`uninstall` restores only what this recorder
        installed (and only if still in place)."""
        if self._installed:
            return self
        self._installed = True
        self._prev_sys_hook = sys.excepthook
        sys.excepthook = self._sys_hook
        self._prev_threading_hook = threading.excepthook
        threading.excepthook = self._threading_hook
        from ..utils.resilience import register_preemption_hook
        register_preemption_hook(self._on_preemption)
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        self._installed = False
        # bound-method EQUALITY, not identity: each `self._sys_hook` access
        # builds a fresh bound-method object, so `is` would never match and
        # the hooks would leak past close()
        if sys.excepthook == self._sys_hook:
            sys.excepthook = self._prev_sys_hook or sys.__excepthook__
        if threading.excepthook == self._threading_hook:
            threading.excepthook = self._prev_threading_hook \
                or threading.__excepthook__
        from ..utils.resilience import unregister_preemption_hook
        unregister_preemption_hook(self._on_preemption)

    def close(self) -> None:
        """Uninstall hooks and unhook the age gauge (its closure pins this
        recorder; a discarded test recorder must not scrape forever)."""
        self.uninstall()
        self._m["age"].remove(recorder=self._label)
        if getattr(self.registry, "_flight_recorder", None) is self:
            self.registry._flight_recorder = None


_recorder_lock = make_lock("flightrecorder._recorder_lock")


def get_flight_recorder(registry: Optional[MetricsRegistry] = None,
                        **kwargs) -> FlightRecorder:
    """The per-registry recorder, created (with crash/preemption hooks
    installed) on first use — ``PipelineServer``/``TopologyService``
    construction goes through here so every serving process records."""
    reg = registry if registry is not None else get_registry()
    rec = getattr(reg, "_flight_recorder", None)
    if rec is None:
        with _recorder_lock:
            rec = getattr(reg, "_flight_recorder", None)
            if rec is None:
                rec = FlightRecorder(registry=reg, install=True, **kwargs)
                reg._flight_recorder = rec
    return rec
