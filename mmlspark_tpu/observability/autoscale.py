"""Per-class autoscale signal from the federated fleet view (PR 11).

The ROADMAP's serving-fleet item names this exactly: "an autoscale signal
derived from the queue-delay EWMA the shedder already computes, served
fleet-wide by TopologyService".  The :class:`AutoscaleAdvisor` turns the
fleet-merged telemetry into a **desired-replica recommendation per request
class**:

- **queue-delay EWMA** (``mmlspark_serving_queue_delay_ewma_seconds``,
  mean over the class's workers) against ``target_queue_delay_s``;
- **queue depth** (``mmlspark_serving_queue_depth``, summed) against
  ``depth_per_replica``;
- **shed rate** (``mmlspark_serving_requests_total{status=shed}`` over
  ``{status=received}``, differenced over ``window_s`` like the SLO
  windows) against ``shed_tolerance``;
- **device-time saturation** (ISSUE 17's cost ledger:
  ``mmlspark_request_class_device_seconds_total`` differenced over the
  same window) against the class's device-seconds budget — each replica
  contributes 1 device-second per wall-second, derated by
  ``target_device_utilization``.  This is cost-aware pressure: the fleet
  scales on *projected device-time saturation*, not just on the queue
  symptoms that lag it.

The scalar ``pressure`` is the max of the four ratios — any one signal
saturating is reason enough to scale.  Anti-flap machinery: a
**hysteresis band** (``down_threshold < pressure < up_threshold`` holds
the previous recommendation), a **cooldown** after every change, and a
**decay** path — when the overload drains, the recommendation halves back
toward the live replica count instead of snapping, and only sustained
calm recommends dropping below it.  Everything runs on the injectable
clock; the recommendation is recomputed on every federation poll and
served on ``GET /fleet/autoscale``.
"""
from __future__ import annotations

import collections
import math
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from .metrics import MetricsRegistry, get_registry
# the ONE cumulative edge-differencing + ring-maintenance implementation —
# the shed-rate window and the SLO burn windows must never drift onto
# different math (or different eviction behavior under high cadence)
from .attribution import _window_delta
from .slo import coalesce_append, window_fraction

__all__ = ["AutoscaleAdvisor"]


class AutoscaleAdvisor:
    """Desired-replica recommendations per request class.

    ``recommend(view, workers_by_class)`` is pure with respect to the
    fleet: the view is the telemetry, ``workers_by_class`` the live
    replicas; state per class (previous recommendation, last-change time,
    shed-counter history, calm streak) lives here so hysteresis and
    cooldown survive across polls.  Classes gone from the fleet take
    their state and their desired-replicas GAUGE series with them (a
    frozen gauge would scrape stale recommendations forever); the
    ``recommendations_total`` counter children stay — they are history
    and hold no object references, the ``uninstrument_breaker``
    convention."""

    EWMA_FAMILY = "mmlspark_serving_queue_delay_ewma_seconds"
    DEPTH_FAMILY = "mmlspark_serving_queue_depth"
    REQUESTS_FAMILY = "mmlspark_serving_requests_total"
    CLASS_DEVICE_FAMILY = "mmlspark_request_class_device_seconds_total"

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 clock: Callable[[], float] = time.monotonic,
                 target_queue_delay_s: float = 0.1,
                 shed_tolerance: float = 0.02,
                 depth_per_replica: float = 64.0,
                 target_device_utilization: float = 0.8,
                 window_s: float = 300.0,
                 up_threshold: float = 1.0, down_threshold: float = 0.5,
                 cooldown_s: float = 60.0, calm_s_for_downscale: float = 300.0,
                 min_replicas: int = 1, max_replicas: int = 64,
                 max_step_up: float = 4.0):
        if not down_threshold < up_threshold:
            raise ValueError("down_threshold must be < up_threshold")
        self.registry = registry if registry is not None else get_registry()
        self.clock = clock
        self.target_queue_delay_s = float(target_queue_delay_s)
        self.shed_tolerance = float(shed_tolerance)
        self.depth_per_replica = float(depth_per_replica)
        if not 0.0 < target_device_utilization <= 1.0:
            raise ValueError("target_device_utilization must be in (0, 1]")
        self.target_device_utilization = float(target_device_utilization)
        self.window_s = float(window_s)
        self.up_threshold = float(up_threshold)
        self.down_threshold = float(down_threshold)
        self.cooldown_s = float(cooldown_s)
        # TIME-based (on the injectable clock), like every other anti-flap
        # bound here: a per-call streak would let two on-demand GETs
        # milliseconds apart count as "sustained calm"
        self.calm_s_for_downscale = float(calm_s_for_downscale)
        self.min_replicas = max(0, int(min_replicas))
        self.max_replicas = int(max_replicas)
        self.max_step_up = float(max_step_up)
        # ring-span guard (see slo.coalesce_append): on-demand callers at
        # any cadence must never age the shed window's edge out of the
        # bounded per-class history (deque maxlen 4096 below)
        self._min_spacing_s = 2.0 * self.window_s / 4096
        self._lock = threading.Lock()
        self._state: Dict[str, Dict] = {}
        from .instruments import instrument_autoscaler
        self._m = instrument_autoscaler(self, self.registry)

    # ------------------------------------------------------------- signals
    def _signals(self, view, workers: List[Dict], now: float,
                 st: Dict) -> Dict[str, float]:
        hist = st["hist"]
        dev_hist = st["dev_hist"]
        addrs = {f"{w['host']}:{w['port']}" for w in workers}
        coverage = frozenset(
            sid for w in workers
            if (sid := w.get("server_id")) is not None
            and view.workers.get(sid, {}).get("ok", False))
        if coverage != st.get("coverage"):
            # scrape coverage changed (a worker's /metrics blipped, or it
            # rejoined with its lifetime counters): cumulative counts are
            # not comparable across the change — re-baseline the shed
            # window rather than read a lifetime's sheds as in-window
            # (the instantaneous EWMA/depth gauges keep steering meanwhile)
            hist.clear()
            dev_hist.clear()
            st["coverage"] = coverage
        ewmas = [v for labels, v in view.gauge_values(self.EWMA_FAMILY)
                 if labels.get("server") in addrs and v == v]  # NaN out
        depth = sum(v for labels, v in view.gauge_values(self.DEPTH_FAMILY)
                    if labels.get("server") in addrs and v == v)
        shed = recv = 0.0
        for labels, v in view.counters.get(self.REQUESTS_FAMILY, {}).items():
            d = dict(labels)
            if d.get("server") not in addrs:
                continue
            if d.get("status") == "shed":
                shed += v
            elif d.get("status") == "received":
                recv += v
        if hist and recv < hist[-1][2]:
            # cumulative received went backwards: a replica restarted with
            # fresh counters or left the class — counter-reset semantics,
            # same rule as the SLO windows (a negative diff must read as
            # "no data yet", never as a signal)
            hist.clear()
        coalesce_append(hist, (now, shed, recv), self._min_spacing_s)
        # cost-aware signal (ISSUE 17): the class's cumulative device-time
        # spend from the attribution ledger, differenced over the same
        # window into a device-seconds-per-wall-second rate
        dev = sum(v for labels, v in
                  view.counters.get(self.CLASS_DEVICE_FAMILY, {}).items()
                  if dict(labels).get("server") in addrs)
        if dev_hist and dev < dev_hist[-1][1]:
            dev_hist.clear()
        coalesce_append(dev_hist, (now, dev), self._min_spacing_s)
        w = _window_delta(list(dev_hist), now, self.window_s)
        return {
            "queue_delay_ewma_s": sum(ewmas) / len(ewmas) if ewmas else 0.0,
            "queue_depth": depth,
            "shed_rate": window_fraction(list(hist), now, self.window_s),
            "device_seconds_per_s": (w[1][0] / w[0]) if w else 0.0,
        }

    # ------------------------------------------------------------ decision
    def recommend(self, view, workers_by_class: Dict[str, List[Dict]],
                  now: Optional[float] = None) -> Dict[str, Dict]:
        """Recompute the desired-replica recommendation for every live
        class from one fleet view.  Returns the ``GET /fleet/autoscale``
        payload: ``{class: {current, desired, reason, pressure, signals,
        cooldown_remaining_s}}``."""
        now = self.clock() if now is None else float(now)
        out: Dict[str, Dict] = {}
        bookings: List[Tuple[str, int, str]] = []
        for klass in sorted(workers_by_class):
            workers = workers_by_class[klass]
            n = len(workers)
            # the whole read-decide-write sequence holds the state lock:
            # concurrent ticks (background poll + on-demand ?refresh=1)
            # must never interleave on calm streaks / last_change /
            # desired — a lost update here IS a flap.  Registry bookings
            # drain after release (LCK discipline).
            with self._lock:
                st = self._state.setdefault(klass, {
                    "desired": None, "last_change": -math.inf,
                    "calm_since": None,
                    "hist": collections.deque(maxlen=4096),
                    "dev_hist": collections.deque(maxlen=4096)})
                signals = self._signals(view, workers, now, st)
                # telemetry-blind guard: when NONE of the class's workers
                # scraped ok (and ids were known to check), absent gauges
                # would read as pressure 0 — "calm" — during exactly the
                # overload that times scrapes out.  Hold the previous
                # recommendation instead; the SLO engine's held_partial_view
                # rule, applied to the scaling signal.
                known_ids = [w.get("server_id") for w in workers
                             if w.get("server_id") is not None]
                if known_ids and not st.get("coverage"):
                    st["calm_since"] = None
                    prev = st["desired"] if st["desired"] is not None else n
                    st["desired"] = prev
                    bookings.append((klass, prev, "hold"))
                    out[klass] = {
                        "current": n, "desired": prev,
                        "reason": "telemetry_blind", "pressure": None,
                        "signals": signals,
                        "cooldown_remaining_s": round(max(
                            0.0, self.cooldown_s
                            - (now - st["last_change"])), 3)}
                    continue
                pressure = max(
                    signals["queue_delay_ewma_s"] / self.target_queue_delay_s,
                    signals["shed_rate"] / self.shed_tolerance,
                    signals["queue_depth"]
                    / (max(1, n) * self.depth_per_replica),
                    # cost-aware: measured device-seconds burn rate vs the
                    # class's derated budget of one device-second per
                    # wall-second per replica — saturating device time is
                    # scale-up pressure before the queues ever feel it
                    signals["device_seconds_per_s"]
                    / (max(1, n) * self.target_device_utilization))
                prev = st["desired"] if st["desired"] is not None else n
                cooldown_left = self.cooldown_s - (now - st["last_change"])
                in_cooldown = cooldown_left > 0
                if pressure >= self.up_threshold:
                    st["calm_since"] = None
                    if in_cooldown:
                        desired, reason = prev, "cooldown"
                    else:
                        # bounded proportional growth: never more than
                        # max_step_up x current, never less than one extra
                        want = math.ceil(n * min(pressure, self.max_step_up))
                        desired = max(prev, min(self.max_replicas,
                                                max(n + 1, want)))
                        reason = "scale_up" if desired > prev else "hold"
                elif pressure < self.down_threshold:
                    if st["calm_since"] is None:
                        st["calm_since"] = now
                    if in_cooldown:
                        desired, reason = prev, "cooldown"
                    elif prev > n:
                        # drain: halve the surplus back toward the live count
                        desired = max(n, prev - max(1, (prev - n + 1) // 2))
                        reason = "decay"
                    elif n > self.min_replicas and \
                            now - st["calm_since"] >= self.calm_s_for_downscale:
                        desired, reason = n - 1, "scale_down"
                    else:
                        desired, reason = min(prev, n), "hold"
                else:
                    # hysteresis band: neither overloaded nor provably calm
                    # — hold the previous recommendation (no flapping
                    # between polls that straddle one threshold)
                    st["calm_since"] = None
                    desired, reason = prev, "hysteresis_band"
                if desired != prev:
                    st["last_change"] = now
                    cooldown_left = self.cooldown_s
                st["desired"] = desired
            direction = "up" if desired > prev else \
                "down" if desired < prev else "hold"
            bookings.append((klass, desired, direction))
            out[klass] = {
                "current": n, "desired": desired, "reason": reason,
                "pressure": round(pressure, 4), "signals": signals,
                "cooldown_remaining_s": round(max(0.0, cooldown_left), 3)}
        for klass, desired, direction in bookings:
            self._m["desired"].set(desired, **{"class": klass})
            self._m["recommendations"].inc(
                **{"class": klass, "direction": direction})
        # classes gone from the fleet drop their state AND gauge series
        with self._lock:
            dead = [k for k in self._state if k not in workers_by_class]
            for k in dead:
                self._state.pop(k)
        for k in dead:
            self._m["desired"].remove(**{"class": k})
        return out
