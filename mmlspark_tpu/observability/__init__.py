"""Unified observability layer — metrics registry + tracing + adapters.

One subsystem replaces three telemetry fragments (the ``core/logging.py``
event ring, ``utils/stopwatch.py``, the hand-rolled serving counters):

- ``metrics``     — MetricsRegistry with Counter/Gauge/Histogram families,
  labels, fixed log-spaced latency buckets, Prometheus-text and JSON
  exposition, injectable clocks (tests run on FakeClock);
- ``tracing``     — contextvar-propagated Spans; the trace id rides
  ``X-MMLSpark-Trace-Id`` through io/http clients -> RoutingClient ->
  PipelineServer; finished spans feed the registry and the logging ring;
- ``instruments`` — adapters (CircuitBreaker -> state gauge / failure-rate
  gauge / transition counter + ``/stats`` exposure; SpanCollector ->
  export/drop counters + flush-latency histogram + queue-depth gauge);
- ``collector``   — bounded drop-counting span ring behind
  ``GET /trace/<id>`` / ``GET /debug/slow``, with an optional OTLP-shaped
  exporter (file sink or ``MMLSPARK_TPU_OTLP_ENDPOINT`` POST through the
  breaker-guarded io/http client).  Histograms carry exemplars linking
  bucket outliers to trace ids;
- ``federation`` / ``slo`` / ``autoscale`` — the fleet plane (ISSUE 11):
  ``MetricsFederator`` scrapes + merges every worker's ``/metrics`` into a
  ``FleetView`` (counters summed, gauges worker-labelled, histograms
  merged only on matching bucket bounds), ``SLOEngine`` evaluates
  declarative SLOs with multi-window burn rates, ``AutoscaleAdvisor``
  derives the per-class desired-replica signal — all served by
  ``TopologyService`` at ``GET /fleet/{metrics,slo,autoscale}``.

Hot paths instrumented: ``serving/server.py`` (GET /metrics, queue gauges,
queue-vs-score phase histograms, EWMA shed signal), ``serving/
distributed.py`` (per-worker request/failover/probe counters, per-worker
breakers), ``lightgbm/core.train`` (per-iteration phase timings),
``parallel/trainer.py`` (step timings).  See docs/OBSERVABILITY.md.
"""
from .metrics import (Counter, DEFAULT_LATENCY_BUCKETS, Gauge, Histogram,
                      MetricsRegistry, get_registry, set_registry)
from .tracing import (Span, TRACE_HEADER, TRACEPARENT_HEADER, ambient_phase,
                      current_span, current_trace_id, format_traceparent,
                      new_trace_id, parse_traceparent, thread_phases,
                      trace_span)
from .instruments import (BREAKER_STATE_CODES, instrument_breaker,
                          instrument_collector)
from .collector import OTLP_ENDPOINT_ENV, SpanCollector, get_collector
from .federation import FleetView, MetricsFederator, parse_prometheus
from .slo import SLO, SLOEngine, parse_slo
from .autoscale import AutoscaleAdvisor
from .compute import (InstrumentedJit, compile_report, device_put,
                      ensure_build_info, ensure_device_memory_gauges,
                      instrumented_jit, transfer_nbytes)
from .profiling import (SamplingProfiler, ProfilerBusy, profile_window,
                        profiler_instruments)
from .flightrecorder import (FlightRecorder, flightrecorder_instruments,
                             get_flight_recorder)
from .trainwatch import (MonitorServer, TrainingRun, active_monitors,
                         active_runs, start_training_monitor,
                         training_instruments)

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "DEFAULT_LATENCY_BUCKETS", "get_registry", "set_registry",
           "Span", "TRACE_HEADER", "TRACEPARENT_HEADER", "current_span",
           "current_trace_id", "new_trace_id", "trace_span",
           "ambient_phase", "thread_phases",
           "parse_traceparent", "format_traceparent", "BREAKER_STATE_CODES",
           "instrument_breaker", "instrument_collector",
           "OTLP_ENDPOINT_ENV", "SpanCollector", "get_collector",
           "InstrumentedJit", "instrumented_jit", "compile_report",
           "device_put", "transfer_nbytes", "ensure_build_info",
           "ensure_device_memory_gauges",
           "FleetView", "MetricsFederator", "parse_prometheus",
           "SLO", "SLOEngine", "parse_slo", "AutoscaleAdvisor",
           "SamplingProfiler", "ProfilerBusy", "profile_window",
           "profiler_instruments", "FlightRecorder",
           "flightrecorder_instruments", "get_flight_recorder",
           "TrainingRun", "MonitorServer", "start_training_monitor",
           "training_instruments", "active_runs", "active_monitors"]
