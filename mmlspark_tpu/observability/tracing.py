"""Tracing — contextvar-propagated spans riding the serving/training paths.

A ``Span`` is one timed operation; spans opened inside another span's scope
become its children and share its ``trace_id``.  The trace id crosses
process/socket boundaries on the ``X-MMLSpark-Trace-Id`` header:
``io/http.py`` clients and ``serving/distributed.RoutingClient`` inject the
ambient span's id into outgoing requests, and ``PipelineServer`` adopts an
incoming header so the worker-side spans of a request join the caller's
trace.

Finished spans are exported twice:

- to a ``MetricsRegistry`` as ``mmlspark_spans_total{name}`` /
  ``mmlspark_span_seconds{name}`` (so latency percentiles per span name come
  for free), and
- to the ``core/logging.py`` event ring as an ``event: "span"`` record, so
  ``recent_events()`` shows per-request/per-fit wall-time decomposition next
  to the BasicLogging verb events.

Spans compose with ``utils.resilience.deadline_scope``: a span opened under
an ambient deadline records ``deadline_remaining_ms`` at start, and
``trace_span(..., deadline_s=...)`` installs a deadline for its block, so
"where did the budget go" is answerable from the trace alone.
"""
from __future__ import annotations

import contextlib
import itertools
import math
import os
import threading
import time
from contextvars import ContextVar
from typing import Any, Dict, Optional

from ..utils.resilience import current_deadline, deadline_scope
from .metrics import MetricsRegistry, get_registry

__all__ = ["Span", "TRACE_HEADER", "TRACEPARENT_HEADER", "current_span",
           "current_trace_id", "new_trace_id", "trace_span", "export_span",
           "parse_traceparent", "format_traceparent", "ambient_phase",
           "thread_phases"]

#: wire header carrying the trace id across HTTP hops
TRACE_HEADER = "X-MMLSpark-Trace-Id"

#: W3C Trace Context header (lowercase per spec); accepted on ingress (its
#: trace id is adopted for spans/exemplars, winning over the legacy header)
#: and injected on egress next to the legacy header, so an external frontend
#: that speaks only W3C still gets end-to-end traces through the fleet
TRACEPARENT_HEADER = "traceparent"

_HEX = frozenset("0123456789abcdef")


def _is_hex(s: str) -> bool:
    return bool(s) and all(c in _HEX for c in s)


def parse_traceparent(value) -> Optional[tuple]:
    """``(trace_id, parent_span_id)`` from a W3C ``traceparent`` header, or
    None when malformed (per spec, a malformed header is ignored and a new
    trace starts).  Future versions (> 00) are accepted as long as the
    00-compatible prefix parses; version ``ff`` is explicitly invalid."""
    if not value:
        return None
    parts = str(value).strip().lower().split("-")
    if len(parts) < 4:
        return None
    version, trace_id, span_id = parts[0], parts[1], parts[2]
    if len(version) != 2 or not _is_hex(version) or version == "ff":
        return None
    if len(trace_id) != 32 or not _is_hex(trace_id) or trace_id == "0" * 32:
        return None
    if len(span_id) != 16 or not _is_hex(span_id) or span_id == "0" * 16:
        return None
    if version == "00" and len(parts) != 4:
        return None
    return trace_id, span_id


def format_traceparent(trace_id: Optional[str] = None,
                       span_id: Optional[str] = None,
                       sampled: bool = True) -> str:
    """A valid ``traceparent`` for this process's ids.  Native trace ids are
    already 32 lowercase hex (process prefix + counter) and span ids 16 —
    they pass through unchanged; a foreign id adopted from the legacy header
    is deterministically re-encoded to hex so the wire value stays valid."""
    tid = (trace_id or new_trace_id()).lower()
    if len(tid) != 32 or not _is_hex(tid):
        tid = tid.encode("utf-8", "replace").hex()[:32].ljust(32, "0")
    if tid == "0" * 32:
        tid = new_trace_id()
    sid = (span_id or "").lower()
    if len(sid) != 16 or not _is_hex(sid) or sid == "0" * 16:
        sid = _new_span_id()
    return f"00-{tid}-{sid}-{'01' if sampled else '00'}"


# id generation sits on the serving hot path INSIDE the serialized scoring
# section, where uuid4's per-call os.urandom syscall (~40 us on this
# container's kernel) measurably cut sustained RPS.  Trace/span ids need
# uniqueness, not entropy: one random per-process prefix + a counter.
# itertools.count.__next__ is a single C call — atomic under the GIL.
_ID_PREFIX = os.urandom(8).hex()
_ID_COUNTER = itertools.count(int.from_bytes(os.urandom(4), "big"))


def new_trace_id() -> str:
    return f"{_ID_PREFIX}{next(_ID_COUNTER) & 0xFFFFFFFFFFFFFFFF:016x}"


def _new_span_id() -> str:
    return f"{next(_ID_COUNTER) & 0xFFFFFFFFFFFFFFFF:016x}"


class Span:
    """One timed operation.  Construct directly (explicit ``start``/
    ``finish`` on an injectable clock — used by the serving scorer, which
    back-dates a request span to its enqueue time) or via ``trace_span``."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "start_s",
                 "end_s", "attributes", "status", "clock")

    def __init__(self, name: str, trace_id: Optional[str] = None,
                 parent_id: Optional[str] = None,
                 attributes: Optional[Dict[str, Any]] = None,
                 clock=time.monotonic, start_s: Optional[float] = None):
        self.name = name
        self.trace_id = trace_id or new_trace_id()
        self.span_id = _new_span_id()
        self.parent_id = parent_id
        self.attributes: Dict[str, Any] = dict(attributes or {})
        self.status = "ok"
        self.clock = clock
        self.start_s = clock() if start_s is None else float(start_s)
        self.end_s: Optional[float] = None

    def set_attribute(self, key: str, value: Any) -> None:
        self.attributes[key] = value

    def finish(self, end_s: Optional[float] = None) -> "Span":
        if self.end_s is None:
            self.end_s = self.clock() if end_s is None else float(end_s)
        return self

    @property
    def duration_s(self) -> float:
        end = self.end_s if self.end_s is not None else self.clock()
        return max(0.0, end - self.start_s)

    def to_event(self) -> Dict[str, Any]:
        """Ring-buffer record.  Carries a ``className`` key so ring
        consumers that filter on it (the BasicLogging tests) never KeyError
        on span records."""
        return {"event": "span", "className": "Span", "name": self.name,
                "traceId": self.trace_id, "spanId": self.span_id,
                "parentId": self.parent_id, "seconds": round(self.duration_s, 6),
                "status": self.status, **{f"attr.{k}": v for k, v
                                          in self.attributes.items()}}

    def __repr__(self):
        return (f"Span({self.name!r}, trace={self.trace_id[:8]}, "
                f"{self.duration_s:.6f}s)")


_current_span: ContextVar[Optional[Span]] = \
    ContextVar("mmlspark_tpu_span", default=None)

#: thread ident -> innermost ambient span/phase NAME.  Contextvars cannot
#: be read across threads, so the sampling profiler
#: (``observability/profiling.py``) attributes each sampled thread through
#: this side table instead: ``trace_span`` and ``ambient_phase`` both
#: maintain it (two dict writes per scope — GIL-atomic, no lock; each
#: thread only ever writes its own key).
_THREAD_PHASE: Dict[int, str] = {}


def thread_phases() -> Dict[int, str]:
    """Snapshot of {thread ident: innermost ambient span/phase name} — the
    profiler's attribution table.  Threads outside any ``trace_span`` /
    ``ambient_phase`` scope are absent (attributed ``unattributed``)."""
    return dict(_THREAD_PHASE)


def _enter_phase(name: str) -> tuple:
    tid = threading.get_ident()
    prev = _THREAD_PHASE.get(tid)
    _THREAD_PHASE[tid] = name
    return tid, prev


def _exit_phase(token: tuple) -> None:
    tid, prev = token
    if prev is None:
        _THREAD_PHASE.pop(tid, None)
    else:
        _THREAD_PHASE[tid] = prev


@contextlib.contextmanager
def ambient_phase(name: str):
    """Mark this thread's work as ``name`` for profiler attribution WITHOUT
    opening a Span — the hot-loop variant (e.g. the continuous decode
    engine's step loop, where a span per token would flood the export
    ring).  Nests: inner scopes shadow outer ones, restored on exit."""
    token = _enter_phase(name)
    try:
        yield
    finally:
        _exit_phase(token)


def current_span() -> Optional[Span]:
    """The innermost active span in this context, or None."""
    return _current_span.get()


def current_trace_id() -> Optional[str]:
    span = _current_span.get()
    return span.trace_id if span is not None else None


def export_span(span: Span, registry: Optional[MetricsRegistry] = None) -> None:
    """Record a finished span into the registry (histogram observation
    carries the span's trace id as an exemplar), the per-registry
    ``SpanCollector`` ring (behind ``/trace/<id>`` + ``/debug/slow`` and
    the OTLP exporter), and the logging event ring."""
    span.finish()
    reg = registry or get_registry()
    # per-registry child cache keyed by span name (low-cardinality: stage
    # class names + a handful of subsystem spans) — exports ride every
    # served request, so label resolution must not repeat per call
    cache = getattr(reg, "_span_children", None)
    if cache is None:
        cache = reg._span_children = {}
    pair = cache.get(span.name)
    if pair is None:
        pair = cache[span.name] = (
            reg.counter("mmlspark_spans_total", "finished spans by name",
                        labels=("name",)).labels(name=span.name),
            reg.histogram("mmlspark_span_seconds", "span durations by name",
                          labels=("name",)).labels(name=span.name))
    pair[0].inc()
    pair[1].observe(span.duration_s, span.trace_id)
    # bounded ring for /trace + /debug/slow + OTLP export; record() is one
    # deque append and never blocks this (often request-serialized) caller
    collector = getattr(reg, "_span_collector", None)
    if collector is None:
        from .collector import get_collector  # lazy: collector imports us
        collector = get_collector(reg)
    collector.record(span)
    from ..core.logging import log_event  # lazy: logging lazily imports us
    log_event(span.to_event())


@contextlib.contextmanager
def trace_span(name: str, trace_id: Optional[str] = None,
               attributes: Optional[Dict[str, Any]] = None,
               registry: Optional[MetricsRegistry] = None,
               clock=time.monotonic, deadline_s: Optional[float] = None):
    """Open a span for the block; child of the ambient span (same trace)
    unless an explicit ``trace_id`` adopts one from the wire.  Exceptions
    mark the span ``error:<Type>`` and propagate.  ``deadline_s`` installs a
    ``deadline_scope`` for the block so trace and budget travel together."""
    parent = _current_span.get()
    span = Span(name,
                trace_id=trace_id or (parent.trace_id if parent else None),
                parent_id=parent.span_id if parent else None,
                attributes=attributes, clock=clock)
    ambient = current_deadline()
    if ambient is not None:
        remaining = ambient.remaining()
        if math.isfinite(remaining):  # inf = "no effective bound": omit
            span.set_attribute("deadline_remaining_ms",
                               int(remaining * 1000))
    token = _current_span.set(span)
    phase_token = _enter_phase(name)  # profiler attribution (side table)
    try:
        if deadline_s is not None:
            with deadline_scope(deadline_s, clock):
                yield span
        else:
            yield span
    except BaseException as e:
        span.status = f"error:{type(e).__name__}"
        raise
    finally:
        _exit_phase(phase_token)
        _current_span.reset(token)
        export_span(span, registry)
