"""Tracing — contextvar-propagated spans riding the serving/training paths.

A ``Span`` is one timed operation; spans opened inside another span's scope
become its children and share its ``trace_id``.  The trace id crosses
process/socket boundaries on the ``X-MMLSpark-Trace-Id`` header:
``io/http.py`` clients and ``serving/distributed.RoutingClient`` inject the
ambient span's id into outgoing requests, and ``PipelineServer`` adopts an
incoming header so the worker-side spans of a request join the caller's
trace.

Finished spans are exported twice:

- to a ``MetricsRegistry`` as ``mmlspark_spans_total{name}`` /
  ``mmlspark_span_seconds{name}`` (so latency percentiles per span name come
  for free), and
- to the ``core/logging.py`` event ring as an ``event: "span"`` record, so
  ``recent_events()`` shows per-request/per-fit wall-time decomposition next
  to the BasicLogging verb events.

Spans compose with ``utils.resilience.deadline_scope``: a span opened under
an ambient deadline records ``deadline_remaining_ms`` at start, and
``trace_span(..., deadline_s=...)`` installs a deadline for its block, so
"where did the budget go" is answerable from the trace alone.
"""
from __future__ import annotations

import contextlib
import itertools
import math
import os
import time
from contextvars import ContextVar
from typing import Any, Dict, Optional

from ..utils.resilience import current_deadline, deadline_scope
from .metrics import MetricsRegistry, get_registry

__all__ = ["Span", "TRACE_HEADER", "current_span", "current_trace_id",
           "new_trace_id", "trace_span", "export_span"]

#: wire header carrying the trace id across HTTP hops
TRACE_HEADER = "X-MMLSpark-Trace-Id"


# id generation sits on the serving hot path INSIDE the serialized scoring
# section, where uuid4's per-call os.urandom syscall (~40 us on this
# container's kernel) measurably cut sustained RPS.  Trace/span ids need
# uniqueness, not entropy: one random per-process prefix + a counter.
# itertools.count.__next__ is a single C call — atomic under the GIL.
_ID_PREFIX = os.urandom(8).hex()
_ID_COUNTER = itertools.count(int.from_bytes(os.urandom(4), "big"))


def new_trace_id() -> str:
    return f"{_ID_PREFIX}{next(_ID_COUNTER) & 0xFFFFFFFFFFFFFFFF:016x}"


def _new_span_id() -> str:
    return f"{next(_ID_COUNTER) & 0xFFFFFFFFFFFFFFFF:016x}"


class Span:
    """One timed operation.  Construct directly (explicit ``start``/
    ``finish`` on an injectable clock — used by the serving scorer, which
    back-dates a request span to its enqueue time) or via ``trace_span``."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "start_s",
                 "end_s", "attributes", "status", "clock")

    def __init__(self, name: str, trace_id: Optional[str] = None,
                 parent_id: Optional[str] = None,
                 attributes: Optional[Dict[str, Any]] = None,
                 clock=time.monotonic, start_s: Optional[float] = None):
        self.name = name
        self.trace_id = trace_id or new_trace_id()
        self.span_id = _new_span_id()
        self.parent_id = parent_id
        self.attributes: Dict[str, Any] = dict(attributes or {})
        self.status = "ok"
        self.clock = clock
        self.start_s = clock() if start_s is None else float(start_s)
        self.end_s: Optional[float] = None

    def set_attribute(self, key: str, value: Any) -> None:
        self.attributes[key] = value

    def finish(self, end_s: Optional[float] = None) -> "Span":
        if self.end_s is None:
            self.end_s = self.clock() if end_s is None else float(end_s)
        return self

    @property
    def duration_s(self) -> float:
        end = self.end_s if self.end_s is not None else self.clock()
        return max(0.0, end - self.start_s)

    def to_event(self) -> Dict[str, Any]:
        """Ring-buffer record.  Carries a ``className`` key so ring
        consumers that filter on it (the BasicLogging tests) never KeyError
        on span records."""
        return {"event": "span", "className": "Span", "name": self.name,
                "traceId": self.trace_id, "spanId": self.span_id,
                "parentId": self.parent_id, "seconds": round(self.duration_s, 6),
                "status": self.status, **{f"attr.{k}": v for k, v
                                          in self.attributes.items()}}

    def __repr__(self):
        return (f"Span({self.name!r}, trace={self.trace_id[:8]}, "
                f"{self.duration_s:.6f}s)")


_current_span: ContextVar[Optional[Span]] = \
    ContextVar("mmlspark_tpu_span", default=None)


def current_span() -> Optional[Span]:
    """The innermost active span in this context, or None."""
    return _current_span.get()


def current_trace_id() -> Optional[str]:
    span = _current_span.get()
    return span.trace_id if span is not None else None


def export_span(span: Span, registry: Optional[MetricsRegistry] = None) -> None:
    """Record a finished span into the registry (histogram observation
    carries the span's trace id as an exemplar), the per-registry
    ``SpanCollector`` ring (behind ``/trace/<id>`` + ``/debug/slow`` and
    the OTLP exporter), and the logging event ring."""
    span.finish()
    reg = registry or get_registry()
    # per-registry child cache keyed by span name (low-cardinality: stage
    # class names + a handful of subsystem spans) — exports ride every
    # served request, so label resolution must not repeat per call
    cache = getattr(reg, "_span_children", None)
    if cache is None:
        cache = reg._span_children = {}
    pair = cache.get(span.name)
    if pair is None:
        pair = cache[span.name] = (
            reg.counter("mmlspark_spans_total", "finished spans by name",
                        labels=("name",)).labels(name=span.name),
            reg.histogram("mmlspark_span_seconds", "span durations by name",
                          labels=("name",)).labels(name=span.name))
    pair[0].inc()
    pair[1].observe(span.duration_s, span.trace_id)
    # bounded ring for /trace + /debug/slow + OTLP export; record() is one
    # deque append and never blocks this (often request-serialized) caller
    collector = getattr(reg, "_span_collector", None)
    if collector is None:
        from .collector import get_collector  # lazy: collector imports us
        collector = get_collector(reg)
    collector.record(span)
    from ..core.logging import log_event  # lazy: logging lazily imports us
    log_event(span.to_event())


@contextlib.contextmanager
def trace_span(name: str, trace_id: Optional[str] = None,
               attributes: Optional[Dict[str, Any]] = None,
               registry: Optional[MetricsRegistry] = None,
               clock=time.monotonic, deadline_s: Optional[float] = None):
    """Open a span for the block; child of the ambient span (same trace)
    unless an explicit ``trace_id`` adopts one from the wire.  Exceptions
    mark the span ``error:<Type>`` and propagate.  ``deadline_s`` installs a
    ``deadline_scope`` for the block so trace and budget travel together."""
    parent = _current_span.get()
    span = Span(name,
                trace_id=trace_id or (parent.trace_id if parent else None),
                parent_id=parent.span_id if parent else None,
                attributes=attributes, clock=clock)
    ambient = current_deadline()
    if ambient is not None:
        remaining = ambient.remaining()
        if math.isfinite(remaining):  # inf = "no effective bound": omit
            span.set_attribute("deadline_remaining_ms",
                               int(remaining * 1000))
    token = _current_span.set(span)
    try:
        if deadline_s is not None:
            with deadline_scope(deadline_s, clock):
                yield span
        else:
            yield span
    except BaseException as e:
        span.status = f"error:{type(e).__name__}"
        raise
    finally:
        _current_span.reset(token)
        export_span(span, registry)
