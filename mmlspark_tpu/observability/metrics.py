"""MetricsRegistry — counters, gauges, histograms with Prometheus exposition.

The repo grew three disjoint telemetry fragments (the ``core/logging.py``
event ring, ``utils/stopwatch.py``, and the hand-rolled ``ServingStats``
counters); this module is the single sink they now feed.  Reference framing:
MMLSpark treats per-stage structured telemetry as a pipeline contract
(``logging/BasicLogging.scala``), and its serving docs tune against latency
percentiles — both need one coherent registry, not ad-hoc counters.

Design points:

- **Families + labels.**  ``registry.counter(name, help, labels=(...))``
  returns a family; ``family.labels(k=v)`` (or the inc/set/observe
  conveniences taking ``**labels``) resolves a child per label-value tuple,
  exactly the Prometheus client model.
- **Histograms** use fixed log-spaced latency buckets by default
  (100 µs … 100 s, 4 per decade) so percentile error is bounded by the
  bucket ratio (~1.78x) at any traffic volume, and expose
  p50/p95/p99 summaries computed by linear interpolation within the
  winning bucket (the ``histogram_quantile`` estimator).
- **Injectable clock** everywhere a timestamp or duration is taken, so the
  deterministic suites drive time with ``utils.resilience.FakeClock``.
- **Callback gauges** (``set_function``) read live values at scrape time —
  queue depths and breaker states are sampled, never pushed.
- **Exemplars.**  ``observe(value, trace_id=...)`` retains a tiny
  per-bucket reservoir of ``(value, trace_id, ts)`` samples — last write
  per bucket plus one slot biased to the maximum observation — so a
  histogram outlier links straight to the trace that caused it
  (OpenMetrics exemplar syntax on the text exposition, ``exemplars`` on
  the JSON one).  Cost when no trace id is supplied: one ``is None``
  check.
- Thread-safe: one lock per family; children are plain slots updated under
  it.  The hot path (child inc/observe) is a dict hit + float add.
"""
from __future__ import annotations

import bisect
import math
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "DEFAULT_LATENCY_BUCKETS", "get_registry", "set_registry"]


def _log_spaced_buckets(lo: float = 1e-4, hi: float = 100.0,
                        per_decade: int = 4) -> Tuple[float, ...]:
    """Fixed log-spaced bucket upper bounds, ``lo`` … ``hi`` inclusive."""
    n = int(round(math.log10(hi / lo) * per_decade))
    return tuple(lo * 10.0 ** (i / per_decade) for i in range(n + 1))


#: 100 µs .. 100 s, 4 buckets per decade — covers sub-ms serving replies
#: through multi-minute fits with a bounded ~1.78x quantile error.
DEFAULT_LATENCY_BUCKETS = _log_spaced_buckets()


def _escape_label(value: str) -> str:
    return str(value).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _fmt_labels(names: Sequence[str], values: Sequence[str],
                extra: Optional[List[Tuple[str, str]]] = None) -> str:
    pairs = [(n, v) for n, v in zip(names, values)] + list(extra or [])
    if not pairs:
        return ""
    return "{" + ",".join(f'{n}="{_escape_label(v)}"' for n, v in pairs) + "}"


def _fmt_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    f = float(v)
    return repr(int(f)) if f.is_integer() and abs(f) < 1e15 else repr(f)


class _Family:
    """Shared machinery: named metric + labelled children."""

    kind = "untyped"

    def __init__(self, name: str, help: str, labels: Sequence[str] = ()):
        if not name.replace("_", "").replace(":", "").isalnum():
            raise ValueError(f"invalid metric name {name!r}")
        self.name = name
        self.help = help
        self.label_names = tuple(labels)
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], object] = {}

    def _child_key(self, labels: Dict[str, str]) -> Tuple[str, ...]:
        # hot path (every inc/observe with labels): no set() allocations
        names = self.label_names
        if len(labels) != len(names):
            raise ValueError(
                f"{self.name}: expected labels {names}, got {tuple(labels)}")
        try:
            return tuple(str(labels[n]) for n in names)
        except KeyError:
            raise ValueError(
                f"{self.name}: expected labels {names}, got {tuple(labels)}")

    def labels(self, **labels):
        key = self._child_key(labels)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = self._new_child()
            return child

    def _new_child(self):
        raise NotImplementedError

    def detached_child(self):
        """A child of this family's shape that is NOT registered under any
        label set — a sink for components that must accept writes before
        their identity (e.g. a server's port) is resolved, without leaking
        ghost zero-valued series into every scrape."""
        return self._new_child()

    def remove(self, **labels) -> None:
        """Drop a labelled child from the family (no-op if absent).  Needed
        for callback gauges whose closures pin otherwise-dead objects — a
        stopped server must unhook its samplers or the registry keeps both
        the stale series and the server alive forever."""
        key = self._child_key(labels)
        with self._lock:
            self._children.pop(key, None)

    def _snapshot(self) -> List[Tuple[Tuple[str, ...], object]]:
        with self._lock:
            return sorted(self._children.items())


class _CounterChild:
    __slots__ = ("_value", "_lock")

    def __init__(self):
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Counter(_Family):
    """Monotonic counter family (Prometheus ``counter``)."""

    kind = "counter"

    def _new_child(self):
        return _CounterChild()

    def inc(self, amount: float = 1.0, **labels) -> None:
        self.labels(**labels).inc(amount)

    def value(self, **labels) -> float:
        return self.labels(**labels).value


class _GaugeChild:
    __slots__ = ("_value", "_fn", "_lock")

    def __init__(self):
        self._value = 0.0
        self._fn: Optional[Callable[[], float]] = None
        self._lock = threading.Lock()

    def set(self, value: float) -> None:
        with self._lock:
            self._fn = None
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def set_function(self, fn: Callable[[], float]) -> None:
        with self._lock:
            self._fn = fn

    @property
    def value(self) -> float:
        with self._lock:
            fn = self._fn
            if fn is None:
                return self._value
        try:  # outside the lock: a callback may itself take locks
            return float(fn())
        except Exception:  # noqa: BLE001 — a dead callback scrapes as NaN
            return float("nan")


class Gauge(_Family):
    """Gauge family; ``set_function`` children are sampled at scrape time."""

    kind = "gauge"

    def _new_child(self):
        return _GaugeChild()

    def set(self, value: float, **labels) -> None:
        self.labels(**labels).set(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        self.labels(**labels).inc(amount)

    def set_function(self, fn: Callable[[], float], **labels) -> None:
        self.labels(**labels).set_function(fn)

    def value(self, **labels) -> float:
        return self.labels(**labels).value


class _HistogramChild:
    __slots__ = ("_uppers", "_counts", "_overflow", "_sum", "_count", "_lock",
                 "_clock", "_exemplars", "_max_exemplar")

    def __init__(self, uppers: Tuple[float, ...],
                 clock: Callable[[], float] = time.monotonic):
        self._uppers = uppers
        self._counts = [0] * len(uppers)       # per-bucket, not cumulative
        self._overflow = 0                      # > last finite bound (+Inf)
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()
        self._clock = clock
        # exemplar reservoir: lazily allocated on the first traced
        # observation — one (value, trace_id, ts) slot per bucket (index
        # len(uppers) is the +Inf overflow bucket, last write wins) plus a
        # biased-to-max slot so THE outlier survives any write pattern
        self._exemplars: Optional[List[Optional[Tuple[float, str, float]]]] = None
        self._max_exemplar: Optional[Tuple[float, str, float]] = None

    def observe(self, value: float, trace_id: Optional[str] = None) -> None:
        v = float(value)
        i = bisect.bisect_left(self._uppers, v)
        # clock read + tuple build stay OUTSIDE the lock (LCK discipline)
        ex = None if trace_id is None else (v, str(trace_id), self._clock())
        with self._lock:
            self._sum += v
            self._count += 1
            if i < len(self._uppers):
                self._counts[i] += 1
            else:
                self._overflow += 1
            if ex is not None:
                slots = self._exemplars
                if slots is None:
                    slots = self._exemplars = [None] * (len(self._uppers) + 1)
                slots[min(i, len(self._uppers))] = ex
                if self._max_exemplar is None or v >= self._max_exemplar[0]:
                    self._max_exemplar = ex

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    def cumulative(self) -> List[Tuple[float, int]]:
        """[(upper_bound, cumulative_count)], ending with (+Inf, count)."""
        with self._lock:
            out, cum = [], 0
            for ub, c in zip(self._uppers, self._counts):
                cum += c
                out.append((ub, cum))
            out.append((math.inf, cum + self._overflow))
            return out

    def exemplars(self) -> Optional[Dict[float, Tuple[float, str, float]]]:
        """{bucket_upper_bound: (value, trace_id, ts)} for buckets holding
        an exemplar; key ``math.inf`` is the +Inf bucket, which prefers the
        biased-to-max slot (THE outlier) over its own last write.  None
        when no traced observation was ever recorded."""
        with self._lock:
            slots = self._exemplars
            if slots is None:
                return None
            slots = list(slots)
            max_ex = self._max_exemplar
        out: Dict[float, Tuple[float, str, float]] = {}
        for ub, ex in zip(self._uppers, slots):
            if ex is not None:
                out[ub] = ex
        inf_ex = max_ex or slots[-1]
        if inf_ex is not None:
            out[math.inf] = inf_ex
        return out

    def percentile(self, q: float) -> float:
        """histogram_quantile estimator: linear interpolation inside the
        bucket containing the q-th rank (lower edge of the first bucket is
        0; observations past the last finite bound clamp to it)."""
        with self._lock:
            total = self._count
            counts = list(self._counts)
        if total == 0:
            return float("nan")
        rank = (q / 100.0) * total
        cum, lower = 0.0, 0.0
        for ub, c in zip(self._uppers, counts):
            if c and cum + c >= rank:
                frac = (rank - cum) / c
                return lower + (ub - lower) * frac
            cum += c
            lower = ub
        return self._uppers[-1]


class Histogram(_Family):
    """Histogram family over fixed bucket bounds (default: log-spaced
    latency buckets) with p50/p95/p99 summaries."""

    kind = "histogram"

    def __init__(self, name: str, help: str, labels: Sequence[str] = (),
                 buckets: Optional[Sequence[float]] = None,
                 clock: Callable[[], float] = time.monotonic):
        super().__init__(name, help, labels)
        bs = tuple(sorted(buckets)) if buckets else DEFAULT_LATENCY_BUCKETS
        if not bs:
            raise ValueError("histogram needs at least one bucket")
        self.buckets = bs
        self.clock = clock  # stamps exemplar timestamps

    def _new_child(self):
        return _HistogramChild(self.buckets, self.clock)

    def observe(self, value: float, trace_id: Optional[str] = None,
                **labels) -> None:
        """Record one observation; ``trace_id`` (reserved — cannot be a
        label name) attaches an exemplar linking the sample to a trace."""
        self.labels(**labels).observe(value, trace_id)

    def percentile(self, q: float, **labels) -> float:
        return self.labels(**labels).percentile(q)

    def sum(self, **labels) -> float:
        return self.labels(**labels).sum

    def count(self, **labels) -> int:
        return self.labels(**labels).count


class MetricsRegistry:
    """Named metric families + exposition.

    ``clock`` is only used by helpers that take durations on behalf of the
    caller (``timer``); metric values themselves are caller-supplied, so a
    test can drive everything from a ``FakeClock``.
    """

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self.clock = clock
        self._lock = threading.Lock()
        self._families: Dict[str, _Family] = {}
        # breakers registered for /stats exposure (observability.instruments)
        self.breakers: Dict[str, object] = {}

    # ------------------------------------------------------------- families
    def family(self, name: str) -> Optional[_Family]:
        """An already-registered family by name, or None — lookups that
        must not create (and thereafter scrape) an empty family."""
        with self._lock:
            return self._families.get(name)

    def _get_or_make(self, cls, name, help, labels, **kw) -> _Family:
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = self._families[name] = cls(name, help, labels, **kw)
                return fam
        if not isinstance(fam, cls) or fam.label_names != tuple(labels):
            raise ValueError(
                f"metric {name!r} re-registered with a different "
                f"type/labels ({fam.kind}{fam.label_names})")
        buckets = kw.get("buckets")
        if buckets and tuple(sorted(buckets)) != fam.buckets:
            # silent acceptance would hand the caller bounds sized for a
            # different value range — every observation lands in overflow
            raise ValueError(
                f"histogram {name!r} re-registered with different buckets")
        return fam

    def counter(self, name: str, help: str = "", labels: Sequence[str] = ()
                ) -> Counter:
        return self._get_or_make(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "", labels: Sequence[str] = ()
              ) -> Gauge:
        return self._get_or_make(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "", labels: Sequence[str] = (),
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        return self._get_or_make(Histogram, name, help, labels,
                                 buckets=buckets, clock=self.clock)

    def timer(self, hist: Histogram, **labels):
        """Context manager observing the block's duration on ``clock``."""
        registry = self

        class _Timer:
            def __enter__(self):
                self.t0 = registry.clock()
                return self

            def __exit__(self, *exc):
                hist.observe(registry.clock() - self.t0, **labels)
                return False

        return _Timer()

    # ----------------------------------------------------------- exposition
    def families(self) -> List[_Family]:
        with self._lock:
            return [self._families[k] for k in sorted(self._families)]

    def to_prometheus(self, openmetrics: bool = False) -> str:
        """Prometheus text exposition format 0.0.4; ``openmetrics=True``
        renders the OpenMetrics dialect instead: histogram bucket lines
        carry exemplar suffixes, and counter metadata drops the ``_total``
        suffix from the family name (the spec puts ``_total`` on the
        sample, not the family — a conformant parser rejects both a
        suffixed family and an exemplar in 0.0.4, so the two dialects must
        never mix).

        Callers gate on the scraper's Accept header (``PipelineServer``
        /metrics does) and, for full OpenMetrics compliance, append the
        ``# EOF`` terminator themselves.
        """
        lines: List[str] = []
        for fam in self.families():
            meta_name = fam.name
            if openmetrics and fam.kind == "counter" and \
                    meta_name.endswith("_total"):
                meta_name = meta_name[:-len("_total")]
            if fam.help:
                lines.append(f"# HELP {meta_name} {fam.help}")
            lines.append(f"# TYPE {meta_name} {fam.kind}")
            for key, child in fam._snapshot():
                if isinstance(fam, Histogram):
                    ex_by_ub = (child.exemplars() or {}) if openmetrics \
                        else {}
                    for ub, cum in child.cumulative():
                        lbl = _fmt_labels(fam.label_names, key,
                                          [("le", _fmt_value(ub))])
                        line = f"{fam.name}_bucket{lbl} {cum}"
                        ex = ex_by_ub.get(ub)
                        if ex is not None:
                            # OpenMetrics exemplar syntax (timestamp
                            # omitted: registry clocks are monotonic)
                            line += (' # {trace_id="'
                                     f'{_escape_label(ex[1])}"}} '
                                     f"{_fmt_value(ex[0])}")
                        lines.append(line)
                    base = _fmt_labels(fam.label_names, key)
                    lines.append(f"{fam.name}_sum{base} "
                                 f"{_fmt_value(child.sum)}")
                    lines.append(f"{fam.name}_count{base} {child.count}")
                else:
                    lbl = _fmt_labels(fam.label_names, key)
                    lines.append(f"{fam.name}{lbl} {_fmt_value(child.value)}")
        return "\n".join(lines) + "\n"

    def to_dict(self) -> Dict:
        """JSON-safe snapshot: {name: {type, help, samples: [...]}}; histogram
        samples carry sum/count and interpolated p50/p95/p99."""
        out: Dict = {}
        for fam in self.families():
            samples = []
            for key, child in fam._snapshot():
                labels = dict(zip(fam.label_names, key))
                if isinstance(fam, Histogram):
                    sample = {
                        "labels": labels, "sum": child.sum,
                        "count": child.count,
                        "p50": child.percentile(50.0),
                        "p95": child.percentile(95.0),
                        "p99": child.percentile(99.0)}
                    exemplars = child.exemplars()
                    if exemplars:
                        sample["exemplars"] = [
                            {"le": _fmt_value(ub), "value": v,
                             "trace_id": tid, "ts": ts}
                            for ub, (v, tid, ts) in sorted(exemplars.items())]
                    samples.append(sample)
                else:
                    samples.append({"labels": labels, "value": child.value})
            out[fam.name] = {"type": fam.kind, "help": fam.help,
                             "samples": samples}
        return out

    def breaker_stats(self) -> Dict[str, Dict]:
        """as_dict() of every breaker registered via instrument_breaker."""
        with self._lock:
            breakers = dict(self.breakers)
        return {name: b.as_dict() for name, b in breakers.items()}


# ---------------------------------------------------------------------------
# process-global default registry (servers/trainers take registry= overrides)
# ---------------------------------------------------------------------------

_global_registry = MetricsRegistry()
_global_lock = threading.Lock()


def get_registry() -> MetricsRegistry:
    """The process-global default registry."""
    return _global_registry


def set_registry(registry: MetricsRegistry) -> MetricsRegistry:
    """Swap the global registry (tests); returns the previous one."""
    global _global_registry
    with _global_lock:
        prev, _global_registry = _global_registry, registry
    return prev
