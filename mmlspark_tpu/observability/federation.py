"""MetricsFederator — fleet-wide ``/metrics`` scrape + merge (PR 11).

PRs 2/4/6 made every *worker* deeply observable; this module gives the
*fleet* one registry-shaped view of all of them.  A ``MetricsFederator``
scrapes each live worker's ``/metrics`` (concurrently, under one overall
deadline — the ``/fleet/slow`` fan-out discipline: a dead worker costs its
own timeout, never the whole sweep, and partial results always serve),
parses the exposition with :func:`parse_prometheus` (promoted here from the
test suite so the production scraper and the round-trip tests share one
parser), and merges families across workers into a :class:`FleetView`:

- **counters are summed** per label-set — the fleet total (per-worker
  attribution survives through the ``server`` label serving families
  already carry);
- **gauges are labelled per worker** — a ``worker="<server_id>"`` label is
  added so ``GET /fleet/metrics`` serves the Prometheus-federation shape;
- **histograms merge bucket-by-bucket only when bucket bounds match** — a
  worker child with mismatched bounds is skipped and counted
  (``mmlspark_federation_bucket_mismatch_total``), never silently merged
  into numbers that look right and are not.

Scrape bookkeeping (``mmlspark_federation_scrape_{total,seconds}``, the
``mmlspark_federation_stale_workers`` callback gauge) rides the same
registry, so the fleet plane watches itself the way the collector does.
Scrape failures book per-worker failure counters ONLY — federation never
feeds the serving-path breakers (``RoutingClient``/``fleet_slow``): a
telemetry outage must not shed traffic.
"""
from __future__ import annotations

import math
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from .metrics import MetricsRegistry, _escape_label, _fmt_value, get_registry
from ..utils.concurrency import make_lock
from ..utils.resilience import Deadline

__all__ = ["parse_prometheus", "FleetView", "MetricsFederator",
           "merge_snapshots"]


# ---------------------------------------------------------------------------
# exposition parsing (shared by the federator and the round-trip tests)
# ---------------------------------------------------------------------------

def _parse_label_pairs(rest: str, line: str) -> List[Tuple[str, str]]:
    """Split ``k="v",k2="v2"`` into pairs, honoring the escapes the
    registry's own ``_escape_label`` emits (``\\\\``, ``\\"``, ``\\n``):
    a comma or quote INSIDE a quoted value must not split the pair, and
    the value is unescaped so label identity survives the round trip."""
    pairs: List[Tuple[str, str]] = []
    i, n = 0, len(rest)
    while i < n:
        eq = rest.find("=", i)
        if eq < 0 or eq + 1 >= n or rest[eq + 1] != '"':
            raise ValueError(f"malformed label block in line {line!r}")
        key = rest[i:eq]
        j, out = eq + 2, []
        while j < n:
            ch = rest[j]
            if ch == "\\":
                if j + 1 >= n:
                    raise ValueError(f"dangling escape in line {line!r}")
                out.append({"n": "\n"}.get(rest[j + 1], rest[j + 1]))
                j += 2
                continue
            if ch == '"':
                break
            out.append(ch)
            j += 1
        else:
            raise ValueError(f"unterminated label value in line {line!r}")
        pairs.append((key, "".join(out)))
        i = j + 1
        if i < n:
            if rest[i] != ",":
                raise ValueError(f"malformed label block in line {line!r}")
            i += 1
    return pairs


def parse_prometheus(text):
    """Tiny exposition-format parser: returns ({(name, frozenset(labels)):
    value}, {name: type}, {key: (exemplar_labels, exemplar_value)}).
    Raises ``ValueError`` on malformed lines — including malformed
    OpenMetrics exemplar suffixes (``... # {trace_id="x"} 0.042``) — so
    the round-trip tests also validate the format itself.  Promoted from
    ``tests/test_observability.py`` (PR 11): the federation scraper and the
    exposition tests must never drift onto different grammars.  Explicit
    raises (not asserts): this is production input validation now, and a
    proxy's HTML error page behind a 200 must become a ``parse_error``
    verdict even under ``python -O``."""
    values, types, exemplars = {}, {}, {}
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split(" ", 3)
            if kind not in ("counter", "gauge", "histogram"):
                raise ValueError(f"unknown TYPE in line {line!r}")
            types[name] = kind
            continue
        if line.startswith("#"):
            if not line.startswith("# HELP ") and line != "# EOF":
                raise ValueError(f"unknown comment line {line!r}")
            continue
        exemplar = None
        if " # " in line:  # OpenMetrics exemplar suffix on a bucket line
            line, _, ex = line.partition(" # ")
            if not ex.startswith("{"):
                raise ValueError(f"malformed exemplar suffix {ex!r}")
            ex_labels, _, ex_val = ex[1:].partition("} ")
            exemplar = (dict(_parse_label_pairs(ex_labels, ex)),
                        float(ex_val))
        body, sval = line.rsplit(" ", 1)
        if "{" in body:
            name, rest = body.split("{", 1)
            if not rest.endswith("}"):
                raise ValueError(f"unterminated label block in {line!r}")
            key = (name, frozenset(_parse_label_pairs(rest[:-1], line)))
        else:
            key = (body, frozenset())
        values[key] = float(sval)
        if exemplar is not None:
            exemplars[key] = exemplar
    return values, types, exemplars


# ---------------------------------------------------------------------------
# fleet view: the merged registry shape
# ---------------------------------------------------------------------------

def _labels_text(labels: frozenset) -> str:
    pairs = sorted(labels)
    if not pairs:
        return ""
    return "{" + ",".join(f'{k}="{_escape_label(v)}"'
                          for k, v in pairs) + "}"


def _label_sort_key(labels: frozenset) -> Tuple:
    return tuple(sorted(labels))


class FleetView:
    """The merged, JSON/exposition-servable fleet registry view.

    ``workers`` records per-worker scrape outcomes (``ok``/``error`` plus
    ``age_s`` since the last successful scrape) so a partial merge is
    visibly partial; ``skipped_histograms`` counts worker histogram
    children whose bucket bounds did not match the merge base.
    """

    def __init__(self):
        self.workers: Dict[str, Dict] = {}
        self.types: Dict[str, str] = {}
        # counter/gauge families: {name: {frozenset(labels): value}}
        self.counters: Dict[str, Dict[frozenset, float]] = {}
        self.gauges: Dict[str, Dict[frozenset, float]] = {}
        # histogram families: {name: {frozenset(base_labels): {"bounds":
        # (..., inf), "cum": {bound: cumulative_count}, "sum", "count"}}}
        self.histograms: Dict[str, Dict[frozenset, Dict]] = {}
        self.skipped_histograms: Dict[str, int] = {}
        self.scraped_at: Optional[float] = None

    # ------------------------------------------------------------- builders
    @classmethod
    def from_texts(cls, texts: Dict[str, str],
                   on_mismatch: Optional[Callable[[str, str], None]] = None
                   ) -> "FleetView":
        """Merge raw exposition texts keyed by worker id (tests, replays)."""
        snapshots = {}
        for sid, text in texts.items():
            values, types, _ = parse_prometheus(text)
            snapshots[sid] = (values, types)
        return merge_snapshots(snapshots, on_mismatch=on_mismatch)

    # -------------------------------------------------------------- queries
    def counter_sum(self, family: str,
                    labels: Optional[Dict[str, str]] = None) -> float:
        """Sum of every counter sample in ``family`` whose label set
        contains ``labels`` (subset match)."""
        sel = set((labels or {}).items())
        return sum(v for ls, v in self.counters.get(family, {}).items()
                   if sel <= set(ls))

    def gauge_values(self, family: str,
                     labels: Optional[Dict[str, str]] = None
                     ) -> List[Tuple[Dict[str, str], float]]:
        """[(labels_dict, value)] for gauge samples matching the subset
        filter (the ``worker`` label added by the merge is included)."""
        sel = set((labels or {}).items())
        return [(dict(ls), v)
                for ls, v in sorted(self.gauges.get(family, {}).items(),
                                    key=lambda kv: _label_sort_key(kv[0]))
                if sel <= set(ls)]

    def histogram_aggregate(self, family: str,
                            labels: Optional[Dict[str, str]] = None
                            ) -> Optional[Dict]:
        """One combined cumulative histogram over every child of ``family``
        matching the subset filter.  Children whose bucket bounds differ
        from the combine base are EXCLUDED — the same never-silently-merge
        rule as the cross-worker merge.  This is a pure read: the
        merge-time ``skipped_histograms`` bookkeeping is the mismatch
        signal (a query must not inflate it on every call)."""
        fam = self.histograms.get(family)
        if not fam:
            return None
        total: Optional[Dict] = None
        sel = set((labels or {}).items())
        for base_labels, acc in sorted(fam.items(),
                                       key=lambda kv: _label_sort_key(kv[0])):
            if not sel <= set(base_labels):
                continue
            if total is None:
                total = {"bounds": acc["bounds"], "cum": dict(acc["cum"]),
                         "sum": acc["sum"], "count": acc["count"]}
            elif total["bounds"] == acc["bounds"]:
                for b in total["bounds"]:
                    total["cum"][b] += acc["cum"][b]
                total["sum"] += acc["sum"]
                total["count"] += acc["count"]
        return total

    def quantile(self, family: str, q: float,
                 labels: Optional[Dict[str, str]] = None) -> float:
        """histogram_quantile estimator over the combined fleet histogram
        (same interpolation as the per-process registry); NaN with no
        data."""
        agg = self.histogram_aggregate(family, labels)
        if not agg or agg["count"] <= 0:
            return float("nan")
        rank = (q / 100.0) * agg["count"]
        prev, lower = 0.0, 0.0
        for b in agg["bounds"]:
            c = agg["cum"][b]
            if c >= rank and c > prev:
                if math.isinf(b):
                    return lower  # clamp to the last finite bound
                return lower + (b - lower) * ((rank - prev) / (c - prev))
            prev = c
            if not math.isinf(b):
                lower = b
        return lower

    def fraction_over(self, family: str, threshold: float,
                      labels: Optional[Dict[str, str]] = None
                      ) -> Tuple[float, float]:
        """(observations over ``threshold``, total observations) for the
        combined fleet histogram — the cumulative "bad events" pair the SLO
        burn-rate windows difference.  Linear interpolation inside the
        bucket containing the threshold; past the last finite bound, the
        whole overflow bucket counts as over."""
        agg = self.histogram_aggregate(family, labels)
        if not agg or agg["count"] <= 0:
            return 0.0, 0.0
        total = agg["count"]
        prev, lower = 0.0, 0.0
        for b in agg["bounds"]:
            c = agg["cum"][b]
            if math.isinf(b) or threshold <= b:
                if math.isinf(b):
                    under = prev
                else:
                    span = b - lower
                    frac = 1.0 if span <= 0 else (threshold - lower) / span
                    under = prev + (c - prev) * min(1.0, max(0.0, frac))
                return max(0.0, total - under), total
            prev, lower = c, b
        return 0.0, total

    # ----------------------------------------------------------- exposition
    def to_prometheus(self, extra_registry: Optional[MetricsRegistry] = None
                      ) -> str:
        """Prometheus 0.0.4 text for the merged view: counters summed,
        gauges carrying the ``worker`` label, histograms with cumulative
        ``le`` buckets.  ``extra_registry`` (the TopologyService's own
        registry — scrape/staleness bookkeeping, SLO and autoscale gauges)
        is appended so one endpoint serves the fleet AND its federation."""
        lines: List[str] = []
        for name in sorted(self.types):
            kind = self.types[name]
            if kind == "histogram":
                lines.append(f"# TYPE {name} histogram")
                fam = self.histograms.get(name, {})
                for base_labels, acc in sorted(
                        fam.items(), key=lambda kv: _label_sort_key(kv[0])):
                    for b in acc["bounds"]:
                        le = "+Inf" if math.isinf(b) else _fmt_value(b)
                        lbl = frozenset(set(base_labels) | {("le", le)})
                        lines.append(f"{name}_bucket{_labels_text(lbl)} "
                                     f"{_fmt_value(acc['cum'][b])}")
                    base = _labels_text(base_labels)
                    lines.append(f"{name}_sum{base} "
                                 f"{_fmt_value(acc['sum'])}")
                    lines.append(f"{name}_count{base} "
                                 f"{_fmt_value(acc['count'])}")
                continue
            if kind != "untyped":
                lines.append(f"# TYPE {name} {kind}")
            series = self.counters.get(name) if kind == "counter" \
                else self.gauges.get(name)
            for labels, v in sorted((series or {}).items(),
                                    key=lambda kv: _label_sort_key(kv[0])):
                lines.append(f"{name}{_labels_text(labels)} {_fmt_value(v)}")
        text = "\n".join(lines) + "\n" if lines else ""
        if extra_registry is not None:
            text += extra_registry.to_prometheus()
        return text

    def to_dict(self) -> Dict:
        """JSON-safe summary (worker verdicts + family inventory), used by
        the fleet endpoints' JSON envelopes."""
        return {
            "workers": {sid: dict(v) for sid, v in sorted(self.workers.items())},
            "families": {name: self.types[name] for name in sorted(self.types)},
            "skipped_histograms": dict(self.skipped_histograms),
            "scraped_at": self.scraped_at,
        }


def _classify(name: str, types: Dict[str, str], hist_names) -> Tuple[str, Optional[str]]:
    kind = types.get(name)
    if kind in ("counter", "gauge"):
        return kind, None
    for base in hist_names:
        if name == base + "_bucket":
            return "hist_bucket", base
        if name == base + "_sum":
            return "hist_sum", base
        if name == base + "_count":
            return "hist_count", base
    # no TYPE line: pass through per worker like a gauge, typed "untyped"
    return "untyped", None


def merge_snapshots(snapshots: Dict[str, Tuple[Dict, Dict]],
                    on_mismatch: Optional[Callable[[str, str], None]] = None
                    ) -> FleetView:
    """Merge parsed per-worker snapshots (``{sid: (values, types)}`` from
    :func:`parse_prometheus`) into one :class:`FleetView`.  Counters sum,
    gauges gain a ``worker`` label, histograms merge bucket-by-bucket only
    on exactly matching bounds — a mismatched worker child is skipped,
    counted into ``skipped_histograms``, and reported via ``on_mismatch``.
    Workers merge in sorted-id order so the merge base is deterministic."""
    view = FleetView()
    for sid in sorted(snapshots):
        values, types = snapshots[sid]
        view.workers[sid] = {"ok": True}
        hist_names = {n for n, k in types.items() if k == "histogram"}
        # this worker's histogram children, grouped before the fleet fold
        hist_acc: Dict[str, Dict[frozenset, Dict]] = {}
        for (name, labels), value in values.items():
            kind, base = _classify(name, types, hist_names)
            if kind == "counter":
                view.types[name] = "counter"
                fam = view.counters.setdefault(name, {})
                fam[labels] = fam.get(labels, 0.0) + value
            elif kind in ("gauge", "untyped"):
                view.types.setdefault(name, kind)
                if kind == "gauge":
                    view.types[name] = "gauge"
                fam = view.gauges.setdefault(name, {})
                fam[frozenset(set(labels) | {("worker", sid)})] = value
            elif kind == "hist_bucket":
                base_labels = frozenset(p for p in labels if p[0] != "le")
                le = dict(labels).get("le", "+Inf")
                bound = math.inf if le in ("+Inf", "inf") else float(le)
                acc = hist_acc.setdefault(base, {}).setdefault(
                    base_labels, {"cum": {}, "sum": 0.0, "count": 0.0})
                acc["cum"][bound] = value
            elif kind == "hist_sum":
                acc = hist_acc.setdefault(base, {}).setdefault(
                    labels, {"cum": {}, "sum": 0.0, "count": 0.0})
                acc["sum"] = value
            elif kind == "hist_count":
                acc = hist_acc.setdefault(base, {}).setdefault(
                    labels, {"cum": {}, "sum": 0.0, "count": 0.0})
                acc["count"] = value
        for fname, by_labels in hist_acc.items():
            view.types[fname] = "histogram"
            dest = view.histograms.setdefault(fname, {})
            for base_labels, acc in by_labels.items():
                bounds = tuple(sorted(acc["cum"]))
                cur = dest.get(base_labels)
                if cur is None:
                    dest[base_labels] = {"bounds": bounds,
                                         "cum": dict(acc["cum"]),
                                         "sum": acc["sum"],
                                         "count": acc["count"]}
                elif cur["bounds"] == bounds:
                    for b in bounds:
                        cur["cum"][b] += acc["cum"][b]
                    cur["sum"] += acc["sum"]
                    cur["count"] += acc["count"]
                else:
                    # NEVER silently merged: mismatched bounds would add
                    # cumulative counts at different edges and produce
                    # quantiles that are confidently wrong
                    view.skipped_histograms[fname] = \
                        view.skipped_histograms.get(fname, 0) + 1
                    if on_mismatch is not None:
                        on_mismatch(fname, sid)
    return view


# ---------------------------------------------------------------------------
# the federator
# ---------------------------------------------------------------------------

class MetricsFederator:
    """Scrape every live worker's ``/metrics`` and serve the merged view.

    ``workers_fn`` returns the routing table (``{server_id: {host, port,
    ...}}`` — ``TopologyService.routing_table`` on the driver).  Scrapes
    fan out concurrently under one overall deadline (``deadline_s``), each
    exchange through the resilient ``io/http`` client with a per-worker
    timeout; a dead worker is a failure row and a counter, never a stall
    of the sweep and never a feed into any serving-path breaker.

    Staleness: ``stale_workers()`` (exported as the
    ``mmlspark_federation_stale_workers`` callback gauge) counts live
    workers whose last successful scrape is older than ``stale_after_s``
    — a worker registered but never scraped is stale by definition.

    Everything time-shaped rides the injectable ``clock``; ``fetcher`` is
    injectable so the deterministic suites scrape canned texts with no
    sockets.
    """

    def __init__(self, workers_fn: Callable[[], Dict[str, Dict]],
                 registry: Optional[MetricsRegistry] = None,
                 timeout_s: float = 2.0, deadline_s: float = 3.0,
                 stale_after_s: float = 15.0,
                 clock: Callable[[], float] = time.monotonic,
                 fetcher: Optional[Callable] = None,
                 name: str = "default"):
        self.workers_fn = workers_fn
        self.registry = registry if registry is not None else get_registry()
        self.timeout_s = float(timeout_s)
        self.deadline_s = float(deadline_s)
        self.stale_after_s = float(stale_after_s)
        self.clock = clock
        self.fetcher = fetcher or self._http_fetch
        # the staleness gauge's label: federators sharing one registry
        # need distinct names or the later one owns the shared series
        self.name = str(name)
        self._client = None  # lazily built io/http client
        self._lock = make_lock("MetricsFederator._lock")
        self._last_ok: Dict[str, float] = {}
        self._view: Optional[FleetView] = None
        self.reopen()

    # ------------------------------------------------------------ transport
    def _http_fetch(self, url: str, timeout_s: float,
                    deadline: Optional[Deadline]) -> str:
        """One scrape exchange through the resilient client (no retries —
        the poll interval IS the retry; no breaker — federation failures
        must never shed anything)."""
        from ..io.http import HTTPClient, HTTPRequestData
        client = self._client
        if client is None:
            client = self._client = HTTPClient(retries=0,
                                               timeout_s=timeout_s)
        resp = client.send(HTTPRequestData(url=url), deadline=deadline)
        if resp is None or resp.status_code != 200:
            raise ConnectionError(
                f"scrape {url} -> {getattr(resp, 'status_code', None)} "
                f"{getattr(resp, 'reason', '')}")
        return (resp.entity or b"").decode("utf-8", "replace")

    # -------------------------------------------------------------- scraping
    def scrape_once(self, deadline_s: Optional[float] = None) -> FleetView:
        """One concurrent sweep over the live workers; returns the merged
        :class:`FleetView` (partial on failures — one dead worker must
        never blind the fleet view).  Books per-worker scrape outcomes and
        the sweep latency."""
        t0 = self.clock()
        workers = dict(self.workers_fn())
        deadline = Deadline.after(
            self.deadline_s if deadline_s is None else float(deadline_s),
            self.clock)
        results: Dict[str, Tuple[str, object]] = {}
        results_lock = make_lock("MetricsFederator._results_lock")

        def fetch(sid: str, w: Dict) -> None:
            url = f"http://{w['host']}:{w['port']}/metrics"
            try:
                text = self.fetcher(url, self.timeout_s, deadline)
            except Exception as e:  # noqa: BLE001 — a dead worker is a row
                verdict = "deadline_exhausted" if deadline.expired() \
                    else "error"
                with results_lock:
                    results[sid] = (verdict, str(e))
                return
            try:
                values, types, _ = parse_prometheus(text)
            except Exception as e:  # noqa: BLE001 — garbage is a verdict
                with results_lock:
                    results[sid] = ("parse_error", str(e))
                return
            with results_lock:
                results[sid] = ("ok", (values, types))

        threads = []
        for sid, w in sorted(workers.items()):
            t = threading.Thread(target=fetch, args=(sid, w), daemon=True,
                                 name=f"federate-{sid}")
            t.start()
            threads.append((sid, t))
        for _sid, t in threads:
            t.join(timeout=max(0.0, deadline.remaining()))
        with results_lock:
            done = dict(results)
        now = self.clock()
        snapshots: Dict[str, Tuple[Dict, Dict]] = {}
        failures: Dict[str, Dict] = {}
        for sid, _t in threads:
            verdict, payload = done.get(
                sid, ("deadline_exhausted", "scrape still in flight"))
            self._m["scrapes"].inc(worker=sid, result=verdict)
            if verdict == "ok":
                snapshots[sid] = payload
            else:
                failures[sid] = {"ok": False, "error": f"{verdict}: {payload}"}
        view = merge_snapshots(
            snapshots,
            on_mismatch=lambda fam, _sid: self._m["bucket_mismatch"].inc(
                family=fam))
        view.workers.update(failures)
        with self._lock:
            for sid in snapshots:
                self._last_ok[sid] = now
            for sid in list(self._last_ok):  # departed workers drop out
                if sid not in workers:
                    self._last_ok.pop(sid)
            last_ok = dict(self._last_ok)
            self._view = view
        for sid, info in view.workers.items():
            seen = last_ok.get(sid)
            # None (not inf) for never-scraped: these rows ride JSON
            # endpoints, and json.dumps renders inf as the non-RFC
            # ``Infinity`` literal that strict parsers reject outright
            info["age_s"] = (now - seen) if seen is not None else None
        view.scraped_at = now
        self._m["scrape_seconds"].observe(max(0.0, self.clock() - t0))
        return view

    def last_view(self) -> Optional[FleetView]:
        with self._lock:
            return self._view

    def reopen(self) -> None:
        """(Re-)register this federator's instruments — called at
        construction and by ``TopologyService.start()`` so a stopped-then-
        restarted service gets its staleness series back (the
        ``CheckpointManager`` re-open convention)."""
        from .instruments import instrument_federator
        self._m = instrument_federator(self, self.registry)

    def close(self) -> None:
        """Unhook THIS federator's stale-workers gauge series (scoped by
        the ``federation`` label — a shared registry's other federators
        keep theirs): the callback closure pins this federator (and,
        through ``workers_fn``, the owning topology service), so a stopped
        service must detach it or the registry keeps both the stale series
        and the dead fleet alive for process lifetime — same hygiene as
        ``PipelineServer.stop()``'s queue gauges."""
        fam = self.registry.family("mmlspark_federation_stale_workers")
        if fam is not None:
            fam.remove(federation=self.name)

    def stale_workers(self) -> int:
        """Live workers whose last successful scrape is older than
        ``stale_after_s`` (never-scraped counts as stale) — the
        ``mmlspark_federation_stale_workers`` gauge callback."""
        try:
            workers = self.workers_fn()
        except Exception:  # noqa: BLE001 — a dying table scrapes as 0
            return 0
        now = self.clock()
        with self._lock:
            last = dict(self._last_ok)
        return sum(1 for sid in workers
                   if now - last.get(sid, -math.inf) > self.stale_after_s)
