"""Superpixel segmentation (SLIC-style).

Reference: legacy ``lime/Superpixel.scala:148`` — SLIC-like clustering used
by image LIME, plus ``SuperpixelTransformer``.  Implemented as a bounded
k-means over (color, position) features with grid initialisation; vectorized
numpy (host-side preprocessing, like the reference's JVM implementation).
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..core import DataFrame, HasInputCol, HasOutputCol, Param, Transformer


def slic_superpixels(img: np.ndarray, cell_size: float = 16.0,
                     modifier: float = 130.0, iters: int = 5) -> np.ndarray:
    """(H, W, C) image -> (H, W) int32 superpixel labels."""
    H, W = img.shape[:2]
    C = img.shape[2] if img.ndim == 3 else 1
    img = img.reshape(H, W, C).astype(np.float64)
    S = max(int(cell_size), 2)
    gy = np.arange(S // 2, H, S)
    gx = np.arange(S // 2, W, S)
    centers = np.array([[y, x] for y in gy for x in gx], np.float64)
    k = len(centers)
    if k <= 1:
        return np.zeros((H, W), np.int32)
    cc = np.stack([img[int(y), int(x)] for y, x in centers])  # (k, C)

    yy, xx = np.mgrid[0:H, 0:W]
    pos = np.stack([yy, xx], axis=-1).astype(np.float64)      # (H, W, 2)
    # spatial weight balances color vs position (SLIC compactness m)
    m = max(modifier, 1e-3)
    ratio = (m / S) ** 2

    labels = np.zeros((H, W), np.int64)
    for _ in range(iters):
        # assign: distance to each center over a local window
        dist = np.full((H, W), np.inf)
        for ci in range(k):
            cy, cx = centers[ci]
            y0, y1 = max(0, int(cy) - 2 * S), min(H, int(cy) + 2 * S)
            x0, x1 = max(0, int(cx) - 2 * S), min(W, int(cx) + 2 * S)
            if y0 >= y1 or x0 >= x1:
                continue
            dc = ((img[y0:y1, x0:x1] - cc[ci]) ** 2).sum(axis=-1)
            ds = ((pos[y0:y1, x0:x1] - centers[ci]) ** 2).sum(axis=-1)
            d = dc + ratio * ds
            sub = dist[y0:y1, x0:x1]
            upd = d < sub
            sub[upd] = d[upd]
            labels[y0:y1, x0:x1][upd] = ci
        # update centers
        for ci in range(k):
            mask = labels == ci
            if mask.any():
                centers[ci] = pos[mask].mean(axis=0)
                cc[ci] = img[mask].mean(axis=0)
    # compact label ids
    uniq, remap = np.unique(labels, return_inverse=True)
    return remap.reshape(H, W).astype(np.int32)


class SuperpixelTransformer(Transformer, HasInputCol, HasOutputCol):
    """Reference ``SuperpixelTransformer``: image column -> superpixel map."""
    cell_size = Param("cell_size", "superpixel size", "float", default=16.0)
    modifier = Param("modifier", "compactness", "float", default=130.0)

    def _transform(self, df: DataFrame) -> DataFrame:
        in_col = self.get_or_fail("input_col")
        cs, mod = self.get("cell_size"), self.get("modifier")

        def per_part(p):
            out = np.empty(len(p[in_col]), dtype=object)
            for i, v in enumerate(p[in_col]):
                out[i] = slic_superpixels(np.asarray(v, np.float64), cs, mod)
            return {**p, self.get_or_fail("output_col"): out}

        return df.map_partitions(per_part)
