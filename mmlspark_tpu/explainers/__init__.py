from .local import (LocalExplainer, TabularLIME, TabularSHAP, VectorLIME,
                    VectorSHAP, TextLIME, TextSHAP, ImageLIME, ImageSHAP)
from .superpixel import SuperpixelTransformer, slic_superpixels
from .regression import lasso_regression, weighted_least_squares

__all__ = ["LocalExplainer", "TabularLIME", "TabularSHAP", "VectorLIME",
           "VectorSHAP", "TextLIME", "TextSHAP", "ImageLIME", "ImageSHAP",
           "SuperpixelTransformer", "slic_superpixels", "lasso_regression",
           "weighted_least_squares"]
