"""Model-agnostic local explainers — LIME + KernelSHAP.

Reference: ``explainers/`` (~2.3k LoC): ``LIMEBase.transform``
(``LIMEBase.scala:67-116``: sample -> score -> weighted-lasso per row),
``KernelSHAPBase`` (:36), samplers per modality (tabular/vector/image/text),
facade ``LocalExplainer.LIME.tabular`` etc. (``LocalExplainer.scala:68-103``).

Each row's perturbed samples are scored through the wrapped model in one
batched transform (the reference uses groupByKey.mapGroups); surrogate fits
run on device (``regression.py``).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..core import (ComplexParam, DataFrame, HasInputCol, HasOutputCol, Model,
                    Param, Transformer)
from ..core.dataframe import _as_column, _part_len
from ..core.schema import ColumnType, stack_vector_column, vector_column
from .regression import lasso_regression, weighted_least_squares


def _extract_target(col: np.ndarray, target_classes: Optional[List[int]]) -> np.ndarray:
    """Model output column -> scalar score per row (probability of target
    class, or the raw value)."""
    first = col[0]
    if isinstance(first, (list, np.ndarray)):
        cls = (target_classes or [int(np.argmax(first))])[0]
        return np.asarray([np.asarray(v)[cls] for v in col], np.float64)
    return np.asarray(col, np.float64)


class _LocalExplainerBase(Transformer, HasInputCol, HasOutputCol):
    model = ComplexParam("model", "transformer to explain")
    target_col = Param("target_col", "model output column to explain", "string",
                       default="probability")
    target_classes = Param("target_classes", "class indices to explain", "list",
                           default=None)
    num_samples = Param("num_samples", "perturbations per row", "int", default=256)
    metrics_col = Param("metrics_col", "surrogate fit metric column", "string",
                        default="r2")
    seed = Param("seed", "sampling seed", "int", default=0)

    kind: str = "lime"   # or "shap"
    regularization = Param("regularization", "lasso alpha (LIME)", "float", default=0.01)
    kernel_width = Param("kernel_width", "LIME kernel width", "float", default=0.75)

    def __init__(self, uid=None, **kwargs):
        super().__init__(uid)
        if kwargs:
            self.set_params(**kwargs)

    # subclass hooks --------------------------------------------------------
    def _make_samples(self, instance, rng, n: int):
        """-> (binary_mask (n, d), model_inputs list[n])."""
        raise NotImplementedError

    def _background_score(self, mask: np.ndarray) -> np.ndarray:
        """Similarity/coalition weights for each sample's mask."""
        if self.kind == "shap":
            d = mask.shape[1]
            z = mask.sum(axis=1)
            from math import comb
            w = np.empty(len(z))
            for i, zi in enumerate(z):
                zi = int(zi)
                if zi == 0 or zi == d:
                    w[i] = 1e6  # enforced endpoints
                else:
                    w[i] = (d - 1) / (comb(d, zi) * zi * (d - zi))
            return w
        # LIME: exponential kernel on cosine/hamming distance
        width = self.get("kernel_width")
        dist = 1.0 - mask.mean(axis=1)
        return np.sqrt(np.exp(-(dist ** 2) / width ** 2))

    def _fit_surrogate(self, mask, scores, weights):
        if self.kind == "shap":
            coefs, intercept = weighted_least_squares(mask, scores, weights)
            return coefs, intercept
        coefs, intercept = lasso_regression(mask, scores, weights,
                                            alpha=self.get("regularization"))
        return coefs, intercept

    # main ------------------------------------------------------------------
    def _transform(self, df: DataFrame) -> DataFrame:
        model = self.get_or_fail("model")
        in_col = self.get_or_fail("input_col")
        out_col = self.get_or_fail("output_col")
        n_samples = self.get("num_samples")
        rng = np.random.default_rng(self.get("seed"))

        def per_part(p):
            n = _part_len(p)
            out = np.empty(n, dtype=object)
            r2s = np.zeros(n, np.float64)
            for i in range(n):
                instance = p[in_col][i]
                mask, inputs = self._make_samples(instance, rng, n_samples)
                sample_df = self._samples_to_frame(inputs)
                scored = model.transform(sample_df)
                scores = _extract_target(scored.collect()[self.get("target_col")],
                                         self.get("target_classes"))
                weights = self._background_score(mask)
                coefs, intercept = self._fit_surrogate(mask, scores, weights)
                pred = mask @ coefs + intercept
                ss_res = float(np.sum(weights * (scores - pred) ** 2))
                ss_tot = float(np.sum(weights * (scores - np.average(scores, weights=weights)) ** 2))
                r2s[i] = 1.0 - ss_res / max(ss_tot, 1e-12)
                out[i] = coefs
            return {**p, out_col: out, self.get("metrics_col"): r2s}

        return df.map_partitions(per_part)

    def _samples_to_frame(self, inputs: List) -> DataFrame:
        col = np.empty(len(inputs), dtype=object)
        for i, v in enumerate(inputs):
            col[i] = v
        return DataFrame([{self._model_input_col(): col}])

    def _model_input_col(self) -> str:
        return self.get_or_fail("input_col")

    def transform_schema(self, schema):
        schema.require(self.get_or_fail("input_col"))
        return schema.add(self.get_or_fail("output_col"), ColumnType.VECTOR)


# ---------------------------------------------------------------------------
# Vector / tabular samplers
# ---------------------------------------------------------------------------

class _VectorExplainer(_LocalExplainerBase):
    background_data = ComplexParam("background_data", "background frame for "
                                   "replacement values")

    def _background_matrix(self, d: int) -> np.ndarray:
        bg = self.get("background_data")
        if bg is None:
            return np.zeros((1, d))
        data = bg.collect()
        in_col = self.get_or_fail("input_col")
        cols = self.get("input_cols") if "input_cols" in self._params else None
        if in_col not in data and cols:
            # tabular mode: the vector column is derived; assemble the
            # background from the raw tabular columns instead
            return np.column_stack([np.asarray(data[c], np.float64)
                                    for c in cols])
        return stack_vector_column(data[in_col])

    def _make_samples(self, instance, rng, n):
        x = np.asarray(instance, np.float64)
        d = len(x)
        mask = rng.integers(0, 2, (n, d)).astype(np.float64)
        mask[0] = 1.0   # all-on coalition
        mask[1] = 0.0   # all-off
        bg = self._background_matrix(d)
        repl = bg[rng.integers(0, len(bg), n)]
        inputs = [np.where(mask[i] > 0, x, repl[i]) for i in range(n)]
        return mask, inputs


class VectorLIME(_VectorExplainer):
    kind = "lime"


class VectorSHAP(_VectorExplainer):
    kind = "shap"


class TabularLIME(_VectorExplainer):
    kind = "lime"
    input_cols = Param("input_cols", "tabular columns to perturb", "list")

    def transform_schema(self, schema):
        cols = self.get("input_cols")
        if cols:  # input_col is DERIVED from the tabular columns in
            # _transform; require those instead (reference TabularLIME takes
            # inputCols and assembles internally)
            for c in cols:
                schema.require(c)
            from ..core.schema import ColumnType
            return schema.add(self.get_or_fail("output_col"),
                              ColumnType.VECTOR)
        return super().transform_schema(schema)

    def _transform(self, df):
        cols = self.get("input_cols")
        if cols:
            work = df.with_column(self.get_or_fail("input_col"),
                                  lambda p: vector_column(
                                      [np.asarray([p[c][i] for c in cols], float)
                                       for i in range(_part_len(p))]))
            return super()._transform(work)
        return super()._transform(df)


class TabularSHAP(TabularLIME):
    kind = "shap"


# ---------------------------------------------------------------------------
# Text sampler
# ---------------------------------------------------------------------------

class _TextExplainer(_LocalExplainerBase):
    tokens_col = Param("tokens_col", "output column of token lists", "string",
                       default="tokens")

    def _make_samples(self, instance, rng, n):
        tokens = str(instance).split()
        d = max(len(tokens), 1)
        mask = rng.integers(0, 2, (n, d)).astype(np.float64)
        mask[0] = 1.0
        inputs = [" ".join(t for t, m in zip(tokens, mask[i]) if m > 0)
                  for i in range(n)]
        self._last_tokens = tokens
        return mask, inputs

    def _transform(self, df):
        out = super()._transform(df)
        in_col = self.get_or_fail("input_col")
        return out.with_column(self.get("tokens_col"),
                               lambda p: _as_column([str(v).split() for v in p[in_col]]))


class TextLIME(_TextExplainer):
    kind = "lime"


class TextSHAP(_TextExplainer):
    kind = "shap"


# ---------------------------------------------------------------------------
# Image sampler (superpixel masking)
# ---------------------------------------------------------------------------

class _ImageExplainer(_LocalExplainerBase):
    cell_size = Param("cell_size", "superpixel size (SLIC-ish grid)", "float", default=16.0)
    modifier = Param("modifier", "superpixel compactness", "float", default=130.0)
    superpixel_col = Param("superpixel_col", "superpixel assignment output",
                           "string", default="superpixels")

    def _make_samples(self, instance, rng, n):
        from .superpixel import slic_superpixels
        img = np.asarray(instance, np.float64)
        segments = slic_superpixels(img, self.get("cell_size"), self.get("modifier"))
        d = int(segments.max()) + 1
        mask = rng.integers(0, 2, (n, d)).astype(np.float64)
        mask[0] = 1.0
        mean_color = img.reshape(-1, img.shape[-1]).mean(axis=0)
        inputs = []
        for i in range(n):
            on = mask[i][segments]  # (H, W)
            out = np.where(on[..., None] > 0, img, mean_color)
            inputs.append(out.astype(np.float32))
        self._last_segments = segments
        return mask, inputs

    def _transform(self, df):
        out = super()._transform(df)
        cs, mod = self.get("cell_size"), self.get("modifier")
        in_col = self.get_or_fail("input_col")

        def seg_col(p):
            from .superpixel import slic_superpixels
            res = np.empty(len(p[in_col]), dtype=object)
            for i, v in enumerate(p[in_col]):
                res[i] = slic_superpixels(np.asarray(v, np.float64), cs, mod)
            return res

        return out.with_column(self.get("superpixel_col"), seg_col)


class ImageLIME(_ImageExplainer):
    kind = "lime"


class ImageSHAP(_ImageExplainer):
    kind = "shap"


class LocalExplainer:
    """Facade (reference ``LocalExplainer.scala:68-103``)."""

    class LIME:
        tabular = TabularLIME
        vector = VectorLIME
        image = ImageLIME
        text = TextLIME

    class KernelSHAP:
        tabular = TabularSHAP
        vector = VectorSHAP
        image = ImageSHAP
        text = TextSHAP
