"""Weighted linear solvers for explainers.

Reference: ``explainers/`` breeze-based ``LassoRegression`` /
``LeastSquaresRegression``.  Here: closed-form weighted least squares and an
ISTA lasso, both jitted so the per-row surrogate fits batch onto the device.
"""
from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import numpy as np


def weighted_least_squares(X: np.ndarray, y: np.ndarray, w: np.ndarray,
                           fit_intercept: bool = True,
                           ridge: float = 1e-6) -> Tuple[np.ndarray, float]:
    """Host float64 normal equations: the SHAP kernel's 1e6 endpoint
    weights make the system ill-conditioned beyond float32 (jax truncates
    float64 by default), and the per-row solve is d<=dozens — too small for
    the device to matter."""
    X = np.asarray(X, np.float64)
    y = np.asarray(y, np.float64)
    w = np.asarray(w, np.float64)
    if fit_intercept:
        X1 = np.concatenate([X, np.ones((X.shape[0], 1))], axis=1)
    else:
        X1 = X
    WX = X1 * w[:, None]
    A = X1.T @ WX + ridge * np.eye(X1.shape[1])
    b = WX.T @ y
    try:
        beta = np.linalg.solve(A, b)
    except np.linalg.LinAlgError:
        beta = np.linalg.lstsq(A, b, rcond=None)[0]
    if fit_intercept:
        return beta[:-1], float(beta[-1])
    return beta, 0.0


def lasso_regression(X: np.ndarray, y: np.ndarray, w: np.ndarray,
                     alpha: float = 0.01, iters: int = 200,
                     fit_intercept: bool = True) -> Tuple[np.ndarray, float]:
    """Weighted lasso via ISTA (proximal gradient); jit-compiled loop."""
    import jax
    import jax.numpy as jnp

    # explicit float32: jax truncates float64 by default, and the ISTA
    # iteration is robust at single precision (unlike the WLS solve above)
    Xj = jnp.asarray(X, jnp.float32)
    yj = jnp.asarray(y, jnp.float32)
    wj = jnp.asarray(w, jnp.float32)
    wj = wj / jnp.maximum(wj.sum(), 1e-12)
    n, d = Xj.shape

    x_mean = (Xj * wj[:, None]).sum(axis=0) if fit_intercept else jnp.zeros(d)
    y_mean = (yj * wj).sum() if fit_intercept else 0.0
    Xc = Xj - x_mean
    yc = yj - y_mean

    A = (Xc * wj[:, None]).T @ Xc
    b = (Xc * wj[:, None]).T @ yc
    L = jnp.maximum(jnp.trace(A), 1e-9)  # Lipschitz upper bound

    @jax.jit
    def solve(A, b, L):
        def body(_, beta):
            grad = A @ beta - b
            z = beta - grad / L
            return jnp.sign(z) * jnp.maximum(jnp.abs(z) - alpha / L, 0.0)
        return jax.lax.fori_loop(0, iters, body, jnp.zeros_like(b))

    beta = np.asarray(solve(A, b, L))
    intercept = float(y_mean - x_mean @ beta) if fit_intercept else 0.0
    return beta, intercept
